"""Tests for the management services: device-management breadth, assets,
batch operations, scheduling, labels (QR), and device streams."""

import asyncio
import datetime

import pytest

from sitewhere_tpu.commands.destinations import (
    CommandDestination,
    LocalDeliveryProvider,
    mqtt_topic_extractor,
)
from sitewhere_tpu.commands.encoders import JsonCommandExecutionEncoder
from sitewhere_tpu.commands.model import DeviceCommand
from sitewhere_tpu.commands.routing import SingleChoiceCommandRouter
from sitewhere_tpu.commands.service import CommandDeliveryService
from sitewhere_tpu.core.types import BatchElementStatus
from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.management.assets import AssetManagement
from sitewhere_tpu.management.batch import (
    BatchCommandInvocationHandler,
    BatchOperationManager,
)
from sitewhere_tpu.management.device_management import AlarmState, DeviceManagement
from sitewhere_tpu.management.entities import DuplicateToken, EntityNotFound
from sitewhere_tpu.management.schedule import (
    CronExpression,
    ScheduleManager,
    command_invocation_executor,
)
from sitewhere_tpu.management.streams import DeviceStreamManager


def _engine():
    return Engine(EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=4096, batch_capacity=16, channels=4,
    ))


@pytest.fixture
def dm():
    return DeviceManagement(_engine())


def test_device_type_and_device_crud(dm):
    dm.create_device_type("thermostat", "Thermostat")
    summary = dm.create_device("d-1", "thermostat")
    assert summary.device_type == "thermostat"
    with pytest.raises(EntityNotFound):
        dm.create_device("d-2", "no-such-type")
    with pytest.raises(DuplicateToken):
        dm.create_device_type("thermostat", "Again")
    res = dm.list_devices(device_type="thermostat")
    assert res.total == 1 and res.results[0].token == "d-1"
    assert dm.delete_device("d-1")


def test_area_customer_zone_hierarchy(dm):
    dm.create_area_type("region", "Region", contained_area_types=["site"])
    dm.create_area_type("site", "Site")
    dm.create_area("southeast", "region", "Southeast")
    dm.create_area("atlanta", "site", "Atlanta", parent_token="southeast")
    with pytest.raises(ValueError, match="cannot contain"):
        dm.create_area("nested-region", "region", "Bad", parent_token="southeast")
    tree = dm.area_tree()
    assert len(tree) == 1 and tree[0].entity.meta.token == "southeast"
    assert tree[0].children[0].entity.meta.token == "atlanta"

    dm.create_zone("z-1", "atlanta", "Loading dock",
                   bounds=[(33.7, -84.4), (33.8, -84.4), (33.8, -84.3)])
    with pytest.raises(ValueError, match="3 vertices"):
        dm.create_zone("z-2", "atlanta", "Bad", bounds=[(0, 0), (1, 1)])
    assert len(dm.zones_for_area("atlanta")) == 1

    dm.create_customer_type("org", "Organization")
    dm.create_customer("acme", "org", "ACME")
    dm.create_customer("acme-south", "org", "ACME South", parent_token="acme")
    ctree = dm.customer_tree()
    assert ctree[0].entity.name == "ACME"
    assert ctree[0].children[0].entity.name == "ACME South"


def test_statuses_and_alarms(dm):
    dm.create_device_type("pump", "Pump")
    dm.create_device("p-1", "pump")
    dm.create_device_status("s-ok", "pump", "ok", "OK")
    dm.create_device_status("s-fault", "pump", "fault", "Fault",
                            background_color="#ff0000")
    assert {s.code for s in dm.statuses_for_type("pump")} == {"ok", "fault"}

    alarm = dm.create_alarm("a-1", "p-1", "Pressure exceeded")
    assert alarm.state is AlarmState.TRIGGERED
    assert dm.acknowledge_alarm("a-1").state is AlarmState.ACKNOWLEDGED
    assert dm.resolve_alarm("a-1").state is AlarmState.RESOLVED
    assert len(dm.alarms_for_device("p-1")) == 1
    with pytest.raises(EntityNotFound):
        dm.create_alarm("a-2", "ghost", "no device")


def test_device_groups_and_expansion(dm):
    for t in ("g-1", "g-2"):
        pass
    dm.create_device("d-1", "default")
    dm.create_device("d-2", "default")
    dm.create_device("d-3", "default")
    dm.create_group("all", "All devices", roles=["monitor"])
    dm.create_group("subset", "Subset")
    dm.add_group_elements("subset", [{"device": "d-3", "roles": ["leaf"]}])
    dm.add_group_elements("all", [
        {"device": "d-1", "roles": ["primary"]},
        {"device": "d-2"},
        {"group": "subset"},
    ])
    assert dm.expand_group_devices("all") == ["d-1", "d-2", "d-3"]
    assert dm.expand_group_devices("all", roles=["primary"]) == ["d-1"]
    with pytest.raises(ValueError, match="exactly one"):
        dm.add_group_elements("all", [{"device": "d-1", "group": "subset"}])
    els = dm.group_elements("all")
    assert dm.remove_group_element("all", els[0].element_id)
    assert len(dm.group_elements("all")) == 2


def test_asset_management():
    am = AssetManagement()
    am.create_asset_type("truck", "Delivery truck")
    am.create_asset("truck-17", "truck", "Truck 17")
    with pytest.raises(EntityNotFound):
        am.create_asset("x", "no-type", "X")
    res = am.list_assets(asset_type="truck")
    assert res.total == 1 and res.results[0].name == "Truck 17"


def _command_stack(engine):
    svc = CommandDeliveryService(engine, SingleChoiceCommandRouter("local"))
    svc.registry.create(DeviceCommand(token="ping", device_type="default", name="ping"))
    provider = LocalDeliveryProvider()
    svc.add_destination(CommandDestination(
        "local", mqtt_topic_extractor(), JsonCommandExecutionEncoder(), provider,
    ))
    return svc, provider


def test_batch_command_invocation():
    engine = _engine()
    for i in range(5):
        engine.register_device(f"b-{i}")
    svc, provider = _command_stack(engine)
    mgr = BatchOperationManager(concurrency=3)
    mgr.register_handler(BatchCommandInvocationHandler(svc))
    op = mgr.create_operation("op-1", "InvokeCommand",
                              [f"b-{i}" for i in range(5)],
                              {"commandToken": "ping"})
    op = asyncio.run(mgr.process_operation("op-1"))
    assert op.status == "Finished"
    assert op.counts()["SUCCEEDED"] == 5
    assert len(provider.delivered) == 5
    assert all(el.response_metadata["invocationId"] for el in op.elements)


def test_batch_failure_tracking():
    engine = _engine()
    engine.register_device("ok-1")
    svc, provider = _command_stack(engine)
    mgr = BatchOperationManager()
    mgr.register_handler(BatchCommandInvocationHandler(svc))
    # 'ghost' device: invoke() validates command, but delivery goes to a
    # failing provider -> simulate handler failure with unknown command
    op = mgr.create_operation("op-2", "InvokeCommand", ["ok-1", "ghost"],
                              {"commandToken": "nope"})
    op = asyncio.run(mgr.process_operation("op-2"))
    assert op.counts()["FAILED"] == 2
    assert len(mgr.failed_elements) == 2
    with pytest.raises(ValueError, match="no handler"):
        mgr.create_operation("op-3", "Unknown", ["ok-1"])


def test_cron_expression():
    c = CronExpression.parse("*/15 3 * * *")
    assert c.matches(datetime.datetime(2026, 7, 29, 3, 45))
    assert not c.matches(datetime.datetime(2026, 7, 29, 4, 0))
    nxt = c.next_fire(datetime.datetime(2026, 7, 29, 3, 46))
    assert nxt == datetime.datetime(2026, 7, 30, 3, 0)
    c2 = CronExpression.parse("0 9 * * 1-5")  # weekdays 9am
    assert c2.matches(datetime.datetime(2026, 7, 29, 9, 0))   # Wednesday
    assert not c2.matches(datetime.datetime(2026, 8, 1, 9, 0))  # Saturday
    with pytest.raises(ValueError):
        CronExpression.parse("61 * * * *")
    with pytest.raises(ValueError):
        CronExpression.parse("* * *")


def test_schedule_manager_fires_jobs():
    engine = _engine()
    engine.register_device("sched-1")
    svc, provider = _command_stack(engine)
    sm = ScheduleManager()
    sm.register_executor("CommandInvocation", command_invocation_executor(svc))
    sm.create_schedule("every-sec", "Every second", "Simple", interval_s=0.01,
                       repeat_count=1)
    sm.create_job("job-1", "every-sec", "CommandInvocation",
                  {"deviceToken": "sched-1", "commandToken": "ping"})

    async def run():
        now = 1_000_000.0
        n1 = await sm.fire_due(now)
        n2 = await sm.fire_due(now + 5)       # too soon
        n3 = await sm.fire_due(now + 20)      # second (last) allowed fire
        n4 = await sm.fire_due(now + 40)      # repeat count exhausted
        return n1, n2, n3, n4

    n1, n2, n3, n4 = asyncio.run(run())
    assert (n1, n2, n3, n4) == (1, 0, 1, 0)
    assert len(provider.delivered) == 2
    job = sm.jobs.get("job-1")
    assert job.fired_count == 2 and job.last_error is None

    with pytest.raises(ValueError, match="cron"):
        sm.create_schedule("bad", "Bad", "Cron")
    with pytest.raises(ValueError, match="no executor"):
        sm.create_job("job-2", "every-sec", "Unknown", {})


def test_qr_code_structure():
    from sitewhere_tpu.labels.qrcode import qr_matrix, qr_png

    M = qr_matrix("sitewhere://tpu/device/dev-123")
    size = len(M)
    assert size in (21 + 4 * v for v in range(10))
    # finder patterns present at three corners
    for r0, c0 in ((0, 0), (0, size - 7), (size - 7, 0)):
        assert M[r0][c0] == 1 and M[r0 + 3][c0 + 3] == 1
        assert M[r0 + 1][c0 + 1] == 0
    # timing pattern alternates
    assert [M[6][i] for i in range(8, 12)] == [1, 0, 1, 0]
    # dark module
    assert M[size - 8][8] == 1
    # all cells assigned
    assert all(v in (0, 1) for row in M for v in row)
    png = qr_png("short", scale=2, border=1)
    assert png[:8] == b"\x89PNG\r\n\x1a\n"
    # larger payloads pick larger versions
    M2 = qr_matrix("x" * 100)
    assert len(M2) > size


def test_label_manager():
    from sitewhere_tpu.labels.manager import LabelGeneratorManager

    mgr = LabelGeneratorManager()
    gen = mgr.get("qrcode")
    png = gen.device_label("dev-1")
    assert png[:8] == b"\x89PNG\r\n\x1a\n"
    assert mgr.list_generators() == [{"id": "qrcode", "name": "QR Code Generator"}]
    with pytest.raises(KeyError):
        mgr.get("missing")


def test_device_streams():
    sm = DeviceStreamManager()
    sm.create_stream("video-1", "cam-1", "video/h264")
    sm.append_chunk("video-1", 2, b"BBB")
    sm.append_chunk("video-1", 1, b"AAA")
    sm.append_chunk("video-1", 3, b"CCC")
    assert sm.get_chunk("video-1", 2) == b"BBB"
    assert sm.get_chunk("video-1", 9) is None
    assert sm.read_all("video-1") == b"AAABBBCCC"
    stream = sm.streams.get("video-1")
    assert stream.chunk_count == 3 and stream.total_bytes == 9
    with pytest.raises(EntityNotFound):
        sm.append_chunk("ghost", 1, b"x")


def test_assignment_triggers_emit_state_changes():
    """Opt-in DeviceManagementTriggers analog: assignment lifecycle emits
    STATE_CHANGE events into the pipeline."""
    from sitewhere_tpu.core.types import EventType
    from sitewhere_tpu.engine import Engine, EngineConfig

    eng = Engine(EngineConfig(
        device_capacity=32, token_capacity=64, assignment_capacity=64,
        store_capacity=512, batch_capacity=8, channels=4,
        assignment_triggers=True))
    eng.register_device("tr-1")
    a = eng.create_assignment("tr-1", token="tr-1-x")
    eng.release_assignment("tr-1-x")
    eng.flush()
    res = eng.query_events(device_token="tr-1",
                           etype=EventType.STATE_CHANGE, limit=10)
    assert res["total"] >= 2  # created + released (per active assignment)
    changes = {e.get("stateChange") for e in res["events"]}
    assert {"assignment.created", "assignment.released"} <= changes
    assert all(e.get("attribute") == "assignment" for e in res["events"])

    # default engines stay trigger-free
    eng2 = Engine(EngineConfig(
        device_capacity=32, token_capacity=64, assignment_capacity=64,
        store_capacity=512, batch_capacity=8, channels=4))
    eng2.register_device("tr-2")
    eng2.create_assignment("tr-2")
    eng2.flush()
    assert eng2.query_events(device_token="tr-2",
                             etype=EventType.STATE_CHANGE)["total"] == 0


def test_update_device_atomic_on_bad_parent():
    """A failed update (unknown parent) must not half-apply host changes."""
    import pytest as _pytest

    from sitewhere_tpu.engine import Engine, EngineConfig

    eng = Engine(EngineConfig(
        device_capacity=32, token_capacity=64, assignment_capacity=64,
        store_capacity=512, batch_capacity=8, channels=4))
    eng.register_device("at-1", device_type="default")
    with _pytest.raises(KeyError):
        eng.update_device("at-1", device_type="other-type",
                          metadata={"parentToken": "ghost"})
    assert eng.get_device("at-1").device_type == "default"  # untouched
    with _pytest.raises(ValueError):
        eng.update_device("at-1", metadata={"parentToken": "at-1"})


# --- device-initiated stream commands over the downlink ----------------------


def test_stream_commands_roundtrip_via_downlink():
    """DeviceStream / DeviceStreamData / SendDeviceStreamData requests from
    a device flow through the stream service; the ack and the requested
    chunk come back over command delivery (reference:
    media/DeviceStreamManager.java:36-80)."""
    import asyncio
    import base64
    import json as _json

    from sitewhere_tpu.engine import Engine, EngineConfig
    from sitewhere_tpu.instance.instance import (
        InstanceConfig,
        SiteWhereTpuInstance,
    )
    from sitewhere_tpu.commands.destinations import (
        CommandDestination,
        LocalDeliveryProvider,
        mqtt_topic_extractor,
    )
    from sitewhere_tpu.commands.encoders import JsonCommandExecutionEncoder
    from sitewhere_tpu.ingest.decoders import JsonDeviceRequestDecoder

    inst = SiteWhereTpuInstance(InstanceConfig(engine=EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=4096, batch_capacity=16, channels=4)))
    provider = LocalDeliveryProvider()
    inst.commands.add_destination(CommandDestination(
        "default", mqtt_topic_extractor(), JsonCommandExecutionEncoder(),
        provider))
    inst.engine.register_device("cam-1")
    dec = JsonDeviceRequestDecoder()

    def send(envelope):
        for req in dec.decode(_json.dumps(envelope).encode(), {}):
            inst._route_device_request(req)

    async def go():
        send({"deviceToken": "cam-1", "type": "DeviceStream",
              "request": {"streamId": "vid-1", "contentType": "video/mjpeg"}})
        send({"deviceToken": "cam-1", "type": "DeviceStreamData",
              "request": {"streamId": "vid-1", "sequenceNumber": 0,
                          "data": base64.b64encode(b"frame-0").decode()}})
        send({"deviceToken": "cam-1", "type": "DeviceStreamData",
              "request": {"streamId": "vid-1", "sequenceNumber": 1,
                          "data": base64.b64encode(b"frame-1").decode()}})
        send({"deviceToken": "cam-1", "type": "SendDeviceStreamData",
              "request": {"streamId": "vid-1", "sequenceNumber": 1}})
        await asyncio.sleep(0.1)   # let the downlink tasks run

    asyncio.new_event_loop().run_until_complete(go())
    # stream stored
    assert inst.streams.read_all("vid-1") == b"frame-0frame-1"
    # downlink carried the ack and the requested chunk
    payloads = [_json.loads(p.decode()) for _, p, system in provider.delivered
                if system]
    kinds = [p["systemCommand"] for p in payloads]
    assert "DeviceStreamAck" in kinds and "DeviceStreamData" in kinds
    chunk = next(p for p in payloads if p["systemCommand"] == "DeviceStreamData")
    assert base64.b64decode(chunk["payload"]["data"]) == b"frame-1"
    assert chunk["payload"]["found"] is True


def test_stream_spill_to_disk_bounds_memory(tmp_path):
    """Streams larger than the memory budget spill oldest chunks to disk;
    content and random chunk access stay correct."""
    from sitewhere_tpu.management.streams import DeviceStreamManager

    mgr = DeviceStreamManager(memory_budget_bytes=256,
                              spill_dir=str(tmp_path))
    mgr.create_stream("big", "cam-9")
    blobs = [bytes([i]) * 64 for i in range(10)]   # 640 bytes total
    for i, b in enumerate(blobs):
        mgr.append_chunk("big", i, b)
    assert mgr.memory_resident_bytes("big") <= 256
    assert mgr.spilled_chunks("big") > 0
    assert mgr.read_all("big") == b"".join(blobs)
    assert mgr.get_chunk("big", 0) == blobs[0]      # spilled chunk
    assert mgr.get_chunk("big", 9) == blobs[9]      # memory chunk
    assert mgr.get_chunk("big", 42) is None
