"""Long-context attention stack: Pallas flash kernel (interpret mode) vs jnp
oracle, ring / Ulysses sequence parallelism on the 8-device CPU mesh, and the
sequence-parallel transformer matching its single-device forward.

SURVEY.md §4 plan (a)+(c): kernel-vs-oracle unit tests plus multi-chip
collectives under --xla_force_host_platform_device_count emulation.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

from sitewhere_tpu.ops.attention import flash_attention, mha_reference
from sitewhere_tpu.parallel.ring_attention import (
    ring_attention_sharded,
    ulysses_attention_sharded,
)
from sitewhere_tpu.models.transformer import (
    TransformerConfig,
    forecast_scores,
    forecast_scores_sp,
    init_params,
    loss_fn,
    make_train_step,
)

# Streaming-softmax f32 tolerance: the oracle itself sits ~3e-3 from a
# float64 softmax on N(0,1) inputs, so block-order differences of the same
# magnitude are expected.
TOL = dict(atol=2e-2, rtol=2e-2)


def _qkv(rng, b=2, s=256, h=4, d=32):
    return tuple(
        jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_oracle(rng, causal):
    q, k, v = _qkv(rng)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=64,
                          force_pallas=True)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, **TOL)


def test_flash_attention_lane_padding(rng):
    # D=32 pads to 128 lanes inside the kernel; result must be unchanged.
    q, k, v = _qkv(rng, s=64, h=2, d=32)
    out = flash_attention(q, k, v, block_q=32, block_k=32, force_pallas=True)
    np.testing.assert_allclose(out, mha_reference(q, k, v), **TOL)


def test_flash_attention_odd_block_fallback(rng):
    # S=96 is not divisible by the preferred 512 block; picker must find one.
    q, k, v = _qkv(rng, s=96, h=2, d=64)
    out = flash_attention(q, k, v, force_pallas=True)
    np.testing.assert_allclose(out, mha_reference(q, k, v), **TOL)


@pytest.fixture
def sp_mesh():
    return Mesh(np.array(jax.devices()[:8]), ("sp",))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_oracle(rng, sp_mesh, causal):
    q, k, v = _qkv(rng, s=256, h=8, d=32)
    out = ring_attention_sharded(q, k, v, sp_mesh, causal=causal)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), ref, **TOL)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_oracle(rng, sp_mesh, causal):
    q, k, v = _qkv(rng, s=128, h=8, d=32)   # H == mesh size
    out = ulysses_attention_sharded(q, k, v, sp_mesh, causal=causal)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), ref, **TOL)


def _small_cfg():
    return TransformerConfig(sensors=8, d_model=64, heads=4, layers=2,
                             mlp=128, dtype=jnp.float32)


def test_transformer_sp_scores_match_single_device(rng, sp_mesh):
    cfg = _small_cfg()
    params = init_params(jax.random.key(0), cfg)
    x = jnp.asarray(rng.standard_normal((2, 64, cfg.sensors)), jnp.float32)
    ref = forecast_scores(
        params, x, cfg, attention_fn=functools.partial(mha_reference, causal=True)
    )
    sp = forecast_scores_sp(params, x, cfg, sp_mesh)
    np.testing.assert_allclose(np.asarray(sp), np.asarray(ref), atol=5e-3,
                               rtol=5e-3)


def test_transformer_train_step_reduces_loss(rng):
    cfg = _small_cfg()
    params = init_params(jax.random.key(0), cfg)
    # learnable structure: a lagged sine across all channels
    t = np.arange(64)
    x = np.stack([np.sin(0.3 * t + p) for p in np.linspace(0, 1, 8)], axis=-1)
    x = jnp.asarray(np.stack([x, x * 0.5]), jnp.float32)   # [2, 64, 8]
    tx = optax.adam(3e-3)
    step = jax.jit(make_train_step(cfg, tx))
    opt_state = tx.init(params)
    first = float(loss_fn(params, x, cfg))
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state, x)
    assert float(loss) < first * 0.5, (first, float(loss))


def test_transformer_sp_grads_finite(rng, sp_mesh):
    """AD flows through the ring (fori_loop + ppermute) — grads are finite
    and match the single-device gradient direction. Depth AND width are
    trimmed purely for gradient-compile time on the virtual CPU mesh
    (~70s at the _small_cfg size): the differentiated ring is identical
    per layer and per head."""
    import dataclasses

    cfg = dataclasses.replace(_small_cfg(), layers=1, d_model=32, heads=2,
                              mlp=64)
    params = init_params(jax.random.key(0), cfg)
    x = jnp.asarray(rng.standard_normal((1, 32, cfg.sensors)), jnp.float32)

    def sp_loss(p):
        return jnp.mean(forecast_scores_sp(p, x, cfg, sp_mesh))

    def ref_loss(p):
        return jnp.mean(forecast_scores(
            p, x, cfg, attention_fn=functools.partial(mha_reference, causal=True)
        ))

    g_sp = jax.grad(sp_loss)(params)
    g_ref = jax.grad(ref_loss)(params)
    flat_sp = jnp.concatenate([jnp.ravel(l) for l in jax.tree_util.tree_leaves(g_sp)])
    flat_ref = jnp.concatenate([jnp.ravel(l) for l in jax.tree_util.tree_leaves(g_ref)])
    assert bool(jnp.all(jnp.isfinite(flat_sp)))
    cos = jnp.vdot(flat_sp, flat_ref) / (
        jnp.linalg.norm(flat_sp) * jnp.linalg.norm(flat_ref) + 1e-12
    )
    assert float(cos) > 0.99, float(cos)
