"""Versioned script management over REST + activation hot-swap.

VERDICT r2 item 5: expose ScriptManager over REST with script CRUD,
versions, content, clone, activate; persist versions to disk; prove
activate-then-decode-with-new-script. Matches the reference's Instance.java
scripting @Path family (/microservices/{id}/tenants/{token}/scripting/...).
"""

import asyncio
import base64

import pytest
from aiohttp.test_utils import TestClient, TestServer

from sitewhere_tpu.engine import EngineConfig
from sitewhere_tpu.instance.instance import InstanceConfig, SiteWhereTpuInstance
from sitewhere_tpu.web.rest import make_app

V1 = """
from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType

def decode(payload, metadata):
    return [DecodedRequest(type=RequestType.DEVICE_MEASUREMENT,
                           device_token=payload.decode(),
                           measurements={"script": 1.0})]
"""

V2 = V1.replace('"script": 1.0', '"script": 2.0')


@pytest.fixture
def inst(tmp_path):
    return SiteWhereTpuInstance(InstanceConfig(
        engine=EngineConfig(device_capacity=64, token_capacity=128,
                            assignment_capacity=128, store_capacity=1024,
                            channels=4, batch_capacity=16),
        script_root=str(tmp_path / "scripts")))


def run(inst, coro_factory):
    async def go():
        client = TestClient(TestServer(make_app(inst)))
        await client.start_server()
        try:
            basic = base64.b64encode(b"admin:password").decode()
            r = await client.get("/api/authapi/jwt",
                                 headers={"Authorization": f"Basic {basic}"})
            h = {"Authorization": f"Bearer {(await r.json())['token']}"}
            return await coro_factory(client, h)
        finally:
            await client.close()

    return asyncio.new_event_loop().run_until_complete(go())


def test_script_lifecycle_over_rest(inst):
    base = "/api/microservices/event-sources/tenants/default/scripting"

    async def flow(client, h):
        # create (v1 auto-activates)
        r = await client.post(f"{base}/scripts", json={
            "id": "my-decoder", "name": "My decoder",
            "category": "decoders", "content": V1}, headers=h)
        assert r.status == 201
        meta = await r.json()
        assert meta["activeVersion"] == "v1"
        # duplicate id -> 409
        r = await client.post(f"{base}/scripts",
                              json={"id": "my-decoder"}, headers=h)
        assert r.status == 409
        # listing + categories
        r = await client.get(f"{base}/scripts", headers=h)
        assert [s["id"] for s in await r.json()] == ["my-decoder"]
        r = await client.get(f"{base}/categories", headers=h)
        cats = await r.json()
        assert cats[0]["id"] == "decoders" and len(cats[0]["scripts"]) == 1
        r = await client.get(f"{base}/categories/decoders", headers=h)
        assert len(await r.json()) == 1
        r = await client.get(f"{base}/categories/ghost", headers=h)
        assert await r.json() == []
        # content
        r = await client.get(f"{base}/scripts/my-decoder/versions/v1/content",
                             headers=h)
        assert "script\": 1.0" in await r.text()
        # clone v1 -> v2, update v2's content
        r = await client.post(f"{base}/scripts/my-decoder/versions/v1/clone",
                              json={"comment": "tweak"}, headers=h)
        assert r.status == 201
        assert [v["versionId"] for v in (await r.json())["versions"]] == \
            ["v1", "v2"]
        r = await client.post(f"{base}/scripts/my-decoder/versions/v2",
                              json={"content": V2}, headers=h)
        assert r.status == 200
        # v2 exists but v1 is still active
        r = await client.get(f"{base}/scripts/my-decoder", headers=h)
        assert (await r.json())["activeVersion"] == "v1"
        # activate v2
        r = await client.post(
            f"{base}/scripts/my-decoder/versions/v2/activate",
            json={}, headers=h)
        assert (await r.json())["activeVersion"] == "v2"
        # unknown version -> 404
        r = await client.post(
            f"{base}/scripts/my-decoder/versions/v9/activate",
            json={}, headers=h)
        assert r.status == 404
        # delete
        r = await client.delete(f"{base}/scripts/my-decoder", headers=h)
        assert r.status == 200
        r = await client.get(f"{base}/scripts/my-decoder", headers=h)
        assert r.status == 404
        return True

    assert run(inst, flow)


def test_activate_then_decode_with_new_script(inst):
    """The acceptance flow: a scripted decoder bound to the store's
    active.py decodes with v1; activating v2 changes the very next decode
    (hot reload through ScriptManager, no restart)."""
    from sitewhere_tpu.ingest.decoders import ScriptedDecoder

    base = "/api/microservices/event-sources/tenants/default/scripting"

    async def flow(client, h):
        await client.post(f"{base}/scripts", json={
            "id": "hot-decoder", "content": V1}, headers=h)

        # bind a scripted decoder to the ACTIVE script path
        handle = inst.scripts.manager.handle(
            inst.scripts.active_path("event-sources", "default",
                                     "hot-decoder"), "decode")
        decoder = ScriptedDecoder(handle)
        reqs = decoder.decode(b"dev-hot", {})
        assert reqs[0].measurements == {"script": 1.0}

        # publish + activate v2; next decode must use it
        await client.post(f"{base}/scripts/hot-decoder/versions/v1/clone",
                          json={}, headers=h)
        await client.post(f"{base}/scripts/hot-decoder/versions/v2",
                          json={"content": V2}, headers=h)
        await client.post(f"{base}/scripts/hot-decoder/versions/v2/activate",
                          json={}, headers=h)
        reqs = decoder.decode(b"dev-hot", {})
        assert reqs[0].measurements == {"script": 2.0}

        # and the decoded request flows into the engine
        inst.engine.process(reqs[0])
        out = inst.engine.flush()
        assert out["persisted"] == 1
        return True

    assert run(inst, flow)


def test_script_templates_endpoints(inst):
    async def flow(client, h):
        r = await client.get(
            "/api/microservices/event-sources/scripting/categories",
            headers=h)
        cats = await r.json()
        assert r.status == 200 and cats[0]["id"] == "templates"
        assert "event-decoder" in cats[0]["templates"]
        r = await client.get(
            "/api/microservices/event-sources/scripting/templates"
            "/event-decoder", headers=h)
        assert r.status == 200 and "decode" in await r.text()
        r = await client.get(
            "/api/microservices/event-sources/scripting/templates/../etc",
            headers=h)
        assert r.status == 404
        return True

    assert run(inst, flow)
