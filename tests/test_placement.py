"""Zero-downtime elastic tenant placement (ISSUE 15).

Pins the placement contract: the genesis map is byte-identical to the
legacy ``owner_rank`` partitioner (adopting the plane re-routes
nothing), every ownership surface resolves through ONE installed epoch,
the epoch-fenced handoff moves a tenant range with zero acked loss and
no dual-ownership window, mid-flight spilled frames re-route on
redirect, chaos kills mid-handoff abort to a consistent single-owner
state (conservation ledger balanced), and join/drain run the same
protocol end to end."""

import dataclasses
import json
import pathlib
import time
import types

import pytest

from sitewhere_tpu.parallel.cluster import (ClusterConfig, ClusterEngine,
                                            build_cluster_rpc, owner_rank)
from sitewhere_tpu.parallel.forward import ForwardQueue, SpillRegistry
from sitewhere_tpu.parallel.placement import (REDIRECT_CODE, PlacementManager,
                                              PlacementMap, decide_balance,
                                              drain_rank, join_rank,
                                              move_slots)
from sitewhere_tpu.rpc.protocol import RpcError
from sitewhere_tpu.utils import faults
from sitewhere_tpu.utils.conservation import build_ledger, check_conservation
from tests.test_cluster import (BASE_S, _engine_cfg, _free_ports,
                                _ServerHost, meas)


# ---------------------------------------------------------------- fixtures

def _mk_placement_cluster(tmp_path, n_ranks=2, initial_ranks=None,
                          wal=True, forwarding=True, slots_per_rank=4,
                          retry_interval_s=0.1):
    """n provisioned ranks with live RPC; optional WAL + durable
    forwarding (the handoff tests need both: catch-up replays the WAL,
    redirects re-route through the spill queue)."""
    ports = _free_ports(n_ranks)
    peers = [f"127.0.0.1:{p}" for p in ports]
    host = _ServerHost()
    clusters, queues = [], []
    for r in range(n_ranks):
        cc = ClusterConfig(
            rank=r, n_ranks=n_ranks, peers=peers, secret="pl-secret",
            epoch_base_unix_s=BASE_S,
            engine=_engine_cfg(tmp_path if wal else None, r),
            connect_timeout_s=5.0, slots_per_rank=slots_per_rank,
            initial_ranks=initial_ranks)
        c = ClusterEngine(cc)
        if forwarding:
            q = ForwardQueue(c, tmp_path / f"fwd-r{r}",
                             retry_interval_s=retry_interval_s)
            reg = SpillRegistry(tmp_path / f"fwd-r{r}" / "registry")
            c.attach_forwarding(q, reg)
            queues.append(q)
        host.start(build_cluster_rpc(c.local, "pl-secret"), ports[r])
        clusters.append(c)
    return clusters, queues, host


def _close(clusters, host):
    faults.clear()
    for c in clusters:
        c.close()
    host.close()


def _token_in_slot_of(cluster, rank, n=1, prefix="plt"):
    """Tokens owned by ``rank`` that all hash into the SAME slot (the
    moving range of the handoff tests)."""
    pm = cluster.placement
    first, out, i = None, [], 0
    while len(out) < n:
        t = f"{prefix}-{i}"
        i += 1
        if pm.owner(t) != rank:
            continue
        s = pm.slot_of(t)
        if first is None:
            first = s
        if s == first:
            out.append(t)
    return first, out


def _assert_balanced(cluster, what=""):
    led = build_ledger(cluster)
    violations = check_conservation(led)
    assert not violations, (what, [v.to_dict() for v in violations])


# ------------------------------------------------------------- pure layer

def test_initial_map_matches_legacy_partitioner():
    """The genesis contract: slot-space routing + the default map is
    BYTE-identical to owner_rank(token, n_ranks) — adopting the
    placement plane re-routes nothing on an existing cluster."""
    for n_ranks in (1, 2, 3, 5, 8):
        m = PlacementMap.initial(n_ranks)
        for i in range(256):
            t = f"dev-{i}-{n_ranks}"
            assert m.owner(t) == owner_rank(t, n_ranks), (t, n_ranks)


def test_map_moves_epoch_roundtrip_and_validation():
    m = PlacementMap.initial(2, slots_per_rank=4)
    assert m.epoch == 1 and m.n_slots == 8
    m2 = m.with_moves({0: 1, 5: 0})
    assert m2.epoch == 2
    assert m2.assignment[0] == 1 and m2.assignment[5] == 0
    assert m.assignment[0] == 0        # immutable
    rt = PlacementMap.from_dict(m2.to_dict())
    assert rt == m2
    with pytest.raises(ValueError):
        m.with_moves({99: 0})
    bad = m2.to_dict()
    bad["assignment"] = bad["assignment"][:-1]
    with pytest.raises(ValueError):
        PlacementMap.from_dict(bad)
    # a narrowed genesis (join-later ranks) covers only the active set
    m3 = PlacementMap.initial(3, slots_per_rank=2, active_ranks=[0, 1])
    assert m3.active_ranks() == [0, 1]
    with pytest.raises(ValueError):
        PlacementMap.initial(3, active_ranks=[0, 7])


def test_manager_epoch_fencing_and_persistence(tmp_path):
    """A manager never adopts a lower epoch, refuses a divergent
    same-epoch assignment (split-brain commit), persists installs, and
    reloads the highest persisted epoch at construction."""
    stub = types.SimpleNamespace(rank=0, n_ranks=2,
                                 local=types.SimpleNamespace())
    pm = PlacementManager(stub, PlacementMap.initial(2, 4),
                          directory=tmp_path / "pl")
    m2 = pm.map().with_moves({0: 1})
    assert pm.install(m2.to_dict())
    assert pm.epoch == 2 and pm.ever_moved
    # lower epoch refused, same-epoch idempotent, divergent loud
    assert not pm.install(PlacementMap.initial(2, 4).to_dict())
    assert pm.install(m2.to_dict())
    divergent = dataclasses.replace(
        pm.map(), assignment=tuple(
            1 - r for r in pm.map().assignment))
    assert not pm.install(divergent.to_dict())
    assert pm.epoch == 2
    # a fresh manager on the same dir resumes from the persisted epoch
    pm2 = PlacementManager(stub, PlacementMap.initial(2, 4),
                           directory=tmp_path / "pl")
    assert pm2.epoch == 2 and pm2.map() == pm.map()
    # the cached hot-path views reload with it (a stale routing table
    # would silently misroute every batch after a restart)
    assert pm2.slot_routing() == list(pm.map().assignment)
    # the slot space is fixed at genesis
    with pytest.raises(ValueError):
        pm.install(PlacementMap.initial(2, 8).with_moves({0: 1})
                   .with_moves({1: 1}).to_dict())


def test_fault_partition_and_delay_jitter_are_deterministic():
    """Satellite: the new fault kinds. ``partition`` severs BOTH
    directions of a rank pair (``drop`` stays one-way); ``delay_jitter``
    draws its jitter from the plan's seeded stream, so the same seed
    sleeps the same sequence."""
    inj = faults.FaultInjector(faults.FaultPlan(seed=3).partition(0, 2))
    with pytest.raises(ConnectionError):
        inj.before_call(0, 2, "Cluster.flush")
    with pytest.raises(ConnectionError):
        inj.before_call(2, 0, "Cluster.flush")
    inj.before_call(0, 1, "Cluster.flush")      # other links live
    inj.before_call(1, 2, "Cluster.flush")
    assert inj.counters["partitioned"] == 2

    def jitter_seq(seed, n=6):
        inj = faults.FaultInjector(faults.FaultPlan(seed=seed)
                                   .delay_jitter(0, 1, base_s=0.0,
                                                 jitter_s=0.002))
        out = []
        for _ in range(n):
            t0 = time.perf_counter()
            inj.before_call(0, 1, "Cluster.queryEvents")
            out.append(inj.counters["jitter_delayed"])
        return inj.counters["jitter_delayed"], out

    assert jitter_seq(11) == jitter_seq(11)
    # the draw sequence is the plan RNG's: two injectors with the same
    # seed burn identical streams (replayability)
    a = faults.FaultInjector(faults.FaultPlan(seed=5)
                             .delay_jitter(jitter_s=0.0))
    b = faults.FaultInjector(faults.FaultPlan(seed=5)
                             .delay_jitter(jitter_s=0.0))
    assert [a._draw() for _ in range(8)] == [b._draw() for _ in range(8)]


def test_decide_balance_policy():
    """The pure half of hot-tenant steering: breach -> peel the hot
    slot onto the lightest active rank; no breach, lightest-already, or
    last-slot cases propose nothing."""
    m = PlacementMap.initial(2, slots_per_rank=2)     # slots 0..3
    moves = decide_balance(
        tenant_p99_ms={"hot": 900.0, "cool": 20.0},
        tenant_rank={"hot": 0, "cool": 1},
        tenant_slots={"hot": [0, 2], "cool": [1]},
        pmap=m.with_moves({1: 0}),    # rank 0 holds 3 slots, rank 1 one
        p99_target_ms=250.0)
    assert moves == [(0, 1)]
    # nothing breaches -> no proposal
    assert decide_balance({"hot": 100.0}, {"hot": 0}, {"hot": [0]},
                          m, 250.0) == []
    # hot rank already lightest -> no proposal
    assert decide_balance({"hot": 900.0}, {"hot": 1}, {"hot": [1]},
                          m.with_moves({3: 0}), 250.0) == []


def test_conservation_placement_equation_is_falsifiable():
    """The new ledger equation: started == completed + aborted +
    in-flight, and a fence with no live move is a violation. Perturbing
    any term by one must produce a Violation (the PR-13 discipline)."""
    ledger = {"stages": {"placement": {
        "epoch": 3, "moves_started": 4, "moves_completed": 2,
        "moves_aborted": 1, "moves_in_flight": 1, "fenced_slots": 0,
        "fenced_write_redirects": 7, "stale_sender_redirects": 2}}}
    assert not check_conservation(ledger)
    bad = json.loads(json.dumps(ledger))
    bad["stages"]["placement"]["moves_started"] += 1
    vs = check_conservation(bad)
    assert [v.equation for v in vs] == ["placement-handoff"]
    bad2 = json.loads(json.dumps(ledger))
    bad2["stages"]["placement"]["fenced_slots"] = 2
    bad2["stages"]["placement"]["moves_in_flight"] = 0
    bad2["stages"]["placement"]["moves_completed"] = 3
    assert [v.equation for v in check_conservation(bad2)] == \
        ["placement-handoff"]


def test_no_runtime_surface_bypasses_the_placement_map():
    """Satellite pin: no ownership surface reads owner_rank(token,
    n_ranks) directly anymore — replication (fire-over), entity sync
    (schedule fire filter), and the cluster facade all resolve through
    the installed map. Source-level assert on the modules that used
    to."""
    root = pathlib.Path(__file__).resolve().parent.parent
    for mod in ("sitewhere_tpu/parallel/replication.py",
                "sitewhere_tpu/parallel/entity_sync.py",
                "sitewhere_tpu/parallel/forward.py"):
        src = (root / mod).read_text()
        assert "owner_rank(" not in src, f"{mod} bypasses the map"
    # cluster.py keeps the hash PRIMITIVE (owner_rank definition) but
    # its facade surface must resolve through the manager
    csrc = (root / "sitewhere_tpu/parallel/cluster.py").read_text()
    assert "return self.placement.owner(token)" in csrc
    assert "owner_rank(token, self.n_ranks)" not in csrc


# ----------------------------------------------------- one-epoch property

def test_every_surface_resolves_through_the_same_epoch(tmp_path):
    """THE versioning property (satellite): after a map with a moved
    slot installs, the facade owner(), the ingest partitioner, the
    scheduler fire filter, the data fan-out set, and the owner-side
    guard ALL answer from the same epoch — no surface left reading the
    static hash."""
    from sitewhere_tpu.parallel.replication import install_fireover

    clusters, _qs, host = _mk_placement_cluster(tmp_path, wal=False,
                                                forwarding=False)
    c0, c1 = clusters
    try:
        slot, (tok,) = _token_in_slot_of(c0, rank=0)
        assert c0.owner(tok) == 0 == c1.owner(tok)
        newmap = c0.placement.map().with_moves({slot: 1})
        for c in clusters:
            assert c.placement.install(newmap.to_dict())
        # 1) facade owner
        assert c0.owner(tok) == 1 == c1.owner(tok)
        # 2) ingest partitioner (native + fallback both resolve slots
        #    through the same installed assignment)
        by_rank = c0._partition_payloads([meas(tok, "t", 1.0, 10)],
                                         kind="json")
        assert list(by_rank) == [1]
        # 3) scheduler fire filter (fire-over wiring)
        sched0 = types.SimpleNamespace(fire_filter=None,
                                       catchup_filter=None)
        sched1 = types.SimpleNamespace(fire_filter=None,
                                       catchup_filter=None)
        install_fireover(sched0, c0)
        install_fireover(sched1, c1)
        assert not sched0.fire_filter(tok)
        assert sched1.fire_filter(tok)
        # 4) the data fan-out set tracks the assignment
        assert c0._data_ranks() == [0, 1]
        # 5) owner-side guard: the OLD owner redirects a stale direct
        #    send with a typed 473 carrying its (newer) map
        with pytest.raises(RpcError) as ei:
            c1._peer(0).call("Cluster.ingestJson",
                             lens=[len(meas(tok, "t", 2.0, 11))],
                             tenant="default",
                             _attachment=meas(tok, "t", 2.0, 11))
        assert ei.value.code == REDIRECT_CODE
        assert ei.value.data["map"]["epoch"] == newmap.epoch
        # 6) single-request process guard on the old owner
        with pytest.raises(RpcError) as ei2:
            c1._peer(0).call(
                "Cluster.processEnvelope",
                envelope={"deviceToken": tok,
                          "type": "DeviceMeasurements",
                          "request": {"measurements": {"t": 3.0}}},
                tenant="default")
        assert ei2.value.code == REDIRECT_CODE
        assert c0.placement.counters["stale_sender_redirects"] >= 2
        # 7) the posture surfaces (satellite): rank-labeled counters on
        #    the federated scrape + the debug-bundle placement section
        fed = c0.cluster_metrics()
        assert "swtpu_placement_epoch" in fed
        assert 'swtpu_placement_epoch{rank="1"}' in fed
        from sitewhere_tpu.utils.tracing import debug_bundle

        bundle = debug_bundle(c0.local)
        assert bundle["placement"]["map"]["epoch"] == newmap.epoch
        assert bundle["placement"]["counters"][
            "stale_sender_redirects"] >= 2
        # 8) the REST/RPC twin payload answers from the same epoch
        assert c0.placement.payload()["map"]["epoch"] == newmap.epoch
    finally:
        _close(clusters, host)


# ------------------------------------------------------------ live handoff

def test_live_handoff_moves_range_with_zero_acked_loss(tmp_path):
    """THE tentpole done-criterion at test scale: a tenant range (one
    slot) moves rank 0 -> rank 1 under the full protocol. Every acked
    event stays visible exactly once from BOTH facades, post-move
    ingest lands at the new owner, a stale spilled frame re-routes
    mid-flight, and the conservation ledger balances on every rank."""
    clusters, queues, host = _mk_placement_cluster(tmp_path)
    c0, c1 = clusters
    try:
        slot, toks = _token_in_slot_of(c0, rank=0, n=2)
        other = next(t for t in (f"oth-{i}" for i in range(64))
                     if c0.owner(t) == 0
                     and c0.placement.slot_of(t) != slot)
        sent = 0
        for rnd in range(3):
            c0.ingest_json_batch(
                [meas(t, "temp", rnd + i, 100 * rnd + i)
                 for i, t in enumerate(toks)]
                + [meas(other, "temp", rnd, 100 * rnd + 7)])
            sent += 1
        c0.flush()

        stats = move_slots(c0, [slot], 1)
        assert [m["state"] for m in stats["moves"]] == ["done"]
        assert stats["epoch_after"] == 2
        assert c0.placement.epoch == c1.placement.epoch == 2
        assert c0.owner(toks[0]) == 1
        # shipped history: every batch's fid recorded at the target
        assert stats["moves"][0]["shippedPayloads"] == sent * len(toks)

        # zero acked loss, exactly-once reads, from BOTH facades; the
        # un-moved token stays untouched at rank 0
        c0.flush()
        for c in clusters:
            for t in toks:
                assert c.query_events(device_token=t)["total"] == sent, \
                    (c.rank, t)
            assert c.query_events(device_token=other)["total"] == sent
        # the new owner's LOCAL engine serves the range now; the old
        # owner's local copy is dead (filtered) but its engine is not
        assert c1.local.query_events(device_token=toks[0])["total"] \
            == sent

        # post-move ingest routes to the new owner
        c0.ingest_json_batch([meas(toks[0], "temp", 99.0, 999)])
        c0.flush()
        assert c0.query_events(device_token=toks[0])["total"] == sent + 1
        assert c1.local.query_events(
            device_token=toks[0])["total"] == sent + 1

        # mid-flight re-route: a stale frame spilled toward the OLD
        # owner redirects (473 + map) and the pump re-spills it to the
        # new owner — delivered, never lost, never dual-applied
        stale = meas(toks[1], "temp", 123.0, 1234)
        queues[0].spill(0, "json", "default", "stale-fid-1",
                        payloads=[stale])
        for _ in range(8):
            queues[0].retry_once()
            if not queues[0].metrics()["forward_queue_depth"]:
                break
            time.sleep(0.05)
        m = queues[0].metrics()
        assert m["forward_queue_depth"] == 0
        assert m["forward_retry_redirects"] >= 1
        assert m["forward_rerouted_batches"] == 1
        c0.flush()
        assert c0.query_events(device_token=toks[1])["total"] == sent + 1

        # conservation: every rank's ledger balances across the
        # migration (the re-route slack term included), and the move
        # accounting closes
        for c in clusters:
            _assert_balanced(c, f"rank {c.rank}")
        st = c0.placement.ledger_stage()
        assert st["moves_started"] == st["moves_completed"] == 1
        assert st["moves_in_flight"] == 0 and st["fenced_slots"] == 0
    finally:
        _close(clusters, host)


def test_chaos_kill_mid_handoff_aborts_to_single_owner(tmp_path):
    """Chaos gate (test scale): the TARGET dies mid-catch-up -> the
    move aborts with ownership unchanged and the ledger balanced; after
    the revive the SAME slots move cleanly. Then the SOURCE dies
    mid-handoff coordinated from the other rank -> abort, unchanged,
    balanced."""
    clusters, _qs, host = _mk_placement_cluster(tmp_path)
    c0, c1 = clusters
    try:
        slot, toks = _token_in_slot_of(c0, rank=0, n=2)
        c0.ingest_json_batch([meas(t, "t", 1.0, i)
                              for i, t in enumerate(toks)])
        c0.flush()

        # ---- kill the TARGET mid-handoff -----------------------------
        faults.install(faults.FaultPlan(seed=7).kill(1))
        stats = move_slots(c0, [slot], 1)
        faults.clear()
        assert [m["state"] for m in stats["moves"]] == ["aborted"]
        assert c0.placement.epoch == 1          # commit never happened
        assert c0.owner(toks[0]) == 0           # single owner: source
        st = c0.placement.ledger_stage()
        assert st["moves_aborted"] == 1 and st["moves_in_flight"] == 0
        assert st["fenced_slots"] == 0          # nothing left fenced
        _assert_balanced(c0, "post-abort source")
        # writes still land at the source — no fence leaked
        c0.ingest_json_batch([meas(toks[0], "t", 2.0, 50)])
        c0.flush()
        assert c0.query_events(device_token=toks[0])["total"] == 2

        # ---- revive: the same range now moves cleanly ----------------
        stats2 = move_slots(c0, [slot], 1)
        assert [m["state"] for m in stats2["moves"]] == ["done"]
        assert c0.owner(toks[0]) == 1
        c0.flush()
        for c in clusters:
            assert c.query_events(device_token=toks[0])["total"] == 2
            _assert_balanced(c, f"post-move rank {c.rank}")

        # ---- kill the SOURCE mid-handoff (coordinator = rank 1) ------
        slot1, toks1 = _token_in_slot_of(c1, rank=0, n=1,
                                         prefix="src")
        faults.install(faults.FaultPlan(seed=9).kill(0))
        stats3 = move_slots(c1, [slot1], 1)
        faults.clear()
        assert [m["state"] for m in stats3["moves"]] == ["aborted"]
        assert c1.placement.epoch == 2          # unchanged by the abort
        assert c1.owner(toks1[0]) == 0
        _assert_balanced(c1, "post-abort coordinator")
    finally:
        _close(clusters, host)


def test_join_and_drain_under_the_same_protocol(tmp_path):
    """Elasticity end to end: a provisioned-but-inactive rank JOINS
    (bootstraps by handoff replay, takes over ranges at commit epochs)
    and an active rank DRAINS (hands off every slot, leaves the data
    fan-out set) — all acked events visible exactly once afterwards,
    ledgers balanced on every surviving rank."""
    clusters, _qs, host = _mk_placement_cluster(
        tmp_path, n_ranks=3, initial_ranks=[0, 1], slots_per_rank=2)
    c0, c1, c2 = clusters
    try:
        assert c0.placement.map().active_ranks() == [0, 1]
        assert c0._data_ranks() == [0, 1]
        toks = []
        for i in range(24):
            t = f"el-{i}"
            if len(toks) < 8:
                toks.append(t)
        c0.ingest_json_batch([meas(t, "t", float(i), i)
                              for i, t in enumerate(toks)])
        c0.flush()

        # ---- JOIN rank 2 ---------------------------------------------
        res = join_rank(c0, 2)
        assert res["joined"], res
        m = c0.placement.map()
        assert 2 in m.active_ranks()
        assert len(m.slots_of(2)) >= 1
        assert c0._data_ranks() == [0, 1, 2]
        # the joiner answers for its ranges; totals hold everywhere
        c0.flush()
        for c in clusters:
            for t in toks:
                assert c.query_events(device_token=t)["total"] == 1, \
                    (c.rank, t)

        # ---- DRAIN rank 1 --------------------------------------------
        res2 = drain_rank(c0, 1)
        assert res2["drained"], res2
        m2 = c0.placement.map()
        assert 1 not in m2.active_ranks()
        assert not m2.slots_of(1)
        assert c0._data_ranks() == [0, 2]
        c0.flush()
        for c in (c0, c2):
            for t in toks:
                assert c.query_events(device_token=t)["total"] == 1, \
                    (c.rank, t)
            _assert_balanced(c, f"post-drain rank {c.rank}")
        # ingest for a token the drained rank used to own lands at its
        # new owner without touching rank 1's engine
        moved = next(t for t in toks if owner_rank(t, 3) == 1
                     or c0.owner(t) != 1)
        before = c1.local.query_events(limit=1)["total"]
        c0.ingest_json_batch([meas(moved, "t", 9.0, 900)])
        c0.flush()
        assert c1.local.query_events(limit=1)["total"] == before
        # placement posture surfaces the journey
        pay = c0.placement.payload()
        assert pay["map"]["epoch"] == c2.placement.epoch
        assert str(1) not in pay["slots"]
    finally:
        _close(clusters, host)


def test_returning_range_never_dual_applies(tmp_path):
    """The ping-pong pin (found by the bench leg): a range moving
    A -> B -> A must NOT dual-count at A — A's dead rows from its first
    ownership era come back live with the slot, so the return handoff's
    replay must re-ingest ONLY what A does not already hold
    (handoff_prepare's content filter). Exact totals from both facades
    after every era, ledgers balanced."""
    clusters, _qs, host = _mk_placement_cluster(tmp_path)
    c0, c1 = clusters
    try:
        slot, toks = _token_in_slot_of(c0, rank=0, n=2)
        sent = 0
        c0.ingest_json_batch([meas(t, "t", 1.0 + i, i)
                              for i, t in enumerate(toks)])
        sent += 1
        c0.flush()

        # era 2: 0 -> 1, new traffic lands at rank 1
        assert [m["state"] for m in move_slots(c0, [slot], 1)["moves"]] \
            == ["done"]
        c0.ingest_json_batch([meas(t, "t", 2.0 + i, 100 + i)
                              for i, t in enumerate(toks)])
        sent += 1
        c0.flush()
        for c in clusters:
            for t in toks:
                assert c.query_events(device_token=t)["total"] == sent

        # era 3: 1 -> 0 (the RETURN): rank 0 already holds era 1
        assert [m["state"] for m in move_slots(c0, [slot], 0)["moves"]] \
            == ["done"]
        c0.ingest_json_batch([meas(t, "t", 3.0 + i, 200 + i)
                              for i, t in enumerate(toks)])
        sent += 1
        c0.flush()
        for c in clusters:
            for t in toks:
                assert c.query_events(device_token=t)["total"] == sent, \
                    (c.rank, t)
            _assert_balanced(c, f"rank {c.rank}")
        # and once more for good measure: 0 -> 1 again
        assert [m["state"] for m in move_slots(c0, [slot], 1)["moves"]] \
            == ["done"]
        c0.flush()
        for c in clusters:
            for t in toks:
                assert c.query_events(device_token=t)["total"] == sent
    finally:
        _close(clusters, host)


def test_commit_install_closes_move_and_finish_never_resurrects():
    """Review pins: (a) the commit INSTALL itself completes the source's
    move (a lost handoffFinish leaves no phantom in-flight handoff —
    install already dropped the fences, so no deadline would ever have
    fired); (b) handoffFinish after an ABORT must not resurrect the
    move — one move can never count in both completed and aborted."""
    stub = types.SimpleNamespace(rank=0, n_ranks=2,
                                 local=types.SimpleNamespace())
    pm = PlacementManager(stub, PlacementMap.initial(2, 4))
    from sitewhere_tpu.parallel.placement import _Move

    # (a) fenced move; the commit map lands; finish is then a no-op
    mv = _Move("m1", (0,), 1, state="fenced")
    with pm._lock:
        pm._moves["m1"] = mv
        pm._fences[0] = (1, "m1", time.monotonic() + 20)
        pm.has_fences = True
    assert pm.install(pm.map().with_moves({0: 1}).to_dict())
    assert mv.state == "done"
    assert pm.counters["moves_completed"] == 1
    assert not pm.fenced_slots() and not pm.has_fences
    pm.handoff_finish("m1")
    assert pm.counters["moves_completed"] == 1      # no double count
    st = pm.ledger_stage()
    assert st["moves_in_flight"] == 0
    assert not check_conservation({"stages": {"placement": st
                                              | {"moves_started": 1}}})

    # (b) an aborted move stays aborted through finish AND abort
    mv2 = _Move("m2", (1,), 1, state="aborted")
    with pm._lock:
        pm._moves["m2"] = mv2
        pm.counters["moves_started"] += 1
        pm.counters["moves_aborted"] += 1
    assert pm.handoff_finish("m2")["state"] == "aborted"
    assert pm.handoff_abort("m2")["state"] == "aborted"
    assert pm.counters["moves_completed"] == 1
    assert pm.counters["moves_aborted"] == 1


def test_fence_expiry_mid_ship_refuses_to_commit():
    """Review pin (the acked-loss hole): if the fences expire while the
    fence round is still shipping/verifying, handoff_fence must REFUSE
    (the coordinator aborts) — committing after writes may have resumed
    at the source would orphan them behind the read filter."""
    stub = types.SimpleNamespace(rank=0, n_ranks=2,
                                 local=types.SimpleNamespace(wal=None,
                                                             lock=None))
    pm = PlacementManager(stub, PlacementMap.initial(2, 4),
                          fence_timeout_s=20.0)
    from sitewhere_tpu.parallel.placement import _Move

    mv = _Move("mx", (0,), 1, state="fenced")
    with pm._lock:
        pm._moves["mx"] = mv
        # the fence ALREADY expired (ship outlasted the deadline) and a
        # concurrent scrape collected it
        pm._fences.pop(0, None)
        pm.has_fences = False
    with pm._lock:
        live = all(pm._fences.get(s, (None, None, 0.0))[1] == "mx"
                   for s in mv.slots)
    assert not live   # the condition handoff_fence's re-check enforces


def test_replay_wal_tails_accepts_generator_args(tmp_path):
    """Review pin: the up-front validation must not exhaust generator
    arguments (a silently-empty zip would drop every tail — the exact
    failure the validation exists to prevent)."""
    from sitewhere_tpu.engine import WAL_JSON
    from sitewhere_tpu.parallel.cluster_reshard import replay_wal_tails
    from sitewhere_tpu.utils.ingestlog import IngestLog

    snap = tmp_path / "snap"
    snap.mkdir()
    (snap / "host_distributed.json").write_text(
        json.dumps({"store_cursor": 0}))
    wal_dir = tmp_path / "wal"
    wal = IngestLog(wal_dir)
    for _ in range(3):
        wal.append(WAL_JSON + b"default\x00" + b'{"deviceToken":"g"}')
    wal.flush()
    wal.close()

    calls = []
    probe = types.SimpleNamespace(
        ingest_json_batch=lambda p, tenant="default":
            calls.append(len(p)) or {},
        ingest_binary_batch=lambda p, tenant="default": {},
        flush=lambda: {})
    n = replay_wal_tails(probe, (d for d in [snap]),
                         (d for d in [wal_dir]))
    assert n == 3 and sum(calls) == 3
