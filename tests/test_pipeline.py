"""End-to-end pipeline tests against the numpy oracle (tests/oracle.py).

Covers the full fused step: lookup, auto-registration, assignment expansion,
ring-store persistence, and windowed state merge — including correctness
across arbitrary batch boundaries (a split stream must produce the same state
as a single batch, since the reference's 5s windows don't align with our
batch boundaries either).
"""

import jax.numpy as jnp
import numpy as np

from sitewhere_tpu.core.events import HostEventBuffer
from sitewhere_tpu.core.state import RECENT_DEPTH
from sitewhere_tpu.core.types import NULL_ID, EventType
from sitewhere_tpu.pipeline import PipelineConfig, PipelineState, make_pipeline_step

from tests.oracle import OracleEngine

CHANNELS = 4


def _random_events(rng, n, n_tokens=12, n_tenants=1, types=(0, 0, 0, 1, 2)):
    events = []
    for i in range(n):
        et = int(rng.choice(types))
        ev = {
            "token": int(rng.integers(0, n_tokens)),
            "tenant": int(rng.integers(0, n_tenants)),
            "etype": et,
            "ts": int(rng.integers(0, 50)),  # few distinct ts -> many ties
            "seq": i,
        }
        if et == EventType.MEASUREMENT:
            chans = rng.choice(CHANNELS, size=int(rng.integers(1, CHANNELS + 1)), replace=False)
            ev["values"] = {int(c): float(np.round(rng.random(), 3)) for c in chans}
        elif et == EventType.LOCATION:
            ev["loc"] = tuple(float(np.round(x, 3)) for x in rng.random(3))
        elif et == EventType.ALERT:
            ev["level"] = int(rng.integers(0, 4))
            ev["atype"] = int(rng.integers(0, 5))
        events.append(ev)
    return events


def _feed(step, state, events, capacity):
    """Push events through the pipeline in batches of ``capacity``."""
    outs = []
    for lo in range(0, len(events), capacity):
        buf = HostEventBuffer(capacity, CHANNELS)
        for ev in events[lo:lo + capacity]:
            vals = np.zeros(CHANNELS, np.float32)
            mask_ch = []
            if ev["etype"] == EventType.MEASUREMENT:
                for c, v in ev["values"].items():
                    vals[c] = v
                    mask_ch.append(c)
            elif ev["etype"] == EventType.LOCATION:
                vals[:3] = ev["loc"]
                mask_ch = [0, 1, 2]
            elif ev["etype"] == EventType.ALERT:
                vals[0] = ev["level"]
                mask_ch = [0]
            buf.append(
                etype=ev["etype"], token_id=ev["token"], tenant_id=ev["tenant"],
                ts_ms=ev["ts"], received_ms=ev["ts"],
                aux0=ev.get("atype", NULL_ID),
            )
            # HostEventBuffer.append sets a prefix mask; patch per-channel mask
            i = len(buf) - 1
            buf.values[i] = vals
            buf.vmask[i] = False
            buf.vmask[i, mask_ch] = True
        batch = buf.emit()
        state, out = step(state, batch)
        outs.append(out)
    return state, outs


def _make_state():
    return PipelineState.create(
        device_capacity=32, token_capacity=64, assignment_capacity=64,
        store_capacity=1024, channels=CHANNELS,
    )


def _check_against_oracle(state, oracle):
    """Compare kernel state against oracle state for every registered device."""
    ds = state.device_state
    for tok, dev in oracle.token_to_device.items():
        st = oracle.states[dev]
        kdev = int(state.registry.token_to_device[tok])
        assert kdev == dev, f"token {tok}: device id {kdev} != oracle {dev}"
        if st.last_interaction is not None:
            assert int(ds.last_interaction_ms[dev]) == st.last_interaction
        # measurements: latest per channel
        for ch, (ts, _seq, val) in st.meas_last.items():
            assert int(ds.meas_last_ms[dev, ch]) == ts
            np.testing.assert_allclose(float(ds.meas_last[dev, ch]), val, rtol=1e-6)
        # recent rings: compare (ts, payload) most-recent-first
        got_n = int(ds.recent_meas_valid[dev].sum())
        assert got_n == len(st.recent_meas)
        for r, (ts, _seq, values) in enumerate(st.recent_meas):
            assert int(ds.recent_meas_ms[dev, r]) == ts
            for c in range(CHANNELS):
                if c in values:
                    assert bool(ds.recent_meas_mask[dev, r, c])
                    np.testing.assert_allclose(float(ds.recent_meas[dev, r, c]), values[c], rtol=1e-6)
                else:
                    assert not bool(ds.recent_meas_mask[dev, r, c])
        got_n = int(ds.recent_loc_valid[dev].sum())
        assert got_n == len(st.recent_loc)
        for r, (ts, _seq, loc) in enumerate(st.recent_loc):
            assert int(ds.recent_loc_ms[dev, r]) == ts
            np.testing.assert_allclose(np.asarray(ds.recent_loc[dev, r]), loc, rtol=1e-6)
        got_n = int(ds.recent_alert_valid[dev].sum())
        assert got_n == len(st.recent_alert)
        for r, (ts, _seq, level, atype) in enumerate(st.recent_alert):
            assert int(ds.recent_alert_ms[dev, r]) == ts
            assert int(ds.recent_alert_level[dev, r]) == level
            assert int(ds.recent_alert_type[dev, r]) == atype
        for et, cnt in st.counts.items():
            assert int(ds.event_counts[dev, et]) == cnt


def test_pipeline_matches_oracle_single_batch(rng):
    events = _random_events(rng, 64)
    step = make_pipeline_step(PipelineConfig(auto_register=True))
    state, _ = _feed(step, _make_state(), events, capacity=64)
    oracle = OracleEngine()
    oracle.process(events)
    _check_against_oracle(state, oracle)


def test_pipeline_batch_split_invariance(rng):
    """Splitting the stream across batches must not change final state."""
    events = _random_events(rng, 96)
    oracle = OracleEngine()
    oracle.process(events)
    for cap in (96, 32, 16, 7):
        step = make_pipeline_step(PipelineConfig(auto_register=True))
        state, _ = _feed(step, _make_state(), events, capacity=cap)
        _check_against_oracle(state, oracle)


def test_pipeline_persistence_counts(rng):
    events = _random_events(rng, 50)
    step = make_pipeline_step(PipelineConfig(auto_register=True))
    state, outs = _feed(step, _make_state(), events, capacity=25)
    oracle = OracleEngine()
    oracle.process(events)
    total = sum(int(o.n_persisted) for o in outs)
    assert total == len(oracle.persisted)
    assert int(state.metrics.persisted) == total
    assert int(state.metrics.processed) == len(events)
    # every persisted row is in the ring (capacity not exceeded here)
    store = state.store
    assert int(store.valid.sum()) == total


def test_pipeline_no_autoregister_dead_letters(rng):
    events = _random_events(rng, 40)
    step = make_pipeline_step(PipelineConfig(auto_register=False))
    state, outs = _feed(step, _make_state(), events, capacity=40)
    # nothing registered -> every event dead-letters
    assert int(state.metrics.found) == 0
    assert int(state.metrics.missed) == len(events)
    dead = [int(t) for o in outs for t in np.asarray(o.dead_tokens) if t != NULL_ID]
    assert len(dead) == len(events)


def test_pipeline_tenant_isolation(rng):
    """A device registered under tenant 0 must not accept tenant-1 events
    under the same token (the reference's per-tenant pipeline isolation)."""
    events = [
        {"token": 1, "tenant": 0, "etype": 0, "ts": 1, "seq": 0, "values": {0: 1.0}},
        {"token": 1, "tenant": 1, "etype": 0, "ts": 2, "seq": 1, "values": {0: 2.0}},
    ]
    step = make_pipeline_step(PipelineConfig(auto_register=True))
    state, outs = _feed(step, _make_state(), events, capacity=2)
    oracle = OracleEngine()
    oracle.process(events)
    _check_against_oracle(state, oracle)
    # second event is a tenant mismatch -> miss, and must NOT update state
    dev = int(state.registry.token_to_device[1])
    assert float(state.device_state.meas_last[dev, 0]) == 1.0


def test_store_ring_wraps(rng):
    events = _random_events(rng, 160, n_tokens=4)
    state = PipelineState.create(
        device_capacity=16, token_capacity=16, assignment_capacity=16,
        store_capacity=64, channels=CHANNELS,
    )
    step = make_pipeline_step(PipelineConfig(auto_register=True))
    state, outs = _feed(step, state, events, capacity=16)
    store = state.store
    assert int(store.valid.sum()) == 64  # full ring after wrap
    total = sum(int(o.n_persisted) for o in outs)
    assert total > 64  # actually wrapped
    assert (int(store.epoch[0]) * 64 + int(store.cursor[0])) == total


def test_store_rejects_oversized_batch():
    """A batch whose expansion exceeds the whole ring is a static config
    error (slot aliasing within one scatter would be order-undefined)."""
    import pytest

    state = PipelineState.create(
        device_capacity=16, token_capacity=16, assignment_capacity=16,
        store_capacity=32, channels=CHANNELS,
    )
    step = make_pipeline_step(PipelineConfig(auto_register=True))
    buf = HostEventBuffer(16, CHANNELS)  # expands to 64 rows > 32 capacity
    buf.append(0, 0, 0, 1, 1, values=[1.0])
    with pytest.raises(ValueError, match="exceeds per-arena event-store"):
        step(state, buf.emit())


def test_out_of_range_tokens_dead_letter():
    """Garbage token ids (negative / beyond capacity) must miss and
    dead-letter, never alias into clipped registry slots."""
    import dataclasses

    from sitewhere_tpu.core.events import EventBatch

    b = EventBatch.zeros(6, CHANNELS)
    b = dataclasses.replace(
        b,
        valid=jnp.ones(6, bool),
        token_id=jnp.asarray([-5, 999999, 0, 1, 64, 2**30], jnp.int32),
        tenant_id=jnp.zeros(6, jnp.int32),
    )
    step = make_pipeline_step(PipelineConfig(auto_register=True))
    state, out = step(_make_state(), b)
    assert int(out.n_registered) == 2  # tokens 0 and 1 only (capacity 64)
    assert int(out.n_missed) == 4
    dead = sorted(int(t) for t in np.asarray(out.dead_tokens) if t != NULL_ID)
    assert dead == sorted([-5, 999999, 64, 2**30])


def test_pack_unpack_roundtrip():
    """pack_batches/unpack_batch must be an exact bit-level inverse (the
    packed single-transfer path feeds the same pipeline as per-field
    batches)."""
    import jax

    from sitewhere_tpu.core.events import (
        EventBatch,
        pack_batches,
        unpack_batch,
    )

    rng = np.random.default_rng(3)
    B, C = 64, 4
    batch = EventBatch(
        valid=rng.random(B) < 0.8,
        etype=rng.integers(0, 6, B).astype(np.int32),
        token_id=rng.integers(-1, 1000, B).astype(np.int32),
        tenant_id=rng.integers(0, 5, B).astype(np.int32),
        ts_ms=rng.integers(-(2**31), 2**31 - 1, B).astype(np.int32),
        received_ms=rng.integers(0, 2**31 - 1, B).astype(np.int32),
        values=rng.standard_normal((B, C)).astype(np.float32),
        vmask=rng.random((B, C)) < 0.5,
        aux=rng.integers(-1, 100, (B, 2)).astype(np.int32),
        seq=np.arange(B, dtype=np.int32),
    )
    packed = pack_batches([batch, batch])
    assert packed.shape[0] == 2 and packed.dtype == np.uint8
    out = jax.jit(lambda p: unpack_batch(p[0], B, C))(packed)
    for name in ("valid", "etype", "token_id", "tenant_id", "ts_ms",
                 "received_ms", "values", "vmask", "aux", "seq"):
        np.testing.assert_array_equal(
            np.asarray(getattr(out, name)), getattr(batch, name), err_msg=name)
