"""Shared-scan batched query engine (ISSUE 5).

Pins the three contracts of the read path:
  * the fused multi-predicate kernel is BYTE-identical to sequential
    ``query_store`` calls (ordering and newest-first tie-breaks included),
  * ``query_events`` holds the engine lock only for mirror sync + id
    resolution — never during device execution or row formatting,
  * ``limit`` buckets to a power of two for the compile cache but the
    caller still gets exactly its requested page.
"""

import json
import threading

import numpy as np
import pytest

import sitewhere_tpu.engine as engine_mod
from sitewhere_tpu.core.types import NULL_ID, EventType
from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.ops.query import (QueryParams, bucket_limit, query_store,
                                     query_store_batch)

IMIN, IMAX = -(2**31), 2**31 - 1


def _engine(**kw):
    cfg = dict(device_capacity=256, token_capacity=512,
               assignment_capacity=512, store_capacity=1 << 12,
               batch_capacity=64, channels=4)
    cfg.update(kw)
    return Engine(EngineConfig(**cfg))


def _fill(eng, n=200, n_dev=10, ties=4):
    """Ingest n measurements across n_dev devices with ``ties``-way event-
    time ties (every run of ``ties`` consecutive events shares one ts)."""
    base = int(eng.epoch.base_unix_s * 1000)
    pays = [json.dumps({
        "deviceToken": f"qb-{i % n_dev}", "type": "DeviceMeasurements",
        "request": {"measurements": {"t": float(i)},
                    "eventDate": base + (i // ties)}}).encode()
        for i in range(n)]
    eng.ingest_json_batch(pays)
    eng.flush()


def test_batched_matches_sequential_bytes():
    """Every field of the batched result equals the sequential
    ``query_store`` result bit for bit — including rows past ``n`` (the
    sort-order padding) and ts-tie ordering."""
    import jax
    import jax.numpy as jnp

    eng = _engine()
    _fill(eng)
    store = eng.state.store
    dev3 = eng.token_device[eng.tokens.lookup("qb-3")]
    base = int(eng.epoch.base_unix_s * 1000)
    t_mid = (0 + 200 // 4) // 2  # falls on a tie boundary
    N = NULL_ID
    preds = [
        # (device, etype, tenant, t0, t1, assignment, aux0, aux1, area, cust)
        (N, N, N, IMIN, IMAX, N, N, N, N, N),                  # everything
        (dev3, N, N, IMIN, IMAX, N, N, N, N, N),               # one device
        (N, int(EventType.MEASUREMENT), 0, IMIN, IMAX, N, N, N, N, N),
        (N, N, N, t_mid, t_mid + 10, N, N, N, N, N),           # tie window
        (dev3, N, N, t_mid, IMAX, N, N, N, N, N),              # combined
        (9999, N, N, IMIN, IMAX, N, N, N, N, N),               # no matches
    ]
    for limit in (1, 7, 64):
        seq = [jax.device_get(query_store(
            store, jnp.int32(d), jnp.int32(e), jnp.int32(t),
            jnp.int32(t0), jnp.int32(t1), limit=limit,
            assignment=jnp.int32(a), aux0=jnp.int32(x0),
            aux1=jnp.int32(x1), area=jnp.int32(ar), customer=jnp.int32(c)))
            for (d, e, t, t0, t1, a, x0, x1, ar, c) in preds]
        cols = list(zip(*preds))
        params = QueryParams(*(jnp.asarray(np.asarray(c, np.int32))
                               for c in cols))
        bat = jax.device_get(query_store_batch(store, params, limit=limit))
        for i, s in enumerate(seq):
            for f in s._fields:
                a = np.asarray(getattr(s, f))
                b = np.asarray(getattr(bat, f)[i])
                assert a.shape == b.shape and np.array_equal(a, b), \
                    (limit, i, f)


def test_limit_bucket_slices_exact_page():
    """pageSize stays exact through the power-of-two compile bucket, and
    two limits in one bucket share one compiled program."""
    eng = _engine()
    _fill(eng, n=50)
    assert bucket_limit(5) == bucket_limit(7) == 8
    assert bucket_limit(8) == 8 and bucket_limit(9) == 16
    r = eng.query_events(limit=7)
    assert r["total"] == 50 and len(r["events"]) == 7
    assert set(eng._query_batcher._programs) == {(1, 8)}
    r = eng.query_events(limit=5)          # same bucket: no new program
    assert len(r["events"]) == 5
    assert set(eng._query_batcher._programs) == {(1, 8)}
    r = eng.query_events(limit=9)          # next bucket: one new program
    assert len(r["events"]) == 9
    assert set(eng._query_batcher._programs) == {(1, 8), (1, 16)}
    r = eng.query_events(limit=12)         # same bucket as 9: no growth
    assert len(r["events"]) == 12
    assert set(eng._query_batcher._programs) == {(1, 8), (1, 16)}
    r = eng.query_events(limit=200)        # more than matches: all rows
    assert len(r["events"]) == 50


def test_query_runs_off_the_engine_lock(monkeypatch):
    """The device wait/readback and every _format_event call happen with
    the engine lock RELEASED (ingest can dispatch meanwhile)."""
    eng = _engine()
    _fill(eng, n=40)
    seen = {"fetch": 0, "format": 0}
    orig_fetch = engine_mod._fetch_query_result

    def fetch(tree):
        assert not eng.lock._is_owned(), \
            "engine lock held during query device wait/readback"
        seen["fetch"] += 1
        return orig_fetch(tree)

    orig_fmt = Engine._format_event

    def fmt(self, *a, **k):
        assert not self.lock._is_owned(), \
            "engine lock held during query row formatting"
        seen["format"] += 1
        return orig_fmt(self, *a, **k)

    monkeypatch.setattr(engine_mod, "_fetch_query_result", fetch)
    monkeypatch.setattr(Engine, "_format_event", fmt)
    res = eng.query_events(limit=10)
    assert res["total"] == 40 and len(res["events"]) == 10
    assert seen["fetch"] >= 1 and seen["format"] == 10
    # the query left a flight record with the read-path stages
    recs = [r for r in eng.flight.recent(10) if r.get("kind") == "query"]
    assert recs and {"lookup", "device", "format"} <= set(
        recs[0]["stagesUs"])


def test_concurrent_queries_coalesce(monkeypatch):
    """Queries issued while a round executes ride the NEXT fused program
    (continuous batching) — and every caller still gets its own result."""
    eng = _engine()
    _fill(eng, n=200, n_dev=8)
    orig_fetch = engine_mod._fetch_query_result
    gate = threading.Event()

    def slow_fetch(tree):
        gate.wait(5.0)   # hold round 1 open so followers can queue up
        return orig_fetch(tree)

    monkeypatch.setattr(engine_mod, "_fetch_query_result", slow_fetch)
    results: dict[int, dict] = {}
    errors: list[Exception] = []

    def query(i):
        try:
            results[i] = eng.query_events(device_token=f"qb-{i}", limit=50)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=query, args=(i,)) for i in range(8)]
    threads[0].start()
    while eng._query_batcher.programs == 0 and threads[0].is_alive():
        threading.Event().wait(0.005)   # leader reaches its slow fetch
    for t in threads[1:]:
        t.start()
    # all followers enqueued before the leader's fetch completes
    deadline = 300
    while len(eng._query_batcher._queue) < 7 and deadline:
        threading.Event().wait(0.01)
        deadline -= 1
    gate.set()
    for t in threads:
        t.join()
    assert not errors, errors
    assert all(results[i]["total"] == 25 for i in range(8))
    assert all(e["deviceToken"] == f"qb-{i}"
               for i in range(8) for e in results[i]["events"])
    assert eng._query_batcher.max_coalesced >= 2
    assert eng._query_batcher.programs < 8   # fewer programs than queries


def test_miss_queries_still_counted():
    """Unknown-string-filter queries (the early-return path) still count
    in swtpu_queries_total and the latency histogram — a high miss-rate
    poller must not read as zero traffic."""
    from sitewhere_tpu.utils.metrics import query_metrics

    eng = _engine()
    _fill(eng, n=10)
    qm = query_metrics()
    before = qm["queries"].value()
    assert eng.query_events(device_token="ghost") == {"total": 0,
                                                      "events": []}
    assert eng.query_events(tenant="ghost")["total"] == 0
    assert eng.query_events(alternate_id="ghost")["total"] == 0
    assert qm["queries"].value() == before + 3


def test_query_reentrant_under_engine_lock():
    """A caller already inside the engine lock (legal with the RLock
    before the batcher existed) must not deadlock — it runs its own
    single-query round re-entrantly."""
    eng = _engine()
    _fill(eng, n=30)
    with eng.lock:
        res = eng.query_events(limit=10)
    assert res["total"] == 30 and len(res["events"]) == 10


def test_search_device_states_vectorized_filters():
    """area/device_type filtering reads the on-device id columns — results
    match the host metadata exactly, unknown tokens match nothing."""
    eng = _engine()
    eng.register_device("sv-1", device_type="sensor", area="north")
    eng.register_device("sv-2", device_type="gateway", area="north")
    eng.register_device("sv-3", device_type="sensor", area="south")
    eng.register_device("sv-4")   # no area; default type
    got = {d["device"] for d in eng.search_device_states(area="north")}
    assert got == {"sv-1", "sv-2"}
    got = {d["device"] for d in eng.search_device_states(
        device_type="sensor")}
    assert got == {"sv-1", "sv-3"}
    got = {d["device"] for d in eng.search_device_states(
        area="north", device_type="sensor")}
    assert got == {"sv-1"}
    assert eng.search_device_states(area="atlantis") == []
    assert eng.search_device_states(device_type="nope") == []


@pytest.mark.slow
def test_concurrent_query_ingest_stress():
    """Writers and readers hammer the engine together: queries (which no
    longer serialize against ingest dispatch) stay consistent, totals
    balance exactly at the end."""
    eng = _engine(store_capacity=1 << 14)
    base = int(eng.epoch.base_unix_s * 1000)
    N_WRITERS, PER_WRITER, BATCH = 4, 40, 32
    errors: list[Exception] = []
    done = threading.Event()

    def writer(w):
        try:
            for b in range(PER_WRITER):
                eng.ingest_json_batch([json.dumps({
                    "deviceToken": f"st-{w}-{i % 8}",
                    "type": "DeviceMeasurements",
                    "request": {"measurements": {"t": float(i)},
                                "eventDate": base + b * BATCH + i}}).encode()
                    for i in range(BATCH)])
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def reader(r):
        try:
            while not done.is_set():
                res = eng.query_events(limit=20)
                assert len(res["events"]) <= 20
                res = eng.query_events(device_token=f"st-{r % 4}-0",
                                       limit=10)
                assert all(e["deviceToken"] == f"st-{r % 4}-0"
                           for e in res["events"]
                           if e["deviceToken"] is not None)
                eng.query_events(since_ms=0, until_ms=10_000, limit=20)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(w,))
               for w in range(N_WRITERS)]
    threads += [threading.Thread(target=reader, args=(r,)) for r in range(4)]
    for t in threads:
        t.start()
    for t in threads[:N_WRITERS]:
        t.join()
    eng.flush()
    done.set()
    for t in threads[N_WRITERS:]:
        t.join()
    assert not errors, errors
    total = N_WRITERS * PER_WRITER * BATCH
    assert eng.metrics()["persisted"] == total
    assert eng.query_events(limit=1)["total"] == total
