"""Prometheus exposition lint + Counter/Gauge API split (PR 3 satellites).

A promtool-style checker over the text format: HELP/TYPE ordering, family
contiguity, cumulative histogram buckets ending in ``+Inf`` with
count == +Inf, label escaping, and no duplicate series — run against both
a synthetic registry and the full engine export that
``/api/instance/metrics/prometheus`` serves.
"""

import re

import pytest

from sitewhere_tpu.utils.metrics import (Counter, Gauge, MetricsRegistry,
                                         export_engine_metrics)

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?P<labels>\{[^{}]*\})? (?P<value>[^ ]+)$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_labels(text):
    if not text:
        return ()
    body = text[1:-1]
    labels = _LABEL_RE.findall(body)
    # the full body must be consumed by well-formed pairs — an unescaped
    # quote or raw newline would leave residue
    rebuilt = ",".join(f'{k}="{v}"' for k, v in labels)
    assert rebuilt == body, f"malformed label set: {text!r}"
    return tuple(sorted(labels))


def lint_prometheus(text: str) -> None:
    """Promtool-style structural lint of one exposition payload."""
    families: dict[str, dict] = {}
    current = None
    seen_series: set = set()
    family_done: set = set()
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            name = line.split()[2]
            assert name not in families, f"duplicate HELP for {name}"
            families[name] = {"help": True, "type": None, "samples": []}
            current = name
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            name, kind = parts[2], parts[3]
            assert kind in ("counter", "gauge", "histogram", "summary",
                            "untyped"), line
            assert current == name, f"TYPE {name} not preceded by its HELP"
            assert families[name]["type"] is None, f"duplicate TYPE {name}"
            families[name]["type"] = kind
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable sample line: {line!r}"
        name = m.group("name")
        base = re.sub(r"_(bucket|sum|count|total)$", "", name)
        fam = name if name in families else base
        assert fam in families, f"sample {name} has no HELP/TYPE"
        assert fam == current, (
            f"family {fam} not contiguous: sample after {current}")
        assert fam not in family_done, f"family {fam} reopened"
        float(m.group("value"))       # value parses
        labels = _parse_labels(m.group("labels"))
        key = (name, labels)
        assert key not in seen_series, f"duplicate series {key}"
        seen_series.add(key)
        families[fam]["samples"].append((name, dict(labels),
                                         float(m.group("value"))))
    # histogram invariants. A family with HELP/TYPE and no samples yet is
    # LEGAL exposition (e.g. a registered histogram that never observed —
    # the WAL fsync histogram on an engine without a WAL); the invariants
    # apply per label set that does expose.
    for fam, info in families.items():
        if info["type"] != "histogram":
            continue
        by_labelset: dict = {}
        for name, labels, value in info["samples"]:
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            slot = by_labelset.setdefault(
                key, {"buckets": [], "sum": None, "count": None})
            if name == f"{fam}_bucket":
                slot["buckets"].append((labels["le"], value))
            elif name == f"{fam}_sum":
                slot["sum"] = value
            elif name == f"{fam}_count":
                slot["count"] = value
        for key, slot in by_labelset.items():
            assert slot["buckets"], f"{fam}{key}: no buckets"
            assert slot["buckets"][-1][0] == "+Inf", (
                f"{fam}{key}: buckets must end with +Inf")
            counts = [v for _, v in slot["buckets"]]
            assert counts == sorted(counts), (
                f"{fam}{key}: buckets not cumulative: {counts}")
            assert slot["count"] is not None and slot["sum"] is not None
            assert slot["count"] == counts[-1], (
                f"{fam}{key}: count != +Inf bucket")


# ------------------------------------------------------------------- lint
def test_lint_synthetic_registry():
    reg = MetricsRegistry()
    c = reg.counter("swtpu_lint_total", "events")
    c.inc(tenant="a")
    c.inc(2, tenant="b")
    g = reg.gauge("swtpu_lint_depth", "queue depth")
    g.set(3, queue="q1")
    h = reg.histogram("swtpu_lint_seconds", "latency")
    h.observe(0.001, stage="x")
    h.observe(9.0, stage="x")
    h.observe(99.0, stage="x")       # beyond the last finite bucket
    lint_prometheus(reg.expose_text())


def test_sampleless_histogram_family_lints():
    """A registered-but-never-observed histogram (the WAL fsync histogram
    on a WAL-less engine) exposes HELP/TYPE with no samples — legal."""
    reg = MetricsRegistry()
    reg.histogram("swtpu_empty_seconds", "never observed")
    lint_prometheus(reg.expose_text())


def test_label_values_escaped():
    reg = MetricsRegistry()
    g = reg.gauge("swtpu_esc", "escaping")
    hostile = 'a"b\\c\nd'
    g.set(1, tenant=hostile)
    text = reg.expose_text()
    assert '\\"b' in text and "\\\\c" in text and "\\nd" in text
    # the hostile value must not break line structure: every line lints
    lint_prometheus(text)


def test_full_engine_exposition_lints():
    """The payload /api/instance/metrics/prometheus actually serves:
    engine export + stage histogram, linted end to end."""
    from sitewhere_tpu.engine import Engine, EngineConfig
    from sitewhere_tpu.utils.tracing import stage

    reg = MetricsRegistry()
    eng = Engine(EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=1024, batch_capacity=16, channels=4))
    import json as _json

    eng.ingest_json_batch([_json.dumps(
        {"deviceToken": f"mx-{i}", "type": "DeviceMeasurements",
         "request": {"measurements": {"t": float(i)}}}).encode()
        for i in range(6)])
    eng.flush()
    export_engine_metrics(eng, reg)
    h = reg.histogram("swtpu_stage_seconds", "host pipeline stage latency")
    with h.time(stage="unit"):
        pass
    text = reg.expose_text()
    lint_prometheus(text)
    assert 'swtpu_engine_processed{tenant="all"} 6' in text
    assert 'swtpu_pipeline_accepted{tenant="default"} 6' in text
    assert "swtpu_dispatch_inflight" in text


# --------------------------------------------------------- API separation
def test_counter_has_no_set_and_rejects_decrease():
    c = Counter("c_total", "")
    assert not hasattr(c, "set")
    c.inc(2, tenant="a")
    with pytest.raises(ValueError):
        c.inc(-1, tenant="a")
    assert c.value(tenant="a") == 2


def test_gauge_moves_freely():
    g = Gauge("g", "")
    g.set(5, q="x")
    g.inc(q="x")
    g.dec(2, q="x")
    assert g.value(q="x") == 4
    g.retain(set())
    assert g.value(q="x") == 0.0     # retained away


def test_registry_kind_mismatch_both_directions():
    reg = MetricsRegistry()
    reg.counter("swtpu_kind_a", "")
    with pytest.raises(TypeError):
        reg.gauge("swtpu_kind_a")
    reg.gauge("swtpu_kind_b", "")
    with pytest.raises(TypeError):
        reg.counter("swtpu_kind_b")
    with pytest.raises(TypeError):
        reg.histogram("swtpu_kind_a")
