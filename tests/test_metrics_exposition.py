"""Prometheus exposition lint + Counter/Gauge API split (PR 3 satellites).

A promtool-style checker over the text format: HELP/TYPE ordering, family
contiguity, cumulative histogram buckets ending in ``+Inf`` with
count == +Inf, label escaping, and no duplicate series — run against both
a synthetic registry and the full engine export that
``/api/instance/metrics/prometheus`` serves.
"""

import re

import pytest

from sitewhere_tpu.utils.metrics import (Counter, Gauge, MetricsRegistry,
                                         export_engine_metrics)

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?P<labels>\{[^{}]*\})? (?P<value>[^ ]+)'
    r'(?P<exemplar> # \{[^{}]*\} [^ ]+( [^ ]+)?)?$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_EXEMPLAR_RE = re.compile(r'^ # (?P<labels>\{[^{}]*\}) (?P<value>[^ ]+)'
                          r'( (?P<ts>[^ ]+))?$')


def _parse_labels(text):
    if not text:
        return ()
    body = text[1:-1]
    labels = _LABEL_RE.findall(body)
    # the full body must be consumed by well-formed pairs — an unescaped
    # quote or raw newline would leave residue
    rebuilt = ",".join(f'{k}="{v}"' for k, v in labels)
    assert rebuilt == body, f"malformed label set: {text!r}"
    return tuple(sorted(labels))


def lint_prometheus(text: str) -> None:
    """Promtool-style structural lint of one exposition payload."""
    families: dict[str, dict] = {}
    current = None
    seen_series: set = set()
    family_done: set = set()
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            name = line.split()[2]
            assert name not in families, f"duplicate HELP for {name}"
            families[name] = {"help": True, "type": None, "samples": []}
            current = name
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            name, kind = parts[2], parts[3]
            assert kind in ("counter", "gauge", "histogram", "summary",
                            "untyped"), line
            assert current == name, f"TYPE {name} not preceded by its HELP"
            assert families[name]["type"] is None, f"duplicate TYPE {name}"
            families[name]["type"] = kind
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable sample line: {line!r}"
        name = m.group("name")
        if m.group("exemplar"):
            # OpenMetrics exemplars are legal only on histogram bucket
            # lines; labels must be well-formed and the value must parse
            assert name.endswith("_bucket"), (
                f"exemplar on non-bucket line: {line!r}")
            em = _EXEMPLAR_RE.match(m.group("exemplar"))
            assert em, f"malformed exemplar: {line!r}"
            _parse_labels(em.group("labels"))
            float(em.group("value"))
        base = re.sub(r"_(bucket|sum|count|total)$", "", name)
        fam = name if name in families else base
        assert fam in families, f"sample {name} has no HELP/TYPE"
        assert fam == current, (
            f"family {fam} not contiguous: sample after {current}")
        assert fam not in family_done, f"family {fam} reopened"
        float(m.group("value"))       # value parses
        labels = _parse_labels(m.group("labels"))
        key = (name, labels)
        assert key not in seen_series, f"duplicate series {key}"
        seen_series.add(key)
        families[fam]["samples"].append((name, dict(labels),
                                         float(m.group("value"))))
    # histogram invariants. A family with HELP/TYPE and no samples yet is
    # LEGAL exposition (e.g. a registered histogram that never observed —
    # the WAL fsync histogram on an engine without a WAL); the invariants
    # apply per label set that does expose.
    for fam, info in families.items():
        if info["type"] != "histogram":
            continue
        by_labelset: dict = {}
        for name, labels, value in info["samples"]:
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            slot = by_labelset.setdefault(
                key, {"buckets": [], "sum": None, "count": None})
            if name == f"{fam}_bucket":
                slot["buckets"].append((labels["le"], value))
            elif name == f"{fam}_sum":
                slot["sum"] = value
            elif name == f"{fam}_count":
                slot["count"] = value
        for key, slot in by_labelset.items():
            assert slot["buckets"], f"{fam}{key}: no buckets"
            assert slot["buckets"][-1][0] == "+Inf", (
                f"{fam}{key}: buckets must end with +Inf")
            counts = [v for _, v in slot["buckets"]]
            assert counts == sorted(counts), (
                f"{fam}{key}: buckets not cumulative: {counts}")
            assert slot["count"] is not None and slot["sum"] is not None
            assert slot["count"] == counts[-1], (
                f"{fam}{key}: count != +Inf bucket")


# ------------------------------------------------------------------- lint
def test_lint_synthetic_registry():
    reg = MetricsRegistry()
    c = reg.counter("swtpu_lint_total", "events")
    c.inc(tenant="a")
    c.inc(2, tenant="b")
    g = reg.gauge("swtpu_lint_depth", "queue depth")
    g.set(3, queue="q1")
    h = reg.histogram("swtpu_lint_seconds", "latency")
    h.observe(0.001, stage="x")
    h.observe(9.0, stage="x")
    h.observe(99.0, stage="x")       # beyond the last finite bucket
    lint_prometheus(reg.expose_text())


def test_sampleless_histogram_family_lints():
    """A registered-but-never-observed histogram (the WAL fsync histogram
    on a WAL-less engine) exposes HELP/TYPE with no samples — legal."""
    reg = MetricsRegistry()
    reg.histogram("swtpu_empty_seconds", "never observed")
    lint_prometheus(reg.expose_text())


def test_label_values_escaped():
    reg = MetricsRegistry()
    g = reg.gauge("swtpu_esc", "escaping")
    hostile = 'a"b\\c\nd'
    g.set(1, tenant=hostile)
    text = reg.expose_text()
    assert '\\"b' in text and "\\\\c" in text and "\\nd" in text
    # the hostile value must not break line structure: every line lints
    lint_prometheus(text)


def test_full_engine_exposition_lints():
    """The payload /api/instance/metrics/prometheus actually serves:
    engine export + stage histogram, linted end to end."""
    from sitewhere_tpu.engine import Engine, EngineConfig
    from sitewhere_tpu.utils.tracing import stage

    reg = MetricsRegistry()
    eng = Engine(EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=1024, batch_capacity=16, channels=4))
    import json as _json

    eng.ingest_json_batch([_json.dumps(
        {"deviceToken": f"mx-{i}", "type": "DeviceMeasurements",
         "request": {"measurements": {"t": float(i)}}}).encode()
        for i in range(6)])
    eng.flush()
    export_engine_metrics(eng, reg)
    h = reg.histogram("swtpu_stage_seconds", "host pipeline stage latency")
    with h.time(stage="unit"):
        pass
    text = reg.expose_text()
    lint_prometheus(text)
    assert 'swtpu_engine_processed{tenant="all"} 6' in text
    assert 'swtpu_pipeline_accepted{tenant="default"} 6' in text
    assert "swtpu_dispatch_inflight" in text
    # device plane (ISSUE 11): the scrape-time exports land in the SAME
    # registry and must lint with everything else (the live watchdog
    # counters go to the process-global REGISTRY, checked in
    # tests/test_devicewatch.py)
    assert 'swtpu_device_mem_bytes{component="ring_store"' in text
    assert "swtpu_xla_programs_live" in text
    assert "swtpu_staged_backlog_hwm_rows" in text
    # conservation plane (ISSUE 14): the flow ledger's scrape-time
    # gauges ride the same exposition and stay 0.0.4-clean
    lbl = eng.metrics_label
    assert (f'swtpu_flow_rows{{engine="{lbl}",stage="staged"}} 6'
            in text)
    assert (f'swtpu_flow_rows{{engine="{lbl}",stage="dispatched"}} 6'
            in text)


def test_rules_counters_export_at_scrape():
    """ISSUE 14 satellite: the cadence-dependent CEP counters
    (missed/late/oob fires) export as swtpu_rules_* at SCRAPE time —
    kept OUT of engine.metrics() (the dispatch-shape pin is asserted by
    bench + tests/test_rules.py) but no longer invisible without the
    Python API. An engine with no rule set exports none of them."""
    from sitewhere_tpu.engine import Engine, EngineConfig
    from sitewhere_tpu.rules import RuleSet, RulesManager

    reg = MetricsRegistry()
    plain = Engine(EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=1024, batch_capacity=16, channels=4))
    export_engine_metrics(plain, reg)
    assert "swtpu_rules_missed_total" not in reg.expose_text()

    eng = Engine(EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=1024, batch_capacity=16, channels=8,
        rule_groups=32, rollup_buckets=8))
    RulesManager(eng).load(RuleSet.parse({
        "name": "x",
        "rules": [{"name": "hot", "kind": "threshold",
                   "channel": "temp", "op": ">", "value": 90.0,
                   "cooldownMs": 1000}]}), precompile=False)
    reg = MetricsRegistry()
    export_engine_metrics(eng, reg)
    text = reg.expose_text()
    lint_prometheus(text)
    for name in ("swtpu_rules_missed_total", "swtpu_rules_late_total",
                 "swtpu_rules_oob_groups_total",
                 "swtpu_rules_fires_total"):
        assert f"{name} 0" in text, name
    assert "swtpu_rules_active 1" in text
    # the dispatch-shape pin's premise: none of these leak into
    # engine.metrics() (missed/late are harvest-cadence dependent)
    assert "rule_missed" not in eng.metrics()
    assert "ruleMissedFires" not in eng.metrics()


def test_spmd_series_export_at_scrape_and_lint():
    """ISSUE 16 satellite: the mesh-sharded engine exports its per-shard
    posture (swtpu_spmd_* / swtpu_shard_* gauges) at SCRAPE time — kept
    OUT of engine.metrics(), whose dict is pinned equal to single-chip.
    A single-chip engine exports none of them."""
    import json as _json

    from sitewhere_tpu.engine import Engine, EngineConfig
    from sitewhere_tpu.parallel.sharded import SpmdEngine

    cfg = EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=1024, batch_capacity=16, channels=4,
        use_native=False)
    reg = MetricsRegistry()
    export_engine_metrics(Engine(cfg), reg)
    assert "swtpu_spmd_shards" not in reg.expose_text()

    eng = SpmdEngine(cfg, n_shards=2)
    eng.ingest_json_batch([_json.dumps(
        {"deviceToken": f"sx-{i}", "type": "DeviceMeasurement",
         "request": {"name": "t", "value": float(i), "eventDate": 1000}}
        ).encode() for i in range(6)])
    eng.flush()
    reg = MetricsRegistry()
    export_engine_metrics(eng, reg)
    text = reg.expose_text()
    lint_prometheus(text)
    lbl = eng.metrics_label
    assert f'swtpu_spmd_shards{{engine="{lbl}"}} 2' in text
    for s in ("0", "1"):
        assert (f'swtpu_shard_staged_rows{{engine="{lbl}",shard="{s}"}}'
                in text)
        assert (f'swtpu_shard_devices{{engine="{lbl}",shard="{s}"}}'
                in text)
        assert (f'swtpu_shard_assignments{{engine="{lbl}",shard="{s}"}}'
                in text)
    # devices landed on BOTH shards and the per-shard counts sum to the
    # registered total
    devs = {s: eng._next_local_device[s] for s in range(2)}
    assert sum(devs.values()) == 6 and all(v > 0 for v in devs.values())


# --------------------------------------------------------- API separation
def test_counter_has_no_set_and_rejects_decrease():
    c = Counter("c_total", "")
    assert not hasattr(c, "set")
    c.inc(2, tenant="a")
    with pytest.raises(ValueError):
        c.inc(-1, tenant="a")
    assert c.value(tenant="a") == 2


def test_gauge_moves_freely():
    g = Gauge("g", "")
    g.set(5, q="x")
    g.inc(q="x")
    g.dec(2, q="x")
    assert g.value(q="x") == 4
    g.retain(set())
    assert g.value(q="x") == 0.0     # retained away


def test_registry_kind_mismatch_both_directions():
    reg = MetricsRegistry()
    reg.counter("swtpu_kind_a", "")
    with pytest.raises(TypeError):
        reg.gauge("swtpu_kind_a")
    reg.gauge("swtpu_kind_b", "")
    with pytest.raises(TypeError):
        reg.counter("swtpu_kind_b")
    with pytest.raises(TypeError):
        reg.histogram("swtpu_kind_a")


# -------------------------------------------- quantile estimator (ISSUE 7)
def test_quantile_interpolates_within_bounding_bucket():
    from sitewhere_tpu.utils.metrics import Histogram

    h = Histogram("swtpu_q_seconds", "", buckets=(1.0, 2.0, 4.0))
    for _ in range(100):
        h.observe(1.5)                 # every sample in the (1, 2] bucket
    # uniform-within-bucket rule: p50 = lo + 0.5 * width
    assert abs(h.quantile(0.5) - 1.5) < 1e-9
    assert h.quantile(1.0) == 2.0      # upper edge of the bounding bucket
    # first bucket interpolates down from 0
    h2 = Histogram("swtpu_q2_seconds", "", buckets=(1.0, 2.0))
    for _ in range(10):
        h2.observe(0.2)
    assert abs(h2.quantile(0.5) - 0.5) < 1e-9


def test_quantile_matches_numpy_percentiles_within_bucket_width():
    """The satellite's contract: bucket-quantile vs exact numpy
    percentiles on known distributions, within one bucket width."""
    import bisect

    import numpy as np

    from sitewhere_tpu.utils.metrics import Histogram

    rng = np.random.default_rng(0)
    for dist in (rng.uniform(0.0, 1.0, 5000),
                 rng.exponential(0.05, 5000),
                 rng.lognormal(-4.0, 1.0, 5000)):
        h = Histogram("swtpu_qn_seconds", "")
        for v in dist:
            h.observe(float(v))
        for q in (0.5, 0.9, 0.99):
            exact = float(np.percentile(dist, q * 100))
            est = h.quantile(q)
            i = bisect.bisect_left(h.buckets, exact)
            if i >= len(h.buckets):      # beyond the last finite bucket
                assert est == h.buckets[-1]
                continue
            lo = h.buckets[i - 1] if i else 0.0
            assert abs(est - exact) <= (h.buckets[i] - lo) + 1e-12, \
                (q, est, exact)


def test_quantile_overflow_clamps_to_last_finite_bound():
    from sitewhere_tpu.utils.metrics import Histogram

    h = Histogram("swtpu_qo_seconds", "", buckets=(0.1, 1.0))
    h.observe(50.0)
    h.observe(60.0)
    assert h.quantile(0.5) == 1.0
    assert h.quantile(0.99) == 1.0
    assert Histogram("swtpu_qe_seconds", "").quantile(0.5) is None


# ------------------------------------------------- exemplars (ISSUE 7)
def test_histogram_exemplars_only_on_request():
    """Exemplars ride ONLY exemplar-aware expositions: the plain
    text-format payload stays strictly Prometheus-0.0.4 parseable."""
    from sitewhere_tpu.utils.metrics import MetricsRegistry

    reg = MetricsRegistry()
    h = reg.histogram("swtpu_ex_seconds", "exemplars")
    h.observe_n(0.2, 3, exemplar="00-abcdef-01", tenant="t")
    plain = reg.expose_text()
    assert "# {" not in plain
    lint_prometheus(plain)
    rich = reg.expose_text(exemplars=True)
    assert '# {trace_id="00-abcdef-01"} 0.2' in rich
    lint_prometheus(rich)


def test_observe_n_weights_event_counts():
    from sitewhere_tpu.utils.metrics import Histogram

    h = Histogram("swtpu_w_seconds", "")
    h.observe_n(0.003, 10, tenant="a")
    h.observe_n(0.03, 90, tenant="a")
    assert h.count(tenant="a") == 100
    q = h.quantile(0.5, tenant="a")    # p50 weighted by EVENTS
    assert 0.025 <= q <= 0.05


# ------------------------------------- federated exposition (ISSUE 7)
def _mk_rank_text(val: float) -> str:
    from sitewhere_tpu.utils.metrics import MetricsRegistry

    reg = MetricsRegistry()
    c = reg.counter("swtpu_fed_total", "events")
    c.inc(val, tenant="a")
    g = reg.gauge("swtpu_fed_depth", "queue depth")
    g.set(val)
    h = reg.histogram("swtpu_fed_seconds", "latency")
    h.observe(0.01 * val)
    return reg.expose_text()


def test_federate_dedups_help_type_and_labels_every_sample():
    from sitewhere_tpu.utils.metrics import federate_expositions

    fed = federate_expositions({0: _mk_rank_text(1), 1: _mk_rank_text(2)})
    lint_prometheus(fed)
    # ONE HELP/TYPE per family across ranks
    assert fed.count("# HELP swtpu_fed_total") == 1
    assert fed.count("# TYPE swtpu_fed_seconds histogram") == 1
    # every sample rank-labeled, existing labels preserved
    assert 'swtpu_fed_total{rank="0",tenant="a"} 1.0' in fed
    assert 'swtpu_fed_total{rank="1",tenant="a"} 2.0' in fed
    assert 'swtpu_fed_depth{rank="0"} 1' in fed
    assert 'swtpu_fed_depth{rank="1"} 2' in fed


def test_federate_escapes_rank_and_survives_hostile_label_values():
    from sitewhere_tpu.utils.metrics import (MetricsRegistry,
                                             federate_expositions)

    reg = MetricsRegistry()
    g = reg.gauge("swtpu_fed_esc", "escaping")
    g.set(1, tenant='a"b\\c\nd')        # hostile VALUE inside the rank text
    fed = federate_expositions({'r"0\\x': reg.expose_text()})
    lint_prometheus(fed)
    assert 'rank="r\\"0\\\\x"' in fed   # hostile RANK key escaped
    assert '\\"b' in fed and "\\\\c" in fed and "\\nd" in fed


def test_federate_preserves_exemplars():
    from sitewhere_tpu.utils.metrics import (MetricsRegistry,
                                             federate_expositions)

    reg = MetricsRegistry()
    h = reg.histogram("swtpu_fed_ex_seconds", "latency")
    h.observe_n(0.02, 1, exemplar="tid-1")
    fed = federate_expositions({3: reg.expose_text(exemplars=True)})
    lint_prometheus(fed)
    assert '# {trace_id="tid-1"}' in fed
    assert 'rank="3"' in fed


def test_federate_cross_rank_type_conflict_is_loud():
    from sitewhere_tpu.utils.metrics import (MetricsRegistry,
                                             federate_expositions)

    ra = MetricsRegistry()
    ra.counter("swtpu_fed_kind", "k").inc()
    rb = MetricsRegistry()
    rb.gauge("swtpu_fed_kind", "k").set(1)
    with pytest.raises(ValueError):
        federate_expositions({0: ra.expose_text(), 1: rb.expose_text()})
