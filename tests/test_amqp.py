"""AMQP 0-9-1 edge tests: codec, embedded broker, client, RabbitMQ receiver
(sources/rabbitmq/RabbitMqInboundEventReceiver.java parity) and outbound
connector (connectors/rabbitmq/RabbitMqOutboundConnector.java parity)."""

import asyncio
import json

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.ingest.amqp import (
    AmqpBroker,
    AmqpClient,
    ArgReader,
    ArgWriter,
    RabbitMqEventReceiver,
    topic_key_matches,
)
from sitewhere_tpu.ingest.decoders import JsonDeviceRequestDecoder
from sitewhere_tpu.ingest.sources import EventSourcesManager, InboundEventSource
from sitewhere_tpu.outbound.feed import OutboundEvent
from sitewhere_tpu.core.types import EventType


def measurement_json(token="dev-1", name="fuel.level", value=123.4):
    return json.dumps({
        "deviceToken": token,
        "type": "DeviceMeasurement",
        "request": {"name": name, "value": value},
    }).encode()


def test_topic_key_matching():
    assert topic_key_matches("a.b.c", "a.b.c")
    assert topic_key_matches("a.*.c", "a.x.c")
    assert not topic_key_matches("a.*.c", "a.x.y.c")
    assert topic_key_matches("a.#", "a")
    assert topic_key_matches("a.#", "a.b.c.d")
    assert topic_key_matches("#.c", "a.b.c")
    assert topic_key_matches("#", "anything.at.all")
    assert not topic_key_matches("a.b", "a.b.c")
    assert not topic_key_matches("a.b.c", "a.b")


def test_arg_codec_roundtrip():
    data = (ArgWriter().short(0).shortstr("queue-name").bit(False).bit(True)
            .bit(False).longstr(b"payload").long(42).longlong(1 << 40)
            .table({"k": "v"}).done())
    r = ArgReader(data)
    assert r.short() == 0
    assert r.shortstr() == "queue-name"
    assert r.bits(3) == [False, True, False]
    assert r.longstr() == b"payload"
    assert r.long() == 42
    assert r.longlong() == 1 << 40
    assert r.table() == {"k": "v"}


def test_broker_publish_consume_default_exchange():
    async def run():
        broker = AmqpBroker()
        await broker.start()
        got: list[tuple[str, str, bytes]] = []
        try:
            consumer = AmqpClient("127.0.0.1", broker.bound_port)
            consumer.on_message = lambda ex, key, body: got.append((ex, key, body))
            await consumer.connect()
            await consumer.declare_queue("q1")
            await consumer.consume("q1")

            producer = AmqpClient("127.0.0.1", broker.bound_port)
            await producer.connect()
            await producer.publish("", "q1", b"hello")
            await producer.publish("", "other-queue", b"dropped")
            await asyncio.sleep(0.2)
            await producer.close()
            await consumer.close()
        finally:
            await broker.stop()
        assert got == [("", "q1", b"hello")]

    asyncio.run(run())


def test_broker_topic_exchange_and_pending_buffer():
    async def run():
        broker = AmqpBroker()
        await broker.start()
        got: list[bytes] = []
        try:
            producer = AmqpClient("127.0.0.1", broker.bound_port)
            await producer.connect()
            await producer.declare_exchange("ex.telemetry", "topic")
            # bind + publish BEFORE any consumer: must buffer in the queue
            await producer.declare_queue("qt")
            await producer.bind_queue("qt", "ex.telemetry", "site.*.temp")
            await producer.publish("ex.telemetry", "site.a.temp", b"m1")
            await producer.publish("ex.telemetry", "site.a.humidity", b"nope")

            consumer = AmqpClient("127.0.0.1", broker.bound_port)
            consumer.on_message = lambda ex, key, body: got.append(body)
            await consumer.connect()
            await consumer.declare_queue("qt")
            await consumer.consume("qt")
            await asyncio.sleep(0.1)
            await producer.publish("ex.telemetry", "site.b.temp", b"m2")
            await asyncio.sleep(0.2)
            await producer.close()
            await consumer.close()
        finally:
            await broker.stop()
        assert got == [b"m1", b"m2"]

    asyncio.run(run())


def test_rabbitmq_receiver_end_to_end():
    async def run():
        broker = AmqpBroker()
        await broker.start()
        engine = Engine(EngineConfig(
            device_capacity=64, token_capacity=128, assignment_capacity=128,
            store_capacity=4096, batch_capacity=16, channels=4,
        ))
        mgr = EventSourcesManager(
            on_event_request=engine.process,
            on_registration_request=engine.process,
        )
        recv = RabbitMqEventReceiver("127.0.0.1", broker.bound_port,
                                     queue="sw.input")
        mgr.add_source(InboundEventSource("amqp", JsonDeviceRequestDecoder(), [recv]))
        await mgr.initialize()
        await mgr.start()
        try:
            pub = AmqpClient("127.0.0.1", broker.bound_port)
            await pub.connect()
            await pub.publish("", "sw.input", measurement_json("amqp-1"))
            await pub.publish("", "sw.input", measurement_json("amqp-2"))
            await asyncio.sleep(0.3)
            await pub.close()
        finally:
            await mgr.stop()
            await broker.stop()
        engine.flush()
        assert engine.metrics()["registered"] == 2

    asyncio.run(run())


def test_rabbitmq_receiver_reconnects():
    """Broker comes up AFTER the receiver starts; the reconnect loop
    (reference: scheduleReconnect, RabbitMqInboundEventReceiver.java:60-75)
    must attach once it is reachable."""

    async def run():
        probe = AmqpBroker()
        await probe.start()
        port = probe.bound_port
        await probe.stop()  # now nothing listens on `port`

        engine = Engine(EngineConfig(
            device_capacity=64, token_capacity=128, assignment_capacity=128,
            store_capacity=4096, batch_capacity=16, channels=4,
        ))
        mgr = EventSourcesManager(
            on_event_request=engine.process,
            on_registration_request=engine.process,
        )
        recv = RabbitMqEventReceiver("127.0.0.1", port, queue="sw.input",
                                     reconnect_interval_s=0.1)
        mgr.add_source(InboundEventSource("amqp", JsonDeviceRequestDecoder(), [recv]))
        await mgr.initialize()
        await mgr.start()
        broker = AmqpBroker(port=port)
        await broker.start()
        try:
            await asyncio.sleep(0.4)  # allow the reconnect loop to attach
            pub = AmqpClient("127.0.0.1", port)
            await pub.connect()
            await pub.publish("", "sw.input", measurement_json("rc-1"))
            await asyncio.sleep(0.3)
            await pub.close()
        finally:
            await mgr.stop()
            await broker.stop()
        engine.flush()
        assert engine.metrics()["registered"] == 1

    asyncio.run(run())


def test_rabbitmq_connector_publishes_to_topic_exchange():
    from sitewhere_tpu.connectors.impl import RabbitMqConnector

    ev = OutboundEvent(
        event_id=1, etype=EventType.MEASUREMENT, device_token="d-1",
        device_id=0, assignment_id=0, tenant="default", area_id=0, asset_id=0,
        ts_ms=1000, received_ms=1001, measurements={"temp": 20.5},
        values=[20.5], aux0=0, aux1=0,
    )

    async def run():
        broker = AmqpBroker()
        await broker.start()
        got: list[tuple[str, bytes]] = []
        try:
            sub = AmqpClient("127.0.0.1", broker.bound_port)
            sub.on_message = lambda ex, key, body: got.append((key, body))
            await sub.connect()
            await sub.declare_exchange("sitewhere.events", "topic")
            await sub.declare_queue("sink")
            await sub.bind_queue("sink", "sitewhere.events", "#")
            await sub.consume("sink")

            conn = RabbitMqConnector("rmq", "127.0.0.1", broker.bound_port)
            await conn.process_event(ev)
            await asyncio.sleep(0.2)
            await conn.on_stop()
            await sub.close()
        finally:
            await broker.stop()
        assert len(got) == 1
        key, body = got[0]
        assert key == "sitewhere.output"
        assert json.loads(body)["deviceToken"] == "d-1"

    asyncio.run(run())
