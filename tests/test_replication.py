"""Event-plane replication RF>=2 (ROADMAP open item #1 / ISSUE 6).

The reference survives any single replica dying because storage is a
shared DB; here each rank's event partition was RF=1 — one SIGKILL'd
rank meant unreadable history and silently dead schedules. These tests
pin the replication contract: follower standby stores are BYTE-equal to
the owner's after a replicated stream, failover reads serve a dead
owner's partition with an explicit stale_ms watermark, schedules pinned
to a dead owner fire on its first follower exactly once (fencing epoch +
replicated fired state — no double-fire on recovery), and no WAL-durable
(acked) event is ever lost.
"""

import asyncio
import dataclasses
import time

import jax
import numpy as np
import pytest

from sitewhere_tpu.parallel.cluster import (ClusterConfig, ClusterEngine,
                                            build_cluster_rpc, owner_rank)
from sitewhere_tpu.parallel.replication import (DOWN, PeerHealth,
                                                ReplicaApplier, ReplicaFeed,
                                                install_fireover,
                                                register_replication_rpc,
                                                replica_ring)
from tests.test_cluster import (BASE_MS, BASE_S, _engine_cfg, _free_ports,
                                _ServerHost, meas, tokens_owned_by)


def _mk_replicated_cluster(tmp_path, rf=2, n_ranks=2, detect_s=1.0,
                           heartbeat_s=0.2, connect_timeout_s=5.0,
                           start_feeds=True):
    """n ranks with full engines + replica feeds/appliers over live RPC."""
    ports = _free_ports(n_ranks)
    peers = [f"127.0.0.1:{p}" for p in ports]
    host = _ServerHost()
    clusters, feeds, appliers, servers = [], [], [], []
    for r in range(n_ranks):
        cc = ClusterConfig(rank=r, n_ranks=n_ranks, peers=peers,
                           secret="rep-secret", epoch_base_unix_s=BASE_S,
                           engine=_engine_cfg(tmp_path, r),
                           connect_timeout_s=connect_timeout_s)
        c = ClusterEngine(cc)
        feed = ReplicaFeed(c, tmp_path / f"replica-r{r}", rf=rf,
                           heartbeat_s=heartbeat_s)
        applier = ReplicaApplier(c, rf=rf, detect_s=detect_s)
        c.attach_replication(feed, applier)
        srv = build_cluster_rpc(c.local, "rep-secret")
        register_replication_rpc(srv, applier)
        host.start(srv, ports[r])
        clusters.append(c)
        feeds.append(feed)
        appliers.append(applier)
        servers.append(srv)
    if start_feeds:
        for f in feeds:
            f.start()
    return clusters, feeds, appliers, servers, host, ports


def _wait(cond, timeout_s=15.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def _close(clusters, feeds, host):
    for f in feeds:
        f.stop()
    for c in clusters:
        c.close()
    host.close()


def test_replica_ring_is_deterministic_and_disjoint():
    assert replica_ring(0, 4, 2) == [1]
    assert replica_ring(3, 4, 3) == [0, 1]
    assert replica_ring(0, 1, 2) == []   # rf clamps to n_ranks
    # every rank's follower set excludes itself and covers the ring
    for n in (2, 3, 5):
        for r in range(n):
            ring = replica_ring(r, n, 2)
            assert r not in ring and len(ring) == 1


def test_follower_store_byte_equal_after_replicated_stream(tmp_path):
    """THE replication oracle (shard-decode style): after streaming the
    owner's WAL-order batches — json batch, binary per-request, a second
    json round — the follower's standby store is BYTE-identical to the
    owner's live store, interner contents included. The feed ships the
    owner's pinned staging clock per batch, so even received_ms agrees."""
    from sitewhere_tpu.ingest.decoders import request_from_envelope

    clusters, feeds, appliers, servers, host, ports = \
        _mk_replicated_cluster(tmp_path)
    c0, c1 = clusters
    try:
        toks = tokens_owned_by(0, 3, prefix="rep")
        c0.ingest_json_batch([meas(t, "temp", 1.0 + i, 100 + i)
                              for i, t in enumerate(toks)])
        # per-request path (WAL_BINARY single-record publish)
        env = {"deviceToken": toks[0], "type": "DeviceMeasurements",
               "request": {"measurements": {"temp": 7.5},
                           "eventDate": BASE_MS + 400}}
        req = request_from_envelope(env)
        req.tenant = "default"
        c0.process(req)
        c0.ingest_json_batch([meas(toks[1], "hum", 40.0, 500)])
        c0.flush()
        _wait(feeds[0].drained, what="feed drain")

        st = appliers[1]._standby(0)
        assert st is not None and st.applied_seq == 3
        st.engine.flush()
        owner = jax.device_get(c0.local.state.store)
        standby = jax.device_get(st.engine.state.store)
        for f in dataclasses.fields(owner):
            a = np.asarray(getattr(owner, f.name))
            b = np.asarray(getattr(standby, f.name))
            assert np.array_equal(a, b), \
                f"standby store field {f.name} diverged"
        for name in ("tokens", "tenants", "event_ids"):
            own = getattr(c0.local, name)
            rep = getattr(st.engine, name)
            assert [own.token(i) for i in range(len(own))] == \
                [rep.token(i) for i in range(len(rep))], name
        # device-state parity through the standby's own read path
        assert st.engine.get_device_state(toks[0])["measurements"] == \
            c0.local.get_device_state(toks[0])["measurements"]
    finally:
        _close(clusters, feeds, host)


def test_wal_resync_rebuilds_standby_from_full_history(tmp_path):
    """A follower that joins LATE (or gapped) converges by WAL resync:
    everything the owner ever acked — including batches ingested before
    the feed even started — serves from the standby."""
    clusters, feeds, appliers, servers, host, ports = \
        _mk_replicated_cluster(tmp_path, start_feeds=False)
    c0, c1 = clusters
    try:
        toks = tokens_owned_by(0, 2, prefix="hist")
        for i in range(3):
            c0.ingest_json_batch([meas(t, "t", float(i), 100 + 10 * i + j)
                                  for j, t in enumerate(toks)])
        c0.flush()
        # feed starts AFTER the history exists: initial resync must ship
        # the whole WAL, then the live stream takes over
        for f in feeds:
            f.start()
        _wait(feeds[0].drained, what="resync + drain")
        c0.ingest_json_batch([meas(toks[0], "t", 9.0, 900)])
        c0.flush()
        _wait(feeds[0].drained, what="live drain")
        res = appliers[1].query_events(0, device_token=toks[0])
        assert res["total"] == 4
        assert res["stale_ms"] >= 0
        assert feeds[0].counters["resyncs"] >= 1
    finally:
        _close(clusters, feeds, host)


def test_failover_reads_served_by_follower_with_stale_ms(tmp_path):
    """Owner dies -> queries over its partition serve from the follower
    standby with snapshot-consistent results and an explicit staleness
    bound; once marked DOWN, repeated reads skip the dead owner's
    connect timeout (probe backoff)."""
    clusters, feeds, appliers, servers, host, ports = \
        _mk_replicated_cluster(tmp_path, connect_timeout_s=1.0)
    c0, c1 = clusters
    try:
        toks = tokens_owned_by(0, 2, prefix="fo")
        c0.ingest_json_batch([meas(t, "temp", 1.0 + i, 100 + i)
                              for i, t in enumerate(toks)])
        c0.flush()
        _wait(feeds[0].drained, what="feed drain")
        host.stop(servers[0])
        feeds[0].stop()

        q = c1.query_events(device_token=toks[0])
        assert q["total"] == 1 and q["stale_ms"] >= 0
        assert q["events"][0]["eventDateMs"] == 100
        ds = c1.get_device_state(toks[1])
        assert ds["measurements"]["temp"]["value"] == 2.0
        assert ds["stale_ms"] >= 0 and ds["served_by_replica"] == 1
        rows = c1.search_device_states()
        assert any(r.get("served_by_replica") == 1 for r in rows)
        _wait(lambda: c1.health.is_down(0), what="health DOWN")
        # down rank skips the connect attempt between probe windows
        t0 = time.monotonic()
        q2 = c1.query_events(device_token=toks[0])
        assert q2["total"] == 1 and q2["stale_ms"] >= 0
        assert time.monotonic() - t0 < 0.8, "DOWN owner must not cost a " \
            "connect timeout per read"
        # an unknown device on the dead partition reads as absent, not 500
        assert c1.get_device_state(
            tokens_owned_by(0, 3, prefix="fo")[2]) is None
    finally:
        _close(clusters, feeds, host)


def test_no_acked_event_lost_on_owner_kill_and_recovery(tmp_path):
    """The chaos invariant: SIGKILL the owner mid-ingest. Every event
    acked (WAL-durable) before the kill is served by the follower during
    the outage; ingest accepted at the survivor during the outage spills
    durably; after the owner replays its WAL everything is back and the
    spilled share redelivers — zero acknowledged loss, no duplicates."""
    from sitewhere_tpu.parallel.distributed import (DistributedConfig,
                                                    DistributedEngine)
    from sitewhere_tpu.parallel.forward import ForwardQueue, SpillRegistry
    from sitewhere_tpu.utils.checkpoint import replay_records
    from sitewhere_tpu.utils.ingestlog import IngestLog

    clusters, feeds, appliers, servers, host, ports = \
        _mk_replicated_cluster(tmp_path, connect_timeout_s=1.0)
    c0, c1 = clusters
    q1 = ForwardQueue(c1, tmp_path / "fwd-r1", retry_budget_s=300.0)
    reg1 = SpillRegistry(tmp_path / "fwd-r1" / "registry")
    c1.attach_forwarding(q1, reg1)
    try:
        toks = tokens_owned_by(0, 2, prefix="loss")
        acked = 0
        for i in range(4):
            s = c0.ingest_json_batch([meas(t, "t", float(i), 100 + 10 * i
                                           + j) for j, t in enumerate(toks)])
            assert s["staged"] == 2
            acked += 2
        c0.flush()
        _wait(feeds[0].drained, what="feed drain")

        # ---- SIGKILL the owner: servers severed, engine abandoned ----
        host.stop(servers[0])
        feeds[0].stop()
        wal0 = c0.local.wal
        wal0.flush()

        # follower serves every acked event during the outage
        for t in toks:
            r = c1.query_events(device_token=t)
            assert r["total"] == 4, (t, r)
            assert r["stale_ms"] >= 0
        # ingest continues at the survivor; the dead owner's share spills
        s = c1.ingest_json_batch([meas(toks[0], "t", 99.0, 990)])
        assert s["spilled"] == 1

        # ---- owner restarts: WAL replay IS the acked history ---------
        wal0.close()
        cfg = dataclasses.asdict(c0.local.config)
        cfg["wal_dir"] = None
        rec = DistributedEngine(DistributedConfig(**cfg))
        rec.epoch = c0.epoch
        ro = IngestLog(tmp_path / "wal-r0", readonly=True)
        replayed = replay_records(ro, rec.ingest_json_batch,
                                  rec.ingest_binary_batch)
        ro.close()
        rec.flush()
        assert replayed == acked
        for t in toks:
            assert rec.query_events(device_token=t)["total"] == 4
        # serve the recovered engine on the old port: the spilled batch
        # redelivers exactly once
        srv0b = build_cluster_rpc(rec, "rep-secret")
        reg0b = SpillRegistry(tmp_path / "reg-r0b")
        rec.spill_registry = reg0b
        host.start(srv0b, ports[0])
        assert q1.retry_once() == 1
        rec.flush()
        assert rec.query_events(device_token=toks[0])["total"] == 5
        reg0b.close()
    finally:
        reg1.close()
        q1.stop()
        _close(clusters, feeds, host)


def test_scheduler_fireover_fencing_and_no_double_fire(tmp_path):
    """Schedules pinned to a dead owner fire on its first follower
    within the detection budget; the takeover bumps the fencing epoch;
    on recovery the owner syncs the follower-updated fired state before
    resuming — the covered window never fires twice."""
    from sitewhere_tpu.engine import EngineConfig
    from sitewhere_tpu.instance.instance import (InstanceConfig,
                                                 SiteWhereTpuInstance)
    from sitewhere_tpu.parallel.entity_sync import EntityReplicator

    clusters, feeds, appliers, servers, host, ports = \
        _mk_replicated_cluster(tmp_path, detect_s=0.8, heartbeat_s=0.15,
                               connect_timeout_s=1.0)
    c0, c1 = clusters
    insts, reps = [], []
    fires = {0: [], 1: []}
    for i, c in enumerate(clusters):
        inst = SiteWhereTpuInstance(
            InstanceConfig(engine=EngineConfig()), engine=c)
        rep = EntityReplicator(c, inst,
                               log_dir=str(tmp_path / f"elog-r{i}"))
        rep.attach()
        rep.register_rpc(host.servers[i])
        inst.scheduler.register_executor(
            "probe", lambda job, _r=i: fires[_r].append(job.meta.token))
        install_fireover(inst.scheduler, c)
        insts.append(inst)
        reps.append(rep)
    feeds[0].on_fenced = lambda: reps[0].sync_from_peers(True)

    def fire(rank, now_ms):
        return asyncio.run(insts[rank].scheduler.fire_due(now_ms))

    try:
        tok = tokens_owned_by(0, 1, prefix="fsch")[0]
        insts[0].scheduler.create_schedule(tok, "interval", "Simple",
                                           interval_s=60)
        insts[0].scheduler.create_job("job-f", tok, "probe", {})
        reps[0].drain_pushes()
        _wait(feeds[0].drained, what="initial feed round-trip")
        _wait(feeds[0].can_fire, what="fence grace clear")

        t = time.time() * 1000
        # owner alive: only the owner fires
        assert fire(0, t) == 1 and fires[0] == ["job-f"]
        assert fire(1, t) == 0 and fires[1] == []
        reps[0].drain_pushes()   # replicate the fired mark

        # ---- owner dies: feed silence past the detection budget ------
        host.stop(servers[0])
        feeds[0].stop()
        _wait(lambda: not appliers[1].leader_alive(0),
              what="feed-silence detection")
        # next window fires at the follower (takeover + fence bump)
        assert fire(1, t + 61_000) == 1 and fires[1] == ["job-f"]
        assert appliers[1].counters["fireovers"] == 1
        st = appliers[1]._standby(0)
        assert st.fence_epoch > feeds[0].epoch
        # the dead owner's window never fires twice at the follower
        assert fire(1, t + 62_000) == 0

        # ---- owner recovers ------------------------------------------
        srv0b = build_cluster_rpc(c0.local, "rep-secret")
        register_replication_rpc(srv0b, appliers[0])
        host.start(srv0b, ports[0])
        old_epoch = feeds[0].epoch
        feeds[0].start()
        _wait(lambda: feeds[0].epoch > old_epoch, what="fence adoption")
        # fencing pulled the follower's fired state: the window the
        # follower covered does NOT re-fire at the owner...
        assert insts[0].scheduler.jobs.get("job-f").last_fired_ms \
            == pytest.approx(t + 61_000)
        assert fire(0, t + 62_000) == 0
        # ...and the follower has handed firing back
        _wait(lambda: appliers[1].leader_alive(0), what="leader alive")
        assert fire(1, t + 121_500) == 0
        assert fire(0, t + 121_500) == 1
        assert fires[0] == ["job-f"] * 2 and fires[1] == ["job-f"]
    finally:
        for rep in reps:
            rep.close()
        _close(clusters, feeds, host)


def test_cron_catchup_fires_missed_window_once():
    """The catch-up predicate: a cron window that passed while the owner
    was dead fires once, late, on the follower — and only when the
    catch-up filter admits the schedule."""
    import datetime

    from sitewhere_tpu.management.schedule import ScheduleManager

    sm = ScheduleManager()
    fired = []
    sm.register_executor("probe", lambda job: fired.append(job.meta.token))
    now = datetime.datetime(2026, 8, 3, 12, 30, 30)
    now_ms = now.timestamp() * 1000
    # fires only at minute 7 of each hour; last fired two hours ago
    sm.create_schedule("cr", "cron-7", "Cron", cron="7 * * * *")
    sm.create_job("cj", "cr", "probe", {})
    sm.jobs.get("cj").last_fired_ms = now_ms - 2 * 3600_000
    # without catch-up: 12:30 is not minute 7 -> nothing fires
    assert asyncio.run(sm.fire_due(now_ms)) == 0
    # with catch-up admitted: the missed 12:07 window fires once
    sm.catchup_filter = lambda tok: True
    assert asyncio.run(sm.fire_due(now_ms)) == 1
    assert asyncio.run(sm.fire_due(now_ms + 1000)) == 0   # once only
    assert fired == ["cj"]


def test_fault_injector_is_deterministic_and_kills(tmp_path):
    from sitewhere_tpu.utils import faults

    plan = faults.FaultPlan(seed=42).drop(src=0, dst=1, prob=0.5)
    a = faults.FaultInjector(plan)
    b = faults.FaultInjector(faults.FaultPlan(seed=42).drop(src=0, dst=1,
                                                           prob=0.5))

    def outcomes(inj):
        out = []
        for _ in range(32):
            try:
                inj.before_call(0, 1, "Cluster.queryEvents")
                out.append("ok")
            except ConnectionError:
                out.append("drop")
        return out

    seq_a, seq_b = outcomes(a), outcomes(b)
    assert seq_a == seq_b and "drop" in seq_a and "ok" in seq_a

    # the kill rule refuses instantly through the real peer path
    clusters, feeds, appliers, servers, host, ports = \
        _mk_replicated_cluster(tmp_path, start_feeds=False)
    try:
        faults.install(faults.FaultPlan(seed=1).kill(1))
        t0 = time.monotonic()
        with pytest.raises(ConnectionError):
            clusters[0]._peer(1).call("Cluster.deviceCount")
        assert time.monotonic() - t0 < 0.2
        faults.clear()
        assert clusters[0]._peer(1).call("Cluster.deviceCount") == 0
    finally:
        faults.clear()
        _close(clusters, feeds, host)


def test_peer_health_state_machine():
    h = PeerHealth(down_after=2, probe_base_s=0.05)
    assert h.state(3) == "up"
    h.record_failure(3)
    assert h.state(3) == "suspect"
    h.record_failure(3)
    assert h.state(3) == DOWN and h.is_down(3)
    # backoff (2nd failure doubles it to 0.1s): an immediate probe is
    # denied; once the window passes one probe is granted and re-arms
    assert not h.should_probe(3)
    time.sleep(0.13)
    assert h.should_probe(3)
    assert not h.should_probe(3)   # re-armed by the granted probe
    h.record_success(3)
    assert h.state(3) == "up" and h.should_probe(3)


@pytest.mark.slow
def test_chaos_kill_recover_loop(tmp_path):
    """Heavy kill/recover loop under a seeded fault plan: repeated owner
    death and recovery with ingest running never loses an acked event
    and always restores failover reads within the detection budget."""
    clusters, feeds, appliers, servers, host, ports = \
        _mk_replicated_cluster(tmp_path, connect_timeout_s=1.0,
                               detect_s=0.8)
    c0, c1 = clusters
    try:
        toks = tokens_owned_by(0, 2, prefix="chaos")
        total = 0
        for round_ in range(3):
            for i in range(3):
                s = c0.ingest_json_batch(
                    [meas(t, "t", float(i), 1000 * round_ + 10 * i + j)
                     for j, t in enumerate(toks)])
                assert s["staged"] == 2
                total += 1
            c0.flush()
            _wait(feeds[0].drained, what=f"drain round {round_}")
            host.stop(servers[0])
            t0 = time.monotonic()
            r = c1.query_events(device_token=toks[0])
            assert r["total"] == total and r["stale_ms"] >= 0
            assert time.monotonic() - t0 < 5.0, "failover read must land " \
                "within the detection budget"
            # recover: same engine, new server (WAL state untouched)
            srv = build_cluster_rpc(c0.local, "rep-secret")
            register_replication_rpc(srv, appliers[0])
            host.start(srv, ports[0])
            servers[0] = srv
            _wait(lambda: not c1.health.is_down(0) or c1.health.
                  should_probe(0), what="probe window")
            c1.health.record_success(0)   # next read re-probes the owner
        q = c0.query_events(device_token=toks[0])
        assert q["total"] == total and "stale_ms" not in q
        # conservation (ISSUE 14): after the kill/recover loop both
        # ranks' flow ledgers must balance — replication publish/ack
        # and the device counters included
        from sitewhere_tpu.utils.conservation import (build_ledger,
                                                      check_conservation)

        for c in clusters:
            assert not check_conservation(build_ledger(c))
    finally:
        _close(clusters, feeds, host)
