"""Pure-numpy oracle for the pipeline semantics, used by property tests.

Implements the reference behavior directly (per-event loops, like the JVM
implementation) so the batched TPU kernels can be checked against it:
  * device lookup + active-assignment expansion
    (DeviceLookupMapper / DeviceAssignmentsLookupMapper semantics)
  * device-state merge keeping latest + 3 most recent per event class
    (RdbDeviceStateMergeStrategy semantics, most-recent-first)
  * auto-registration get-or-create (DeviceRegistrationManager semantics)
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict

import numpy as np

RECENT = 3


@dataclasses.dataclass
class OracleDeviceState:
    last_interaction: int | None = None
    meas_last: dict = dataclasses.field(default_factory=dict)      # ch -> (ts, val)
    recent_meas: list = dataclasses.field(default_factory=list)    # [(ts, seq, {ch: val})]
    recent_loc: list = dataclasses.field(default_factory=list)     # [(ts, seq, (lat,lon,elev))]
    recent_alert: list = dataclasses.field(default_factory=list)   # [(ts, seq, level, type)]
    counts: dict = dataclasses.field(default_factory=lambda: defaultdict(int))


class OracleEngine:
    """Reference-faithful per-event implementation."""

    def __init__(self, auto_register: bool = True, default_type: int = 0):
        self.auto_register = auto_register
        self.default_type = default_type
        self.token_to_device: dict[int, int] = {}
        self.device_tenant: dict[int, int] = {}
        self.device_assignments: dict[int, list[int]] = {}
        self.next_device = 0
        self.next_assignment = 0
        self.states: dict[int, OracleDeviceState] = defaultdict(OracleDeviceState)
        self.persisted: list = []  # (etype, device, assignment, tenant, ts)
        self.dead: list = []

    def register(self, token: int, tenant: int) -> int:
        dev = self.next_device
        self.next_device += 1
        self.token_to_device[token] = dev
        self.device_tenant[dev] = tenant
        aid = self.next_assignment
        self.next_assignment += 1
        self.device_assignments[dev] = [aid]
        return dev

    def process(self, events: list[dict]) -> None:
        """events: dicts with token, tenant, etype, ts, seq, values (dict ch->val),
        aux0."""
        for ev in events:
            tok, tenant = ev["token"], ev["tenant"]
            dev = self.token_to_device.get(tok)
            if dev is not None and self.device_tenant[dev] != tenant and tenant != -1:
                self.dead.append(tok)
                continue
            if dev is None:
                if self.auto_register:
                    dev = self.register(tok, tenant)
                else:
                    self.dead.append(tok)
                    continue
            st = self.states[dev]
            ts, seq, et = ev["ts"], ev["seq"], ev["etype"]
            st.last_interaction = ts if st.last_interaction is None else max(st.last_interaction, ts)
            st.counts[et] += 1
            for aid in self.device_assignments[dev]:
                self.persisted.append((et, dev, aid, tenant, ts))
            # Tie-breaking on equal timestamps: the later *arrival* wins
            # (matches the kernel's replace-on-merge semantics and the
            # reference's last-write-wins DB merge). Events are processed in
            # arrival order here, so inserting at the front + stable sort by
            # -ts keeps newest-arrival-first among equal timestamps.
            if et == 0:  # measurement
                for ch, val in ev.get("values", {}).items():
                    prev = st.meas_last.get(ch)
                    if prev is None or ts >= prev[0]:
                        st.meas_last[ch] = (ts, seq, val)
                st.recent_meas.insert(0, (ts, seq, dict(ev.get("values", {}))))
                st.recent_meas.sort(key=lambda x: -x[0])
                del st.recent_meas[RECENT:]
            elif et == 1:  # location
                st.recent_loc.insert(0, (ts, seq, tuple(ev.get("loc", (0, 0, 0)))))
                st.recent_loc.sort(key=lambda x: -x[0])
                del st.recent_loc[RECENT:]
            elif et == 2:  # alert
                st.recent_alert.insert(0, (ts, seq, int(ev.get("level", 0)), int(ev.get("atype", 0))))
                st.recent_alert.sort(key=lambda x: -x[0])
                del st.recent_alert[RECENT:]
