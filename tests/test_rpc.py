"""Control-plane RPC tests (reference: the L3 gRPC APIs + routers +
cached api channels; SURVEY.md §1-L3)."""

import asyncio

import pytest

from sitewhere_tpu.engine import EngineConfig
from sitewhere_tpu.instance.instance import InstanceConfig, SiteWhereTpuInstance
from sitewhere_tpu.rpc.client import CachedDeviceClient, RpcClient
from sitewhere_tpu.rpc.protocol import RpcError
from sitewhere_tpu.rpc.server import build_instance_rpc, system_jwt


def _instance():
    return SiteWhereTpuInstance(InstanceConfig(engine=EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=4096, batch_capacity=16, channels=4,
    )))


def test_rpc_end_to_end():
    async def go():
        inst = _instance()
        srv = build_instance_rpc(inst)
        port = await srv.start()
        cli = await RpcClient(port=port,
                              auth_token=system_jwt(inst)).connect()
        try:
            # device-management family
            dev = await cli.call("DeviceManagement.createDevice",
                                 token="r-1", deviceType="default")
            assert dev["token"] == "r-1"
            got = await cli.call("DeviceManagement.getDeviceByToken",
                                 token="r-1")
            assert got["device_type"] == "default"
            assert await cli.call("DeviceManagement.getDeviceByToken",
                                  token="ghost") is None
            listing = await cli.call("DeviceManagement.listDevices")
            assert listing["numResults"] == 1
            asgs = await cli.call("DeviceManagement.getActiveAssignments",
                                  token="r-1")
            assert len(asgs) == 1 and asgs[0]["status"] == "ACTIVE"

            # event-management family
            await cli.call("DeviceEventManagement.addDeviceEvent",
                           envelope={"deviceToken": "r-1",
                                     "type": "DeviceMeasurement",
                                     "request": {"name": "t", "value": 9.5}})
            evs = await cli.call("DeviceEventManagement.listDeviceEvents",
                                 token="r-1")
            assert evs["total"] == 1
            assert evs["events"][0]["measurements"]["t"] == 9.5

            # device-state family
            st = await cli.call("DeviceState.getDeviceState", token="r-1")
            assert st["presence"] == "PRESENT"
            states = await cli.call("DeviceState.searchDeviceStates",
                                    presence="PRESENT")
            assert len(states) == 1

            # concurrent in-flight multiplexing on one connection
            results = await asyncio.gather(*(
                cli.call("DeviceState.getDeviceState", token="r-1")
                for _ in range(16)))
            assert all(r["presence"] == "PRESENT" for r in results)

            # errors: unknown method 404, bad params 400
            with pytest.raises(RpcError) as ei:
                await cli.call("Nope.method")
            assert ei.value.code == 404
            with pytest.raises(RpcError) as ei:
                await cli.call("DeviceManagement.getDeviceByToken", bogus=1)
            assert ei.value.code == 400
        finally:
            await cli.close()
            await srv.stop()

    asyncio.new_event_loop().run_until_complete(go())


def test_rpc_tenant_dispatch_and_cache():
    async def go():
        inst = _instance()
        srv = build_instance_rpc(inst)
        port = await srv.start()
        tok = system_jwt(inst)
        # unknown tenant rejected like the reference's router
        bad = await RpcClient(port=port, tenant="nope",
                              auth_token=tok).connect()
        try:
            with pytest.raises(RpcError) as ei:
                await bad.call("DeviceManagement.listDevices")
            assert ei.value.code == 404
        finally:
            await bad.close()

        cli = await RpcClient(port=port, tenant="default",
                              auth_token=tok).connect()
        try:
            await cli.call("DeviceManagement.createDevice", token="c-1")
            cached = CachedDeviceClient(cli, ttl_s=60)
            a = await cached.get_device_by_token("c-1")
            b = await cached.get_device_by_token("c-1")
            assert a == b
            assert cached.hits == 1 and cached.misses == 1
            # negative lookups are not cached
            assert await cached.get_device_by_token("ghost") is None
            assert await cached.get_device_by_token("ghost") is None
            assert cached.misses == 3
            cached.invalidate("c-1")
            await cached.get_device_by_token("c-1")
            assert cached.misses == 4
        finally:
            await cli.close()
            await srv.stop()

    asyncio.new_event_loop().run_until_complete(go())


def test_rpc_rejects_unauthenticated_and_bad_tokens():
    """VERDICT r3 weak #6: the RPC protocol authenticates connections the
    way the reference wraps cross-service calls in system-user JWT
    security context (SystemUserRunnable / ITokenManagement)."""
    async def go():
        inst = _instance()
        srv = build_instance_rpc(inst)
        port = await srv.start()
        # no handshake at all -> every call rejected
        anon = await RpcClient(port=port).connect()
        try:
            with pytest.raises(RpcError) as ei:
                await anon.call("DeviceManagement.listDevices")
            assert ei.value.code == 401
        finally:
            await anon.close()
        # corrupt token -> handshake itself fails
        with pytest.raises(RpcError) as ei:
            await RpcClient(port=port, auth_token="not-a-jwt").connect()
        assert ei.value.code == 401
        # expired/forged signature -> 401 too
        from sitewhere_tpu.instance.auth import JwtService

        forged = JwtService(secret=b"x" * 32, expiration_s=60).generate(
            "system", ["GRP_ACCESS"])
        with pytest.raises(RpcError) as ei:
            await RpcClient(port=port, auth_token=forged).connect()
        assert ei.value.code == 401
        # the real instance token works
        cli = await RpcClient(port=port,
                              auth_token=system_jwt(inst)).connect()
        try:
            assert (await cli.call(
                "DeviceManagement.listDevices"))["numResults"] == 0
        finally:
            await cli.close()
            await srv.stop()

    asyncio.new_event_loop().run_until_complete(go())


def test_rpc_authority_gating():
    """Tenant/user management families require their granted authorities
    (reference: instance-management gRPC guarded by system/admin users)."""
    async def go():
        inst = _instance()
        inst.users.create_user("op", "pw", roles=["user"])
        srv = build_instance_rpc(inst)
        port = await srv.start()
        op_jwt = inst.jwt.generate(
            "op", inst.users.authorities_for(inst.users.users["op"]))
        # a non-admin WITHOUT any tenant binding is refused outright:
        # tenant-less calls see instance-wide data (review r4)
        unbound = await RpcClient(port=port, auth_token=op_jwt).connect()
        try:
            for method, params in (
                    ("DeviceManagement.listDevices", {}),
                    ("DeviceEventManagement.getDeviceEventById",
                     {"eventId": 0}),
                    ("DeviceEventManagement.listDeviceEvents", {})):
                with pytest.raises(RpcError) as ei:
                    await unbound.call(method, **params)
                assert ei.value.code == 403, method
        finally:
            await unbound.close()
        cli = await RpcClient(port=port, tenant="default",
                              auth_token=op_jwt).connect()
        try:
            # tenant-bound data-plane families are open to any authorized
            # authenticated caller
            await cli.call("DeviceManagement.createDevice", token="ag-1")
            # admin families are not
            for method, params in (
                    ("UserManagement.listUsers", {}),
                    ("UserManagement.createUser",
                     {"username": "x", "password": "y"}),
                    ("TenantManagement.createTenant",
                     {"token": "t-x", "name": "X"})):
                with pytest.raises(RpcError) as ei:
                    await cli.call(method, **params)
                assert ei.value.code == 403, method
        finally:
            await cli.close()
        adm = await RpcClient(port=port,
                              auth_token=system_jwt(inst)).connect()
        try:
            users = await adm.call("UserManagement.listUsers")
            assert {u["username"] for u in users} >= {"admin", "op"}
        finally:
            await adm.close()
            await srv.stop()

    asyncio.new_event_loop().run_until_complete(go())


def test_rpc_tenant_authorization():
    """Identity is not tenant access (review r4): a restricted tenant
    admits only its authorized users, matching the REST tier's
    user_can_access gate; and a tenant claim inside the JWT binds the
    connection to that tenant regardless of what the client asserts."""
    async def go():
        inst = _instance()
        inst.users.create_user("alice", "pw", roles=["user"])
        inst.users.create_user("bob", "pw", roles=["user"])
        inst.tenants.create_tenant("locked", "Locked",
                                   authorized_users=["alice"])
        srv = build_instance_rpc(inst)
        port = await srv.start()

        def jwt_for(user, tenant=None):
            return inst.jwt.generate(
                user, inst.users.authorities_for(inst.users.users[user]),
                tenant=tenant)

        # bob is not on the locked tenant's list: bound connection refused
        bob = await RpcClient(port=port, tenant="locked",
                              auth_token=jwt_for("bob")).connect()
        try:
            with pytest.raises(RpcError) as ei:
                await bob.call("DeviceManagement.listDevices")
            assert ei.value.code == 403
            # ...and naming it per-call on an unbound param fails too
            with pytest.raises(RpcError) as ei:
                await bob.call("DeviceManagement.listDevices",
                               tenant="locked")
            assert ei.value.code == 403
        finally:
            await bob.close()
        # alice is authorized
        alice = await RpcClient(port=port, tenant="locked",
                                auth_token=jwt_for("alice")).connect()
        try:
            assert (await alice.call(
                "DeviceManagement.listDevices"))["numResults"] == 0
        finally:
            await alice.close()
        # a tenant-scoped JWT pins the connection: asserting another
        # tenant is rejected, and calls run in the token's tenant
        pinned = await RpcClient(
            port=port, tenant="default",
            auth_token=jwt_for("alice", tenant="locked")).connect()
        try:
            with pytest.raises(RpcError) as ei:
                await pinned.call("DeviceManagement.listDevices")
            assert ei.value.code == 403
        finally:
            await pinned.close()
        ok = await RpcClient(
            port=port,
            auth_token=jwt_for("alice", tenant="locked")).connect()
        try:
            await ok.call("DeviceEventManagement.addDeviceEvent",
                          envelope={"deviceToken": "ta-1",
                                    "type": "DeviceMeasurement",
                                    "request": {"name": "t", "value": 1.0}})
            assert inst.engine.query_events(tenant="locked")["total"] == 1
            assert inst.engine.query_events(tenant="default")["total"] == 0
        finally:
            await ok.close()
            await srv.stop()

    asyncio.new_event_loop().run_until_complete(go())


def test_rpc_full_family_surface():
    """VERDICT r3 missing #3 parity check: every reference gRPC ``*Impl``
    service family is registered, and one round-trip per family works
    (DeviceManagementImpl.java:75-90; asset/batch/schedule/label/tenant/
    user gRPC servers)."""
    async def go():
        inst = _instance()
        srv = build_instance_rpc(inst)
        # family enumeration: the reference's per-service gRPC servers
        registered = {m.split(".")[0] for m in srv.methods}
        assert registered >= {
            "DeviceManagement", "DeviceEventManagement", "DeviceState",
            "AssetManagement", "BatchManagement", "ScheduleManagement",
            "LabelGeneration", "TenantManagement", "UserManagement"}
        # DeviceManagement covers the entity families of
        # RdbDeviceManagement: types/statuses/commands/alarms/customers/
        # areas/zones/groups beyond plain device CRUD
        dm = {m.split(".")[1] for m in srv.methods
              if m.startswith("DeviceManagement.")}
        for stem in ("DeviceType", "DeviceStatus", "DeviceCommand",
                     "DeviceAlarm", "Customer", "Area", "Zone",
                     "DeviceGroup"):
            assert any(stem in m for m in dm), stem

        port = await srv.start()
        cli = await RpcClient(port=port,
                              auth_token=system_jwt(inst)).connect()
        try:
            # --- device-management entity families ---------------------
            dt = await cli.call("DeviceManagement.createDeviceType",
                                token="ff-type", name="FF")
            assert dt["token"] == "ff-type"
            assert (await cli.call(
                "DeviceManagement.listDeviceTypes"))["numResults"] >= 1
            await cli.call("DeviceManagement.createDevice",
                           token="ff-1", deviceType="ff-type")
            await cli.call("DeviceManagement.createDeviceStatus",
                           token="ff-ok", deviceType="ff-type",
                           code="ok", name="OK")
            assert (await cli.call("DeviceManagement.listDeviceStatuses",
                                   deviceType="ff-type"))[0]["code"] == "ok"
            await cli.call("DeviceManagement.createDeviceCommand",
                           token="ff-reboot", deviceType="ff-type",
                           name="reboot")
            assert (await cli.call(
                "DeviceManagement.listDeviceCommands",
                deviceType="ff-type"))[0]["name"] == "reboot"
            await cli.call("DeviceManagement.createDeviceAlarm",
                           token="ff-al", deviceToken="ff-1",
                           message="hot")
            await cli.call("DeviceManagement.acknowledgeDeviceAlarm",
                           token="ff-al")
            al = await cli.call("DeviceManagement.resolveDeviceAlarm",
                                token="ff-al")
            assert al["state"] == "Resolved"
            await cli.call("DeviceManagement.createAreaType",
                           token="ff-site", name="Site")
            await cli.call("DeviceManagement.createArea", token="ff-a1",
                           areaType="ff-site", name="A1")
            tree = await cli.call("DeviceManagement.getAreaTree")
            assert any(n["entity"]["token"] == "ff-a1" for n in tree)
            await cli.call("DeviceManagement.createZone", token="ff-z1",
                           areaToken="ff-a1", name="Z1",
                           bounds=[[0, 0], [0, 1], [1, 0]])
            assert (await cli.call("DeviceManagement.listZones",
                                   areaToken="ff-a1"))[0]["token"] == "ff-z1"
            await cli.call("DeviceManagement.createDeviceGroup",
                           token="ff-g", name="G", roles=["prod"])
            await cli.call("DeviceManagement.addDeviceGroupElements",
                           groupToken="ff-g",
                           elements=[{"device": "ff-1", "roles": ["prod"]}])
            assert len(await cli.call(
                "DeviceManagement.listDeviceGroupElements",
                groupToken="ff-g")) == 1

            # --- event-management: by-id lookup ------------------------
            # event ids surface through feed records (the outbound fork),
            # same as the REST /api/events/id/{id} flow
            feed = inst.engine.make_feed_consumer("rpc-ids")
            await cli.call("DeviceEventManagement.addDeviceEvent",
                           envelope={"deviceToken": "ff-1",
                                     "type": "DeviceMeasurement",
                                     "request": {"name": "t", "value": 1.5}})
            evs = await cli.call("DeviceEventManagement.listDeviceEvents",
                                 token="ff-1")
            assert evs["total"] == 1
            eid = feed.poll()[0].event_id
            ev = await cli.call("DeviceEventManagement.getDeviceEventById",
                                eventId=eid)
            assert ev["measurements"]["t"] == 1.5

            # --- asset-management --------------------------------------
            await cli.call("AssetManagement.createAssetType",
                           token="ff-at", name="AT")
            await cli.call("AssetManagement.createAsset", token="ff-as",
                           assetType="ff-at", name="AS")
            assert (await cli.call("AssetManagement.getAssetByToken",
                                   token="ff-as"))["name"] == "AS"
            assert (await cli.call(
                "AssetManagement.listAssets"))["numResults"] == 1

            # --- batch-operations --------------------------------------
            op = await cli.call(
                "BatchManagement.createBatchCommandInvocation",
                token="ff-b1", deviceTokens=["ff-1"],
                commandToken="ff-reboot")
            assert op["counts"]["SUCCEEDED"] == 1
            assert (await cli.call("BatchManagement.getBatchOperation",
                                   token="ff-b1"))["status"] == "Finished"
            assert (await cli.call(
                "BatchManagement.listBatchOperations"))["numResults"] == 1
            els = await cli.call("BatchManagement.listBatchElements",
                                 token="ff-b1")
            assert els[0]["status"] == "SUCCEEDED"

            # --- schedule-management -----------------------------------
            await cli.call("ScheduleManagement.createSchedule",
                           token="ff-s", name="S", triggerType="Simple",
                           intervalS=60)
            await cli.call("ScheduleManagement.createScheduledJob",
                           token="ff-j", scheduleToken="ff-s",
                           jobType="CommandInvocation",
                           configuration={"deviceToken": "ff-1",
                                          "commandToken": "ff-reboot"})
            assert (await cli.call(
                "ScheduleManagement.listSchedules"))["numResults"] == 1
            assert (await cli.call(
                "ScheduleManagement.listScheduledJobs"))["numResults"] == 1

            # --- label-generation --------------------------------------
            gens = await cli.call("LabelGeneration.listGenerators")
            assert gens[0]["id"] == "qrcode"
            lab = await cli.call("LabelGeneration.getLabel",
                                 entityType="device", token="ff-1")
            import base64 as b64
            assert b64.b64decode(lab["image"])[:8] == b"\x89PNG\r\n\x1a\n"

            # --- tenant + user management (admin families) -------------
            t = await cli.call("TenantManagement.createTenant",
                               token="ff-t", name="FFT")
            assert t["bootstrap_state"] == "Bootstrapped"
            assert (await cli.call("TenantManagement.getTenantByToken",
                                   token="ff-t"))["name"] == "FFT"
            assert (await cli.call(
                "TenantManagement.listTenants"))["numResults"] == 2
            await cli.call("UserManagement.createUser", username="ff-u",
                           password="pw", roles=["user"])
            await cli.call("TenantManagement.authorizeUser",
                           token="ff-t", username="ff-u")
            u = await cli.call("UserManagement.addRoles",
                               username="ff-u", roles=["admin"])
            assert set(u["roles"]) == {"user", "admin"}
            u = await cli.call("UserManagement.removeRoles",
                               username="ff-u", roles=["admin"])
            assert u["roles"] == ["user"]
            auths = await cli.call("UserManagement.getAuthoritiesForUser",
                                   username="ff-u")
            assert "VIEW_SERVER_INFORMATION" in auths
            await cli.call("UserManagement.updateUser", username="ff-u",
                           enabled=False)
            assert (await cli.call("UserManagement.getUserByUsername",
                                   username="ff-u"))["enabled"] is False
            assert (await cli.call("UserManagement.deleteUser",
                                   username="ff-u"))["deleted"] is True
        finally:
            await cli.close()
            await srv.stop()

    asyncio.new_event_loop().run_until_complete(go())


def test_rpc_tenant_binding_enforced():
    """A tenant-bound connection cannot address another tenant's data
    (executeInTenantEngine semantics)."""
    async def go():
        inst = _instance()
        inst.tenants.create_tenant("t-b", "Tenant B")
        srv = build_instance_rpc(inst)
        port = await srv.start()
        feed = inst.engine.make_feed_consumer("tb-ids")
        cli = await RpcClient(port=port, tenant="default",
                              auth_token=system_jwt(inst)).connect()
        try:
            await cli.call("DeviceEventManagement.addDeviceEvent",
                           envelope={"deviceToken": "tb-1",
                                     "type": "DeviceMeasurement",
                                     "request": {"name": "t", "value": 1.0}},
                           tenant="t-b")   # override attempt ignored
            evs = await cli.call("DeviceEventManagement.listDeviceEvents",
                                 tenant="t-b")  # forced back to 'default'
            assert evs["total"] == 1  # sees its OWN tenant's event
            assert inst.engine.query_events(tenant="t-b")["total"] == 0
            assert inst.engine.query_events(tenant="default")["total"] == 1
            # by-id lookups honor the binding too: ids are enumerable ring
            # positions, so a t-b-bound connection must not read default's
            # rows (review r4 finding)
            eid = feed.poll()[0].event_id
            assert await cli.call("DeviceEventManagement.getDeviceEventById",
                                  eventId=eid) is not None
            tb = await RpcClient(port=port, tenant="t-b",
                                 auth_token=system_jwt(inst)).connect()
            try:
                assert await tb.call(
                    "DeviceEventManagement.getDeviceEventById",
                    eventId=eid) is None
            finally:
                await tb.close()
        finally:
            await cli.close()
            await srv.stop()

    asyncio.new_event_loop().run_until_complete(go())


def test_attachment_frames_round_trip_and_spoof_protection():
    """Binary attachment frames (protocol.py ATTACH_BIT): a bytes blob
    rides the frame raw after the JSON body. Covers: round-trip through
    a live server, json-borne "_attachment" impostors discarded,
    attachments dropped for handlers that don't declare one, and the
    oversize guards."""
    import asyncio

    import pytest as _pytest

    from sitewhere_tpu.rpc.client import RpcClient
    from sitewhere_tpu.rpc.protocol import (MAX_FRAME, RpcError,
                                            encode_frame)
    from sitewhere_tpu.rpc.server import RpcServer

    srv = RpcServer()
    got: dict = {}

    def takes_blob(lens: list, _attachment: bytes = None):
        got["blob"] = _attachment
        got["type"] = type(_attachment).__name__
        return {"n": len(_attachment) if _attachment is not None else -1,
                "lens_ok": sum(lens) == (len(_attachment)
                                         if _attachment else 0)}

    def no_blob(x: int):
        return {"x": x}

    srv.register("T.blob", takes_blob)
    srv.register("T.plain", no_blob)

    async def drive():
        port = await srv.start()
        cli = await RpcClient(port=port).connect()
        try:
            blob = bytes(range(256)) * 64
            r = await cli.call("T.blob", lens=[256] * 64,
                               _attachment=blob)
            assert r == {"n": len(blob), "lens_ok": True}
            assert got["blob"] == blob and got["type"] == "bytes"
            # no attachment at all: handler sees None
            r = await cli.call("T.blob", lens=[5])
            assert r == {"n": -1, "lens_ok": False}
            # handler without the param never sees a stray attachment
            r = await cli.call("T.plain", x=7, _attachment=b"stray")
            assert r == {"x": 7}
            # spoofed json impostor: encode by hand, bypassing the client
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", port)
            writer.write(encode_frame(
                {"id": 99, "method": "T.blob",
                 "params": {"lens": [5], "_attachment": "fake"}}))
            await writer.drain()
            from sitewhere_tpu.rpc.protocol import read_frame
            resp = await read_frame(reader)
            assert resp["id"] == 99
            assert resp["result"] == {"n": -1, "lens_ok": False}
            writer.close()
        finally:
            await cli.close()
            await srv.stop()

    asyncio.new_event_loop().run_until_complete(drive())

    with _pytest.raises(RpcError, match="attachment too large"):
        encode_frame({"id": 1}, b"\0" * (MAX_FRAME + 1))
