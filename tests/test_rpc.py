"""Control-plane RPC tests (reference: the L3 gRPC APIs + routers +
cached api channels; SURVEY.md §1-L3)."""

import asyncio

import pytest

from sitewhere_tpu.engine import EngineConfig
from sitewhere_tpu.instance.instance import InstanceConfig, SiteWhereTpuInstance
from sitewhere_tpu.rpc.client import CachedDeviceClient, RpcClient
from sitewhere_tpu.rpc.protocol import RpcError
from sitewhere_tpu.rpc.server import build_instance_rpc


def _instance():
    return SiteWhereTpuInstance(InstanceConfig(engine=EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=4096, batch_capacity=16, channels=4,
    )))


def test_rpc_end_to_end():
    async def go():
        inst = _instance()
        srv = build_instance_rpc(inst)
        port = await srv.start()
        cli = await RpcClient(port=port).connect()
        try:
            # device-management family
            dev = await cli.call("DeviceManagement.createDevice",
                                 token="r-1", deviceType="default")
            assert dev["token"] == "r-1"
            got = await cli.call("DeviceManagement.getDeviceByToken",
                                 token="r-1")
            assert got["device_type"] == "default"
            assert await cli.call("DeviceManagement.getDeviceByToken",
                                  token="ghost") is None
            listing = await cli.call("DeviceManagement.listDevices")
            assert listing["numResults"] == 1
            asgs = await cli.call("DeviceManagement.getActiveAssignments",
                                  token="r-1")
            assert len(asgs) == 1 and asgs[0]["status"] == "ACTIVE"

            # event-management family
            await cli.call("DeviceEventManagement.addDeviceEvent",
                           envelope={"deviceToken": "r-1",
                                     "type": "DeviceMeasurement",
                                     "request": {"name": "t", "value": 9.5}})
            evs = await cli.call("DeviceEventManagement.listDeviceEvents",
                                 token="r-1")
            assert evs["total"] == 1
            assert evs["events"][0]["measurements"]["t"] == 9.5

            # device-state family
            st = await cli.call("DeviceState.getDeviceState", token="r-1")
            assert st["presence"] == "PRESENT"
            states = await cli.call("DeviceState.searchDeviceStates",
                                    presence="PRESENT")
            assert len(states) == 1

            # concurrent in-flight multiplexing on one connection
            results = await asyncio.gather(*(
                cli.call("DeviceState.getDeviceState", token="r-1")
                for _ in range(16)))
            assert all(r["presence"] == "PRESENT" for r in results)

            # errors: unknown method 404, bad params 400
            with pytest.raises(RpcError) as ei:
                await cli.call("Nope.method")
            assert ei.value.code == 404
            with pytest.raises(RpcError) as ei:
                await cli.call("DeviceManagement.getDeviceByToken", bogus=1)
            assert ei.value.code == 400
        finally:
            await cli.close()
            await srv.stop()

    asyncio.new_event_loop().run_until_complete(go())


def test_rpc_tenant_dispatch_and_cache():
    async def go():
        inst = _instance()
        srv = build_instance_rpc(inst)
        port = await srv.start()
        # unknown tenant rejected like the reference's router
        bad = await RpcClient(port=port, tenant="nope").connect()
        try:
            with pytest.raises(RpcError) as ei:
                await bad.call("DeviceManagement.listDevices")
            assert ei.value.code == 404
        finally:
            await bad.close()

        cli = await RpcClient(port=port, tenant="default").connect()
        try:
            await cli.call("DeviceManagement.createDevice", token="c-1")
            cached = CachedDeviceClient(cli, ttl_s=60)
            a = await cached.get_device_by_token("c-1")
            b = await cached.get_device_by_token("c-1")
            assert a == b
            assert cached.hits == 1 and cached.misses == 1
            # negative lookups are not cached
            assert await cached.get_device_by_token("ghost") is None
            assert await cached.get_device_by_token("ghost") is None
            assert cached.misses == 3
            cached.invalidate("c-1")
            await cached.get_device_by_token("c-1")
            assert cached.misses == 4
        finally:
            await cli.close()
            await srv.stop()

    asyncio.new_event_loop().run_until_complete(go())


def test_rpc_tenant_binding_enforced():
    """A tenant-bound connection cannot address another tenant's data
    (executeInTenantEngine semantics)."""
    async def go():
        inst = _instance()
        inst.tenants.create_tenant("t-b", "Tenant B")
        srv = build_instance_rpc(inst)
        port = await srv.start()
        cli = await RpcClient(port=port, tenant="default").connect()
        try:
            await cli.call("DeviceEventManagement.addDeviceEvent",
                           envelope={"deviceToken": "tb-1",
                                     "type": "DeviceMeasurement",
                                     "request": {"name": "t", "value": 1.0}},
                           tenant="t-b")   # override attempt ignored
            evs = await cli.call("DeviceEventManagement.listDeviceEvents",
                                 tenant="t-b")  # forced back to 'default'
            assert evs["total"] == 1  # sees its OWN tenant's event
            assert inst.engine.query_events(tenant="t-b")["total"] == 0
            assert inst.engine.query_events(tenant="default")["total"] == 1
        finally:
            await cli.close()
            await srv.stop()

    asyncio.new_event_loop().run_until_complete(go())
