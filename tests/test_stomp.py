"""STOMP 1.2 edge tests: frame codec, broker queue/topic semantics, and the
ActiveMQ-equivalent receivers (sources/activemq/*.java parity)."""

import asyncio
import json

import pytest

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.ingest.decoders import JsonDeviceRequestDecoder
from sitewhere_tpu.ingest.sources import EventSourcesManager, InboundEventSource
from sitewhere_tpu.ingest.stomp import (
    ActiveMqBrokerEventReceiver,
    ActiveMqClientEventReceiver,
    StompBroker,
    StompClient,
    encode_frame,
    read_frame,
)


def measurement_json(token="dev-1"):
    return json.dumps({
        "deviceToken": token,
        "type": "DeviceMeasurement",
        "request": {"name": "temp", "value": 20.0},
    }).encode()


def test_frame_codec_roundtrip():
    async def run():
        frame = encode_frame("SEND", {"destination": "/queue/q",
                                      "weird:key": "line\nbreak"}, b"\x00binary\x00")
        reader = asyncio.StreamReader()
        reader.feed_data(b"\n" + frame)  # leading heart-beat newline skipped
        reader.feed_eof()
        command, headers, body = await read_frame(reader)
        assert command == "SEND"
        assert headers["destination"] == "/queue/q"
        assert headers["weird:key"] == "line\nbreak"
        assert body == b"\x00binary\x00"

    asyncio.run(run())


def test_queue_round_robin_and_topic_fanout():
    async def run():
        broker = StompBroker()
        await broker.start()
        got = {"a": [], "b": []}
        try:
            clients = {}
            for name in ("a", "b"):
                c = StompClient("127.0.0.1", broker.bound_port)
                c.on_message = (lambda n: lambda d, h, body: got[n].append(body))(name)
                await c.connect()
                await c.subscribe("/queue/work")
                await c.subscribe("/topic/news")
                clients[name] = c

            pub = StompClient("127.0.0.1", broker.bound_port)
            await pub.connect()
            for i in range(4):
                await pub.send("/queue/work", b"q%d" % i)
            await pub.send("/topic/news", b"t0")
            await asyncio.sleep(0.2)
            # queue: each message to exactly one consumer; topic: to both
            q_a = [m for m in got["a"] if m.startswith(b"q")]
            q_b = [m for m in got["b"] if m.startswith(b"q")]
            assert sorted(q_a + q_b) == [b"q0", b"q1", b"q2", b"q3"]
            assert len(q_a) == 2 and len(q_b) == 2  # round-robin
            assert got["a"].count(b"t0") == 1 and got["b"].count(b"t0") == 1
            for c in clients.values():
                await c.disconnect()
            await pub.disconnect()
        finally:
            await broker.stop()

    asyncio.run(run())


def test_queue_buffers_until_subscriber():
    async def run():
        broker = StompBroker()
        await broker.start()
        got = []
        try:
            pub = StompClient("127.0.0.1", broker.bound_port)
            await pub.connect()
            await pub.send("/queue/later", b"early")
            sub = StompClient("127.0.0.1", broker.bound_port)
            sub.on_message = lambda d, h, body: got.append(body)
            await sub.connect()
            await sub.subscribe("/queue/later")
            await asyncio.sleep(0.2)
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await broker.stop()
        assert got == [b"early"]

    asyncio.run(run())


def _engine_and_mgr():
    engine = Engine(EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=4096, batch_capacity=16, channels=4,
    ))
    mgr = EventSourcesManager(
        on_event_request=engine.process,
        on_registration_request=engine.process,
    )
    return engine, mgr


def test_activemq_broker_receiver_end_to_end():
    async def run():
        engine, mgr = _engine_and_mgr()
        recv = ActiveMqBrokerEventReceiver("swbroker", "SITEWHERE.IN",
                                           num_consumers=2)
        mgr.add_source(InboundEventSource("amq", JsonDeviceRequestDecoder(), [recv]))
        await mgr.initialize()
        await mgr.start()
        try:
            pub = StompClient("127.0.0.1", recv.bound_port)
            await pub.connect()
            await pub.send("/queue/SITEWHERE.IN", measurement_json("amq-1"))
            await pub.send("/queue/SITEWHERE.IN", measurement_json("amq-2"))
            await asyncio.sleep(0.3)
            await pub.disconnect()
        finally:
            await mgr.stop()
        engine.flush()
        assert engine.metrics()["registered"] == 2
        return engine

    asyncio.run(run())


def test_activemq_client_receiver_against_external_broker():
    async def run():
        broker = StompBroker(broker_name="external")
        await broker.start()
        engine, mgr = _engine_and_mgr()
        recv = ActiveMqClientEventReceiver("127.0.0.1", broker.bound_port,
                                           "SITEWHERE.IN", num_consumers=3)
        mgr.add_source(InboundEventSource("amq", JsonDeviceRequestDecoder(), [recv]))
        await mgr.initialize()
        await mgr.start()
        try:
            pub = StompClient("127.0.0.1", broker.bound_port)
            await pub.connect()
            for i in range(6):
                await pub.send("/queue/SITEWHERE.IN", measurement_json(f"c-{i}"))
            await asyncio.sleep(0.3)
            await pub.disconnect()
        finally:
            await mgr.stop()
            await broker.stop()
        engine.flush()
        # competing consumers: all 6 arrive exactly once
        assert engine.metrics()["registered"] == 6
        assert engine.metrics()["persisted"] == 6

    asyncio.run(run())


def test_receiver_requires_names():
    with pytest.raises(ValueError, match="Broker name"):
        ActiveMqBrokerEventReceiver("", "q")
    with pytest.raises(ValueError, match="Queue name"):
        ActiveMqBrokerEventReceiver("b", "")
    with pytest.raises(ValueError, match="Queue name"):
        ActiveMqClientEventReceiver("h", 1, "")
