"""Ingestion-edge tests: decoders, dedup, receivers (socket/websocket/MQTT/
CoAP), and the engine integration (decode -> batch -> TPU step -> state)."""

import asyncio
import json

import numpy as np
import pytest

from sitewhere_tpu.core.types import AlertLevel
from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.ingest.decoders import (
    BinaryEventDecoder,
    CompositeDecoder,
    JsonBatchEventDecoder,
    JsonDeviceRequestDecoder,
    ScriptedDecoder,
    encode_binary_request,
)
from sitewhere_tpu.ingest.dedup import AlternateIdDeduplicator
from sitewhere_tpu.ingest.requests import (
    DecodedRequest,
    EventDecodeException,
    RequestType,
)
from sitewhere_tpu.ingest.sources import (
    EventSourcesManager,
    InboundEventSource,
    InMemoryEventReceiver,
    SocketEventReceiver,
    WebSocketEventReceiver,
)


def measurement_json(token="dev-1", name="fuel.level", value=123.4, **kw):
    """The reference's canonical JSON measurement message
    (EventsHelper.generateJsonMeasurementsMessage)."""
    return json.dumps(
        {
            "deviceToken": token,
            "type": "DeviceMeasurement",
            "request": {"name": name, "value": value, **kw},
        }
    ).encode()


# --- decoders ----------------------------------------------------------------


def test_json_decoder_measurement():
    (req,) = JsonDeviceRequestDecoder().decode(measurement_json(), {})
    assert req.type is RequestType.DEVICE_MEASUREMENT
    assert req.device_token == "dev-1"
    assert req.measurements == {"fuel.level": 123.4}


def test_json_decoder_location_alert_ack():
    d = JsonDeviceRequestDecoder()
    (loc,) = d.decode(
        json.dumps(
            {"deviceToken": "d", "type": "DeviceLocation",
             "request": {"latitude": 33.7, "longitude": -84.4, "elevation": 10}}
        ).encode(),
        {},
    )
    assert (loc.latitude, loc.longitude, loc.elevation) == (33.7, -84.4, 10.0)
    (al,) = d.decode(
        json.dumps(
            {"deviceToken": "d", "type": "DeviceAlert",
             "request": {"type": "engine.overheat", "level": "Critical",
                         "message": "too hot"}}
        ).encode(),
        {},
    )
    assert al.alert_type == "engine.overheat"
    assert al.alert_level is AlertLevel.CRITICAL
    (ack,) = d.decode(
        json.dumps(
            {"deviceToken": "d", "type": "Acknowledge",
             "request": {"originatingEventId": "evt-9", "response": "ok"}}
        ).encode(),
        {},
    )
    assert ack.type is RequestType.ACKNOWLEDGE
    assert ack.originating_event_id == "evt-9"


def test_json_decoder_registration_and_aliases():
    d = JsonDeviceRequestDecoder()
    (reg,) = d.decode(
        json.dumps(
            {"hardwareId": "d9", "type": "RegisterDevice",
             "request": {"deviceTypeToken": "mega2560", "areaToken": "peachtree"}}
        ).encode(),
        {},
    )
    assert reg.type is RequestType.REGISTER_DEVICE
    assert reg.device_token == "d9"
    assert reg.extras["deviceTypeToken"] == "mega2560"


def test_json_decoder_errors():
    d = JsonDeviceRequestDecoder()
    for bad in [b"{not json", b"[1,2]", b"{}",
                json.dumps({"type": "DeviceMeasurement", "request": {}}).encode(),
                json.dumps({"deviceToken": "d", "type": "Nope", "request": {}}).encode()]:
        with pytest.raises((EventDecodeException, ValueError)):
            d.decode(bad, {})


def test_batch_decoder():
    payload = json.dumps(
        {
            "deviceToken": "shared",
            "requests": [
                {"type": "DeviceMeasurement", "request": {"name": "a", "value": 1}},
                {"type": "DeviceMeasurement", "request": {"name": "b", "value": 2}},
            ],
        }
    ).encode()
    reqs = JsonBatchEventDecoder().decode(payload, {})
    assert [r.device_token for r in reqs] == ["shared", "shared"]


def test_binary_roundtrip():
    d = BinaryEventDecoder()
    for req in [
        DecodedRequest(type=RequestType.DEVICE_MEASUREMENT, device_token="dev-7",
                       event_ts_ms=1234, measurements={"t": 20.5, "rpm": 900.0}),
        DecodedRequest(type=RequestType.DEVICE_LOCATION, device_token="x",
                       latitude=1.5, longitude=-2.5, elevation=3.0),
        DecodedRequest(type=RequestType.DEVICE_ALERT, device_token="y",
                       alert_type="fire", alert_level=AlertLevel.ERROR,
                       alert_message="hot"),
    ]:
        (back,) = d.decode(encode_binary_request(req), {})
        assert back.type is req.type
        assert back.device_token == req.device_token
        if req.measurements:
            assert back.measurements == req.measurements
        if req.latitude is not None:
            assert (back.latitude, back.longitude, back.elevation) == (1.5, -2.5, 3.0)
        if req.alert_type:
            assert (back.alert_type, back.alert_level) == ("fire", AlertLevel.ERROR)
    with pytest.raises(EventDecodeException):
        d.decode(b"\x07garbage", {})


def test_composite_and_scripted_decoders():
    inner = JsonDeviceRequestDecoder()

    def extractor(payload, metadata):
        obj = json.loads(payload)
        return obj["deviceType"], json.dumps(obj["body"]).encode()

    comp = CompositeDecoder(extractor, {"sensor": inner})
    payload = json.dumps(
        {"deviceType": "sensor",
         "body": {"deviceToken": "c1", "type": "DeviceMeasurement",
                  "request": {"name": "x", "value": 5}}}
    ).encode()
    (req,) = comp.decode(payload, {})
    assert req.device_token == "c1"
    with pytest.raises(EventDecodeException):
        comp.decode(json.dumps({"deviceType": "unknown", "body": {}}).encode(), {})

    scripted = ScriptedDecoder(
        lambda p, m: [DecodedRequest(type=RequestType.DEVICE_MEASUREMENT,
                                     device_token=p.decode(),
                                     measurements={"v": 1.0})]
    )
    (req,) = scripted.decode(b"tok", {})
    assert req.device_token == "tok"


def test_alternate_id_dedup():
    d = AlternateIdDeduplicator(capacity=4)
    r1 = DecodedRequest(type=RequestType.DEVICE_MEASUREMENT, device_token="a",
                        alternate_id="m1", measurements={"x": 1})
    assert not d.is_duplicate(r1)
    assert d.is_duplicate(r1)
    r2 = DecodedRequest(type=RequestType.DEVICE_MEASUREMENT, device_token="a",
                        measurements={"x": 1})  # no alternate id -> never dup
    assert not d.is_duplicate(r2)
    assert not d.is_duplicate(r2)


# --- sources + receivers -----------------------------------------------------


def _mini_engine():
    return Engine(EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=4096, batch_capacity=16, channels=4,
    ))


def _wire(engine):
    mgr = EventSourcesManager(
        on_event_request=engine.process,
        on_registration_request=engine.process,
    )
    return mgr


def test_source_decode_and_dlq():
    engine = _mini_engine()
    mgr = _wire(engine)
    recv = InMemoryEventReceiver()
    src = InboundEventSource("json-src", JsonDeviceRequestDecoder(), [recv],
                             AlternateIdDeduplicator())
    mgr.add_source(src)
    assert recv.submit(measurement_json("m-1")) == 1
    assert recv.submit(b"not json at all") == 0
    assert recv.submit(measurement_json("m-1", alternateId="dup-1")) == 1
    assert recv.submit(measurement_json("m-1", alternateId="dup-1")) == 0  # dup
    assert src.decoded_count == 2
    assert src.failed_count == 1
    assert src.duplicate_count == 1
    assert len(mgr.failed_decodes) == 1
    engine.flush()
    m = engine.metrics()
    assert m["processed"] == 2
    assert m["registered"] == 1


def test_engine_end_to_end_state():
    engine = _mini_engine()
    mgr = _wire(engine)
    recv = InMemoryEventReceiver()
    mgr.add_source(InboundEventSource("s", JsonDeviceRequestDecoder(), [recv]))
    recv.submit(measurement_json("dev-A", "temp", 21.5))
    recv.submit(measurement_json("dev-A", "temp", 23.5))
    recv.submit(json.dumps(
        {"deviceToken": "dev-A", "type": "DeviceLocation",
         "request": {"latitude": 1.0, "longitude": 2.0}}
    ).encode())
    engine.flush()
    st = engine.get_device_state("dev-A")
    assert st is not None
    assert st["measurements"]["temp"]["value"] == 23.5
    assert st["presence"] == "PRESENT"
    assert len(st["recent_locations"]) == 1
    assert st["event_counts"]["MEASUREMENT"] == 2
    # registration request path (explicit metadata beats auto-register)
    recv.submit(json.dumps(
        {"deviceToken": "dev-B", "type": "RegisterDevice",
         "request": {"deviceTypeToken": "mega2560", "areaToken": "peachtree"}}
    ).encode())
    info = engine.get_device("dev-B")
    assert info is not None and info.device_type == "mega2560"
    assert not info.auto_registered


def test_socket_receiver_framings():
    async def run():
        engine = _mini_engine()
        mgr = _wire(engine)
        recv = SocketEventReceiver(framing="newline")
        mgr.add_source(InboundEventSource("sock", JsonDeviceRequestDecoder(), [recv]))
        await mgr.initialize()
        await mgr.start()
        try:
            r, w = await asyncio.open_connection("127.0.0.1", recv.bound_port)
            w.write(measurement_json("sock-1") + b"\n" + measurement_json("sock-2") + b"\n")
            await w.drain()
            w.close()
            await asyncio.sleep(0.2)
        finally:
            await mgr.stop()
        engine.flush()
        assert engine.metrics()["registered"] == 2

    asyncio.run(run())


def test_websocket_receiver():
    pytest.importorskip("websockets")

    async def run():
        engine = _mini_engine()
        mgr = _wire(engine)
        recv = WebSocketEventReceiver()
        mgr.add_source(InboundEventSource("ws", JsonDeviceRequestDecoder(), [recv]))
        await mgr.initialize()
        await mgr.start()
        try:
            import websockets

            async with websockets.connect(f"ws://127.0.0.1:{recv.bound_port}") as ws:
                await ws.send(measurement_json("ws-1"))
                await ws.send(measurement_json("ws-2").decode())  # text frame
                await asyncio.sleep(0.2)
        finally:
            await mgr.stop()
        engine.flush()
        assert engine.metrics()["registered"] == 2

    asyncio.run(run())


def test_mqtt_broker_and_receiver():
    from sitewhere_tpu.ingest.mqtt import MqttBroker, MqttClient, MqttEventReceiver

    async def run():
        broker = MqttBroker()
        await broker.start()
        engine = _mini_engine()
        mgr = _wire(engine)
        recv = MqttEventReceiver("127.0.0.1", broker.bound_port,
                                 topic="sitewhere/input/#")
        mgr.add_source(InboundEventSource("mqtt", JsonDeviceRequestDecoder(), [recv]))
        await mgr.initialize()
        await mgr.start()
        try:
            pub = MqttClient("127.0.0.1", broker.bound_port, "publisher")
            await pub.connect()
            await pub.publish("sitewhere/input/mq-1", measurement_json("mq-1"), qos=0)
            await pub.publish("sitewhere/input/mq-2", measurement_json("mq-2"), qos=1)
            await pub.publish("other/topic", measurement_json("mq-3"))  # not subscribed
            await asyncio.sleep(0.3)
            await pub.disconnect()
        finally:
            await mgr.stop()
            await broker.stop()
        engine.flush()
        assert engine.metrics()["registered"] == 2  # mq-3 filtered by topic

    asyncio.run(run())


def test_coap_receiver_and_client():
    from sitewhere_tpu.ingest.coap import (
        CoapClient,
        CoapServerEventReceiver,
        CREATED,
        POST,
    )

    async def run():
        engine = _mini_engine()
        mgr = _wire(engine)
        recv = CoapServerEventReceiver()
        mgr.add_source(InboundEventSource("coap", JsonDeviceRequestDecoder(), [recv]))
        await mgr.initialize()
        await mgr.start()
        try:
            client = CoapClient("127.0.0.1", recv.bound_port)
            reply = await client.request(POST, ["events", "co-1"],
                                         measurement_json("co-1"))
            assert reply["code"] == CREATED
            await asyncio.sleep(0.1)
        finally:
            await mgr.stop()
        engine.flush()
        assert engine.metrics()["registered"] == 1

    asyncio.run(run())


def test_native_fast_ingest_path():
    """Native C++ batch decode -> vectorized staging -> pipeline step."""
    import json as _json

    from sitewhere_tpu.ingest.fast_decode import native_available

    if not native_available():
        pytest.skip("native library unavailable")
    engine = _mini_engine()
    payloads = [
        _json.dumps({"deviceToken": f"n-{i % 5}", "type": "DeviceMeasurement",
                     "request": {"name": "temp", "value": 20.0 + i,
                                 "eventDate": int(engine.epoch.base_unix_s * 1000) + i}}
                    ).encode()
        for i in range(12)
    ]
    payloads.append(_json.dumps(
        {"deviceToken": "n-loc", "type": "DeviceLocation",
         "request": {"latitude": 1.0, "longitude": 2.0}}).encode())
    payloads.append(_json.dumps(
        {"deviceToken": "n-0", "type": "DeviceAlert",
         "request": {"type": "hot", "level": "Error"}}).encode())
    payloads.append(b"{broken")
    summary = engine.ingest_json_batch(payloads)
    assert summary["decoded"] == 14
    assert summary["failed"] == 1
    engine.flush()
    m = engine.metrics()
    assert m["processed"] == 14
    assert m["registered"] == 6  # n-0..n-4 + n-loc
    st = engine.get_device_state("n-0")
    assert st["measurements"]["temp"]["value"] == 30.0  # i=10 is latest for n-0
    assert st["recent_alerts"][0]["type"] == "hot"
    assert st["recent_alerts"][0]["level"] == 2
    stl = engine.get_device_state("n-loc")
    assert stl["recent_locations"][0]["latitude"] == 1.0


def test_native_and_python_paths_agree():
    """The fast path and the per-request path must produce identical state."""
    import json as _json

    from sitewhere_tpu.ingest.fast_decode import native_available

    if not native_available():
        pytest.skip("native library unavailable")
    msgs = [
        {"deviceToken": f"agree-{i % 3}", "type": "DeviceMeasurement",
         "request": {"name": "x", "value": float(i), "eventDate": 1000 + i}}
        for i in range(9)
    ]
    eng_native = _mini_engine()
    base = int(eng_native.epoch.base_unix_s * 1000)
    for m in msgs:
        m["request"]["eventDate"] = base + m["request"]["eventDate"]
    eng_native.ingest_json_batch([_json.dumps(m).encode() for m in msgs])
    eng_native.flush()

    from sitewhere_tpu.engine import Engine, EngineConfig as _EC

    eng_py = Engine(_EC(device_capacity=64, token_capacity=128,
                        assignment_capacity=128, store_capacity=4096,
                        batch_capacity=16, channels=4, use_native=False))
    eng_py.epoch = eng_native.epoch
    eng_py.ingest_json_batch([_json.dumps(m).encode() for m in msgs])
    eng_py.flush()

    for tok in ("agree-0", "agree-1", "agree-2"):
        a = eng_native.get_device_state(tok)
        b = eng_py.get_device_state(tok)
        assert a["measurements"]["x"]["value"] == b["measurements"]["x"]["value"]
        assert a["measurements"]["x"]["ts_ms"] == b["measurements"]["x"]["ts_ms"]
        assert a["event_counts"] == b["event_counts"]


def test_native_decode_tolerates_json_literals():
    """null/true/false in number-valued fields must not fail the payload
    (the reference's JSON model routinely serializes eventDate: null)."""
    from sitewhere_tpu.engine import Engine, EngineConfig

    eng = Engine(EngineConfig(
        device_capacity=32, token_capacity=64, assignment_capacity=64,
        store_capacity=512, batch_capacity=8, channels=4))
    payloads = [
        b'{"deviceToken": "n-1", "type": "DeviceMeasurement", "request":'
        b' {"name": "t", "value": 5.5, "eventDate": null, "updateState": true}}',
        b'{"deviceToken": "n-1", "type": "DeviceLocation", "request":'
        b' {"latitude": 1.0, "longitude": 2.0, "elevation": null}}',
        b'{"deviceToken": "n-1", "type": "DeviceMeasurement", "request":'
        b' {"name": "t", "value": null}}',  # no usable value -> still decodes
    ]
    res = eng.ingest_json_batch(payloads)
    assert res["failed"] == 0, res
    eng.flush()
    st = eng.get_device_state("n-1")
    assert st["measurements"]["t"]["value"] == 5.5
    assert st["recent_locations"][0]["latitude"] == 1.0


def test_native_decode_escaped_strings():
    """JSON escapes (\\", \\\\, \\uXXXX) in tokens, names, and alert types
    must take the unescape path and intern the DECODED bytes — the
    zero-copy string-view fast path only covers escape-free strings, and
    a view/unescape mix-up would intern raw backslash sequences."""
    import json as _json

    from sitewhere_tpu.engine import Engine, EngineConfig
    from sitewhere_tpu.ingest.fast_decode import native_available

    if not native_available():
        pytest.skip("native library unavailable")
    eng = Engine(EngineConfig(
        device_capacity=32, token_capacity=64, assignment_capacity=64,
        store_capacity=512, batch_capacity=8, channels=4))
    token = 'esc "quoted" back\\slash'
    name = "température"      # é -> é under ensure_ascii
    payloads = [
        _json.dumps({"deviceToken": token, "type": "DeviceMeasurement",
                     "request": {"name": name, "value": 7.25}},
                    ensure_ascii=True).encode(),
        _json.dumps({"deviceToken": token, "type": "DeviceAlert",
                     "request": {"type": 'over\\heat "now"',
                                 "level": "Critical"}}).encode(),
    ]
    res = eng.ingest_json_batch(payloads)
    assert res["failed"] == 0, res
    eng.flush()
    st = eng.get_device_state(token)   # escaped token round-trips exactly
    assert st["measurements"][name]["value"] == 7.25
    assert st["recent_alerts"][0]["type"] == 'over\\heat "now"'
    assert st["recent_alerts"][0]["level"] == 3


def test_python_decoder_tolerates_json_literals():
    """REST / non-native path accepts the same null-bearing payloads as the
    native batch decoder (parity)."""
    from sitewhere_tpu.ingest.decoders import request_from_envelope

    r = request_from_envelope({
        "deviceToken": "n-2", "type": "DeviceMeasurement",
        "request": {"name": "t", "value": None, "eventDate": None}})
    assert r.measurements == {}
    r = request_from_envelope({
        "deviceToken": "n-2", "type": "DeviceMeasurement",
        "request": {"measurements": {"a": 1.0, "b": None}}})
    assert r.measurements == {"a": 1.0}
    r = request_from_envelope({
        "deviceToken": "n-2", "type": "DeviceLocation",
        "request": {"latitude": 1.5, "longitude": 2.5, "elevation": None}})
    assert r.elevation == 0.0
    r = request_from_envelope({
        "deviceToken": "n-2", "type": "DeviceAlert",
        "request": {"type": None, "level": None, "message": "x"}})
    assert r.alert_type == "alert"


def test_null_location_never_null_island():
    """null lat/lon must not create a (0, 0) location on either path."""
    from sitewhere_tpu.engine import Engine, EngineConfig
    from sitewhere_tpu.ingest.decoders import request_from_envelope

    r = request_from_envelope({
        "deviceToken": "ni-1", "type": "DeviceLocation",
        "request": {"latitude": None, "longitude": None}})
    assert r.latitude is None and r.longitude is None

    eng = Engine(EngineConfig(
        device_capacity=32, token_capacity=64, assignment_capacity=64,
        store_capacity=512, batch_capacity=8, channels=4))
    eng.process(r)
    eng.flush()
    st = eng.get_device_state("ni-1")
    assert st is not None
    assert st["recent_locations"] == []          # event persisted, no coords
    assert st["event_counts"]["LOCATION"] == 1


def test_binary_roundtrip_null_location():
    """NaN wires absent coords through the binary codec (no null island)."""
    from sitewhere_tpu.ingest.decoders import (
        BinaryEventDecoder,
        encode_binary_request,
    )
    from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType

    req = DecodedRequest(type=RequestType.DEVICE_LOCATION, device_token="bl-1")
    wire = encode_binary_request(req)
    back = BinaryEventDecoder().decode(wire, {})[0]
    assert back.latitude is None and back.longitude is None
    # real coordinates still round-trip exactly
    req2 = DecodedRequest(type=RequestType.DEVICE_LOCATION, device_token="bl-1",
                          latitude=12.5, longitude=-3.25, elevation=7.0)
    back2 = BinaryEventDecoder().decode(encode_binary_request(req2), {})[0]
    assert (back2.latitude, back2.longitude, back2.elevation) == (12.5, -3.25, 7.0)


def test_split_json_array():
    from sitewhere_tpu.ingest.decoders import EventDecodeException, split_json_array

    raw = b' [ {"a": [1, 2], "s": "x,]}"} , {"b": {"c": 3}},\n {"d": 4} ] '
    parts = split_json_array(raw)
    assert parts == [b'{"a": [1, 2], "s": "x,]}"}', b'{"b": {"c": 3}}',
                     b'{"d": 4}']
    assert split_json_array(b"[]") == []
    assert split_json_array(b'["lone"]') == [b'"lone"']
    import pytest as _pytest
    with _pytest.raises(EventDecodeException):
        split_json_array(b'{"not": "array"}')
    with _pytest.raises(EventDecodeException):
        split_json_array(b'[{"unterminated": 1}')


def test_fair_mode_preserves_alert_levels():
    """Regression: alert levels ride the values row with chmask unset; the
    fair-mode fast path must not drop them."""
    from sitewhere_tpu.engine import Engine, EngineConfig

    for fair in (False, True):
        eng = Engine(EngineConfig(
            device_capacity=32, token_capacity=64, assignment_capacity=64,
            store_capacity=512, batch_capacity=8, channels=4,
            fair_tenancy=fair))
        eng.ingest_json_batch([
            b'{"deviceToken": "al-1", "type": "DeviceAlert", "request":'
            b' {"type": "fire", "level": "Error", "message": "hot"}}'])
        eng.flush()
        st = eng.get_device_state("al-1")
        assert st["recent_alerts"][0]["level"] == 2, (fair, st)
        assert st["recent_alerts"][0]["type"] == "fire"


def test_native_binary_batch_decode():
    """Binary wire format decodes natively and matches the Python decoder
    on every event family."""
    from sitewhere_tpu.core.types import AlertLevel
    from sitewhere_tpu.engine import Engine, EngineConfig
    from sitewhere_tpu.ingest.decoders import encode_binary_request
    from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType

    eng = Engine(EngineConfig(
        device_capacity=32, token_capacity=64, assignment_capacity=64,
        store_capacity=1024, batch_capacity=16, channels=4))
    payloads = [
        encode_binary_request(DecodedRequest(
            type=RequestType.DEVICE_MEASUREMENT, device_token="bb-1",
            measurements={"temp": 21.5, "rpm": 900.0})),
        encode_binary_request(DecodedRequest(
            type=RequestType.DEVICE_LOCATION, device_token="bb-1",
            latitude=33.7, longitude=-84.4, elevation=5.0)),
        encode_binary_request(DecodedRequest(
            type=RequestType.DEVICE_LOCATION, device_token="bb-1")),  # null coords
        encode_binary_request(DecodedRequest(
            type=RequestType.DEVICE_ALERT, device_token="bb-2",
            alert_type="fire", alert_level=AlertLevel.CRITICAL)),
        b"\x07garbage",
    ]
    res = eng.ingest_binary_batch(payloads)
    assert res["failed"] == 1 and res["decoded"] == 4, res
    eng.flush()
    st = eng.get_device_state("bb-1")
    assert st["measurements"]["temp"]["value"] == 21.5
    assert st["measurements"]["rpm"]["value"] == 900.0
    locs = st["recent_locations"]
    assert len(locs) == 1 and locs[0]["latitude"] == pytest.approx(33.7, abs=1e-4)
    st2 = eng.get_device_state("bb-2")
    assert st2["recent_alerts"][0]["type"] == "fire"
    assert st2["recent_alerts"][0]["level"] == int(AlertLevel.CRITICAL)

    # registration envelope routes through the slow path
    reg = encode_binary_request(DecodedRequest(
        type=RequestType.REGISTER_DEVICE, device_token="bb-new"))
    res = eng.ingest_binary_batch([reg])
    assert res["decoded"] == 1
    assert eng.get_device("bb-new") is not None


def test_map_device_via_native_bulk_path():
    """MapDevice envelopes in a native JSON bulk batch take the slow path
    (parity with the pure-Python fallback)."""
    from sitewhere_tpu.commands.routing import NestedDeviceSupport
    from sitewhere_tpu.engine import Engine, EngineConfig

    eng = Engine(EngineConfig(
        device_capacity=32, token_capacity=64, assignment_capacity=64,
        store_capacity=512, batch_capacity=8, channels=4))
    eng.register_device("gw-b")
    eng.register_device("leaf-b")
    res = eng.ingest_json_batch([
        b'{"deviceToken": "leaf-b", "type": "MapDevice",'
        b' "request": {"parentToken": "gw-b"}}'])
    assert res["failed"] == 0 and res["decoded"] == 1, res
    assert NestedDeviceSupport(eng).resolve_target_token("leaf-b") == "gw-b"
    # wholesale metadata update must not drop the mapping
    eng.update_device("leaf-b", metadata={"rack": "r1"})
    assert NestedDeviceSupport(eng).resolve_target_token("leaf-b") == "gw-b"


def test_update_device_parent_lockstep():
    """metadata parentToken changes keep the on-device parent column in
    lockstep: remap follows, explicit None unmaps."""
    from sitewhere_tpu.commands.routing import NestedDeviceSupport
    from sitewhere_tpu.core.types import NULL_ID
    from sitewhere_tpu.engine import Engine, EngineConfig

    eng = Engine(EngineConfig(
        device_capacity=32, token_capacity=64, assignment_capacity=64,
        store_capacity=512, batch_capacity=8, channels=4))
    for t in ("gw1", "gw2", "leaf"):
        eng.register_device(t)
    eng.map_device("leaf", "gw1")
    did = eng.token_device[eng.tokens.lookup("leaf")]

    # remap via metadata update
    eng.update_device("leaf", metadata={"parentToken": "gw2"})
    assert NestedDeviceSupport(eng).resolve_target_token("leaf") == "gw2"
    assert int(eng.state.registry.device_parent[did]) == \
        eng.token_device[eng.tokens.lookup("gw2")]
    # unknown parent rejected
    import pytest as _pytest
    with _pytest.raises(KeyError):
        eng.update_device("leaf", metadata={"parentToken": "ghost"})
    # explicit None unmaps both views
    eng.update_device("leaf", metadata={"parentToken": None})
    assert "parentToken" not in eng.get_device("leaf").metadata
    assert int(eng.state.registry.device_parent[did]) == NULL_ID
    assert NestedDeviceSupport(eng).resolve_target_token("leaf") == "leaf"


def test_binary_roundtrip_register_and_ack_fidelity():
    """Registration extras and ACK linkage survive the binary wire (WAL
    replay fidelity)."""
    from sitewhere_tpu.ingest.decoders import (
        BinaryEventDecoder,
        encode_binary_request,
    )

    reg = DecodedRequest(
        type=RequestType.REGISTER_DEVICE, device_token="fid-1",
        extras={"deviceTypeToken": "meter", "areaToken": "plant"})
    (back,) = BinaryEventDecoder().decode(encode_binary_request(reg), {})
    assert back.extras == {"deviceTypeToken": "meter", "areaToken": "plant"}

    ack = DecodedRequest(
        type=RequestType.ACKNOWLEDGE, device_token="fid-1",
        originating_event_id="inv-77", response="done")
    (back,) = BinaryEventDecoder().decode(encode_binary_request(ack), {})
    assert back.originating_event_id == "inv-77"
    assert back.response == "done"

    # bulk binary ACKs keep their linkage end to end (slow-path routing)
    eng = Engine(EngineConfig(
        device_capacity=32, token_capacity=64, assignment_capacity=64,
        store_capacity=512, batch_capacity=8, channels=4))
    eng.register_device("fid-1")
    res = eng.ingest_binary_batch([encode_binary_request(ack)])
    assert res["decoded"] == 1 and res["failed"] == 0
    eng.flush()
    evs = eng.query_events(device_token="fid-1", limit=10)["events"]
    resp = [e for e in evs if e["type"] == "COMMAND_RESPONSE"]
    assert len(resp) == 1 and resp[0]["originatingEventId"] == "inv-77"


def test_strict_channels_python_path():
    """Strict channel mode: distinct measurement names beyond ``channels``
    raise (no silent lane aliasing) on the per-request path."""
    import pytest

    from sitewhere_tpu.engine import ChannelCapacityError

    eng = Engine(EngineConfig(
        device_capacity=32, token_capacity=64, assignment_capacity=64,
        store_capacity=512, batch_capacity=8, channels=2,
        strict_channels=True, use_native=False))
    eng.process(DecodedRequest(
        type=RequestType.DEVICE_MEASUREMENT, device_token="sc-1",
        measurements={"a": 1.0, "b": 2.0}))
    with pytest.raises(ChannelCapacityError):
        eng.process(DecodedRequest(
            type=RequestType.DEVICE_MEASUREMENT, device_token="sc-1",
            measurements={"c": 3.0}))
    assert eng.metrics()["channel_collisions"] == 1


def test_strict_channels_native_batch_rejected():
    """Strict mode on the native fast path rejects the whole batch before
    WAL/staging when the decode interned a name past capacity."""
    import pytest

    from sitewhere_tpu.engine import ChannelCapacityError

    eng = Engine(EngineConfig(
        device_capacity=32, token_capacity=64, assignment_capacity=64,
        store_capacity=512, batch_capacity=8, channels=2,
        strict_channels=True))
    if eng._native_decoder is None:
        pytest.skip("native library unavailable")
    ok = eng.ingest_json_batch([measurement_json("sc-n", name="a"),
                                measurement_json("sc-n", name="b")])
    assert ok["failed"] == 0
    with pytest.raises(ChannelCapacityError):
        eng.ingest_json_batch([measurement_json("sc-n", name="c")])
    assert eng.staged_count == 2  # rejected batch staged nothing
    # the rejected batch's names rolled back (no lane leak): the interner
    # holds exactly the accepted names, and re-sending them still works
    assert len(eng.channel_map.names) == 2
    ok2 = eng.ingest_json_batch([measurement_json("sc-n", name="a")])
    assert ok2["failed"] == 0 and eng.staged_count == 3


def test_lenient_channels_roundtrip_within_capacity():
    """With channels sized to the name population, every distinct name keeps
    its own lane and round-trips through query_events."""
    eng = Engine(EngineConfig(
        device_capacity=32, token_capacity=64, assignment_capacity=64,
        store_capacity=512, batch_capacity=8, channels=8))
    names = [f"lane{i}" for i in range(8)]
    for i, n in enumerate(names):
        eng.process(DecodedRequest(
            type=RequestType.DEVICE_MEASUREMENT, device_token="rt-1",
            measurements={n: float(i)}))
    eng.flush()
    assert eng.channel_map.collisions == 0
    evs = eng.query_events(device_token="rt-1", limit=20)["events"]
    seen = {}
    for e in evs:
        seen.update(e.get("measurements", {}))
    assert seen == {n: float(i) for i, n in enumerate(names)}


def test_strict_channels_reject_precedes_wal(tmp_path):
    """A strict rejection must never be durable: the WAL contains no record
    for the refused request, so crash recovery replays cleanly."""
    import pytest

    from sitewhere_tpu.engine import ChannelCapacityError
    from sitewhere_tpu.utils.checkpoint import recover_engine, save_engine

    eng = Engine(EngineConfig(
        device_capacity=32, token_capacity=64, assignment_capacity=64,
        store_capacity=512, batch_capacity=8, channels=3,
        strict_channels=True, use_native=False,
        wal_dir=str(tmp_path / "wal")))
    save_engine(eng, tmp_path / "snap")   # empty snapshot; WAL replays all
    eng.process(DecodedRequest(
        type=RequestType.DEVICE_MEASUREMENT, device_token="wr-1",
        measurements={"a": 1.0}))
    with pytest.raises(ChannelCapacityError):
        eng.process(DecodedRequest(
            type=RequestType.DEVICE_MEASUREMENT, device_token="wr-1",
            measurements={"b": 2.0, "c": 3.0, "d": 4.0}))
    # the refusal left no trace: "b".."d" never interned, so a later
    # within-capacity name is ACCEPTED (lane-leak regression guard)
    ok = eng.ingest_json_batch([measurement_json("wr-1", name="e")])
    assert ok["failed"] == 0
    with pytest.raises(ChannelCapacityError):   # 2 used + 3 new > 3
        eng.ingest_json_batch([measurement_json("wr-1", name="f"),
                               measurement_json("wr-1", name="g"),
                               measurement_json("wr-1", name="h")])
    eng.flush()
    assert eng.metrics()["persisted"] == 2
    eng.wal.close()
    # recovery must not raise (no refused record is durable) and must see
    # only the accepted rows
    eng2 = recover_engine(tmp_path / "snap")
    eng2.flush()
    assert eng2.metrics()["persisted"] == 2


def test_search_index_readd_purges_stale_postings():
    """Re-delivered event ids (at-least-once feed) replace their old posting
    keys — stale keys never crash a later search."""
    from sitewhere_tpu.core.types import EventType
    from sitewhere_tpu.outbound.feed import OutboundEvent
    from sitewhere_tpu.search.index import EventSearchIndex

    idx = EventSearchIndex(capacity=4)

    def ev(i, name):
        return OutboundEvent(
            event_id=i, etype=EventType.MEASUREMENT, device_token="d-0",
            device_id=0, assignment_id=i, tenant="default", area_id=-1,
            asset_id=-1, ts_ms=i, received_ms=i, measurements={name: 1.0},
            values=[], aux0=-1, aux1=-1)

    idx.add(ev(1, "old"))
    idx.add(ev(1, "new"))       # same id, changed content
    assert idx.search("measurement:old") == []
    assert [d["eventId"] for d in idx.search("measurement:new")] == [1]
    assert ("measurement", "old") not in idx.postings


def test_scan_chunk_matches_single_step():
    """scan_chunk>1 dispatches K batches as one scanned program; results
    (metrics, state, registrations, queries) must match per-batch dispatch
    exactly."""
    def build(chunk):
        return Engine(EngineConfig(
            device_capacity=256, token_capacity=512, assignment_capacity=512,
            store_capacity=4096, batch_capacity=16, channels=4,
            scan_chunk=chunk))

    a, b = build(1), build(4)
    base = int(a.epoch.base_unix_s * 1000)
    b.epoch = a.epoch                  # identical relative timestamps
    payloads = [measurement_json(token=f"sc2-{i % 40}", value=float(i),
                                 eventDate=base + i)
                for i in range(160)]
    for eng in (a, b):
        for lo in range(0, 160, 16):
            eng.ingest_json_batch(payloads[lo:lo + 16])
        eng.flush()
    assert a.metrics() == b.metrics()
    assert a.metrics()["persisted"] == 160
    sa = a.get_device_state("sc2-7")
    sb = b.get_device_state("sc2-7")
    assert sa == sb

    def strip_received(q):   # receive time is wall-clock, engine-specific
        return [{k: v for k, v in e.items() if k != "receivedDateMs"}
                for e in q["events"]]

    qa = a.query_events(device_token="sc2-3", limit=10)
    qb = b.query_events(device_token="sc2-3", limit=10)
    assert strip_received(qa) == strip_received(qb) and qa["total"] == 4


def test_scan_chunk_remainder_dispatches_on_flush():
    """A partial chunk must not strand: flush() pushes the remainder through
    as single steps."""
    eng = Engine(EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=1024, batch_capacity=8, channels=4, scan_chunk=4))
    eng.ingest_json_batch([measurement_json(token=f"rm-{i}") for i in range(24)])
    assert eng.staged_count > 0        # 3 staged batches < chunk of 4
    out = eng.flush()
    assert eng.staged_count == 0
    assert eng.metrics()["persisted"] == 24


def test_mqtt_qos2_exactly_once():
    """QoS 2 publish completes the 4-way handshake and delivers exactly
    once, even when the PUBLISH is redelivered with the same packet id
    (reference: MqttInboundEventReceiver QoS EXACTLY_ONCE)."""
    from sitewhere_tpu.ingest.mqtt import (
        CONNACK,
        PUBCOMP,
        PUBREC,
        MqttBroker,
        MqttClient,
        encode_connect,
        encode_packet,
        encode_publish,
        read_packet,
    )

    async def run():
        broker = MqttBroker()
        await broker.start()
        got: list[bytes] = []
        sub = MqttClient("127.0.0.1", broker.bound_port, "sub")
        sub.on_message = lambda t, p: got.append(p)
        await sub.connect()
        await sub.subscribe("q2/#", qos=2)

        # happy path: client API QoS 2 publish
        pub = MqttClient("127.0.0.1", broker.bound_port, "pub")
        await pub.connect()
        await pub.publish("q2/a", b"one", qos=2)
        await asyncio.sleep(0.2)
        assert got == [b"one"]

        # duplicate PUBLISH with the same pid before PUBREL: raw wire drive
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", broker.bound_port)
        writer.write(encode_connect("raw"))
        await writer.drain()
        ptype, _, _ = await read_packet(reader)
        assert ptype == CONNACK
        pkt = encode_publish("q2/b", b"two", qos=2, packet_id=7)
        writer.write(pkt)
        await writer.drain()
        assert (await read_packet(reader))[0] == PUBREC
        writer.write(pkt)                      # redelivery, same pid
        await writer.drain()
        assert (await read_packet(reader))[0] == PUBREC
        writer.write(encode_packet(6, 0x02, (7).to_bytes(2, "big")))  # PUBREL
        await writer.drain()
        assert (await read_packet(reader))[0] == PUBCOMP
        await asyncio.sleep(0.2)
        assert got == [b"one", b"two"]          # exactly once
        writer.close()
        await pub.disconnect()
        await sub.disconnect()
        await broker.stop()

    asyncio.run(run())


def test_mqtt_client_inbound_qos2_dedup():
    """The CLIENT side of the exactly-once handshake: a server redelivering
    PUBLISH(qos2, same pid) before PUBREL reaches on_message once; the
    client answers PUBREC and PUBCOMP."""
    from sitewhere_tpu.ingest.mqtt import (
        CONNACK,
        CONNECT,
        PUBCOMP,
        PUBREC,
        PUBREL,
        SUBACK,
        SUBSCRIBE,
        MqttClient,
        encode_packet,
        encode_publish,
        read_packet,
    )

    async def run():
        seen: list[bytes] = []
        replies: list[int] = []

        async def server(reader, writer):
            ptype, _, _ = await read_packet(reader)
            assert ptype == CONNECT
            writer.write(encode_packet(CONNACK, 0, b"\x00\x00"))
            ptype, _, body = await read_packet(reader)
            assert ptype == SUBSCRIBE
            writer.write(encode_packet(SUBACK, 0, body[:2] + b"\x02"))
            # redeliver the same qos2 packet twice, then release
            pkt = encode_publish("t/1", b"payload", qos=2, packet_id=9)
            writer.write(pkt)
            await writer.drain()
            ptype, _, _ = await read_packet(reader)
            replies.append(ptype)               # PUBREC
            writer.write(pkt)                   # dup before PUBREL
            await writer.drain()
            ptype, _, _ = await read_packet(reader)
            replies.append(ptype)               # PUBREC again
            writer.write(encode_packet(PUBREL, 0x02, (9).to_bytes(2, "big")))
            await writer.drain()
            ptype, _, _ = await read_packet(reader)
            replies.append(ptype)               # PUBCOMP
            writer.close()   # 3.12: wait_closed() blocks on open transports

        srv = await asyncio.start_server(server, "127.0.0.1", 0)
        port = srv.sockets[0].getsockname()[1]
        cli = MqttClient("127.0.0.1", port, "c")
        cli.on_message = lambda t, p: seen.append(p)
        await cli.connect()
        await cli.subscribe("t/#", qos=2)
        await asyncio.sleep(0.3)
        assert seen == [b"payload"]
        assert replies == [PUBREC, PUBREC, PUBCOMP]
        await cli.disconnect()
        srv.close()
        await srv.wait_closed()

    asyncio.run(run())


def test_mqtt_receiver_reconnects_after_broker_restart():
    """A dropped broker connection triggers the receiver's scheduled
    reconnect (exponential backoff) and re-subscription — events flow
    again without operator action."""
    from sitewhere_tpu.ingest.mqtt import MqttBroker, MqttClient, MqttEventReceiver

    async def run():
        broker = MqttBroker()
        await broker.start()
        port = broker.bound_port
        engine = _mini_engine()
        mgr = _wire(engine)
        recv = MqttEventReceiver("127.0.0.1", port,
                                 topic="sitewhere/input/#",
                                 reconnect_initial_s=0.05)
        mgr.add_source(InboundEventSource("mqtt", JsonDeviceRequestDecoder(),
                                          [recv]))
        await mgr.initialize()
        await mgr.start()
        try:
            pub = MqttClient("127.0.0.1", port, "p1")
            await pub.connect()
            await pub.publish("sitewhere/input/a", measurement_json("rc-1"))
            await asyncio.sleep(0.2)
            await pub.disconnect()
            # broker dies and comes back on the same port
            await broker.stop()
            broker2 = MqttBroker(port=port)
            for _ in range(50):
                try:
                    await broker2.start()
                    break
                except OSError:
                    await asyncio.sleep(0.05)
            for _ in range(100):    # wait for the receiver to reconnect
                if recv.reconnects:
                    break
                await asyncio.sleep(0.05)
            assert recv.reconnects == 1
            pub2 = MqttClient("127.0.0.1", port, "p2")
            await pub2.connect()
            await pub2.publish("sitewhere/input/b", measurement_json("rc-2"))
            await asyncio.sleep(0.3)
            await pub2.disconnect()
            await broker2.stop()
        finally:
            await mgr.stop()
        engine.flush()
        assert engine.metrics()["registered"] == 2   # rc-1 AND rc-2 arrived

    asyncio.run(run())


def test_pylist_and_packed_decode_paths_agree():
    """decode() silently routes through the zero-copy list entry point
    when libswtpu_py.so builds — BOTH paths must stay covered and
    byte-identical (a packed-fallback regression must not pass green on
    hosts where the bridge builds, and vice versa)."""
    import numpy as np

    from sitewhere_tpu.ingest.decoders import encode_binary_request
    from sitewhere_tpu.ingest.fast_decode import (NativeBatchDecoder,
                                                  native_available)
    from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType
    from sitewhere_tpu.native.binding import NativeInterner

    if not native_available():
        pytest.skip("native library unavailable")
    payloads = []
    for i in range(257):
        if i % 41 == 0:
            payloads.append(b"{torn")
        else:
            payloads.append(measurement_json(
                f"pp-{i % 9}", name=f"ch{i % 5}", value=float(i)))
    bpayloads = [encode_binary_request(DecodedRequest(
        type=RequestType.DEVICE_MEASUREMENT, device_token=f"pb-{i % 9}",
        measurements={"a": float(i)})) for i in range(64)]
    for batch, binary in ((payloads, False), (bpayloads, True)):
        fast_dec = NativeBatchDecoder(NativeInterner(1 << 12), 8)
        packed_dec = NativeBatchDecoder(NativeInterner(1 << 12), 8)
        packed_dec.py_lib = None        # force the packed fallback
        if fast_dec.py_lib is None:
            pytest.skip("py-bridge unavailable: packed path already "
                        "the only (tested) path")
        fast = fast_dec._decode(batch, binary=binary)
        ref = packed_dec._decode(batch, binary=binary)
        assert fast.n_ok == ref.n_ok
        assert fast.collisions == ref.collisions
        for f in ("rtype", "token_id", "ts_ms64", "aux0", "level",
                  "values", "chmask"):
            assert np.array_equal(getattr(fast, f), getattr(ref, f)), f


def test_scanner_and_router_randomized_differential():
    """Seeded fuzz over the native scanner + router: every randomly
    generated valid envelope (unicode/escapes/nulls/extra keys) must
    decode, the native router must agree with its Python port on every
    payload, and random mutations (truncation, byte flips, inserts) must
    never crash the scanner or break route parity."""
    import json as _json
    import random

    from sitewhere_tpu.ingest.fast_decode import (NativeBatchDecoder,
                                                  native_available)
    from sitewhere_tpu.native.binding import NativeInterner, route_payloads
    from sitewhere_tpu.native.route_fallback import route_json_payload

    if not native_available():
        pytest.skip("native library unavailable")
    rng = random.Random(1234)
    alphabet = "abcXYZ0189-_.é😀\"\\\n\t"

    def rand_token():
        return "".join(rng.choice(alphabet)
                       for _ in range(rng.randint(1, 24)))

    def rand_envelope():
        t = rng.choice(["DeviceMeasurement", "DeviceMeasurements",
                        "DeviceLocation", "DeviceAlert", "Acknowledge"])
        req = {}
        if t == "DeviceMeasurement":
            req = {"name": rand_token(), "value": rng.choice(
                [rng.uniform(-1e6, 1e6), rng.randint(0, 10**14), None])}
        elif t == "DeviceMeasurements":
            req = {"measurements": {rand_token(): rng.uniform(-100, 100)
                                    for _ in range(rng.randint(0, 5))}}
        elif t == "DeviceLocation":
            req = {"latitude": rng.uniform(-90, 90),
                   "longitude": rng.uniform(-180, 180),
                   "elevation": rng.choice([rng.uniform(0, 1000), None])}
        elif t == "DeviceAlert":
            req = {"type": rand_token(),
                   "level": rng.choice(["Info", "Warning", "Error",
                                        "Critical", 2, None]),
                   "message": rand_token()}
        if rng.random() < 0.8:
            req["eventDate"] = rng.randint(1, 2**45)
        env = {"deviceToken": rand_token(), "type": t, "request": req}
        if rng.random() < 0.2:
            env["extraKey"] = rng.choice([None, True, [1, {"a": "b"}], "x"])
        return env

    payloads = [
        _json.dumps(rand_envelope(),
                    ensure_ascii=rng.random() < 0.5).encode()
        for _ in range(1500)]
    dec = NativeBatchDecoder(NativeInterner(1 << 16), 8)
    res = dec.decode(payloads)
    assert res.n_ok == len(payloads)

    ranks = route_payloads(payloads, 7)
    if ranks is None:
        pytest.skip("py-bridge (list router) unavailable")
    for i, p in enumerate(payloads):
        assert int(ranks[i]) == route_json_payload(p, 7), p[:80]

    mut = []
    for p in payloads[:800]:
        b = bytearray(p)
        for _ in range(rng.randint(1, 4)):
            op = rng.random()
            if op < 0.4 and len(b) > 2:
                del b[rng.randrange(len(b)):]
            elif op < 0.8 and b:
                b[rng.randrange(len(b))] = rng.randrange(256)
            else:
                b.insert(rng.randrange(len(b) + 1), rng.randrange(256))
        mut.append(bytes(b))
    res2 = dec.decode(mut)          # must not crash; count stays sane
    assert 0 <= res2.n_ok <= len(mut)
    ranks2 = route_payloads(mut, 7)
    for i, p in enumerate(mut):
        assert int(ranks2[i]) == route_json_payload(p, 7), p[:80]
