"""Multi-shard engine tests on the virtual 8-device CPU mesh.

Validates the sharding design of SURVEY.md §7.3: host token-partitioned
routing (Kafka partitioner analog), shard-local pipelines over stacked state,
and the ICI all-to-all exchange path — all against the same numpy oracle as
the single-chip tests (global results must be identical to an unsharded run).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sitewhere_tpu.core.events import EventBatch
from sitewhere_tpu.core.types import EventType
from sitewhere_tpu.parallel.router import ShardRouter
from sitewhere_tpu.parallel.sharded import ShardedEngine
from sitewhere_tpu.pipeline import PipelineConfig

from tests.oracle import OracleEngine

CHANNELS = 4


def _engine(exchange=False, bucket=0):
    return ShardedEngine(
        n_shards=8,
        device_capacity_per_shard=32,
        token_capacity_per_shard=32,
        assignment_capacity_per_shard=32,
        store_capacity_per_shard=1024,
        channels=CHANNELS,
        config=PipelineConfig(auto_register=True),
        exchange=exchange,
        bucket_capacity=bucket,
    )


def _random_stream(rng, n, n_tokens=256):  # tokens span all 8 shards' slices
    return [
        {
            "token": int(rng.integers(0, n_tokens)),
            "ts": int(rng.integers(0, 50)),
            "val": float(np.round(rng.random(), 3)),
        }
        for _ in range(n)
    ]


def test_sharded_engine_routed(rng):
    """Host-routed events: per-shard pipelines must jointly match the oracle."""
    eng = _engine()
    events = _random_stream(rng, 200)
    router = ShardRouter(eng.n_shards, eng.tokens_per_shard, batch_capacity=64,
                         channels=CHANNELS)
    for ev in events:
        assert router.append(EventType.MEASUREMENT, ev["token"], 0, ev["ts"], ev["ts"],
                             values=[ev["val"]])
    eng.step(router.emit())

    metrics = eng.global_metrics()
    assert metrics["processed"] == len(events)
    assert metrics["found"] == len(events)
    assert metrics["missed"] == 0
    distinct = len({ev["token"] for ev in events})
    assert metrics["registered"] == distinct
    assert metrics["persisted"] == len(events)

    # spot-check per-device latest values against the oracle
    oracle = OracleEngine()
    oracle.process(
        [
            {"token": ev["token"], "tenant": 0, "etype": 0, "ts": ev["ts"],
             "seq": i, "values": {0: ev["val"]}}
            for i, ev in enumerate(events)
        ]
    )
    tps = eng.tokens_per_shard
    state = eng.state
    for tok in {ev["token"] for ev in events}:
        shard, local = divmod(tok, tps)
        dev = int(state.registry.token_to_device[shard, local])
        assert dev >= 0
        odev = oracle.token_to_device[tok]
        ost = oracle.states[odev]
        ts, _seq, val = ost.meas_last[0]
        assert int(state.device_state.meas_last_ms[shard, dev, 0]) == ts
        np.testing.assert_allclose(
            float(state.device_state.meas_last[shard, dev, 0]), val, rtol=1e-6
        )


def test_sharded_engine_exchange_matches_routed(rng):
    """Unrouted ingest + on-device all-to-all must equal host-routed results.

    Device ids are allocation-order dependent and cross-shard arrival order is
    unordered (exactly like Kafka cross-partition ordering), so states are
    compared per token with unique timestamps."""
    events = _random_stream(rng, 150)
    for i, ev in enumerate(events):
        ev["ts"] = i  # unique ts: no cross-path tie ambiguity

    # host-routed reference run
    eng_a = _engine()
    router = ShardRouter(eng_a.n_shards, eng_a.tokens_per_shard, 64, CHANNELS)
    for ev in events:
        router.append(EventType.MEASUREMENT, ev["token"], 0, ev["ts"], ev["ts"],
                      values=[ev["val"]])
    eng_a.step(router.emit())

    # unrouted run: events land on arbitrary shards, device routes via a2a
    eng_b = _engine(exchange=True, bucket=32)
    from sitewhere_tpu.core.events import HostEventBuffer

    bufs = [HostEventBuffer(32, CHANNELS) for _ in range(eng_b.n_shards)]
    for i, ev in enumerate(events):
        # round-robin arrival shard, GLOBAL token ids (exchange localizes)
        bufs[i % eng_b.n_shards].append(
            EventType.MEASUREMENT, ev["token"], 0, ev["ts"], ev["ts"], values=[ev["val"]]
        )
    stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *[b.emit() for b in bufs])
    eng_b.step(stacked)

    ma, mb = eng_a.global_metrics(), eng_b.global_metrics()
    assert mb["processed"] == len(events)
    assert mb["found"] == ma["found"] == len(events)
    assert mb["registered"] == ma["registered"]
    assert mb["persisted"] == ma["persisted"]

    # per-token state must be identical across the two ingest paths
    tps = eng_a.tokens_per_shard
    for tok in {ev["token"] for ev in events}:
        shard, local = divmod(tok, tps)
        dev_a = int(eng_a.state.registry.token_to_device[shard, local])
        dev_b = int(eng_b.state.registry.token_to_device[shard, local])
        assert dev_a >= 0 and dev_b >= 0
        for fld in ("meas_last", "meas_last_ms", "last_interaction_ms", "recent_meas_ms"):
            a = np.asarray(getattr(eng_a.state.device_state, fld)[shard, dev_a])
            b = np.asarray(getattr(eng_b.state.device_state, fld)[shard, dev_b])
            np.testing.assert_array_equal(a, b, err_msg=f"token {tok} field {fld}")


def test_exchange_overflow_counted(rng):
    """Bucket overflow must be dead-lettered and counted, not silently lost."""
    eng = _engine(exchange=True, bucket=2)  # tiny per-destination bucket
    from sitewhere_tpu.core.events import HostEventBuffer

    bufs = [HostEventBuffer(32, CHANNELS) for _ in range(eng.n_shards)]
    # 20 events from shard 0, all owned by shard 0 -> bucket 2 overflows
    for i in range(20):
        bufs[0].append(EventType.MEASUREMENT, i % 8, 0, i, i, values=[1.0])
    stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *[b.emit() for b in bufs])
    eng.step(stacked)
    m = eng.global_metrics()
    assert m["found"] == 2
    assert m["missed"] == 18


def test_sharded_query_presence_and_snapshot(rng, tmp_path):
    """Global query, presence sweep, state readback, save/restore."""
    eng = _engine()
    router = ShardRouter(eng.n_shards, eng.tokens_per_shard, batch_capacity=64,
                         channels=CHANNELS)
    events = _random_stream(rng, 120)
    for ev in events:
        router.append(EventType.MEASUREMENT, ev["token"], 0, ev["ts"], ev["ts"],
                      values=[ev["val"]])
    eng.step(router.emit())

    # global newest-first query merges per-shard pages
    res = eng.query_events(limit=50)
    assert res["total"] == len(events)
    assert len(res["events"]) == 50
    ts = [e["eventDateMs"] for e in res["events"]]
    assert ts == sorted(ts, reverse=True)
    # shards represented match the token distribution
    shards_seen = {e["shard"] for e in res["events"]}
    assert shards_seen <= set(range(eng.n_shards))

    # type filter on-device
    res_m = eng.query_events(etype=EventType.MEASUREMENT, limit=10)
    assert res_m["total"] == len(events)
    assert eng.query_events(etype=EventType.ALERT, limit=10)["total"] == 0

    # state readback for one registered device
    tok = events[0]["token"]
    shard, local = divmod(tok, eng.tokens_per_shard)
    dev = int(eng.state.registry.token_to_device[shard, local])
    summary = eng.device_state_summary(shard, dev)
    assert summary["presence"] == "PRESENT"
    assert summary["eventCounts"]["MEASUREMENT"] >= 1

    # presence sweep: far-future now marks every registered device missing
    newly = eng.presence_sweep(now_ms=10_000_000, missing_ms=1000)
    distinct = len({ev["token"] for ev in events})
    assert len(newly) == distinct
    assert eng.device_state_summary(shard, dev)["presence"] == "MISSING"

    # snapshot round-trip preserves state bit-for-bit
    eng.save(tmp_path)
    eng2 = _engine()
    eng2.restore(tmp_path)
    for (p1, l1), (p2, l2) in zip(
        jax.tree_util.tree_flatten_with_path(eng.state)[0],
        jax.tree_util.tree_flatten_with_path(eng2.state)[0],
    ):
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    assert eng2.global_metrics() == eng.global_metrics()
    # restored engine keeps serving queries
    assert eng2.query_events(limit=5)["total"] == len(events)

    # shard-count mismatch is rejected
    eng4 = ShardedEngine(n_shards=4, device_capacity_per_shard=32,
                         token_capacity_per_shard=32,
                         assignment_capacity_per_shard=32,
                         store_capacity_per_shard=1024, channels=CHANNELS)
    with pytest.raises(ValueError):
        eng4.restore(tmp_path)


def test_multihost_helpers(rng):
    """Single-process degenerate case: all shards local, assembled batch
    matches a host-stacked one."""
    from sitewhere_tpu.parallel.multihost import (
        assemble_stacked_batch,
        initialize,
        local_shard_ids,
    )

    assert initialize() is False  # single process, no coordinator
    eng = _engine()
    assert local_shard_ids(eng.mesh) == list(range(eng.n_shards))

    router = ShardRouter(eng.n_shards, eng.tokens_per_shard, batch_capacity=16,
                         channels=CHANNELS)
    events = _random_stream(rng, 40)
    for ev in events:
        router.append(EventType.MEASUREMENT, ev["token"], 0, ev["ts"], ev["ts"],
                      values=[ev["val"]])
    stacked = router.emit()

    per_shard = {
        i: jax.tree_util.tree_map(lambda x: np.asarray(x)[i], stacked)
        for i in range(eng.n_shards)
    }
    glued = assemble_stacked_batch(eng.mesh, per_shard)
    for f in dataclasses.fields(stacked):
        np.testing.assert_array_equal(
            np.asarray(getattr(glued, f.name)),
            np.asarray(getattr(stacked, f.name)),
        )
    # the glued batch drives the engine exactly like the host-stacked one
    eng.step(glued)
    assert eng.global_metrics()["processed"] == len(events)

    # missing local shard is an error
    with pytest.raises(ValueError):
        assemble_stacked_batch(eng.mesh, {0: per_shard[0]})
