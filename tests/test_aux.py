"""Aux subsystem tests: metrics/Prometheus, checkpoint/resume, ingest log,
config-driven component factories, tracing spans."""

import asyncio
import json

import pytest

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType
from sitewhere_tpu.utils.metrics import MetricsRegistry, export_engine_metrics


def _engine(**kw):
    return Engine(EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=4096, batch_capacity=16, channels=4, **kw,
    ))


def _measure(engine, token, name="temp", value=1.0, ts=None):
    engine.process(DecodedRequest(
        type=RequestType.DEVICE_MEASUREMENT, device_token=token,
        measurements={name: value}, event_ts_ms=ts,
    ))


def test_metrics_registry_and_prometheus_text():
    reg = MetricsRegistry()
    c = reg.counter("swtpu_test_total", "test counter")
    c.inc(tenant="a")
    c.inc(2, tenant="a")
    c.inc(tenant="b")
    h = reg.histogram("swtpu_lat_seconds", "latency")
    with h.time(stage="lookup"):
        pass
    h.observe(0.003, stage="lookup")
    h.observe(0.2, stage="lookup")
    text = reg.expose_text()
    assert 'swtpu_test_total{tenant="a"} 3.0' in text
    assert 'swtpu_test_total{tenant="b"} 1.0' in text
    assert "# TYPE swtpu_lat_seconds histogram" in text
    assert 'swtpu_lat_seconds_count{stage="lookup"} 3' in text
    assert h.quantile(0.5, stage="lookup") is not None
    with pytest.raises(TypeError):
        reg.gauge("swtpu_test_total")  # kind mismatch


def test_engine_metrics_export():
    reg = MetricsRegistry()
    engine = _engine()
    _measure(engine, "m-1")
    engine.flush()
    export_engine_metrics(engine, reg)
    text = reg.expose_text()
    assert 'swtpu_engine_processed{tenant="all"} 1' in text
    assert 'swtpu_engine_registered{tenant="all"} 1' in text


def test_checkpoint_roundtrip(tmp_path):
    from sitewhere_tpu.utils.checkpoint import restore_engine, save_engine

    engine = _engine()
    _measure(engine, "ck-1", "temp", 21.5)
    _measure(engine, "ck-2", "temp", 22.5)
    engine.register_device("ck-admin", device_type="default",
                           metadata={"phone": "+1555"})
    engine.flush()
    before = engine.get_device_state("ck-1")
    manifest = save_engine(engine, tmp_path / "snap")
    assert manifest["devices"] == 3

    restored = restore_engine(tmp_path / "snap")
    after = restored.get_device_state("ck-1")
    assert after == before
    assert restored.get_device("ck-admin").metadata == {"phone": "+1555"}
    assert restored.metrics()["processed"] == engine.metrics()["processed"]
    # restored engine keeps working: same ids, new events merge correctly
    _measure(restored, "ck-1", "temp", 30.0)
    restored.flush()
    assert restored.get_device_state("ck-1")["measurements"]["temp"]["value"] == 30.0
    assert restored.metrics()["registered"] == 2  # no re-registration


def test_ingest_log_replay_and_watermark(tmp_path):
    from sitewhere_tpu.utils.ingestlog import IngestLog

    log = IngestLog(tmp_path / "wal", segment_bytes=256)
    for i in range(5):
        log.append(f"msg-{i}".encode())
    log.append_watermark(store_cursor=100)
    for i in range(5, 8):
        log.append(f"msg-{i}".encode())
    log.close()

    log2 = IngestLog(tmp_path / "wal")
    # full replay
    assert [p.decode() for p in log2.replay()] == [f"msg-{i}" for i in range(8)]
    # snapshot at cursor 100 covers the first five
    assert [p.decode() for p in log2.replay(after_cursor=100)] == [
        "msg-5", "msg-6", "msg-7"]
    # snapshot older than the first watermark replays everything after it too
    assert [p.decode() for p in log2.replay(after_cursor=10)] == [
        f"msg-{i}" for i in range(8)]
    log2.close()


def test_crash_resume_end_to_end(tmp_path):
    """snapshot + WAL replay reconverges to pre-crash state."""
    from sitewhere_tpu.utils.checkpoint import restore_engine, save_engine
    from sitewhere_tpu.utils.ingestlog import IngestLog
    from sitewhere_tpu.ops.readback import absolute_cursor

    wal = IngestLog(tmp_path / "wal")

    def payload(i):
        return json.dumps({
            "deviceToken": f"cr-{i % 3}", "type": "DeviceMeasurement",
            "request": {"name": "x", "value": float(i)},
        }).encode()

    engine = _engine()
    for i in range(6):
        p = payload(i)
        wal.append(p)
        engine.ingest_json_batch([p])
    engine.flush()
    save_engine(engine, tmp_path / "snap")
    wal.append_watermark(absolute_cursor(engine.state.store))
    # post-snapshot traffic, then "crash"
    for i in range(6, 10):
        p = payload(i)
        wal.append(p)
        engine.ingest_json_batch([p])
    engine.flush()
    final = engine.get_device_state("cr-0")
    wal.close()

    restored = restore_engine(tmp_path / "snap")
    wal2 = IngestLog(tmp_path / "wal")
    cursor = json.loads((tmp_path / "snap" / "manifest.json").read_text())["store_cursor"]
    for p in wal2.replay(after_cursor=cursor):
        restored.ingest_json_batch([p])
    restored.flush()
    wal2.close()
    got = restored.get_device_state("cr-0")
    assert got["measurements"]["x"]["value"] == final["measurements"]["x"]["value"]
    assert got["event_counts"] == final["event_counts"]


def test_config_driven_components():
    from sitewhere_tpu.config import ConfigError, apply_tenant_config
    from sitewhere_tpu.engine import EngineConfig
    from sitewhere_tpu.instance.instance import InstanceConfig, SiteWhereTpuInstance

    inst = SiteWhereTpuInstance(InstanceConfig(engine=EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=4096, batch_capacity=16, channels=4,
    )))
    summary = apply_tenant_config(inst, {
        "eventSources": [
            {"id": "mem-src", "type": "inmemory", "decoder": {"type": "json"},
             "deduplicator": {"type": "alternate-id"}},
        ],
        "outboundConnectors": [
            {"id": "audit", "type": "inmemory"},
        ],
        "commandRouting": {
            "router": {"type": "single-choice", "destination": "local-dest"},
            "destinations": [
                {"id": "local-dest", "type": "local", "encoder": {"type": "json"}},
            ],
        },
    })
    assert summary == {"eventSources": ["mem-src"], "connectors": ["audit"],
                       "destinations": ["local-dest"]}
    # the configured source actually feeds the engine
    src = inst.event_sources.sources["mem-src"]
    recv = src.receivers[0]
    recv.submit(json.dumps({"deviceToken": "cfg-1", "type": "DeviceMeasurement",
                            "request": {"name": "t", "value": 9}}).encode())
    inst.engine.flush()
    assert inst.engine.get_device_state("cfg-1") is not None
    # the configured connector consumes the feed
    asyncio.run(inst.pump_outbound())
    audit = inst.connector_hosts[-1].connector
    assert len(audit.events) == 1
    # bad configs fail loudly
    with pytest.raises(ConfigError, match="unknown event source type"):
        apply_tenant_config(inst, {"eventSources": [{"id": "x", "type": "bogus"}]})
    with pytest.raises(ConfigError, match="unknown connector type"):
        apply_tenant_config(inst, {"outboundConnectors": [{"id": "x", "type": "bogus"}]})


def test_tracing_stage_spans():
    from sitewhere_tpu.utils.metrics import REGISTRY
    from sitewhere_tpu.utils.tracing import stage

    with stage("unit-test-stage", tenant="t"):
        with stage("unit-test-child"):
            pass
    text = REGISTRY.expose_text()
    assert 'stage="unit-test-stage"' in text
    assert 'stage="unit-test-child"' in text


def test_checkpoint_preserves_assignments(tmp_path):
    """Assignment mirrors (tokens, slots, status) survive snapshot/restore."""
    from sitewhere_tpu.engine import Engine, EngineConfig
    from sitewhere_tpu.utils.checkpoint import restore_engine, save_engine

    engine = Engine(EngineConfig(
        device_capacity=32, token_capacity=64, assignment_capacity=64,
        store_capacity=512, batch_capacity=8, channels=4))
    engine.register_device("d1", area="hq", customer="acme")
    engine.create_assignment("d1", token="d1-x", asset="forklift")
    engine.release_assignment("d1-x")
    engine.create_assignment("d1", token="d1-y")

    save_engine(engine, tmp_path / "snap")
    restored = restore_engine(tmp_path / "snap")

    assert {a.token for a in restored.list_assignments("d1")} == \
        {a.token for a in engine.list_assignments("d1")}
    assert restored.get_assignment("d1-x").status == "RELEASED"
    assert restored.get_assignment("d1-y").status == "ACTIVE"
    assert restored.get_assignment("d1-x").asset == "forklift"
    assert restored.device_slots == engine.device_slots
    # the restored engine can keep allocating without colliding
    a = restored.create_assignment("d1", token="d1-z")
    assert a.id == engine._next_assignment


def test_scripting_component_end_to_end(tmp_path):
    """File-loaded script hooks across decoder, filter, connector, and
    router slots (reference: ScriptingComponent + script-templates)."""
    from sitewhere_tpu.config import apply_tenant_config
    from sitewhere_tpu.engine import EngineConfig
    from sitewhere_tpu.instance.instance import InstanceConfig, SiteWhereTpuInstance
    from sitewhere_tpu.utils.scripting import ScriptError, ScriptManager

    # repo-shipped templates resolve and validate
    mgr = ScriptManager("script-templates")
    assert "event-decoder.py" in mgr.list_scripts()
    decode = mgr.handle("event-decoder.py", "decode")
    reqs = decode(b"dev-9,temp,21.5", {})
    assert reqs[0].device_token == "dev-9"
    with pytest.raises(ScriptError, match="does not define"):
        mgr.handle("event-decoder.py", "nope")

    # hot reload: edits are picked up on the next call
    import time as _time

    script = tmp_path / "dec.py"
    script.write_text("def decode(p, m):\n    return []\n")
    h = ScriptManager().handle(script, "decode")
    assert h(b"", {}) == []
    _time.sleep(0.01)
    script.write_text(
        "from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType\n"
        "def decode(p, m):\n"
        "    return [DecodedRequest(type=RequestType.DEVICE_MEASUREMENT,\n"
        "            device_token=p.decode(), measurements={'x': 1.0})]\n")
    import os
    os.utime(script)
    assert h(b"sc-1", {})[0].device_token == "sc-1"

    # config-driven scripted components drive a live instance
    connector_script = tmp_path / "conn.py"
    connector_script.write_text(
        "SEEN = []\n"
        "def process_event(event):\n"
        "    SEEN.append(event.device_token)\n")
    filter_script = tmp_path / "filt.py"
    filter_script.write_text(
        "def is_excluded(event):\n"
        "    return event.etype.name != 'MEASUREMENT'\n")
    inst = SiteWhereTpuInstance(InstanceConfig(engine=EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=4096, batch_capacity=16, channels=4,
    )))
    summary = apply_tenant_config(inst, {
        "eventSources": [
            {"id": "script-src", "type": "inmemory",
             "decoder": {"type": "scripted", "script": str(script)}},
        ],
        "outboundConnectors": [
            {"id": "script-conn", "type": "scripted",
             "configuration": {"script": str(connector_script)},
             "filters": [{"type": "scripted", "script": str(filter_script)}]},
        ],
    })
    assert summary["eventSources"] == ["script-src"]
    src = inst.event_sources.sources["script-src"]
    src.receivers[0].submit(b"sdev-1")
    inst.engine.flush()
    asyncio.run(inst.pump_outbound())
    from sitewhere_tpu.utils.scripting import DEFAULT_MANAGER

    ns = DEFAULT_MANAGER._load(connector_script)
    assert ns["SEEN"] == ["sdev-1"]


def test_wal_crc_detects_corruption(tmp_path):
    """Corrupted or torn WAL records stop replay cleanly instead of
    feeding garbage to the pipeline."""
    from sitewhere_tpu.utils.ingestlog import IngestLog

    log = IngestLog(tmp_path, segment_bytes=1 << 20)
    log.append(b"good-1")
    log.append(b"good-2")
    log.append(b"good-3")
    log.close()
    seg = sorted(tmp_path.glob("segment-*.log"))[0]
    data = bytearray(seg.read_bytes())
    # flip a byte inside the LAST record's payload
    data[-2] ^= 0xFF
    seg.write_bytes(bytes(data))
    replayed = list(IngestLog(tmp_path).replay())
    assert replayed == [b"good-1", b"good-2"]
    # torn tail: truncate mid-record
    seg.write_bytes(bytes(data[:-3]))
    replayed = list(IngestLog(tmp_path).replay())
    assert replayed == [b"good-1", b"good-2"]


def test_wal_legacy_and_midchain_corruption(tmp_path):
    """Legacy (pre-CRC) segments still replay; corruption in a mid-chain
    segment stops the whole replay instead of leaving a silent gap."""
    import struct

    from sitewhere_tpu.utils.ingestlog import IngestLog

    # hand-write a legacy segment (length-only framing, no magic)
    legacy = tmp_path / "segment-00000000.log"
    with open(legacy, "wb") as fh:
        for msg in (b"old-1", b"old-2"):
            fh.write(struct.pack("<I", len(msg)))
            fh.write(msg)
    # new-format segment continues the chain
    log = IngestLog(tmp_path)
    log.append(b"new-1")
    log.close()
    assert list(IngestLog(tmp_path).replay()) == [b"old-1", b"old-2", b"new-1"]

    # corruption in a NEW-format mid-chain segment stops the whole replay
    # (CRC catches the flipped byte; a later segment exists)
    log = IngestLog(tmp_path)     # rotates to a fresh tail segment
    log.append(b"new-2")
    log.close()
    segs = sorted(tmp_path.glob("segment-*.log"))
    assert len(segs) >= 3
    mid = segs[1]                  # the segment holding new-1
    data = bytearray(mid.read_bytes())
    data[-2] ^= 0xFF               # flip a byte inside new-1's payload
    mid.write_bytes(bytes(data))
    out = list(IngestLog(tmp_path).replay())
    assert b"new-2" not in out and b"new-1" not in out
    assert out[:2] == [b"old-1", b"old-2"]


def test_tenant_labeled_metrics():
    """Per-tenant event counts via the on-device segment-sum, exported with
    tenant labels (buildLabels() analog)."""
    from sitewhere_tpu.utils.metrics import MetricsRegistry

    engine = _engine()
    for t, n in (("acme", 3), ("globex", 2)):
        for i in range(n):
            engine.process(DecodedRequest(
                type=RequestType.DEVICE_MEASUREMENT,
                device_token=f"{t}-{i}", tenant=t,
                measurements={"v": 1.0}))
    engine.flush()
    tm = engine.tenant_metrics()
    assert tm["acme"]["MEASUREMENT"] == 3
    assert tm["globex"]["MEASUREMENT"] == 2
    assert "default" not in tm  # no events there

    reg = MetricsRegistry()
    export_engine_metrics(engine, reg)
    text = reg.expose_text()
    assert 'swtpu_tenant_events{tenant="acme",type="MEASUREMENT"} 3' in text \
        or 'swtpu_tenant_events{type="MEASUREMENT",tenant="acme"} 3' in text


def test_wired_wal_recovery_mixed_formats(tmp_path):
    """EngineConfig.wal_dir wires durability into every ingest path; one
    recover_engine call restores the snapshot and replays the tagged tail
    (JSON bulk + binary bulk + per-request) through the right decoders."""
    from sitewhere_tpu.ingest.decoders import encode_binary_request
    from sitewhere_tpu.utils.checkpoint import recover_engine, save_engine

    cfg = dict(device_capacity=64, token_capacity=128,
               assignment_capacity=128, store_capacity=4096,
               batch_capacity=16, channels=4,
               wal_dir=str(tmp_path / "wal"))
    engine = Engine(EngineConfig(**cfg))

    def jrow(i):
        return json.dumps({
            "deviceToken": f"wx-{i % 2}", "type": "DeviceMeasurement",
            "request": {"name": "a", "value": float(i)}}).encode()

    engine.ingest_json_batch([jrow(i) for i in range(4)])
    engine.flush()
    save_engine(engine, tmp_path / "snap")   # writes the WAL watermark
    # post-snapshot traffic across all three ingest paths, then "crash"
    engine.ingest_json_batch([jrow(i) for i in range(4, 8)])
    engine.ingest_binary_batch([encode_binary_request(DecodedRequest(
        type=RequestType.DEVICE_MEASUREMENT, device_token="wx-0",
        measurements={"b": 42.0}))])
    engine.process(DecodedRequest(
        type=RequestType.DEVICE_LOCATION, device_token="wx-1",
        latitude=3.0, longitude=4.0))
    engine.flush()
    final = {t: engine.get_device_state(t) for t in ("wx-0", "wx-1")}
    engine.wal.close()

    restored = recover_engine(tmp_path / "snap")
    for t in ("wx-0", "wx-1"):
        got = restored.get_device_state(t)
        assert got["event_counts"] == final[t]["event_counts"], t
        # replayed no-eventDate events re-stamp at ingest time; values match
        assert {k: v["value"] for k, v in got["measurements"].items()} == \
            {k: v["value"] for k, v in final[t]["measurements"].items()}, t
    assert restored.get_device_state("wx-1")["recent_locations"][0]["latitude"] == 3.0
    # the recovered engine logs new traffic into the SAME wal
    assert restored.wal is not None
    restored.ingest_json_batch([jrow(99)])
    restored.flush()
    assert restored.get_device_state("wx-1")["measurements"]["a"]["value"] == 99.0


def test_http_connector_scripted_builders(tmp_path):
    """uri-builder / payload-builder script templates bind through config
    (the reference's last two Groovy template families)."""
    from sitewhere_tpu.config import build_connector
    from sitewhere_tpu.utils.scripting import ScriptManager

    # repo-shipped templates resolve
    mgr = ScriptManager("script-templates")
    assert {"payload-builder.py", "uri-builder.py"} <= set(mgr.list_scripts())

    uri_script = tmp_path / "u.py"
    uri_script.write_text(
        "def uri(event):\n"
        "    return f'http://x.invalid/{event.device_token}'\n")
    pay_script = tmp_path / "p.py"
    pay_script.write_text(
        "def payload(event):\n"
        "    return event.device_token.upper().encode()\n")
    engine = _engine()
    conn = build_connector({
        "id": "h", "type": "http",
        "configuration": {
            "uri": {"script": str(uri_script)},
            "payloadBuilder": {"script": str(pay_script)},
        },
    }, engine)
    from sitewhere_tpu.outbound.feed import OutboundEvent
    from sitewhere_tpu.core.types import EventType

    ev = OutboundEvent(event_id=1, etype=EventType.MEASUREMENT,
                       device_token="dv-1", device_id=0, assignment_id=0,
                       tenant="default", area_id=-1, asset_id=-1, ts_ms=1,
                       received_ms=1, measurements={}, values=[], aux0=-1,
                       aux1=-1)
    assert conn.uri(ev) == "http://x.invalid/dv-1"
    assert conn.payload_builder(ev) == b"DV-1"
