"""Device-plane observability (ISSUE 11): compile/retrace watchdog,
memory ledger, per-program cost, device profiler capture.

Pins the acceptance surface: every engine program family reports its
compiles (timed, with cost analysis) and holds a retrace budget that a
deliberate shape churn trips — loudly in normal mode, as a typed
:class:`RetraceError` BEFORE dispatch in strict mode; the memory
ledger's ring/arena byte totals reconcile with independently recomputed
capacities; the capacity high-watermarks reset on scrape; the REST
surfaces (``/api/instance/device/memory``,
``/api/instance/profile/device``) and the debug bundle's ``device``
section serve the same breakdown; and none of it leaks into
``engine.metrics()`` — the dispatch-shape equality pin runs WITH
devicewatch enabled (it defaults on), the test_ingest ~line 872
pattern."""

import json

import numpy as np
import pytest

from sitewhere_tpu.engine import Engine, EngineConfig, _empty_host_batch
from sitewhere_tpu.loadgen import generate_measurements_message
from sitewhere_tpu.utils.devicewatch import (WATCH, RetraceError,
                                             WatchScope, compile_posture,
                                             compile_totals,
                                             device_memory_payload,
                                             memory_ledger,
                                             strict_retraces)

SMALL = dict(device_capacity=64, token_capacity=128,
             assignment_capacity=128, store_capacity=4096,
             batch_capacity=16, channels=4)


def _engine(**kw) -> Engine:
    cfg = dict(SMALL)
    cfg.update(kw)
    return Engine(EngineConfig(**cfg))


def _batch(prefix="dw", n=16, base=0):
    return [generate_measurements_message(f"{prefix}-{i % 8}", base + i)
            for i in range(n)]


# ===================================================================
# Watchdog: compiles counted/timed/cost-analyzed, budgets enforced
# ===================================================================

def test_ingest_family_compiles_once_with_cost_and_timing():
    before = compile_totals().get("ingest.step", 0)
    # a shape combination no other test uses: under the full suite the
    # SMALL shape is already in jax's (and the watch's global) cache,
    # which would make this engine's first dispatch a HIT by design
    eng = _engine(store_capacity=8192, batch_capacity=48)
    eng.ingest_json_batch(_batch())
    eng.flush()
    eng.ingest_json_batch(_batch(base=100))
    eng.flush()
    post = compile_posture()["ingest.step"]
    # exactly one program for this engine, hit on the second dispatch
    assert compile_totals()["ingest.step"] == before + 1
    assert post["lastCompileS"] is not None and post["lastCompileS"] > 0
    assert post["retraceExcess"] == 0
    cost = post["lastCost"]
    assert cost and cost["flops"] > 0 and cost["bytes_accessed"] > 0


def test_warm_cache_second_engine_counts_hit_not_compile():
    """Two engines with identical shapes share jax's jit cache — the
    second engine's first dispatch must count as a cache HIT, or the
    compile counters would claim work XLA never did."""
    a = _engine()
    a.ingest_json_batch(_batch(prefix="wc"))
    a.flush()
    n0 = compile_totals().get("ingest.step", 0)
    hits0 = compile_posture()["ingest.step"]["cacheHits"]
    b = _engine()
    b.ingest_json_batch(_batch(prefix="wd"))
    b.flush()
    assert compile_totals()["ingest.step"] == n0
    assert compile_posture()["ingest.step"]["cacheHits"] > hits0


def test_retrace_budget_fires_on_shape_churn_and_strict_raises(caplog):
    """The watchdog's reason to exist: a batch whose shape drifted (here:
    capacity 24 against a 16-capacity engine) is a retrace beyond the
    engine's one-program budget — counted + shape-diff-logged in normal
    mode, raised as RetraceError BEFORE dispatch in strict mode."""
    import logging

    eng = _engine()
    eng.ingest_json_batch(_batch())
    eng.flush()
    fam0 = compile_posture()["ingest.step"]["retraceExcess"]
    churned = _empty_host_batch(24, 4)
    with caplog.at_level(logging.WARNING,
                         logger="sitewhere_tpu.utils.devicewatch"):
        eng.state, _ = eng._step(eng.state, churned)   # executes, loudly
    assert compile_posture()["ingest.step"]["retraceExcess"] == fam0 + 1
    assert any("retrace budget exceeded" in r.message for r in caplog.records)
    assert any("bool[16] -> bool[24]" in r.message
               for r in caplog.records), "shape diff not logged"
    # strict mode: raises BEFORE the jitted call — engine state is NOT
    # donated away by the refused dispatch
    churned32 = _empty_host_batch(32, 4)
    with strict_retraces():
        with pytest.raises(RetraceError):
            eng._step(eng.state, churned32)
    # the engine still works (state untouched by the strict refusal)
    eng.ingest_json_batch(_batch(base=50))
    assert eng.flush()["persisted"] > 0


def test_declared_transitions_do_not_trip_the_budget():
    """set_geofence_zones and a scan_chunk retune are DECLARED program
    changes — allowance granted / fresh scope — so legitimate operation
    never looks like churn."""
    eng = _engine(scan_chunk=2)
    eng.ingest_json_batch(_batch(n=32))
    eng.flush()
    excess0 = WATCH.excess_total()
    eng.set_geofence_zones([[(0.0, 0.0), (0.0, 1.0), (1.0, 1.0)]])
    eng.ingest_json_batch(_batch(n=32, base=100))
    eng.flush()
    eng.set_ingest_tuning(scan_chunk=4)
    eng.ingest_json_batch(_batch(n=64, base=200))
    eng.flush()
    eng.presence_sweep()
    assert WATCH.excess_total() == excess0
    # the converse guard: a NO-OP declaration (clearing already-None
    # zones, reinstalling the same zone shape) must NOT leak allowance —
    # genuine churn right after still trips the strict watchdog
    eng2 = _engine()
    eng2.ingest_json_batch(_batch(prefix="nz"))
    eng2.flush()
    eng2.set_geofence_zones([])            # zones already None: no grant
    with strict_retraces():
        with pytest.raises(RetraceError):
            eng2._step(eng2.state, _empty_host_batch(24, 4))


def test_query_batcher_records_aot_compiles_per_bucket():
    eng = _engine()
    eng.ingest_json_batch(_batch(prefix="qb"))
    eng.flush()
    before = compile_totals().get("query.batch", 0)
    eng.query_events(device_token="qb-1", limit=5)
    eng.query_events(device_token="qb-2", limit=5)    # same bucket: cached
    after1 = compile_totals()["query.batch"]
    assert after1 == before + 1
    eng.query_events(device_token="qb-1", limit=200)  # new limit bucket
    assert compile_totals()["query.batch"] == after1 + 1
    post = compile_posture()["query.batch"]
    assert post["retraceExcess"] == 0
    assert post["lastCost"] and post["lastCost"]["flops"] > 0


def test_scope_budget_allowance_semantics():
    """WatchScope unit pin: one program per bucket by default, allow()
    raises the cap, unbudgeted (bucket=None) scopes never fire."""
    scope = WatchScope(WATCH, "unit.test")
    k1 = (1, ("f32[4]",), ())
    k2 = (1, ("f32[8]",), ())
    k3 = (1, ("f32[16]",), ())
    assert scope.observe(k1, "b") == "compile"
    assert scope.observe(k1, "b") == "seen"
    fam0 = compile_posture()["unit.test"]["retraceExcess"]
    scope.observe(k2, "b")                      # beyond budget: counted
    assert compile_posture()["unit.test"]["retraceExcess"] == fam0 + 1
    scope.allow(1, "b")
    scope.observe(k3, "b")                      # granted: no new excess
    assert compile_posture()["unit.test"]["retraceExcess"] == fam0 + 1
    free = WatchScope(WATCH, "unit.free")
    for i in range(5):                          # unbudgeted: never fires
        free.observe((1, (f"f32[{i}]",), ()), None)
    assert compile_posture()["unit.free"]["retraceExcess"] == 0


def test_device_exec_histogram_harvests_from_flight_records():
    """Ingest and query device intervals land in swtpu_device_exec_seconds
    at scrape time, riding the existing consume-once flight drains — and
    repeated scrapes don't double-count."""
    from sitewhere_tpu.utils.metrics import (MetricsRegistry,
                                             devicewatch_metrics,
                                             export_engine_metrics)

    reg = MetricsRegistry()
    eng = _engine()
    eng.ingest_json_batch(_batch(prefix="ex"))
    eng.flush()
    eng.query_events(device_token="ex-1", limit=5)
    export_engine_metrics(eng, reg)
    h = devicewatch_metrics(reg)["exec"]
    n_ing = h.count(family="ingest")
    n_q = h.count(family="query")
    assert n_ing >= 1 and n_q >= 1
    export_engine_metrics(eng, reg)              # nothing new to drain
    assert h.count(family="ingest") == n_ing
    assert h.count(family="query") == n_q


# ===================================================================
# The standing pin: metrics() dispatch-shape equality WITH devicewatch
# ===================================================================

def test_metrics_dict_equality_across_dispatch_shapes_with_devicewatch():
    """The test_ingest ~line 872 pin, run explicitly WITH devicewatch on
    (its default): scan_chunk 1 vs 4 produce byte-equal metrics dicts
    and zero excess retraces — no watchdog key leaks into
    engine.metrics()."""
    def build(chunk):
        return Engine(EngineConfig(
            device_capacity=256, token_capacity=512,
            assignment_capacity=512, store_capacity=4096,
            batch_capacity=16, channels=4, scan_chunk=chunk,
            devicewatch=True))

    excess0 = WATCH.excess_total()
    a, b = build(1), build(4)
    b.epoch = a.epoch
    base = int(a.epoch.base_unix_s * 1000)
    payloads = [json.dumps(
        {"deviceToken": f"dwsc-{i % 40}", "type": "DeviceMeasurements",
         "eventDate": base + i,
         "request": {"measurements": {"t": float(i)}}}).encode()
        for i in range(160)]
    for eng in (a, b):
        for lo in range(0, 160, 16):
            eng.ingest_json_batch(payloads[lo:lo + 16])
        eng.flush()
    assert a.metrics() == b.metrics()
    assert a.metrics()["persisted"] == 160
    assert WATCH.excess_total() == excess0


# ===================================================================
# Memory ledger
# ===================================================================

def test_memory_ledger_reconciles_with_configured_capacities():
    """The bench hard-gate's logic as a unit pin: ring-store bytes equal
    the eval_shape-derived size of the configured EventStore, arena-pool
    bytes equal n_arenas x a freshly built arena of the configured
    geometry."""
    import jax

    from sitewhere_tpu.core.store import EventStore
    from sitewhere_tpu.ingest.arena import StagingArena

    eng = _engine()
    led = memory_ledger(eng)
    comp = led["components"]
    exp_store = sum(
        int(np.prod(leaf.shape)) * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(
            jax.eval_shape(lambda: EventStore.zeros(4096, 4, 1))))
    assert comp["ring_store"] == exp_store
    if eng._arena_pool is not None:
        assert comp["arena_pool"] == (
            eng._arena_pool.n_arenas * StagingArena(16, 4, lanes=1).nbytes)
    assert led["totalBytes"] == sum(comp.values())
    assert led["liveArrays"] is None or led["liveArrays"]["bytes"] > 0


def test_high_watermarks_track_peaks_and_reset_on_scrape():
    eng = _engine()
    eng.ingest_json_batch(_batch(prefix="hw", n=16))
    eng.flush()
    # peek (no reset): the ingest drove at least one arena out of the
    # pool / rows through the backlog sample point
    led = memory_ledger(eng, reset_hwm=False)
    hwm = led["highWatermarks"]
    if eng._arena_pool is not None:
        assert hwm["arena_occupancy"] >= 1
        # scrape semantics: reset drains the peak back to "current"
        assert eng._arena_pool.take_occupancy_hwm(reset=True) >= 1
        assert eng._arena_pool.take_occupancy_hwm(reset=False) \
            == eng._arena_pool.n_arenas - eng._arena_pool.free_count
    assert eng.take_backlog_hwm(reset=True) >= 0
    assert eng.take_backlog_hwm(reset=False) == eng.staged_count


# ===================================================================
# Surfaces: REST endpoints, debug bundle, open-loop compile counts
# ===================================================================

def _rest_roundtrip(paths_params):
    """Start a real instance server, GET each (path, params), return
    bodies (json)."""
    import asyncio
    import base64

    from sitewhere_tpu.instance.instance import (InstanceConfig,
                                                 SiteWhereTpuInstance)
    from sitewhere_tpu.web.rest import start_server

    async def go():
        import aiohttp

        inst = SiteWhereTpuInstance(InstanceConfig(
            engine=EngineConfig(**SMALL)))
        inst.engine.ingest_json_batch(_batch(prefix="rest"))
        inst.engine.flush()
        server = await start_server(inst)
        base = f"http://127.0.0.1:{server.port}"
        try:
            async with aiohttp.ClientSession() as s:
                basic = base64.b64encode(b"admin:password").decode()
                async with s.get(
                        f"{base}/api/authapi/jwt",
                        headers={"Authorization": f"Basic {basic}"}) as r:
                    jwt = (await r.json())["token"]
                out = []
                for path, params in paths_params:
                    async with s.get(
                            base + path, params=params,
                            headers={"Authorization":
                                     f"Bearer {jwt}"}) as r:
                        out.append((r.status, await r.json()))
                return out
        finally:
            await server.cleanup()

    return asyncio.new_event_loop().run_until_complete(go())


def test_rest_device_memory_endpoint():
    (status, body), = _rest_roundtrip(
        [("/api/instance/device/memory", None)])
    assert status == 200
    assert body["components"]["ring_store"] > 0
    assert "highWatermarks" in body and "totalBytes" in body
    fams = body["compileFamilies"]
    assert fams["ingest.step"]["compiles"] >= 1


def test_rest_device_profile_endpoint(tmp_path):
    """GET /api/instance/profile/device?ms=N captures a jax profiler
    trace into a named directory (CPU captures host runtime; TPU runs
    get real device timelines) — or degrades to 503 if this backend has
    no profiler."""
    import os

    (status, body), = _rest_roundtrip(
        [("/api/instance/profile/device", {"ms": "60"})])
    if status == 503:
        pytest.skip(f"profiler unavailable: {body}")
    assert status == 200
    assert os.path.isdir(body["dir"])
    assert body["files"], "profiler capture produced no files"
    assert body["bytes"] > 0


def test_debug_bundle_carries_device_section():
    from sitewhere_tpu.utils.tracing import debug_bundle

    eng = _engine()
    eng.ingest_json_batch(_batch(prefix="db"))
    eng.flush()
    bundle = debug_bundle(eng)
    dev = bundle["device"]
    assert dev["components"]["ring_store"] > 0
    assert dev["compileFamilies"]["ingest.step"]["compiles"] >= 1
    json.dumps(bundle)                     # the bundle stays one document


def test_open_loop_reports_compile_counts():
    from sitewhere_tpu.loadgen import (OpenLoopSpec, TenantLoad,
                                       build_open_loop_schedule,
                                       run_open_loop)

    eng = _engine()
    spec = OpenLoopSpec(
        tenants=(TenantLoad("dwol", 400.0, n_devices=8),),
        duration_s=0.3, frame_size=16, seed=7)
    res = run_open_loop(eng, build_open_loop_schedule(spec))
    assert res.compile_counts is not None
    # a COLD engine compiles its step during the run; a second identical
    # run is steady-state and must report no ingest compiles
    res2 = run_open_loop(eng, build_open_loop_schedule(spec))
    assert not any(f.startswith("ingest.")
                   for f in (res2.compile_counts or {}))
