"""Geofencing tests: vectorized point-in-polygon kernel + zone monitor
entry/exit alerts over the location feed."""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType
from sitewhere_tpu.ops.geofence import pack_zones, points_in_zones


def _pip_oracle(point, poly):
    """Classic ray-casting reference implementation."""
    x, y = point[1], point[0]
    inside = False
    n = len(poly)
    for i in range(n):
        ay, ax = poly[i]
        by, bx = poly[(i + 1) % n]
        if (ay > y) != (by > y):
            if x < ax + (y - ay) * (bx - ax) / (by - ay):
                inside = not inside
    return inside


def test_points_in_zones_matches_oracle():
    rng = np.random.default_rng(0)
    square = [(0.0, 0.0), (0.0, 10.0), (10.0, 10.0), (10.0, 0.0)]
    triangle = [(20.0, 20.0), (30.0, 25.0), (20.0, 30.0)]
    concave = [(0.0, 20.0), (10.0, 20.0), (10.0, 30.0), (5.0, 25.0),
               (0.0, 30.0)]   # notched — concave polygons must work
    zones = [square, triangle, concave]
    verts, valid = pack_zones(zones, max_vertices=8)
    pts = rng.uniform(-5, 35, size=(256, 2)).astype(np.float32)
    got = np.asarray(points_in_zones(jnp.asarray(pts), jnp.asarray(verts),
                                     jnp.asarray(valid)))
    for i in range(len(pts)):
        for z, poly in enumerate(zones):
            assert got[i, z] == _pip_oracle(pts[i], poly), (pts[i], z)


def test_pack_zones_validation():
    with pytest.raises(ValueError, match=">= 3 vertices"):
        pack_zones([[(0, 0), (1, 1)]])
    with pytest.raises(ValueError, match="> capacity"):
        pack_zones([[(0, 0)] * 20], max_vertices=8)
    verts, valid = pack_zones([])
    assert not valid.any()


def test_zone_monitor_entry_exit_alerts():
    """Locations crossing a zone boundary raise entered/exited alerts that
    flow through the pipeline like any device alert."""
    from sitewhere_tpu.instance.instance import InstanceConfig, SiteWhereTpuInstance

    inst = SiteWhereTpuInstance(InstanceConfig(engine=EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=4096, batch_capacity=16, channels=4)))
    dm = inst.device_management
    dm.create_area_type("site", "Site")
    dm.create_area("plant", "site", "Plant")
    dm.create_zone("fence", "plant", "Fence",
                   bounds=[(0.0, 0.0), (0.0, 10.0), (10.0, 10.0), (10.0, 0.0)])
    inst.engine.register_device("rover")

    def locate(lat, lon):
        inst.engine.process(DecodedRequest(
            type=RequestType.DEVICE_LOCATION, device_token="rover",
            latitude=lat, longitude=lon))
        inst.engine.flush()
        return asyncio.new_event_loop().run_until_complete(
            inst.zone_monitor.pump())

    assert locate(5.0, 5.0) == 1        # entered
    assert locate(6.0, 6.0) == 0        # still inside: no new alert
    assert locate(50.0, 50.0) == 1      # exited
    inst.engine.flush()
    st = inst.engine.get_device_state("rover")
    kinds = [a["type"] for a in st["recent_alerts"]]
    assert "zone.entered:fence" in kinds
    assert "zone.exited:fence" in kinds


def test_zone_contains_rest():
    import base64

    from sitewhere_tpu.instance.instance import InstanceConfig, SiteWhereTpuInstance
    from sitewhere_tpu.web.rest import start_server

    async def go():
        import aiohttp

        inst = SiteWhereTpuInstance(InstanceConfig(engine=EngineConfig(
            device_capacity=32, token_capacity=64, assignment_capacity=64,
            store_capacity=1024, batch_capacity=8, channels=4)))
        dm = inst.device_management
        dm.create_area_type("site", "Site")
        dm.create_area("plant", "site", "Plant")
        dm.create_zone("z1", "plant", "Z1",
                       bounds=[(0.0, 0.0), (0.0, 4.0), (4.0, 4.0), (4.0, 0.0)])
        server = await start_server(inst)
        base = f"http://127.0.0.1:{server.port}"
        try:
            async with aiohttp.ClientSession() as s:
                basic = base64.b64encode(b"admin:password").decode()
                async with s.get(f"{base}/api/authapi/jwt",
                                 headers={"Authorization": f"Basic {basic}"}) as r:
                    jwt = (await r.json())["token"]
                h = {"Authorization": f"Bearer {jwt}"}
                async with s.get(f"{base}/api/zones/z1/contains",
                                 params={"latitude": "2", "longitude": "2"},
                                 headers=h) as r:
                    assert (await r.json())["contains"] is True
                async with s.get(f"{base}/api/zones/z1/contains",
                                 params={"latitude": "9", "longitude": "9"},
                                 headers=h) as r:
                    assert (await r.json())["contains"] is False
        finally:
            await server.cleanup()

    asyncio.new_event_loop().run_until_complete(go())


def test_zone_monitor_resilience():
    """Bounds edits invalidate the cache; deleting all zones flushes exits;
    oversized zones are rejected at create and skipped by the monitor."""
    from sitewhere_tpu.instance.instance import InstanceConfig, SiteWhereTpuInstance

    inst = SiteWhereTpuInstance(InstanceConfig(engine=EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=4096, batch_capacity=16, channels=4)))
    dm = inst.device_management
    dm.create_area_type("site", "Site")
    dm.create_area("plant", "site", "Plant")
    dm.create_zone("fence", "plant", "Fence",
                   bounds=[(0.0, 0.0), (0.0, 10.0), (10.0, 10.0), (10.0, 0.0)])
    inst.engine.register_device("rover")
    loop = asyncio.new_event_loop()

    def locate(lat, lon):
        inst.engine.process(DecodedRequest(
            type=RequestType.DEVICE_LOCATION, device_token="rover",
            latitude=lat, longitude=lon))
        inst.engine.flush()
        return loop.run_until_complete(inst.zone_monitor.pump())

    assert locate(5.0, 5.0) == 1        # entered original fence

    # delete + recreate the same token with moved bounds: cache must follow
    dm.zones.delete("fence")
    dm.create_zone("fence", "plant", "Fence",
                   bounds=[(100.0, 100.0), (100.0, 110.0), (110.0, 110.0),
                           (110.0, 100.0)])
    assert locate(5.0, 5.0) == 1        # exited (new fence elsewhere)
    assert locate(105.0, 105.0) == 1    # entered relocated fence

    # deleting every zone flushes a final exit
    dm.zones.delete("fence")
    assert locate(105.0, 105.0) == 1    # zone.exited despite zero zones

    # oversized zones: rejected at create; a hand-inserted one is skipped
    with pytest.raises(ValueError, match="exceed 16"):
        dm.create_zone("big", "plant", "Big",
                       bounds=[(float(i), float(i)) for i in range(20)])
    from sitewhere_tpu.management.device_management import Zone

    dm.zones.create("sneaky", lambda m: Zone(
        meta=m, area_token="plant", name="Sneaky",
        bounds=[(float(i), 0.0) for i in range(20)]))
    assert locate(1.0, 1.0) == 0        # pump survives, zone ignored
