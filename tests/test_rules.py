"""Streaming-rules CEP tier (ISSUE 13): fused in-step rule evaluation,
continuous rollups, hot reload, and the surfaces.

The contract pinned here:
  * every rule kind (threshold / windowed aggregate / sequence / absence)
    fires exactly the key set the sequential host oracle computes, and
    the fire set is BATCH-PARTITION INVARIANT (the replay/standby parity
    foundation);
  * rollup reads match a host-side recompute exactly;
  * alert events ride the normal ingest pipeline (persisted, queryable
    by their rule+group+window alternate id);
  * rule-set hot reload is compile-before-swap: a parameter tweak
    preserves carried state and compiles nothing, a shape change rides a
    devicewatch allowance (never an excess retrace), and a bad document
    is rejected loudly with the active set still serving.
"""

import json

import numpy as np
import pytest

from sitewhere_tpu.engine import Engine, EngineConfig, _PrecompiledStep
from sitewhere_tpu.rules import RuleSet, RuleSetError, RulesManager
from sitewhere_tpu.rules import oracle
from sitewhere_tpu.utils.devicewatch import WATCH, strict_retraces

CFG = dict(device_capacity=256, token_capacity=512,
           assignment_capacity=512, store_capacity=4096,
           batch_capacity=32, channels=4, rule_groups=64,
           rollup_buckets=8)

RULESET = {
    "name": "t",
    "rules": [
        {"name": "hot", "kind": "threshold", "channel": "temp",
         "op": ">", "value": 90.0, "cooldownMs": 1000},
        {"name": "burst", "kind": "window", "agg": "count",
         "channel": "temp", "op": ">=", "value": 3, "windowMs": 2000,
         "where": {"channel": "temp", "op": ">", "value": 50.0}},
        {"name": "updown", "kind": "sequence",
         "first": {"channel": "temp", "op": ">", "value": 90.0},
         "then": {"channel": "temp", "op": "<", "value": 5.0},
         "withinMs": 4000},
        {"name": "silent", "kind": "absence", "channel": "temp",
         "deadlineMs": 3000},
    ],
    "rollups": [{"name": "temp-1s", "channel": "temp",
                 "windowMs": 1000, "scope": "device"}],
}


def _engine(**kw):
    return Engine(EngineConfig(**{**CFG, **kw}))


def _meas(eng, tok, v, ts_rel):
    return json.dumps({
        "deviceToken": tok, "type": "DeviceMeasurement",
        "request": {"name": "temp", "value": v,
                    "eventDate": int(eng.epoch.base_unix_s * 1000)
                    + ts_rel}}).encode()


# deterministic stream: (device-suffix, value, ts) — halves only, so
# float32 sum parity is rounding-order free
def _stream(n=96, devs=6, quiet_after=None):
    out = []
    for i in range(n):
        d = i % devs
        if quiet_after is not None and d == 0 and i >= quiet_after:
            d = 1
        v = 96.5 if i % 11 == 0 else 20.0 + (i % 40) * 0.5
        if i % 23 == 0:
            v = 2.5
        out.append((d, v, i * 100))
    return out


def _oracle_keys(events, final_wm):
    ev = [{"ts": ts, "group": d, "value": v, "value_b": v}
          for d, v, ts in events]
    exp = set()
    for g, w in oracle.threshold_fire_keys(ev, op=0, value=90.0,
                                           cooldown_ms=1000):
        exp.add(f"swr:hot:r-{g}:{w}")
    for g, w in oracle.window_fire_keys(ev, agg="count", op=1, value=3,
                                        window_ms=2000, where=(0, 50.0)):
        exp.add(f"swr:burst:r-{g}:{w}")
    for g, w in oracle.sequence_fire_keys(ev, op_a=0, val_a=90.0,
                                          op_b=2, val_b=5.0,
                                          within_ms=4000):
        exp.add(f"swr:updown:r-{g}:{w}")
    for g, w in oracle.absence_fire_keys(ev, op=1, value=float("-inf"),
                                         deadline_ms=3000,
                                         final_watermark=final_wm):
        exp.add(f"swr:silent:r-{g}:{w}")
    return exp


def _run(eng, events, chunk=32):
    for lo in range(0, len(events), chunk):
        eng.ingest_json_batch([_meas(eng, f"r-{d}", v, ts)
                               for d, v, ts in events[lo:lo + chunk]])
        eng.flush()


def test_all_rule_kinds_match_oracle_and_alerts_persist():
    eng = _engine()
    mgr = RulesManager(eng)
    mgr.load(RULESET)
    events = _stream(quiet_after=48)
    _run(eng, events)
    alerts = mgr.poll()
    got = {a["alternateId"] for a in alerts}
    assert got == _oracle_keys(events, final_wm=events[-1][2])
    assert eng.metrics()["rule_fires"] == len(got)
    eng.flush()
    # alert events persisted through the NORMAL pipeline: queryable by
    # type and by their dedup alternate id
    from sitewhere_tpu.core.types import EventType

    q = eng.query_events(etype=EventType.ALERT, limit=100)
    assert q["total"] == len(got)
    one = alerts[0]
    byid = eng.query_events(alternate_id=one["alternateId"], limit=10)
    assert byid["total"] == 1
    assert byid["events"][0]["alertType"] == one["alertType"]
    # a second poll harvests nothing new and re-emits nothing
    assert mgr.poll() == []
    # rollup parity, exact
    ev = [{"ts": ts, "group": d, "value": v} for d, v, ts in events]
    want = oracle.rollup_oracle(ev, window_ms=1000, buckets=8)
    for g in range(6):
        got_r = mgr.read_rollup("temp-1s", group=f"r-{g}")
        got_map = {b["windowStartMs"]: (b["count"], b["sum"], b["min"],
                                        b["max"])
                   for b in got_r["buckets"]}
        want_map = {st[0] * 1000: (st[1], st[2], st[3], st[4])
                    for (gg, s), st in want.items() if gg == g}
        assert got_map == want_map, f"rollup mismatch for r-{g}"


def test_fire_set_is_batch_partition_invariant():
    """Same stream, radically different ingest batch boundaries ->
    identical fire keys, identical rule_fires counter, identical rollup
    state (the replay/standby re-evaluation contract)."""
    events = _stream(n=80, quiet_after=40)
    results = []
    for chunk in (80, 7, 1):
        eng = _engine()
        mgr = RulesManager(eng)
        mgr.load(RULESET, precompile=False)
        _run(eng, events, chunk=chunk)
        alerts = mgr.poll()
        rollup = mgr.read_rollup("temp-1s", group="r-1")
        results.append(({a["alternateId"] for a in alerts},
                        eng.metrics()["rule_fires"],
                        rollup["buckets"]))
    assert results[0] == results[1] == results[2]
    assert results[0][0]     # the scenario actually fired


def test_threshold_dedup_within_window_and_refire_next_window():
    eng = _engine()
    mgr = RulesManager(eng)
    mgr.load({"rules": [{"name": "hot", "kind": "threshold",
                         "channel": "temp", "op": ">", "value": 90.0,
                         "cooldownMs": 1000}]})
    # three crossings inside one window -> ONE alert; next window refires
    _run(eng, [(0, 95.0, 100), (0, 97.5, 200), (0, 99.0, 900),
               (0, 95.0, 1500)], chunk=2)
    alerts = mgr.poll()
    assert sorted(a["key"] for a in alerts) == [0, 1]
    assert all(a["rule"] == "hot" for a in alerts)


def test_hot_reload_param_tweak_preserves_state_and_program(tmp_path):
    eng = _engine()
    mgr = RulesManager(eng)
    path = tmp_path / "rules.json"
    path.write_text(json.dumps(RULESET))
    mgr.watch_file(path)
    step_before = eng._step
    # two of the three window events land BEFORE the swap
    _run(eng, [(0, 60.0, 100), (0, 61.0, 200)], chunk=2)
    compiles_before = WATCH.compile_totals()
    doc = json.loads(json.dumps(RULESET))
    doc["rules"][0]["value"] = 80.0        # tweak another rule's param
    path.write_text(json.dumps(doc))
    import os

    os.utime(path, (path.stat().st_mtime + 2,) * 2)
    assert mgr.check_reload() is True
    assert eng._step is step_before        # no rewrap...
    assert WATCH.compile_totals() == compiles_before   # ...no recompile
    # third event completes the carried window -> the accumulator
    # survived the swap
    _run(eng, [(0, 62.0, 300)], chunk=1)
    alerts = mgr.poll()
    assert any(a["rule"] == "burst" for a in alerts)


def test_window_change_resets_state_instead_of_preserving():
    """Fire keys are denominated in window units: a cooldown/window
    tweak must NOT take the preserve-state path, or old-unit fired keys
    would suppress the rule until uptime catches up (review-found)."""
    eng = _engine()
    mgr = RulesManager(eng)
    doc = {"rules": [{"name": "hot", "kind": "threshold",
                      "channel": "temp", "op": ">", "value": 90.0,
                      "cooldownMs": 1000}]}
    mgr.load(doc, precompile=False)
    _run(eng, [(0, 95.0, 500_000)], chunk=1)   # fired_key = 500
    assert len(mgr.poll()) == 1
    doc2 = json.loads(json.dumps(doc))
    doc2["rules"][0]["cooldownMs"] = 60_000
    summary = mgr.load(doc2, precompile=False)
    assert summary["preservedState"] is False
    # under the new 60s windows this crossing is wid 11 — it must fire
    # (old-unit fired_key=500 would have silently swallowed it)
    _run(eng, [(0, 96.0, 700_000)], chunk=1)
    assert [a["key"] for a in mgr.poll()] == [700_000 // 60_000]


def test_hot_reload_shape_change_is_allowance_not_excess(tmp_path):
    eng = _engine()
    mgr = RulesManager(eng)
    path = tmp_path / "rules.json"
    path.write_text(json.dumps(RULESET))
    mgr.watch_file(path)
    _run(eng, [(0, 95.0, 100)], chunk=1)
    # adding a rule changes the device-table shapes: a DECLARED swap —
    # strict mode must not see an excess retrace, and the precompiled
    # program must have been built OFF the engine lock
    doc = json.loads(json.dumps(RULESET))
    doc["rules"].append({"name": "cold", "kind": "threshold",
                         "channel": "temp", "op": "<", "value": -50.0,
                         "cooldownMs": 1000})
    path.write_text(json.dumps(doc))
    import os

    os.utime(path, (path.stat().st_mtime + 2,) * 2)
    seen = {}
    orig = eng.precompile_rules

    def spy(rules_state):
        seen["locked_during_compile"] = eng.lock._is_owned()
        return orig(rules_state)

    eng.precompile_rules = spy
    excess0 = WATCH.excess_total()
    with strict_retraces():
        assert mgr.check_reload() is True
        _run(eng, [(0, 95.0, 1100), (0, -60.0, 1200)], chunk=2)
    assert WATCH.excess_total() == excess0
    assert seen["locked_during_compile"] is False
    # the installed hot program is the AOT-compiled shim
    assert isinstance(getattr(eng._step, "fn", eng._step),
                      _PrecompiledStep)
    alerts = mgr.poll()
    assert {a["rule"] for a in alerts} >= {"hot", "cold"}


def test_bad_ruleset_rejected_loudly_old_set_keeps_serving(tmp_path):
    eng = _engine()
    mgr = RulesManager(eng)
    path = tmp_path / "rules.json"
    path.write_text(json.dumps(RULESET))
    mgr.watch_file(path)
    import os

    for bad in ("{not json", json.dumps({"rules": [
            {"name": "x", "kind": "window", "agg": "count",
             "channel": "temp", "op": "<", "value": 1,
             "windowMs": 1000}]})):   # non-monotone (agg, op) combo
        path.write_text(bad)
        os.utime(path, (path.stat().st_mtime + 2,) * 2)
        with pytest.raises((RuleSetError, ValueError)):
            mgr.check_reload()
        assert mgr.ruleset is not None and mgr.ruleset.name == "t"
    assert mgr.reload_errors == 2
    # the active set still evaluates
    _run(eng, [(0, 95.0, 100)], chunk=1)
    assert any(a["rule"] == "hot" for a in mgr.poll())


def test_ruleset_validation_errors():
    with pytest.raises(RuleSetError):
        RuleSet.parse({"rules": []})                     # empty
    with pytest.raises(RuleSetError):
        RuleSet.parse({"rules": [{"name": "a:b", "kind": "threshold",
                                  "channel": "t", "op": ">",
                                  "value": 1}]})         # ':' in name
    with pytest.raises(RuleSetError):
        RuleSet.parse({"rules": [{"name": "a", "kind": "nope"}]})
    with pytest.raises(RuleSetError):
        RuleSet.parse({"rules": [
            {"name": "a", "kind": "sequence",
             "first": {"channel": "t", "op": ">", "value": 1},
             "then": {"channel": "t", "op": "<", "value": 0}}]})
    with pytest.raises(RuleSetError):                    # dup names
        RuleSet.parse({"rules": [
            {"name": "a", "kind": "threshold", "channel": "t",
             "op": ">", "value": 1},
            {"name": "a", "kind": "threshold", "channel": "t",
             "op": ">", "value": 2}]})


def test_area_scoped_rule_fires_on_emitter_device():
    eng = _engine()
    eng.register_device("a-1", tenant="default", area="zone-a")
    eng.register_device("a-2", tenant="default", area="zone-a")
    mgr = RulesManager(eng)
    mgr.load({"rules": [{"name": "area-hot", "kind": "window",
                         "agg": "count", "channel": "temp", "op": ">=",
                         "value": 3, "windowMs": 10000,
                         "scope": "area"}]})
    # three events across TWO devices of one area cross the count
    _run(eng, [], chunk=1)
    eng.ingest_json_batch([_meas(eng, "a-1", 10.0, 100),
                           _meas(eng, "a-2", 11.0, 200),
                           _meas(eng, "a-1", 12.0, 300)])
    eng.flush()
    alerts = mgr.poll()
    assert len(alerts) == 1
    a = alerts[0]
    assert a["scope"] == "area" and a["group"] == "zone-a"
    assert a["deviceToken"].startswith("swrules-")
    # the emitter device persisted the alert through the normal path
    from sitewhere_tpu.core.types import EventType

    eng.flush()
    q = eng.query_events(device_token=a["deviceToken"],
                         etype=EventType.ALERT, limit=10)
    assert q["total"] == 1


def test_metrics_dict_equality_across_dispatch_shapes_with_rules():
    """The standing dispatch-shape pin, WITH the CEP tier enabled:
    scan_chunk 1 vs 4 produce byte-equal metrics dicts (rule_fires
    included) after identical streams + polls."""
    events = _stream(n=64)

    def build(chunk):
        e = _engine(scan_chunk=chunk)
        m = RulesManager(e)
        m.load(RULESET, precompile=False)
        return e, m

    a, ma = build(1)
    b, mb = build(4)
    b.epoch = a.epoch
    for eng, mgr in ((a, ma), (b, mb)):
        for lo in range(0, len(events), 16):
            eng.ingest_json_batch([_meas(a, f"r-{d}", v, ts)
                                   for d, v, ts in events[lo:lo + 16]])
        eng.flush()
        mgr.poll()
        eng.flush()
    assert a.metrics() == b.metrics()
    assert a.metrics()["rule_fires"] > 0


def test_rules_rest_surface():
    """REST CRUD + rollup reads + status over a live gateway."""
    import asyncio
    import base64

    import aiohttp

    from sitewhere_tpu.instance.instance import (InstanceConfig,
                                                 SiteWhereTpuInstance)
    from sitewhere_tpu.web.rest import start_server

    loop = asyncio.new_event_loop()
    inst = SiteWhereTpuInstance(InstanceConfig(engine=EngineConfig(**CFG)))
    server = loop.run_until_complete(start_server(inst))
    session = aiohttp.ClientSession(loop=loop)
    base = f"http://127.0.0.1:{server.port}"
    try:
        async def get_token():
            basic = base64.b64encode(b"admin:password").decode()
            async with session.get(
                    f"{base}/api/authapi/jwt",
                    headers={"Authorization": f"Basic {basic}"}) as r:
                return (await r.json())["token"]

        token = loop.run_until_complete(get_token())

        def call(method, path, json_body=None, params=None):
            async def go():
                async with session.request(
                        method, base + path, json=json_body,
                        params=params,
                        headers={"Authorization": f"Bearer {token}"}) as r:
                    return r.status, await r.json()

            return loop.run_until_complete(go())

        st, body = call("POST", "/api/rules", RULESET)
        assert st == 201 and body["summary"]["rules"] == 4
        st, body = call("POST", "/api/rules", {"rules": [
            {"name": "bad", "kind": "window", "agg": "count",
             "channel": "t", "op": "<", "value": 1, "windowMs": 10}]})
        assert st == 400
        eng = inst.engine
        eng.ingest_json_batch([_meas(eng, "rest-0", 95.0, 100)])
        eng.flush()
        st, body = call("POST", "/api/rules/poll", {"flush": False})
        assert st == 200
        assert {a["rule"] for a in body["alerts"]} == {"hot"}
        st, body = call("GET", "/api/rules")
        assert st == 200 and body["status"]["alertsEmitted"] == 1
        assert body["ruleSet"]["name"] == "t"
        st, body = call("GET", "/api/rules/rollups")
        assert st == 200 and body[0]["name"] == "temp-1s"
        st, body = call("GET", "/api/rules/rollups/temp-1s",
                        params={"group": "rest-0"})
        assert st == 200 and body["buckets"][0]["count"] == 1
        st, _ = call("GET", "/api/rules/rollups/nope")
        assert st == 404
    finally:
        loop.run_until_complete(session.close())
        loop.run_until_complete(server.cleanup())
        loop.close()
