"""Cluster-scale observability (ISSUE 7): the federated metrics/SLO
plane and per-peer replication staleness.

Covers the acceptance surface end to end: ``ClusterEngine.cluster_metrics``
returns ONE lint-clean rank-labeled exposition covering every live rank
(HELP/TYPE deduped), the ``GET /api/instance/cluster/metrics`` REST
endpoint serves it, SLO histogram exemplars resolve back through
``/api/instance/trace/<id>``, and a follower's staleness watermark is
visible per LEADER both on the Prometheus plane
(``swtpu_replication_stale_ms{leader=...}``) and in the
``cluster_status`` health block.

Topology note: both ranks live in one process here, so they share the
process-global metrics REGISTRY — each rank's exposition text is
captured by that rank's own export call, which is exactly the per-rank
snapshot a real (per-process) deployment federates.
"""

import json
import time

import pytest

from sitewhere_tpu.parallel.cluster import ClusterEngine
from sitewhere_tpu.parallel.replication import (ReplicaApplier, ReplicaFeed,
                                                register_replication_rpc)
from tests.test_cluster import (_close, _free_ports, _mk_cluster, meas,
                                tokens_owned_by)
from tests.test_metrics_exposition import lint_prometheus


def _ingest_both_ranks(c0, n=8, prefix="fm", tenant="default"):
    toks = tokens_owned_by(0, n // 2, prefix=prefix) + \
        tokens_owned_by(1, n // 2, prefix=prefix)
    c0.ingest_json_batch([meas(t, "t", float(i), 50 + i)
                          for i, t in enumerate(toks)], tenant)
    c0.flush()
    return toks


def test_cluster_metrics_is_one_lint_clean_rank_labeled_exposition(tmp_path):
    clusters, host, _ = _mk_cluster(tmp_path)
    c0, _c1 = clusters
    try:
        _ingest_both_ranks(c0)
        text = c0.cluster_metrics()
        lint_prometheus(text)
        # every live rank present, under a rank label
        assert 'rank="0"' in text and 'rank="1"' in text
        assert 'swtpu_cluster_rank_up{rank="0"} 1' in text
        assert 'swtpu_cluster_rank_up{rank="1"} 1' in text
        # HELP/TYPE deduped across ranks even though both expose the
        # same families
        assert text.count("# HELP swtpu_engine_persisted") == 1
        assert text.count("# TYPE swtpu_ingest_e2e_seconds histogram") == 1
        # the per-tenant SLO histogram harvested from flight records
        assert 'swtpu_ingest_e2e_seconds_bucket{' in text
        # device plane (ISSUE 11): every rank's scrape carries the XLA
        # watchdog counters and the memory-ledger gauges — the federated
        # payload is the single pane the ROADMAP-2 sharded-store work
        # reads "does tenants x devices still fit one chip's HBM" from
        for rank in ("0", "1"):
            assert (f'swtpu_xla_compiles_total{{rank="{rank}",'
                    f'family="sharded.step"}}') in text
        import re as _re

        for rank in ("0", "1"):
            assert _re.search(
                rf'swtpu_device_mem_bytes\{{rank="{rank}",'
                r'component="ring_store",engine="e\d+"\}', text), (
                f"rank {rank} exports no memory ledger")
    finally:
        _close(clusters, host)


def test_cluster_metrics_exemplar_links_to_a_resolvable_trace(tmp_path):
    """A slowest-decile SLO observation carries a trace-id exemplar, and
    that id resolves through the cluster trace fan-out — the p99-spike →
    flight-record drill-down path."""
    import re

    clusters, host, _ = _mk_cluster(tmp_path)
    c0, _c1 = clusters
    try:
        # a FRESH tenant: the process-global registry accumulates SLO
        # series (and exemplars) across tests in this process, and an
        # old exemplar's records live in recorders long since closed
        _ingest_both_ranks(c0, prefix="ex", tenant="ex-tenant")
        text = c0.cluster_metrics()
        m = re.search(r'swtpu_ingest_e2e_seconds_bucket\{[^{}]*'
                      r'tenant="ex-tenant"[^{}]*\} \d+ '
                      r'# \{trace_id="([^"]+)"\}', text)
        assert m, "no exemplar on the SLO histogram buckets"
        trace = c0.get_trace(m.group(1))
        assert trace["records"], "exemplar trace id did not resolve"
    finally:
        _close(clusters, host)


def test_slo_harvest_consumes_each_record_once(tmp_path):
    """Two consecutive scrapes must not double-count: the flight-record
    harvest marks records consumed, so the histogram's event count equals
    ingested events no matter how many scrape surfaces race."""
    from sitewhere_tpu.utils.metrics import slo_metrics

    clusters, host, _ = _mk_cluster(tmp_path)
    c0, _c1 = clusters
    try:
        _ingest_both_ranks(c0, n=8, prefix="hv")
        hist = slo_metrics()["ingest_e2e"]
        before = hist.count_where(tenant="default")
        c0.cluster_metrics()
        mid = hist.count_where(tenant="default")
        c0.cluster_metrics()          # second scrape: nothing new
        after = hist.count_where(tenant="default")
        assert mid - before >= 8      # every ingested event observed once
        assert after == mid
    finally:
        _close(clusters, host)


def test_cluster_metrics_down_rank_degrades_not_fails(tmp_path):
    clusters, host, _ = _mk_cluster(tmp_path)
    # short timeout so the tolerant fan-out does not stall the test
    for c in clusters:
        c.cluster_config.connect_timeout_s = 1.0
    c0, _c1 = clusters
    try:
        _ingest_both_ranks(c0, prefix="dn")
        host.stop(host.servers[1])
        text = c0.cluster_metrics()
        lint_prometheus(text)
        assert 'swtpu_cluster_rank_up{rank="0"} 1' in text
        assert 'swtpu_cluster_rank_up{rank="1"} 0' in text
    finally:
        _close(clusters, host)


def test_rest_cluster_metrics_endpoint(tmp_path):
    """GET /api/instance/cluster/metrics serves the federated payload;
    on a SINGLE-NODE instance it degrades to the local registry under
    rank=\"0\" — the scrape contract is topology-independent."""
    import asyncio
    import base64

    from sitewhere_tpu.engine import EngineConfig
    from sitewhere_tpu.instance.instance import (InstanceConfig,
                                                 SiteWhereTpuInstance)
    from sitewhere_tpu.web.rest import start_server

    async def go():
        import aiohttp

        inst = SiteWhereTpuInstance(InstanceConfig(engine=EngineConfig(
            device_capacity=64, token_capacity=128, assignment_capacity=128,
            store_capacity=4096, batch_capacity=16, channels=4)))
        inst.engine.ingest_json_batch([json.dumps(
            {"deviceToken": f"rm-{i}", "type": "DeviceMeasurements",
             "request": {"measurements": {"t": float(i)}}}).encode()
            for i in range(6)])
        inst.engine.flush()
        server = await start_server(inst)
        base = f"http://127.0.0.1:{server.port}"
        try:
            async with aiohttp.ClientSession() as s:
                basic = base64.b64encode(b"admin:password").decode()
                async with s.get(
                    f"{base}/api/authapi/jwt",
                    headers={"Authorization": f"Basic {basic}"},
                ) as r:
                    jwt = (await r.json())["token"]
                H = {"Authorization": f"Bearer {jwt}"}
                async with s.get(
                    f"{base}/api/instance/cluster/metrics", headers=H,
                ) as r:
                    assert r.status == 200
                    assert r.content_type == "text/plain"
                    plain = await r.text()
                async with s.get(
                    f"{base}/api/instance/cluster/metrics",
                    headers={**H,
                             "Accept": "application/openmetrics-text"},
                ) as r:
                    assert r.status == 200
                    assert r.content_type == "application/openmetrics-text"
                    om = await r.text()
                return plain, om
        finally:
            await server.cleanup()

    plain, om = asyncio.new_event_loop().run_until_complete(go())
    # default: strict text-format 0.0.4 — no exemplar syntax at all
    lint_prometheus(plain)
    assert 'rank="0"' in plain
    assert "swtpu_ingest_e2e_seconds" in plain
    assert "# {" not in plain
    # the same-contract availability series exists on a single node too
    assert 'swtpu_cluster_rank_up{rank="0"} 1' in plain
    # negotiated OpenMetrics: exemplars allowed, mandatory EOF terminator
    assert om.endswith("# EOF\n")
    lint_prometheus(om.rsplit("# EOF\n", 1)[0])


def _mk_replicated_cluster(tmp_path):
    """Two ranks with RF=2 replication attached (feed + applier + the
    replication RPC surface), feeds running."""
    from sitewhere_tpu.parallel.cluster import (ClusterConfig,
                                                build_cluster_rpc)
    from tests.test_cluster import BASE_S, _ServerHost, _engine_cfg

    ports = _free_ports(2)
    peers = [f"127.0.0.1:{p}" for p in ports]
    host = _ServerHost()
    clusters, feeds = [], []
    for r in range(2):
        cc = ClusterConfig(rank=r, n_ranks=2, peers=peers,
                           secret="obs-secret", epoch_base_unix_s=BASE_S,
                           engine=_engine_cfg(tmp_path, r),
                           connect_timeout_s=10.0)
        c = ClusterEngine(cc)
        feed = ReplicaFeed(c, str(tmp_path / f"rep-r{r}"), rf=2,
                           heartbeat_s=0.2)
        applier = ReplicaApplier(c, rf=2, detect_s=5.0)
        c.attach_replication(feed, applier)
        srv = build_cluster_rpc(c.local, "obs-secret")
        register_replication_rpc(srv, applier)
        host.start(srv, ports[r])
        clusters.append(c)
        feeds.append(feed)
    for f in feeds:
        f.start()
    return clusters, feeds, host


def test_per_peer_stale_in_health_block_and_exposition(tmp_path):
    """The staleness watermark is per LEADER rank (labels, not one
    global gauge), surfaced in the cluster_status health block AND as
    swtpu_replication_stale_ms{leader=...} — a single lagging follower
    is visible before any failover read hits it."""
    from sitewhere_tpu.utils.metrics import MetricsRegistry
    from sitewhere_tpu.utils.metrics import export_engine_metrics

    clusters, feeds, host = _mk_replicated_cluster(tmp_path)
    c0, c1 = clusters
    try:
        toks = tokens_owned_by(0, 4, prefix="st")
        c0.ingest_json_batch([meas(t, "t", 1.0, 60 + i)
                              for i, t in enumerate(toks)])
        c0.flush()
        deadline = time.monotonic() + 20
        while not feeds[0].drained() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert feeds[0].drained()
        # rank 1 follows rank 0: its applier tracks leader 0 per-peer
        stale = c1.replica_applier.stale_by_leader()
        assert 0 in stale and stale[0] >= 0.0
        # cluster_status health block carries it, keyed by leader rank
        s = c1.cluster_status()
        assert s["health"]["peers"]                      # peer FSM states
        assert "0" in s["health"]["replicationStaleMs"]
        assert s["health"]["replicationStaleMs"]["0"] >= 0.0
        # and the Prometheus plane exports one labeled series per leader
        reg = MetricsRegistry()
        export_engine_metrics(c1.local, reg)
        text = reg.expose_text()
        lint_prometheus(text)
        assert 'swtpu_replication_stale_ms{leader="0"}' in text
    finally:
        for f in feeds:
            f.stop()
        _close(clusters, host)


def test_forward_hop_histogram_observes_forwards(tmp_path):
    """Every cross-rank forward lands in swtpu_forward_hop_seconds under
    its destination-rank label — the forwarded-hop p99 the bench cluster
    leg reports comes straight off this series via Histogram.quantile."""
    from sitewhere_tpu.utils.metrics import cluster_metrics_instruments

    clusters, host, _ = _mk_cluster(tmp_path)
    c0, _c1 = clusters
    try:
        hop = cluster_metrics_instruments()["forward_hop"]
        before = hop.count(dst="1")
        remote = tokens_owned_by(1, 3, prefix="fh")
        c0.ingest_json_batch([meas(t, "t", 1.0, 70 + i)
                              for i, t in enumerate(remote)])
        c0.flush()
        assert hop.count(dst="1") > before
        assert hop.quantile(0.99, dst="1") > 0.0
    finally:
        _close(clusters, host)


@pytest.mark.slow
def test_open_loop_cluster_load_stress(tmp_path):
    """Heavy cluster-load leg in miniature (slow; the full >=1e5-event
    version is bench.py's cluster leg): open-loop mixed traffic over a
    replicated 2-rank cluster with a federated scrape mid-load, then
    no-loss + SLO-plane accounting at the end."""
    from sitewhere_tpu.loadgen import (OpenLoopSpec, TenantLoad,
                                       build_open_loop_schedule,
                                       run_open_loop)
    from sitewhere_tpu.utils.metrics import slo_metrics

    clusters, feeds, host = _mk_replicated_cluster(tmp_path)
    c0, _c1 = clusters
    try:
        # warm: compile both ranks before the measured run
        warm = tokens_owned_by(0, 4, prefix="wl") + \
            tokens_owned_by(1, 4, prefix="wl")
        c0.ingest_json_batch([meas(t, "t", 1.0, 10 + i)
                              for i, t in enumerate(warm)])
        c0.flush()
        spec = OpenLoopSpec(
            tenants=(TenantLoad("load-a", 2500.0, n_devices=32,
                                query_every=4, mutate_every=8),
                     TenantLoad("load-b", 1500.0, n_devices=32)),
            duration_s=2.5, frame_size=128, seed=21)
        sched = build_open_loop_schedule(spec)
        expected = sum(len(op.payloads) for op in sched
                       if op.kind == "ingest")
        res = run_open_loop(c0, sched, checkpoint_frames=4)
        assert res.events == expected
        # federated scrape under/after load covers both ranks and the
        # per-tenant SLO series exists for every tenant that ingested
        text = c0.cluster_metrics()
        lint_prometheus(text)
        assert 'rank="0"' in text and 'rank="1"' in text
        hist = slo_metrics()["ingest_e2e"]
        assert hist.count_where(tenant="load-a") == \
            res.per_tenant["load-a"]["events"]
        assert hist.count_where(tenant="load-b") == \
            res.per_tenant["load-b"]["events"]
        # no loss: the cluster-merged persisted counter accounts every
        # event (the RING query would undercount here by design — this
        # load wraps the small test store several times over)
        m = c0.metrics()
        assert m["persisted"] >= res.events
        # replication kept pace (feeds drain within the test budget)
        deadline = time.monotonic() + 30
        while (not all(f.drained() for f in feeds)
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert all(f.drained() for f in feeds)
    finally:
        for f in feeds:
            f.stop()
        _close(clusters, host)
