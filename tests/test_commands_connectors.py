"""Downlink (command delivery) + outbound connector + search tests."""

import asyncio
import json

import pytest

from sitewhere_tpu.commands.destinations import (
    CommandDestination,
    LocalDeliveryProvider,
    MqttDeliveryProvider,
    mqtt_topic_extractor,
)
from sitewhere_tpu.commands.encoders import (
    BinaryCommandExecutionEncoder,
    JsonCommandExecutionEncoder,
)
from sitewhere_tpu.commands.model import (
    CommandParameter,
    DeviceCommand,
    ParameterType,
    SystemCommand,
    SystemCommandType,
)
from sitewhere_tpu.commands.routing import (
    DeviceTypeMappingCommandRouter,
    SingleChoiceCommandRouter,
)
from sitewhere_tpu.commands.service import CommandDeliveryService
from sitewhere_tpu.connectors.base import (
    AreaFilter,
    ConnectorHost,
    DeviceTypeFilter,
    ScriptedFilter,
)
from sitewhere_tpu.connectors.impl import (
    InMemoryConnector,
    SearchIndexConnector,
)
from sitewhere_tpu.core.types import EventType
from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType
from sitewhere_tpu.search.index import EventSearchIndex


def _engine():
    return Engine(EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=4096, batch_capacity=16, channels=4,
    ))


def _measure(engine, token, name="temp", value=1.0, tenant="default"):
    engine.process(DecodedRequest(
        type=RequestType.DEVICE_MEASUREMENT, device_token=token, tenant=tenant,
        measurements={name: value},
    ))


def _service(engine, router=None):
    svc = CommandDeliveryService(engine, router or SingleChoiceCommandRouter("local"))
    svc.registry.create(DeviceCommand(
        token="reboot", device_type="default", name="reboot",
        parameters=(CommandParameter("delay", ParameterType.INT64, required=True),),
    ))
    provider = LocalDeliveryProvider()
    svc.add_destination(CommandDestination(
        "local", mqtt_topic_extractor(), JsonCommandExecutionEncoder(), provider,
    ))
    return svc, provider


def test_command_invoke_end_to_end():
    engine = _engine()
    _measure(engine, "dev-1")  # registers dev-1
    engine.flush()
    svc, provider = _service(engine)
    inv = svc.invoke("dev-1", "reboot", {"delay": 5})
    assert asyncio.run(svc.pump()) == 1
    assert len(provider.delivered) == 1
    token, payload, system = provider.delivered[0]
    assert token == "dev-1" and not system
    body = json.loads(payload)
    assert body["command"] == "reboot"
    assert body["parameters"] == {"delay": 5}
    assert body["invocationId"] == inv.invocation_id
    # the invocation itself was persisted as an event
    st = engine.get_device_state("dev-1")
    assert st["event_counts"]["COMMAND_INVOCATION"] == 1


def test_command_validation_and_unknown():
    engine = _engine()
    _measure(engine, "dev-1")
    engine.flush()
    svc, _ = _service(engine)
    with pytest.raises(ValueError, match="missing required parameter"):
        svc.invoke("dev-1", "reboot", {})
    with pytest.raises(ValueError, match="unknown parameters"):
        svc.invoke("dev-1", "reboot", {"delay": 1, "bogus": 2})
    with pytest.raises(ValueError, match="unknown command"):
        svc.invoke("dev-1", "nope", {})


def test_command_undelivered_dead_letter():
    engine = _engine()
    _measure(engine, "dev-1")
    engine.flush()
    svc, provider = _service(engine)
    provider.fail = True
    svc.invoke("dev-1", "reboot", {"delay": 1})
    asyncio.run(svc.pump())
    assert len(svc.undelivered) == 1
    assert svc.undelivered[0].destination_id == "local"
    # unknown destination also dead-letters
    svc2, _ = _service(engine, SingleChoiceCommandRouter("missing"))
    svc2.invoke("dev-1", "reboot", {"delay": 1})
    asyncio.run(svc2.pump())
    assert svc2.undelivered[0].error == "unknown destination"


def test_device_type_router_and_nested_target():
    engine = _engine()
    engine.register_device("gw-1", device_type="gateway")
    engine.register_device("child-1", device_type="sensor",
                           metadata={"parentToken": "gw-1"})
    router = DeviceTypeMappingCommandRouter({"sensor": "local"})
    svc = CommandDeliveryService(engine, router)
    svc.registry.create(DeviceCommand(token="ping", device_type="sensor", name="ping"))
    provider = LocalDeliveryProvider()
    svc.add_destination(CommandDestination(
        "local", mqtt_topic_extractor(), JsonCommandExecutionEncoder(), provider,
    ))
    svc.invoke("child-1", "ping")
    asyncio.run(svc.pump())
    # nested resolution delivers to the gateway parent
    assert provider.delivered[0][0] == "gw-1"


def test_mqtt_command_destination_end_to_end():
    """Command delivery over the real (embedded) MQTT broker: device
    subscribes to its command topic and receives the encoded execution."""
    from sitewhere_tpu.ingest.mqtt import MqttBroker, MqttClient

    async def run():
        broker = MqttBroker()
        await broker.start()
        engine = _engine()
        _measure(engine, "dev-9")
        engine.flush()
        svc = CommandDeliveryService(engine, SingleChoiceCommandRouter("mqtt"))
        svc.registry.create(DeviceCommand(token="blink", device_type="default",
                                          name="blink"))
        svc.add_destination(CommandDestination(
            "mqtt", mqtt_topic_extractor(),
            BinaryCommandExecutionEncoder(),
            MqttDeliveryProvider("127.0.0.1", broker.bound_port),
        ))
        got: list[bytes] = []
        device = MqttClient("127.0.0.1", broker.bound_port, "device-9")
        await device.connect()
        device.on_message = lambda t, p: got.append(p)
        await device.subscribe("sitewhere/commands/dev-9")
        svc.invoke("dev-9", "blink")
        await svc.pump()
        await asyncio.sleep(0.2)
        await device.disconnect()
        await broker.stop()
        assert len(got) == 1
        assert got[0][1] == 1  # binary kind=user
        return True

    assert asyncio.run(run())


def test_system_command_registration_ack():
    engine = _engine()
    engine.register_device("dev-s", device_type="default")
    svc, provider = _service(engine)
    asyncio.run(svc.send_system_command(
        "dev-s",
        SystemCommand(SystemCommandType.REGISTRATION_ACK, "dev-s"),
    ))
    token, payload, system = provider.delivered[0]
    assert system and json.loads(payload)["systemCommand"] == "RegistrationAck"


# --- connectors --------------------------------------------------------------


def test_connector_host_filters_and_offsets():
    engine = _engine()
    sink = InMemoryConnector("sink", filters=[
        ScriptedFilter(lambda ev: ev.etype is not EventType.MEASUREMENT),
    ])
    host = ConnectorHost(engine, sink)
    _measure(engine, "c-1", "temp", 20.0)
    _measure(engine, "c-2", "temp", 21.0)
    engine.process(DecodedRequest(type=RequestType.DEVICE_LOCATION,
                                  device_token="c-1", latitude=1, longitude=2))
    engine.flush()
    assert asyncio.run(host.pump()) == 2  # location filtered out
    assert {e.device_token for e in sink.events} == {"c-1", "c-2"}
    assert all(e.etype is EventType.MEASUREMENT for e in sink.events)
    # offsets committed: nothing new on second pump
    assert asyncio.run(host.pump()) == 0
    _measure(engine, "c-3", "temp", 22.0)
    engine.flush()
    assert asyncio.run(host.pump()) == 1


def test_connector_failed_batch_dead_letter():
    engine = _engine()

    class Exploding(InMemoryConnector):
        async def process_batch(self, events):
            raise RuntimeError("boom")

    conn = Exploding("explode")
    host = ConnectorHost(engine, conn)
    _measure(engine, "x-1")
    engine.flush()
    asyncio.run(host.pump())
    assert len(conn.failed_batches) == 1
    # offset still advanced (at-least-once with DLQ, not stuck)
    assert asyncio.run(host.pump()) == 0


def test_device_type_and_area_filters():
    engine = _engine()
    engine.register_device("t-1", device_type="thermostat")
    engine.register_device("t-2", device_type="camera")
    sink = InMemoryConnector("typed", filters=[
        DeviceTypeFilter(engine, ["thermostat"], "include"),
    ])
    host = ConnectorHost(engine, sink)
    _measure(engine, "t-1")
    _measure(engine, "t-2")
    engine.flush()
    asyncio.run(host.pump())
    assert [e.device_token for e in sink.events] == ["t-1"]


def test_search_index_connector_and_queries():
    engine = _engine()
    index = EventSearchIndex()
    host = ConnectorHost(engine, SearchIndexConnector("solr", index))
    _measure(engine, "s-1", "fuel.level", 10.0)
    _measure(engine, "s-2", "temp", 30.0)
    engine.process(DecodedRequest(type=RequestType.DEVICE_ALERT,
                                  device_token="s-1", alert_type="hot"))
    engine.flush()
    asyncio.run(host.pump())
    assert len(index.search("*:*")) == 3
    assert len(index.search("deviceToken:s-1")) == 2
    assert len(index.search("type:ALERT")) == 1
    assert len(index.search("deviceToken:s-1 type:MEASUREMENT")) == 1
    assert len(index.search("measurement:fuel.level")) == 1
    docs = index.search("type:MEASUREMENT eventDateMs:[0 TO *]")
    assert len(docs) == 2


def test_connector_surface_importable():
    """Every reference connector type resolves to a real class (no
    unavailable-stub gates remain)."""
    from sitewhere_tpu.connectors.impl import (  # noqa: F401
        EventHubConnector,
        HttpConnector,
        MqttConnector,
        RabbitMqConnector,
        ScriptedConnector,
        SearchIndexConnector,
        SqsConnector,
    )


def test_undelivered_retry_targets_failed_destination():
    """Parked invocations retry against their failed destination only
    (undelivered-command-invocations topic redelivery analog)."""
    import asyncio

    import pytest

    from sitewhere_tpu.commands.destinations import (
        CommandDestination,
        DeliveryError,
        LocalDeliveryProvider,
        mqtt_topic_extractor,
    )
    from sitewhere_tpu.commands.encoders import JsonCommandExecutionEncoder
    from sitewhere_tpu.commands.model import DeviceCommand
    from sitewhere_tpu.commands.routing import SingleChoiceCommandRouter
    from sitewhere_tpu.commands.service import CommandDeliveryService
    from sitewhere_tpu.engine import Engine, EngineConfig

    async def go():
        eng = Engine(EngineConfig(
            device_capacity=32, token_capacity=64, assignment_capacity=64,
            store_capacity=512, batch_capacity=8, channels=4))
        eng.register_device("rt-1")
        svc = CommandDeliveryService(eng, SingleChoiceCommandRouter("flaky"))
        svc.registry.create(DeviceCommand(token="ping", device_type="default",
                                          name="ping"))

        class FlakyProvider(LocalDeliveryProvider):
            def __init__(self):
                super().__init__()
                self.fail = True

            async def deliver(self, target, payload, is_system=False):
                if self.fail:
                    raise DeliveryError("destination down")
                await super().deliver(target, payload, is_system)

        provider = FlakyProvider()
        svc.add_destination(CommandDestination(
            "flaky", mqtt_topic_extractor(), JsonCommandExecutionEncoder(),
            provider))
        svc.invoke("rt-1", "ping", {})
        await svc.pump()
        assert len(svc.undelivered) == 1
        # destination still down: retry re-parks it
        res = await svc.retry_undelivered()
        assert res == {"retried": 1, "stillUndelivered": 1}
        # destination recovers: retry delivers
        provider.fail = False
        res = await svc.retry_undelivered()
        assert res == {"retried": 1, "stillUndelivered": 0}
        assert svc.delivered_count == 1
        assert provider.delivered  # payload reached the local sink

    asyncio.new_event_loop().run_until_complete(go())


def test_search_index_eviction_keeps_postings_consistent():
    """Ring eviction drops the oldest doc and its posting entries in
    O(doc keys) — stale ids never match queries."""
    from sitewhere_tpu.core.types import EventType
    from sitewhere_tpu.outbound.feed import OutboundEvent

    idx = EventSearchIndex(capacity=4)

    def ev(i):
        return OutboundEvent(
            event_id=i, etype=EventType.MEASUREMENT,
            device_token=f"d-{i % 2}", device_id=i % 2, assignment_id=i,
            tenant="default", area_id=-1, asset_id=-1, ts_ms=i,
            received_ms=i, measurements={f"m{i}": 1.0}, values=[],
            aux0=-1, aux1=-1)

    for i in range(6):
        idx.add(ev(i))
    assert sorted(idx.docs) == [2, 3, 4, 5]
    assert idx.search("measurement:m0") == []
    assert idx.search("measurement:m1") == []
    assert ("measurement", "m0") not in idx.postings
    assert [d["eventId"] for d in idx.search("deviceToken:d-0")] == [4, 2]


def test_search_index_event_time_order_survives_truncation():
    """order=\"eventDate\" ranks BEFORE truncation, so a backdated-forward
    event (late arrival, newest event time) stays in the top-N — the
    ordering the cluster fan-out merge depends on."""
    from sitewhere_tpu.core.types import EventType
    from sitewhere_tpu.outbound.feed import OutboundEvent

    idx = EventSearchIndex()

    def ev(i, ts, recv=None):
        return OutboundEvent(
            event_id=i, etype=EventType.MEASUREMENT, device_token=f"d-{i}",
            device_id=i, assignment_id=i, tenant="default", area_id=-1,
            asset_id=-1, ts_ms=ts, received_ms=recv if recv is not None
            else i, measurements={"m": 1.0}, values=[], aux0=-1, aux1=-1)

    # FIRST arrival carries the NEWEST event time (backdated-forward)
    idx.add(ev(0, ts=9_000))
    for i in range(1, 6):
        idx.add(ev(i, ts=100 + i))
    # arrival order would rank doc 0 last and truncate it out...
    assert [d["eventId"] for d in idx.search("*:*", 3,
                                             order="id")] == [5, 4, 3]
    # ...the event-time default keeps it on top
    assert [d["eventId"] for d in idx.search("*:*", 3)][0] == 0
    # ties break on deviceToken so every rank sorts identically
    idx2 = EventSearchIndex()
    idx2.add(ev(7, ts=500, recv=1))
    idx2.add(ev(3, ts=500, recv=1))
    docs = idx2.search("*:*", 10, order="eventDate")
    assert [d["deviceToken"] for d in docs] == ["d-3", "d-7"]
