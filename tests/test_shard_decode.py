"""Sharded multi-core arena decode (ISSUE 4 tentpole, pillar 1).

One wire batch splits across N decode workers by payload BYTES, each
worker filling a disjoint row range of the same staging arena through
per-shard overlay interners; a serial merge interns first-seen strings
in shard order (== first-occurrence row order). The contract these
tests pin: arena contents — every column, including interner id
assignment — are BYTE-IDENTICAL to the single-threaded decode, for JSON
and binary wire batches, under an odd payload-size mix, with first-seen
tokens / measurement names / alert types / alternate ids appearing
mid-batch.
"""

import dataclasses
import json

import numpy as np
import pytest

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.ingest.arena import StagingArena
from sitewhere_tpu.ingest.decoders import encode_binary_request
from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType
from sitewhere_tpu.loadgen import generate_measurements_message

SMALL = dict(device_capacity=1 << 10, token_capacity=1 << 11,
             assignment_capacity=1 << 11, store_capacity=1 << 12,
             batch_capacity=128)


def _require_shard(eng):
    if eng._arena_pool is None:
        pytest.skip("native arena path unavailable")
    if eng._sharder is None:
        pytest.skip("sharded decode entry points unavailable")


def _odd_mix_json(n=420):
    """Payload-size spread from tiny to multi-KB so byte-based cuts land
    at uneven payload indexes; new strings of every kind appear at odd
    positions (including inside what becomes a later shard)."""
    pay = []
    for i in range(n):
        if i % 11 == 0:
            # fat multi-measurement envelope with fresh names + alt ids
            pay.append(json.dumps({
                "deviceToken": f"fat-{i % 13}", "type": "DeviceMeasurements",
                "request": {
                    "measurements": {f"lane.{i % 29}": float(i),
                                     "engine.temperature": float(i % 80),
                                     f"pad.{'x' * (i % 200)}": 1.0},
                    "alternateId": f"alt-{i % 37}",
                    "eventDate": 1700000000000 + i}}).encode())
        elif i % 7 == 0:
            pay.append(json.dumps({
                "deviceToken": f"al-{i % 9}", "type": "DeviceAlert",
                "request": {"type": f"alert.kind{i % 17}",
                            "level": "Critical",
                            "alternateId": f"alt-{i % 23}",
                            "eventDate": None}}).encode())
        elif i % 5 == 0:
            pay.append(json.dumps({
                "deviceToken": f"lo-{i % 8}", "type": "DeviceLocation",
                "request": {"latitude": 33.75 + i * 0.01,
                            "longitude": -84.39,
                            "elevation": 300.0}}).encode())
        else:
            pay.append(generate_measurements_message(
                f"sd-{i % 40}", i, value=float(i % 90)))
    return pay


def _bin_mix(n=260):
    return [encode_binary_request(DecodedRequest(
        type=RequestType.DEVICE_MEASUREMENT,
        device_token=f"bn-{i % 31}",
        measurements={f"bin.lane{i % 19}": float(i % 100)},
        event_ts_ms=1700000000000 + i)) for i in range(n)]


def _run(workers, min_shard=16):
    eng = Engine(EngineConfig(**SMALL, ingest_workers=workers))
    if eng._arena_pool is None:
        pytest.skip("native arena path unavailable")
    if workers > 1:
        _require_shard(eng)
        eng._sharder.min_shard_payloads = min_shard
    eng.epoch.base_unix_s = 1700000000.0 - 1000.0
    eng.epoch.now_ms = lambda: 12345
    eng.ingest_json_batch(_odd_mix_json())
    eng.ingest_binary_batch(_bin_mix())
    eng.flush()
    return eng


def _assert_engines_identical(a, b):
    import jax

    sa, sb = jax.device_get(a.state.store), jax.device_get(b.state.store)
    for f in dataclasses.fields(sa):
        assert np.array_equal(np.asarray(getattr(sa, f.name)),
                              np.asarray(getattr(sb, f.name))), \
            f"store.{f.name} diverges"
    da, db = (jax.device_get(a.state.device_state),
              jax.device_get(b.state.device_state))
    for f in dataclasses.fields(da):
        assert np.array_equal(np.asarray(getattr(da, f.name)),
                              np.asarray(getattr(db, f.name))), \
            f"device_state.{f.name} diverges"
    # interner ID ASSIGNMENT parity — the merge-order invariant
    assert list(a.tokens.items()) == list(b.tokens.items())
    assert list(a.channel_map.names.items()) == \
        list(b.channel_map.names.items())
    assert list(a.alert_types.items()) == list(b.alert_types.items())
    assert list(a.event_ids.items()) == list(b.event_ids.items())
    ma, mb = a.metrics(), b.metrics()
    for k in ("processed", "found", "missed", "registered", "persisted",
              "channel_collisions"):
        assert ma[k] == mb[k], k


def test_sharded_decode_byte_identical_two_workers():
    single = _run(1)
    sharded = _run(2)
    assert sharded._sharder.sharded_batches > 0, \
        "sharded path never engaged — the test proved nothing"
    _assert_engines_identical(single, sharded)


def test_sharded_decode_byte_identical_three_workers():
    """More shards than cores is legal (threads, not processes) and must
    still merge deterministically."""
    single = _run(1)
    sharded = _run(3)
    assert sharded._sharder.sharded_batches > 0
    _assert_engines_identical(single, sharded)


def test_sharded_decoder_raw_arena_columns():
    """Column-level check without the engine: the shard merge writes the
    same bytes into every arena column the direct decoder writes —
    including the strided aux0/aux1 lanes."""
    from sitewhere_tpu.ingest.fast_decode import (NativeBatchDecoder,
                                                  native_available)
    from sitewhere_tpu.ingest.workers import ShardedArenaDecoder
    from sitewhere_tpu.native.binding import NativeInterner

    if not native_available():
        pytest.skip("native library unavailable")
    pay = _odd_mix_json(300)

    def decode(sharded):
        dec = NativeBatchDecoder(NativeInterner(1 << 11), 8)
        if not dec.has_arena:
            pytest.skip("arena entry points unavailable")
        arena = StagingArena(512, 8)
        if sharded:
            if not dec.has_shard:
                pytest.skip("shard entry points unavailable")
            sh = ShardedArenaDecoder(dec, 3)
            sh.min_shard_payloads = 16
            out = sh.decode_into(pay, arena, 0)
            assert sh.last_workers > 1
        else:
            out = dec.decode_into(pay, arena, 0)
        return out, arena, dec

    (ok1, coll1), a1, d1 = decode(False)
    (ok2, coll2), a2, d2 = decode(True)
    assert (ok1, coll1) == (ok2, coll2)
    n = len(pay)
    for col in ("rtype", "token_id", "ts64", "values", "vmask", "aux",
                "level"):
        assert np.array_equal(getattr(a1, col)[:n], getattr(a2, col)[:n]), \
            f"arena.{col} diverges"
    assert list(d1.tokens.items()) == list(d2.tokens.items())
    assert list(d1.names.items()) == list(d2.names.items())
    assert list(d1.event_ids.items()) == list(d2.event_ids.items())


def test_sharded_decoder_nonlist_falls_back():
    """A non-list payload iterable can't take the pylist shard path; the
    sharder must degrade to the single decoder, not fail."""
    from sitewhere_tpu.ingest.fast_decode import (NativeBatchDecoder,
                                                  native_available)
    from sitewhere_tpu.ingest.workers import ShardedArenaDecoder
    from sitewhere_tpu.native.binding import NativeInterner

    if not native_available():
        pytest.skip("native library unavailable")
    dec = NativeBatchDecoder(NativeInterner(1 << 11), 8)
    if not (dec.has_arena and dec.has_shard):
        pytest.skip("arena/shard entry points unavailable")
    sh = ShardedArenaDecoder(dec, 2)
    sh.min_shard_payloads = 4
    pay = tuple(generate_measurements_message(f"t-{i}", i)
                for i in range(64))
    arena = StagingArena(128, 8)
    n_ok, _ = sh.decode_into(pay, arena, 0)
    assert n_ok == 64
    assert sh.last_workers == 1


def test_sharded_small_batch_stays_single():
    """Below the per-shard minimum the batch must not pay thread+merge
    overhead."""
    eng = Engine(EngineConfig(**SMALL, ingest_workers=2))
    _require_shard(eng)
    eng.ingest_json_batch([generate_measurements_message(f"s-{i}", i)
                           for i in range(16)])
    eng.flush()
    assert eng._sharder.sharded_batches == 0
    assert eng.metrics()["persisted"] == 16


def test_set_active_workers_clamps():
    eng = Engine(EngineConfig(**SMALL, ingest_workers=2))
    _require_shard(eng)
    assert eng._sharder.set_active_workers(99) == 2
    assert eng._sharder.set_active_workers(0) == 1
    assert eng.set_ingest_tuning(ingest_workers=2)["ingest_workers"] == 2


@pytest.mark.slow
def test_sharded_decode_stress_random_batches():
    """Hundreds of random-size batches with churning new strings stay
    byte-identical between one and three workers."""
    rng = np.random.default_rng(7)
    sizes = [int(rng.integers(1, 400)) for _ in range(60)]

    def run(workers):
        eng = Engine(EngineConfig(**SMALL, ingest_workers=workers))
        if eng._arena_pool is None:
            pytest.skip("native arena path unavailable")
        if workers > 1:
            _require_shard(eng)
            eng._sharder.min_shard_payloads = 8
        eng.epoch.base_unix_s = 1700000000.0 - 1000.0
        eng.epoch.now_ms = lambda: 777
        base = 0
        for n in sizes:
            eng.ingest_json_batch([
                generate_measurements_message(
                    f"st-{(base + i) % 257}", base + i,
                    value=float(i % 90))
                for i in range(n)])
            base += n
        eng.flush()
        return eng

    _assert_engines_identical(run(1), run(3))
