"""REST gateway integration tests: drive the whole instance over HTTP."""

import asyncio
import base64
import json

import pytest

from sitewhere_tpu.engine import EngineConfig
from sitewhere_tpu.instance.auth import JwtError, JwtService, hash_password, verify_password
from sitewhere_tpu.instance.instance import InstanceConfig, SiteWhereTpuInstance
from sitewhere_tpu.web.rest import make_app, start_server


def _instance():
    return SiteWhereTpuInstance(InstanceConfig(
        engine=EngineConfig(
            device_capacity=64, token_capacity=128, assignment_capacity=128,
            store_capacity=4096, batch_capacity=16, channels=4,
        ),
    ))


@pytest.fixture
def api():
    """(session, base_url, jwt) against a live server."""
    import aiohttp

    loop = asyncio.new_event_loop()
    inst = _instance()
    server = loop.run_until_complete(start_server(inst))
    session = aiohttp.ClientSession(loop=loop)
    base = f"http://127.0.0.1:{server.port}"

    async def get_token():
        basic = base64.b64encode(b"admin:password").decode()
        async with session.get(f"{base}/api/authapi/jwt",
                               headers={"Authorization": f"Basic {basic}"}) as r:
            assert r.status == 200
            return (await r.json())["token"]

    token = loop.run_until_complete(get_token())

    def call(method, path, json_body=None, headers=None, raw=False, params=None):
        async def go():
            h = {"Authorization": f"Bearer {token}", **(headers or {})}
            async with session.request(method, base + path, json=json_body,
                                       headers=h, params=params) as r:
                body = await (r.read() if raw else r.json())
                return r.status, body

        return loop.run_until_complete(go())

    yield call, inst, loop
    loop.run_until_complete(session.close())
    loop.run_until_complete(server.cleanup())
    loop.close()


def test_auth_flow(api):
    call, inst, loop = api

    # bad credentials rejected
    async def bad_auth():
        import aiohttp

        async with aiohttp.ClientSession() as s:
            basic = base64.b64encode(b"admin:wrong").decode()
            async with s.get(
                f"http://127.0.0.1:1/api/authapi/jwt"
            ) as r:  # pragma: no cover
                pass

    status, _ = call("GET", "/api/instance")
    assert status == 200
    # no token -> 401
    async def no_token():
        import aiohttp

        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://127.0.0.1:{0}/api/devices"
            ) as r:  # pragma: no cover
                return r.status

    # tampered token -> 401 (direct middleware check)
    status, body = call("GET", "/api/devices", headers={"Authorization": "Bearer x.y.z"})
    assert status == 401


def test_device_lifecycle_over_rest(api):
    call, inst, loop = api
    status, dt = call("POST", "/api/devicetypes",
                      {"token": "thermo", "name": "Thermostat"})
    assert status == 201
    status, dev = call("POST", "/api/devices",
                       {"token": "t-1", "deviceTypeToken": "thermo"})
    assert status == 201 and dev["device_type"] == "thermo"
    # duplicate -> conflict via engine get-or-create returns same id (200/201)
    status, listing = call("GET", "/api/devices")
    assert status == 200 and listing["numResults"] == 1

    # ingest events over REST
    status, _ = call("POST", "/api/devices/t-1/events",
                     {"type": "DeviceMeasurement",
                      "request": {"name": "temp", "value": 21.5}})
    assert status == 201
    status, _ = call("POST", "/api/devices/t-1/events",
                     {"type": "DeviceLocation",
                      "request": {"latitude": 33.7, "longitude": -84.4}})
    assert status == 201
    status, state = call("GET", "/api/devices/t-1/state")
    assert status == 200
    assert state["measurements"]["temp"]["value"] == 21.5
    assert state["presence"] == "PRESENT"

    status, events = call("GET", "/api/devices/t-1/events")
    assert status == 200 and events["total"] == 2
    status, events = call("GET", "/api/devices/t-1/events",
                          params={"type": "location"})
    assert events["total"] == 1
    # 404 for unknown device state
    status, _ = call("GET", "/api/devices/ghost/state")
    assert status == 404


def test_commands_over_rest(api):
    call, inst, loop = api
    call("POST", "/api/devicetypes", {"token": "pump", "name": "Pump"})
    call("POST", "/api/devices", {"token": "p-1", "deviceTypeToken": "pump"})
    status, cmd = call("POST", "/api/devicetypes/pump/commands",
                       {"token": "prime", "name": "prime",
                        "parameters": [{"name": "seconds", "type": "Int64",
                                        "required": True}]})
    assert status == 201
    # missing required parameter -> 400
    status, err = call("POST", "/api/devices/p-1/invocations",
                       {"commandToken": "prime", "parameterValues": {}})
    assert status == 400 and "required" in err["error"]
    # wire a local destination so delivery succeeds
    from sitewhere_tpu.commands.destinations import (
        CommandDestination,
        LocalDeliveryProvider,
        mqtt_topic_extractor,
    )
    from sitewhere_tpu.commands.encoders import JsonCommandExecutionEncoder
    from sitewhere_tpu.commands.routing import SingleChoiceCommandRouter

    provider = LocalDeliveryProvider()
    inst.commands.router = SingleChoiceCommandRouter("local")
    inst.commands.add_destination(CommandDestination(
        "local", mqtt_topic_extractor(), JsonCommandExecutionEncoder(), provider))
    status, inv = call("POST", "/api/devices/p-1/invocations",
                       {"commandToken": "prime", "parameterValues": {"seconds": 5}})
    assert status == 201
    assert len(provider.delivered) == 1
    # batch over the same command
    call("POST", "/api/devices", {"token": "p-2", "deviceTypeToken": "pump"})
    status, op = call("POST", "/api/batch/command",
                      {"token": "op-1", "commandToken": "prime",
                       "deviceTokens": ["p-1", "p-2"],
                       "parameterValues": {"seconds": 1}})
    assert status == 201 and op["counts"]["SUCCEEDED"] == 2
    status, op = call("GET", "/api/batch/op-1")
    assert status == 200 and op["status"] == "Finished"


def test_hierarchy_assets_labels_search(api):
    call, inst, loop = api
    call("POST", "/api/areatypes", {"token": "site", "name": "Site"})
    status, _ = call("POST", "/api/areas",
                     {"token": "atl", "areaTypeToken": "site", "name": "Atlanta"})
    assert status == 201
    status, _ = call("POST", "/api/zones",
                     {"token": "z1", "areaToken": "atl", "name": "Dock",
                      "bounds": [{"latitude": 1, "longitude": 2},
                                 {"latitude": 2, "longitude": 2},
                                 {"latitude": 2, "longitude": 3}]})
    assert status == 201
    status, zones = call("GET", "/api/areas/atl/zones")
    assert len(zones) == 1
    status, tree = call("GET", "/api/areas/tree")
    assert tree[0]["entity"]["token"] == "atl"

    status, _ = call("POST", "/api/assettypes", {"token": "truck", "name": "Truck"})
    status, _ = call("POST", "/api/assets",
                     {"token": "t17", "assetTypeToken": "truck", "name": "Truck 17"})
    assert status == 201

    status, png = call("GET", "/api/labels/device/any-device", raw=True)
    assert status == 200 and png[:8] == b"\x89PNG\r\n\x1a\n"

    # search: ingest an event, pump the indexing connector, query
    call("POST", "/api/devices", {"token": "s-1"})
    call("POST", "/api/devices/s-1/events",
         {"type": "DeviceMeasurement", "request": {"name": "rpm", "value": 900}})
    loop.run_until_complete(inst.pump_outbound())
    status, res = call("GET", "/api/search/events", params={"q": "deviceToken:s-1"})
    assert status == 200 and res["numResults"] == 1


def test_groups_schedules_streams_tenants_users(api):
    call, inst, loop = api
    call("POST", "/api/devices", {"token": "g-1"})
    call("POST", "/api/devices", {"token": "g-2"})
    status, _ = call("POST", "/api/devicegroups",
                     {"token": "fleet", "name": "Fleet", "roles": ["all"]})
    assert status == 201
    status, _ = call("POST", "/api/devicegroups/fleet/elements",
                     {"elements": [{"device": "g-1"}, {"device": "g-2"}]})
    assert status == 201
    status, devices = call("GET", "/api/devicegroups/fleet/devices")
    assert devices == ["g-1", "g-2"]

    status, _ = call("POST", "/api/schedules",
                     {"token": "nightly", "name": "Nightly", "triggerType": "Cron",
                      "cron": "0 3 * * *"})
    assert status == 201
    status, err = call("POST", "/api/schedules",
                       {"token": "bad", "name": "Bad", "triggerType": "Cron"})
    assert status == 400

    status, _ = call("POST", "/api/devices/g-1/streams",
                     {"token": "cam", "contentType": "video/mp4"})
    assert status == 201
    status, _ = call("POST", "/api/streams/cam/chunks?sequence=1", raw=True,
                     json_body=None, headers={"Content-Type": "application/octet-stream"})
    status, content = call("GET", "/api/streams/cam/content", raw=True)
    assert status == 200

    # tenants + users (admin-only)
    status, t = call("POST", "/api/tenants",
                     {"token": "acme", "name": "ACME",
                      "datasetTemplate": "construction"})
    assert status == 201 and t["bootstrap_state"] == "Bootstrapped"
    # construction template seeded device types
    assert "acme-excavator" in inst.device_management.device_types

    status, u = call("POST", "/api/users",
                     {"username": "operator", "password": "secret",
                      "roles": ["user"]})
    assert status == 201
    status, auths = call("GET", "/api/users/operator/authorities")
    assert "VIEW_SERVER_INFORMATION" in auths

    # non-admin JWT cannot create users
    non_admin_jwt = inst.jwt.generate("operator", inst.users.authorities_for(
        inst.users.users["operator"]))
    status, err = call("POST", "/api/users",
                       {"username": "x", "password": "y"},
                       headers={"Authorization": f"Bearer {non_admin_jwt}"})
    assert status == 403


def test_jwt_and_password_primitives():
    svc = JwtService(secret=b"k" * 32, expiration_s=60)
    token = svc.generate("alice", ["A", "B"], tenant="t1")
    claims = svc.validate(token)
    assert claims["sub"] == "alice" and claims["tenant"] == "t1"
    with pytest.raises(JwtError, match="signature"):
        svc.validate(token[:-4] + "AAAA")
    with pytest.raises(JwtError, match="malformed"):
        svc.validate("nope")
    expired = JwtService(secret=b"k" * 32, expiration_s=-10)
    with pytest.raises(JwtError, match="expired"):
        expired.validate(expired.generate("bob", []))
    # wrong key
    other = JwtService(secret=b"j" * 32)
    with pytest.raises(JwtError):
        other.validate(token)

    h = hash_password("hunter2")
    assert verify_password("hunter2", h)
    assert not verify_password("hunter3", h)
    assert not verify_password("hunter2", "garbage")


def test_assignments_over_rest(api):
    call, inst, loop = api
    call("POST", "/api/devicetypes", {"token": "meter", "name": "Meter"})
    call("POST", "/api/devices", {"token": "m-1", "deviceTypeToken": "meter"})

    # registering a device creates a default ACTIVE assignment
    status, existing = call("GET", "/api/devices/m-1/assignments")
    assert status == 200 and len(existing) == 1
    assert existing[0]["status"] == "ACTIVE"

    # attach a second assignment with an explicit token
    status, a = call("POST", "/api/assignments",
                     {"deviceToken": "m-1", "token": "m-1-winter",
                      "areaToken": "plant-a"})
    assert status == 201 and a["token"] == "m-1-winter"
    status, got = call("GET", "/api/assignments/m-1-winter")
    assert status == 200 and got["areaToken"] == "plant-a"

    # events now expand to both active assignments
    call("POST", "/api/devices/m-1/events",
         {"type": "DeviceMeasurement", "request": {"name": "kwh", "value": 5.0}})
    status, evs = call("GET", "/api/assignments/m-1-winter/events")
    assert status == 200 and evs["total"] == 1

    # mark missing keeps it active; end releases + detaches the slot
    status, a = call("POST", "/api/assignments/m-1-winter/missing")
    assert status == 200 and a["status"] == "MISSING"
    status, a = call("POST", "/api/assignments/m-1-winter/end")
    assert status == 200 and a["status"] == "RELEASED"
    assert a["releasedDateMs"] is not None
    status, active = call("GET", "/api/assignments",
                          params={"deviceToken": "m-1", "status": "ACTIVE"})
    assert status == 200 and len(active) == 1

    # released assignment no longer receives expanded events
    call("POST", "/api/devices/m-1/events",
         {"type": "DeviceMeasurement", "request": {"name": "kwh", "value": 6.0}})
    status, evs = call("GET", "/api/assignments/m-1-winter/events")
    assert evs["total"] == 1

    # unknown device / assignment -> 404
    status, _ = call("POST", "/api/assignments", {"deviceToken": "ghost"})
    assert status == 404
    status, _ = call("GET", "/api/assignments/ghost")
    assert status == 404


def test_crud_update_delete_over_rest(api):
    call, inst, loop = api
    call("POST", "/api/devicetypes", {"token": "cam", "name": "Camera"})
    status, dt = call("PUT", "/api/devicetypes/cam",
                      {"name": "IP Camera", "description": "PoE"})
    assert status == 200 and dt["name"] == "IP Camera"

    call("POST", "/api/devices", {"token": "c-1", "deviceTypeToken": "cam"})
    call("POST", "/api/areatypes", {"token": "site", "name": "Site"})
    call("POST", "/api/areas", {"token": "hq", "areaTypeToken": "site",
                                "name": "HQ"})
    status, dev = call("PUT", "/api/devices/c-1",
                       {"areaToken": "hq", "metadata": {"rack": "r7"}})
    assert status == 200 and dev["area"] == "hq"

    # asset type + asset get/update/delete
    call("POST", "/api/assettypes", {"token": "person", "name": "Person"})
    call("POST", "/api/assets", {"token": "bob", "assetTypeToken": "person",
                                 "name": "Bob"})
    status, a = call("PUT", "/api/assets/bob", {"name": "Robert"})
    assert status == 200 and a["name"] == "Robert"
    status, a = call("GET", "/api/assets/bob")
    assert a["name"] == "Robert"
    status, _ = call("DELETE", "/api/assets/bob")
    assert status == 200
    status, _ = call("GET", "/api/assets/bob")
    assert status == 404

    # delete propagates 404 afterwards across stores
    status, _ = call("DELETE", "/api/devicetypes/cam")
    assert status == 200
    status, _ = call("GET", "/api/devicetypes/cam")
    assert status == 404


def test_roles_system_and_state_search(api):
    call, inst, loop = api
    # roles / authorities (Roles.java / Authorities.java analogs)
    status, roles = call("GET", "/api/roles")
    assert status == 200 and {r["role"] for r in roles} >= {"admin", "user"}
    status, _ = call("POST", "/api/roles",
                     {"role": "operator", "authorities": ["VIEW_SERVER_INFORMATION"]})
    assert status == 201
    status, auths = call("GET", "/api/authorities")
    assert status == 200 and "ADMINISTER_USERS" in auths

    # user get/update/delete
    call("POST", "/api/users", {"username": "carol", "password": "pw",
                                "roles": ["user"]})
    status, u = call("PUT", "/api/users/carol", {"roles": ["operator"]})
    assert status == 200 and u["roles"] == ["operator"]
    status, _ = call("DELETE", "/api/users/carol")
    assert status == 200
    status, _ = call("GET", "/api/users/carol")
    assert status == 404

    # system version (System.java analog)
    status, v = call("GET", "/api/system/version")
    assert status == 200 and v["edition"] == "SiteWhere-TPU"

    # device-state search (DeviceStates.java POST /search analog)
    call("POST", "/api/devices", {"token": "s-1", "deviceTypeToken": "default"})
    call("POST", "/api/devices/s-1/events",
         {"type": "DeviceMeasurement", "request": {"name": "t", "value": 1.0}})
    status, res = call("POST", "/api/devicestates/search",
                       {"presence": "PRESENT"})
    assert status == 200 and res["numResults"] == 1
    assert res["results"][0]["device"] == "s-1"
    status, res = call("POST", "/api/devicestates/search",
                       {"deviceTokens": ["nope"]})
    assert res["numResults"] == 0

    # command invocation retained queries (CommandInvocations.java analog)
    call("POST", "/api/devicetypes/default/commands",
         {"token": "ping", "name": "ping"})
    status, inv = call("POST", "/api/devices/s-1/invocations",
                       {"commandToken": "ping"})
    assert status == 201
    inv_id = inv["invocationId"]
    status, got = call("GET", f"/api/invocations/{inv_id}")
    assert status == 200 and got["commandToken"] == "ping"
    # device posts a response naming the invocation id
    call("POST", "/api/devices/s-1/events",
         {"type": "DeviceCommandResponse",
          "request": {"originatingEventId": str(inv_id), "response": "pong"}})
    status, resp = call("GET", f"/api/invocations/{inv_id}/responses")
    assert status == 200 and len(resp) == 1


def test_trace_endpoints(api):
    """Flight recorder REST surface (PR 3): a batch id returned by ingest
    resolves to a complete lifecycle record via /api/instance/trace/<id>,
    and /recent lists it."""
    call, inst, loop = api
    rows = [
        {"deviceToken": f"tr-{i % 2}", "type": "DeviceMeasurement",
         "request": {"name": "t", "value": float(i)}}
        for i in range(6)
    ]
    status, res = call("POST", "/api/events/batch", rows)
    assert status == 201
    tid = res["trace_id"]
    assert tid
    status, trace = call("GET", f"/api/instance/trace/{tid}")
    assert status == 200 and trace["traceId"] == tid
    stages = trace["records"][0]["stagesUs"]
    for name in ("decode", "commit", "dispatch", "device_ready",
                 "readback"):
        assert name in stages, stages
    status, recent = call("GET", "/api/instance/trace/recent")
    assert status == 200
    assert any(r["traceId"] == tid for r in recent)
    status, _ = call("GET", "/api/instance/trace/" + "0" * 32)
    assert status == 404
    status, _ = call("GET", "/api/instance/trace/recent",
                     params={"limit": "nope"})
    assert status == 400


def test_span_plane_endpoints(api):
    """Span-plane REST surface (ISSUE 10): /trace/<id>/timeline serves a
    Perfetto-loadable Chrome-trace document, /profile serves folded
    stacks (flamegraph.pl-ready) or structured JSON, and /debug/bundle
    is one self-contained triage snapshot whose embedded exposition
    stays on the strict 0.0.4 surface — lint-clean, NO exemplar syntax
    (the exposition-lint satellite extended to the new endpoints)."""
    from tests.test_metrics_exposition import lint_prometheus

    call, inst, loop = api
    rows = [
        {"deviceToken": f"sp-{i % 2}", "type": "DeviceMeasurement",
         "request": {"name": "t", "value": float(i)}}
        for i in range(6)
    ]
    status, res = call("POST", "/api/events/batch", rows)
    assert status == 201
    tid = res["trace_id"]
    # stitched timeline document: root lifecycle + stage intervals,
    # numeric pids/tids with naming metadata (chrome://tracing loads it)
    status, doc = call("GET", f"/api/instance/trace/{tid}/timeline")
    assert status == 200 and doc["traceId"] == tid
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert {"ingest", "ingest.decode", "ingest.device"} <= \
        {e["name"] for e in xs}
    assert any(e["name"] == "process_name" for e in doc["traceEvents"])
    status, _ = call("GET", "/api/instance/trace/" + "0" * 32 + "/timeline")
    assert status == 404
    # profiler: folded stacks by default, JSON on request, clamped input
    status, folded = call("GET", "/api/instance/profile",
                          params={"seconds": "0.1"}, raw=True)
    assert status == 200
    for line in folded.decode().strip().splitlines():
        stack, n = line.rsplit(" ", 1)
        assert ";" in stack and int(n) >= 1
    status, prof = call("GET", "/api/instance/profile",
                        params={"seconds": "0.1", "format": "json"})
    assert status == 200 and prof["samples"] >= 1
    status, _ = call("GET", "/api/instance/profile",
                     params={"seconds": "nope"})
    assert status == 400
    # debug bundle: self-contained, exposition lint-clean, exemplar-free
    status, bundle = call("GET", "/api/instance/debug/bundle")
    assert status == 200
    assert bundle["flights"] and bundle["config"]
    assert any(t["traceId"] == tid for t in bundle["slowestTraces"])
    lint_prometheus(bundle["prometheus"])
    assert "# {" not in bundle["prometheus"]


def test_prometheus_exposition_lints_over_rest(api):
    """The full /api/instance/metrics/prometheus payload passes the
    promtool-style structural lint (PR 3 satellite)."""
    from tests.test_metrics_exposition import lint_prometheus

    call, inst, loop = api
    rows = [{"deviceToken": "px-1", "type": "DeviceMeasurement",
             "request": {"name": "t", "value": 1.0}}]
    status, _ = call("POST", "/api/events/batch", rows)
    assert status == 201
    status, body = call("GET", "/api/instance/metrics/prometheus",
                        raw=True)
    assert status == 200
    lint_prometheus(body.decode())


def test_batch_ingest_and_openapi(api):
    call, inst, loop = api
    rows = [
        {"deviceToken": f"bi-{i % 4}", "type": "DeviceMeasurement",
         "request": {"name": "t", "value": float(i)}}
        for i in range(20)
    ]
    status, res = call("POST", "/api/events/batch", rows)
    assert status == 201 and res["decoded"] == 20 and res["failed"] == 0
    status, ev = call("GET", "/api/events")
    assert ev["total"] == 20

    # malformed body -> 400, and bad rows count as failed decodes
    status, _ = call("POST", "/api/events/batch", {"not": "a list"})
    assert status == 400
    status, res = call("POST", "/api/events/batch",
                       [{"type": "DeviceMeasurement", "request": {}}])
    assert status == 201 and res["failed"] == 1

    status, spec = call("GET", "/api/openapi.json")
    assert status == 200 and spec["openapi"] == "3.0.0"
    assert "/api/devices" in spec["paths"]
    assert "post" in spec["paths"]["/api/events/batch"]
    assert len(spec["paths"]) > 60


def test_device_mapping_and_nested_routing(api):
    call, inst, loop = api
    call("POST", "/api/devices", {"token": "gw-1"})
    call("POST", "/api/devices", {"token": "leaf-1"})

    status, res = call("POST", "/api/devices/leaf-1/parent",
                       {"parentToken": "gw-1"})
    assert status == 201 and res["parentToken"] == "gw-1"
    # unknown parent -> 404; self-parent -> 400
    status, _ = call("POST", "/api/devices/leaf-1/parent",
                     {"parentToken": "ghost"})
    assert status == 404
    status, _ = call("POST", "/api/devices/gw-1/parent",
                     {"parentToken": "gw-1"})
    assert status == 400

    # MapDevice ingest envelope takes the same path
    status, _ = call("POST", "/api/devices/leaf-1/events",
                     {"type": "MapDevice",
                      "request": {"parentToken": "gw-1"}})
    assert status == 201

    # nested command routing resolves to the gateway parent
    from sitewhere_tpu.commands.routing import NestedDeviceSupport

    nested = NestedDeviceSupport(inst.engine)
    assert nested.resolve_target_token("leaf-1") == "gw-1"
    # on-device parent column mirrors the mapping
    import numpy as np

    tid = inst.engine.tokens.lookup("leaf-1")
    did = inst.engine.token_device[tid]
    pdid = int(inst.engine.state.registry.device_parent[did])
    assert inst.engine.devices[pdid].token == "gw-1"


def test_batch_operation_listing(api):
    call, inst, loop = api
    call("POST", "/api/devicetypes/default/commands",
         {"token": "blink", "name": "blink"})
    call("POST", "/api/devices", {"token": "bl-1"})
    call("POST", "/api/batch/command",
         {"token": "op-1", "deviceTokens": ["bl-1"], "commandToken": "blink"})
    status, listing = call("GET", "/api/batch")
    assert status == 200 and listing["numResults"] == 1
    assert listing["results"][0]["token"] == "op-1"
    assert listing["results"][0]["status"] == "Finished"


def test_assignment_put_delete_over_rest(api):
    call, inst, loop = api
    call("POST", "/api/devices", {"token": "ap-1"})
    status, a = call("POST", "/api/assignments",
                     {"deviceToken": "ap-1", "token": "ap-1-extra"})
    assert status == 201
    # PUT updates associations + metadata
    status, a = call("PUT", "/api/assignments/ap-1-extra",
                     {"areaToken": "plant-a", "assetToken": "pump-7",
                      "metadata": {"k": "v"}})
    assert status == 200
    assert a["areaToken"] == "plant-a" and a["assetToken"] == "pump-7"
    assert a["metadata"] == {"k": "v"}
    # criteria filters on the listing surface see the update
    status, listing = call("GET", "/api/assignments",
                           params={"assetToken": "pump-7"})
    assert status == 200 and [x["token"] for x in listing] == ["ap-1-extra"]
    # DELETE removes it; device keeps its default assignment
    status, body = call("DELETE", "/api/assignments/ap-1-extra")
    assert status == 200 and body["deleted"]
    status, _ = call("GET", "/api/assignments/ap-1-extra")
    assert status == 404
    status, listing = call("GET", "/api/assignments",
                           params={"deviceToken": "ap-1"})
    assert status == 200 and len(listing) == 1
    # PUT on a missing assignment -> 404
    status, _ = call("PUT", "/api/assignments/nope", {"areaToken": "x"})
    assert status == 404


def test_batch_elements_and_criteria_over_rest(api):
    call, inst, loop = api
    call("POST", "/api/devicetypes", {"token": "valve", "name": "Valve"})
    call("POST", "/api/devicetypes", {"token": "pump", "name": "Pump"})
    for i in range(3):
        call("POST", "/api/devices",
             {"token": f"bv-{i}", "deviceTypeToken": "valve"})
    call("POST", "/api/devices", {"token": "bp-0", "deviceTypeToken": "pump"})
    call("POST", "/api/devicetypes/valve/commands",
         {"token": "close", "name": "close"})
    call("POST", "/api/devicetypes/pump/commands",
         {"token": "close", "name": "close"})

    # by device criteria: only the valves
    status, op = call("POST", "/api/batch/command/criteria/device",
                      {"deviceTypeToken": "valve", "commandToken": "close"})
    assert status == 201
    assert op["counts"] == {"SUCCEEDED": 3} or op["counts"].get("SUCCEEDED") == 3

    # element listing is paged + filterable by status
    status, els = call("GET", f"/api/batch/{op['token']}/elements")
    assert status == 200 and els["numResults"] == 3
    assert {e["device_token"] for e in els["results"]} == {"bv-0", "bv-1", "bv-2"}
    status, els = call("GET", f"/api/batch/{op['token']}/elements",
                       params={"status": "failed"})
    assert status == 200 and els["numResults"] == 0
    status, page2 = call("GET", f"/api/batch/{op['token']}/elements",
                         params={"page": "2", "pageSize": "2"})
    assert page2["numResults"] == 3 and len(page2["results"]) == 1

    # by assignment criteria: area-scoped
    call("PUT", "/api/assignments/" +
         inst.engine.list_assignments(device_token="bp-0")[0].token,
         {"areaToken": "zone-9"})
    status, op2 = call("POST", "/api/batch/command/criteria/assignment",
                       {"areaToken": "zone-9", "commandToken": "close"})
    assert status == 201
    status, els = call("GET", f"/api/batch/{op2['token']}/elements")
    assert {e["device_token"] for e in els["results"]} == {"bp-0"}

    # criteria matching nothing -> 400
    status, _ = call("POST", "/api/batch/command/criteria/device",
                     {"deviceTypeToken": "nonexistent", "commandToken": "close"})
    assert status == 400


def test_command_status_crud_per_token(api):
    """GET/PUT/DELETE for commands and statuses under their device type
    (reference: DeviceTypes.java /{token}/commands/{commandToken},
    /{token}/statuses/{statusToken})."""
    call, inst, loop = api
    call("POST", "/api/devicetypes", json_body={"token": "dt-1", "name": "DT"})
    s, _ = call("POST", "/api/devicetypes/dt-1/commands", json_body={
        "token": "cmd-1", "name": "reboot",
        "parameters": [{"name": "delay", "type": "Int64"}]})
    assert s == 201
    s, body = call("GET", "/api/devicetypes/dt-1/commands/cmd-1")
    assert s == 200 and body["name"] == "reboot"
    s, body = call("PUT", "/api/devicetypes/dt-1/commands/cmd-1",
                   json_body={"description": "restart the device"})
    assert s == 200 and body["description"] == "restart the device"
    # wrong device type -> 404
    s, _ = call("GET", "/api/devicetypes/other/commands/cmd-1")
    assert s == 404
    s, body = call("DELETE", "/api/devicetypes/dt-1/commands/cmd-1")
    assert s == 200 and body["deleted"]
    s, _ = call("GET", "/api/devicetypes/dt-1/commands/cmd-1")
    assert s == 404

    s, _ = call("POST", "/api/devicetypes/dt-1/statuses", json_body={
        "token": "st-1", "code": "ok", "name": "OK"})
    assert s == 201
    s, body = call("GET", "/api/devicetypes/dt-1/statuses/st-1")
    assert s == 200
    s, body = call("PUT", "/api/devicetypes/dt-1/statuses/st-1",
                   json_body={"name": "All good"})
    assert s == 200 and body["name"] == "All good"
    s, body = call("DELETE", "/api/devicetypes/dt-1/statuses/st-1")
    assert s == 200 and body["deleted"]
    s, _ = call("GET", "/api/devicetypes/dt-1/statuses/st-1")
    assert s == 404


def test_group_element_delete(api):
    call, inst, loop = api
    call("POST", "/api/devices", json_body={"token": "ge-1"})
    call("POST", "/api/devices", json_body={"token": "ge-2"})
    call("POST", "/api/devicegroups", json_body={"token": "g-1", "name": "G"})
    s, els = call("POST", "/api/devicegroups/g-1/elements", json_body={
        "elements": [{"device": "ge-1"}, {"device": "ge-2"}]})
    assert s == 201
    ids = [e["element_id"] for e in els]
    s, body = call("DELETE", f"/api/devicegroups/g-1/elements/{ids[0]}")
    assert s == 200 and body["deleted"]
    s, body = call("GET", "/api/devicegroups/g-1/elements")
    assert len(body) == 1
    s, body = call("DELETE", "/api/devicegroups/g-1/elements",
                   json_body=[ids[1]])
    assert s == 200 and body["deleted"] == 1
    s, _ = call("DELETE", f"/api/devicegroups/g-1/elements/{ids[0]}")
    assert s == 404


def test_event_lookup_by_id_and_alternate(api):
    call, inst, loop = api
    call("POST", "/api/devices/ev-1/events", json_body={
        "deviceToken": "ev-1", "type": "DeviceMeasurement",
        "request": {"name": "temp", "value": 7.5, "alternateId": "alt-99"}})
    inst.engine.flush()
    s, body = call("GET", "/api/events/alternate/alt-99")
    assert s == 200 and body["measurements"]["temp"] == 7.5
    s, _ = call("GET", "/api/events/alternate/no-such")
    assert s == 404
    s, body = call("GET", "/api/events/id/0")
    assert s == 200 and body["type"] == "MEASUREMENT"
    s, _ = call("GET", "/api/events/id/999999")
    assert s == 404


def test_area_customer_event_rollups(api):
    """Per-area and per-customer event rollups come from the on-device
    area/customer store lanes (reference: Areas.java:{token}/measurements)."""
    call, inst, loop = api
    call("POST", "/api/areatypes", json_body={"token": "at", "name": "AT"})
    call("POST", "/api/areas", json_body={
        "token": "plant", "areaType": "at", "name": "Plant"})
    call("POST", "/api/customertypes", json_body={"token": "ct", "name": "CT"})
    call("POST", "/api/customers", json_body={
        "token": "acme", "customerType": "ct", "name": "ACME"})
    inst.engine.register_device("roll-1", area="plant", customer="acme")
    inst.engine.register_device("roll-2")   # no area/customer
    for tok in ("roll-1", "roll-2"):
        call("POST", f"/api/devices/{tok}/events", json_body={
            "deviceToken": tok, "type": "DeviceMeasurement",
            "request": {"name": "t", "value": 1.0}})
    inst.engine.flush()
    s, body = call("GET", "/api/areas/plant/measurements")
    assert s == 200 and body["numResults"] == 1
    assert body["results"][0]["deviceToken"] == "roll-1"
    s, body = call("GET", "/api/customers/acme/measurements")
    assert s == 200 and body["numResults"] == 1
    s, body = call("GET", "/api/areas/plant/alerts")
    assert s == 200 and body["numResults"] == 0
    s, body = call("GET", "/api/areas/plant/assignments")
    assert s == 200 and len(body) == 1
    s, _ = call("GET", "/api/areas/plant/bogus")
    assert s == 404


def test_device_summaries_group_listings_mappings(api):
    call, inst, loop = api
    call("POST", "/api/devices", json_body={"token": "sum-1"})
    call("POST", "/api/devices", json_body={"token": "sum-2"})
    s, body = call("GET", "/api/devices/summaries")
    assert s == 200 and len(body) >= 2
    call("POST", "/api/devicegroups", json_body={
        "token": "sg", "name": "SG", "roles": ["prod"]})
    call("POST", "/api/devicegroups/sg/elements",
         json_body={"elements": [{"device": "sum-1", "roles": ["prod"]}]})
    s, body = call("GET", "/api/devices/group/sg")
    assert s == 200 and body == ["sum-1"]
    s, body = call("GET", "/api/devices/grouprole/prod")
    assert s == 200 and body == ["sum-1"]
    # parent mappings
    call("POST", "/api/devices/sum-2/parent", json_body={"parentToken": "sum-1"})
    s, body = call("GET", "/api/devices/sum-2/mappings")
    assert s == 200 and body["parentToken"] == "sum-1"
    s, body = call("DELETE", "/api/devices/sum-2/mappings")
    assert s == 200 and body["parentToken"] is None
    s, body = call("GET", "/api/devices/sum-2/mappings")
    assert s == 200 and body == {}


def test_invocation_summary(api):
    call, inst, loop = api
    call("POST", "/api/devices", json_body={"token": "is-1"})
    call("POST", "/api/devicetypes/default/commands", json_body={
        "token": "ping", "name": "ping"})
    s, inv = call("POST", "/api/devices/is-1/invocations",
                  json_body={"commandToken": "ping"})
    assert s in (200, 201)
    inv_id = inv["invocationId"] if "invocationId" in inv else inv.get("id")
    s, body = call("GET", f"/api/invocations/{inv_id}/summary")
    assert s == 200 and body["invocation"]["command_token"] == "ping"
    assert body["responses"] == []
    # a device response must surface in the summary (ADVICE r2: responses
    # store aux0 = interner id of originatingEventId, not the raw counter)
    call("POST", "/api/devices/is-1/events", json_body={
        "type": "DeviceCommandResponse",
        "request": {"originatingEventId": str(inv_id), "response": "pong"}})
    s, body = call("GET", f"/api/invocations/{inv_id}/summary")
    assert s == 200 and len(body["responses"]) == 1


def test_tenant_templates_endpoints(api):
    """VERDICT r2 missing #5: Tenants.java /templates/configuration and
    /templates/dataset."""
    call, inst, loop = api
    s, body = call("GET", "/api/tenants/templates/configuration")
    assert s == 200 and {t["id"] for t in body} >= {"default", "mqtt"}
    assert all("configuration" in t and "description" in t for t in body)
    s, body = call("GET", "/api/tenants/templates/dataset")
    assert s == 200
    ids = {t["id"] for t in body}
    assert ids >= {"empty", "construction"}
    # a listed configuration template actually applies
    from sitewhere_tpu.config import apply_tenant_config
    s, cfg_tpls = call("GET", "/api/tenants/templates/configuration")
    tpl = next(t for t in cfg_tpls if t["id"] == "default")
    summary = apply_tenant_config(inst, tpl["configuration"])
    assert summary["eventSources"] == ["default-in"]
    # /api/tenants/{token} still resolves normal tokens
    s, body = call("GET", "/api/tenants/default")
    assert s == 200 and body["token"] == "default"


def test_user_role_mutation(api):
    """VERDICT r2 missing #5: Users.java @PUT/@DELETE /{username}/roles."""
    call, inst, loop = api
    call("POST", "/api/users", {"username": "roley", "password": "pw",
                                "roles": ["user"]})
    s, body = call("GET", "/api/users/roley/roles")
    assert s == 200 and body["results"] == ["user"]
    s, body = call("PUT", "/api/users/roley/roles", ["admin"])
    assert s == 200 and set(body["roles"]) == {"user", "admin"}
    # adding an existing role is idempotent
    s, body = call("PUT", "/api/users/roley/roles", ["admin"])
    assert s == 200 and body["roles"].count("admin") == 1
    # unknown role rejected
    s, body = call("PUT", "/api/users/roley/roles", ["ghost-role"])
    assert s == 400
    s, body = call("DELETE", "/api/users/roley/roles", ["user"])
    assert s == 200 and body["roles"] == ["admin"]
    # empty list is an error (reference: InvalidUserInformation)
    s, body = call("PUT", "/api/users/roley/roles", [])
    assert s == 400
    s, body = call("GET", "/api/users/ghost/roles")
    assert s == 404
    # advisor r3 (low): a non-admin may read their OWN roles but cannot
    # enumerate another user's (the mutations are admin-only already)
    call("POST", "/api/users", {"username": "peeker", "password": "pw",
                                "roles": ["user"]})
    peeker_jwt = inst.jwt.generate("peeker", inst.users.authorities_for(
        inst.users.users["peeker"]))
    hdr = {"Authorization": f"Bearer {peeker_jwt}"}
    s, body = call("GET", "/api/users/peeker/roles", headers=hdr)
    assert s == 200 and body["results"] == ["user"]
    s, body = call("GET", "/api/users/roley/roles", headers=hdr)
    assert s == 403
    # ...and the sibling read paths that expose the same data share the gate
    s, _ = call("GET", "/api/users/roley", headers=hdr)
    assert s == 403
    s, _ = call("GET", "/api/users/roley/authorities", headers=hdr)
    assert s == 403
    s, _ = call("GET", "/api/users", headers=hdr)
    assert s == 403
    s, _ = call("GET", "/api/users/peeker", headers=hdr)
    assert s == 200
    s, _ = call("GET", "/api/users/peeker/authorities", headers=hdr)
    assert s == 200
