"""DistributedEngine: the product runtime over the sharded mesh.

End-to-end cases the VERDICT asked for: string-token JSON ingest routed by
token hash, sharded step, queries/state reads from stacked state, admin
CRUD, fair tenancy, and (in test_distributed_durability.py) WAL recovery.
Runs on the virtual 8-device CPU mesh from conftest.
"""

import json

import numpy as np
import pytest

from sitewhere_tpu.core.types import EventType
from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType
from sitewhere_tpu.parallel.distributed import (
    DistributedConfig,
    DistributedEngine,
)


def small_config(**kw) -> DistributedConfig:
    base = dict(
        n_shards=4,
        device_capacity_per_shard=64,
        token_capacity_per_shard=128,
        assignment_capacity_per_shard=128,
        store_capacity_per_shard=512,
        channels=4,
        batch_capacity_per_shard=64,
        use_native=True,
    )
    base.update(kw)
    return DistributedConfig(**base)


def meas_payload(token: str, temp: float, ts_ms: int | None = None) -> bytes:
    req = {
        "deviceToken": token,
        "type": "DeviceMeasurements",
        "request": {"measurements": {"temp.celsius": temp}},
    }
    if ts_ms is not None:
        req["request"]["eventDate"] = ts_ms
    return json.dumps(req).encode()


@pytest.fixture
def engine():
    return DistributedEngine(small_config())


def test_json_ingest_routes_across_shards(engine):
    payloads = [meas_payload(f"dev-{i}", 20.0 + i) for i in range(32)]
    summary = engine.ingest_json_batch(payloads)
    assert summary["decoded"] == 32 and summary["failed"] == 0
    out = engine.flush()
    assert out["registered"] == 32
    m = engine.metrics()
    assert m["found"] == 32 and m["persisted"] == 32
    # round-robin interning: every shard owns some devices
    per_shard = [s["devices"] for s in engine.shard_metrics()]
    assert all(n > 0 for n in per_shard)
    assert sum(per_shard) == 32


def test_device_state_readback(engine):
    engine.ingest_json_batch([meas_payload("dev-a", 21.5, ts_ms=None)])
    engine.flush()
    st = engine.get_device_state("dev-a")
    assert st is not None
    assert st["presence"] == "PRESENT"
    assert st["measurements"]["temp.celsius"]["value"] == pytest.approx(21.5)
    assert st["event_counts"]["MEASUREMENT"] == 1
    info = engine.get_device("dev-a")
    assert info is not None and info.auto_registered


def test_query_events_global_merge(engine):
    base_ms = int(engine.epoch.base_unix_s * 1000)
    payloads = [
        meas_payload(f"dev-{i}", float(i), ts_ms=base_ms + i * 1000)
        for i in range(16)
    ]
    engine.ingest_json_batch(payloads)
    engine.flush()
    res = engine.query_events(limit=8)
    assert res["total"] == 16
    assert len(res["events"]) == 8
    # newest-first across ALL shards
    ts = [e["eventDateMs"] for e in res["events"]]
    assert ts == sorted(ts, reverse=True)
    assert res["events"][0]["deviceToken"] == "dev-15"
    # per-device filter hits only the owning shard
    one = engine.query_events(device_token="dev-3")
    assert one["total"] == 1
    assert one["events"][0]["measurements"]["temp.celsius"] == pytest.approx(3.0)


def test_admin_register_and_slow_path(engine):
    gdid = engine.register_device("adm-1", tenant="acme", area="plant")
    assert engine.get_device("adm-1").tenant == "acme"
    # same token again -> same id (get-or-create)
    assert engine.register_device("adm-1") == gdid
    # events for the admin-registered device flow through its shard
    engine.process(DecodedRequest(
        type=RequestType.DEVICE_MEASUREMENT,
        device_token="adm-1",
        tenant="acme",
        measurements={"pressure": 3.5},
    ))
    out = engine.flush()
    assert out["found"] == 1 and out["registered"] == 0
    st = engine.get_device_state("adm-1")
    assert st["measurements"]["pressure"]["value"] == pytest.approx(3.5)


def test_assignment_lifecycle(engine):
    engine.register_device("asg-1", tenant="t1")
    a = engine.create_assignment("asg-1", token="asg-1:extra", asset="pump")
    assert engine.get_assignment("asg-1:extra").asset == "pump"
    assert len(engine.list_assignments(device_token="asg-1")) == 2
    rel = engine.release_assignment("asg-1:extra")
    assert rel.status == "RELEASED"
    # events now expand only to the remaining active assignment
    engine.process(DecodedRequest(
        type=RequestType.DEVICE_MEASUREMENT, device_token="asg-1",
        tenant="t1", measurements={"x": 1.0}))
    out = engine.flush()
    assert out["persisted"] == 1


def test_map_device_cross_and_same_shard(engine):
    # interning order makes dev ids 0..n round-robin: 0 and n_shards land
    # on shard 0 (same shard); 0 and 1 land on different shards
    toks = [f"map-{i}" for i in range(engine.n_shards + 1)]
    for t in toks:
        engine.register_device(t)
    info = engine.map_device(toks[engine.n_shards], toks[0])  # same shard
    assert info.metadata["parentToken"] == toks[0]
    info2 = engine.map_device(toks[1], toks[0])               # cross shard
    assert info2.metadata["parentToken"] == toks[0]
    with pytest.raises(ValueError):
        engine.map_device(toks[0], toks[0])


def test_dead_letters_without_auto_register():
    eng = DistributedEngine(small_config(auto_register=False))
    eng.ingest_json_batch([meas_payload("ghost-1", 1.0)])
    out = eng.flush()
    assert out["missed"] == 1 and out["registered"] == 0
    assert "ghost-1" in eng.dead_letters


def test_presence_sweep_marks_missing():
    eng = DistributedEngine(small_config(presence_missing_s=0.0))
    eng.ingest_json_batch([meas_payload(f"pres-{i}", 1.0) for i in range(8)])
    eng.flush()
    import time

    time.sleep(0.01)
    tokens = eng.presence_sweep()
    assert set(tokens) == {f"pres-{i}" for i in range(8)}
    states = eng.search_device_states(presence="MISSING")
    assert len(states) == 8


def test_fair_tenancy_quota():
    eng = DistributedEngine(small_config(fair_tenancy=True,
                                         batch_capacity_per_shard=32))
    # tenant A floods, tenant B trickles — B's events must still land
    for i in range(64):
        eng.ingest_json_batch([meas_payload(f"a-{i}", 1.0)], tenant="bulk")
    for i in range(4):
        eng.ingest_json_batch([meas_payload(f"b-{i}", 2.0)], tenant="tiny")
    eng.flush()
    assert eng.fair_backlog("bulk") == 0 and eng.fair_backlog("tiny") == 0
    m = eng.metrics()
    assert m["persisted"] == 68
    assert eng.get_device_state("b-0") is not None


def test_binary_wire_ingest(engine):
    from sitewhere_tpu.ingest.decoders import encode_binary_request

    reqs = [
        DecodedRequest(
            type=RequestType.DEVICE_MEASUREMENT, device_token=f"bin-{i}",
            tenant="default", measurements={"v": float(i)})
        for i in range(8)
    ]
    payloads = [encode_binary_request(r) for r in reqs]
    summary = engine.ingest_binary_batch(payloads)
    assert summary["decoded"] == 8
    engine.flush()
    assert engine.metrics()["persisted"] == 8
    assert engine.get_device_state("bin-3")["measurements"]["v"]["value"] == 3.0


def test_multi_batch_steady_state(engine):
    """Many async flushes, mirrors sync lazily — totals must reconcile."""
    rng = np.random.default_rng(1)
    total = 0
    for _ in range(6):
        n = int(rng.integers(10, 40))
        payloads = [meas_payload(f"ss-{rng.integers(0, 50)}", 1.0)
                    for _ in range(n)]
        engine.ingest_json_batch(payloads)
        engine.flush_async()
        total += n
    engine.flush()
    m = engine.metrics()
    assert m["persisted"] == total
    assert m["processed"] == total


def test_instance_and_rest_over_distributed_engine():
    """The full product surface — REST gateway, management, outbound feed,
    command delivery — serves from the SHARDED mesh state when the instance
    is built over a DistributedEngine (VERDICT item 1's 'REST served from
    the sharded state')."""
    import asyncio
    import base64

    import aiohttp
    from aiohttp.test_utils import TestClient, TestServer

    from sitewhere_tpu.instance.instance import (
        InstanceConfig,
        SiteWhereTpuInstance,
    )
    from sitewhere_tpu.web.rest import make_app

    deng = DistributedEngine(small_config())
    inst = SiteWhereTpuInstance(InstanceConfig(), engine=deng)
    assert inst.engine is deng

    async def go():
        client = TestClient(TestServer(make_app(inst)))
        await client.start_server()
        try:
            basic = base64.b64encode(b"admin:password").decode()
            r = await client.get("/api/authapi/jwt",
                                 headers={"Authorization": f"Basic {basic}"})
            token = (await r.json())["token"]
            h = {"Authorization": f"Bearer {token}"}

            # device CRUD through management -> sharded registry
            r = await client.post("/api/devices",
                                  json={"token": "dr-1"}, headers=h)
            assert r.status == 201
            # telemetry through REST -> sharded step -> state readback
            r = await client.post("/api/devices/dr-1/events", json={
                "deviceToken": "dr-1", "type": "DeviceMeasurement",
                "request": {"name": "temp", "value": 21.0}}, headers=h)
            assert r.status == 201
            inst.engine.flush()
            r = await client.get("/api/devices/dr-1/state", headers=h)
            body = await r.json()
            assert body["measurements"]["temp"]["value"] == 21.0
            r = await client.get("/api/events", headers=h)
            assert (await r.json())["total"] >= 1
            # device update (PUT) against the stacked admin path
            r = await client.put("/api/devices/dr-1",
                                 json={"deviceType": "default",
                                       "metadata": {"k": "v"}}, headers=h)
            assert r.status == 200
            # assignment PUT/missing/DELETE + event-by-id: the Engine
            # admin endpoints must serve (not 500) from the mesh (ADVICE r2)
            r = await client.post("/api/assignments", json={
                "deviceToken": "dr-1", "token": "dr-1:x"}, headers=h)
            assert r.status == 201
            r = await client.put("/api/assignments/dr-1:x",
                                 json={"assetToken": "pump"}, headers=h)
            assert r.status == 200 and (await r.json())["assetToken"] == "pump"
            r = await client.post("/api/assignments/dr-1:x/missing",
                                  headers=h)
            assert r.status == 200
            r = await client.delete("/api/assignments/dr-1:x", headers=h)
            assert r.status == 200
            feed = deng.make_feed_consumer("rest-ev")
            evs = feed.poll()
            assert evs
            r = await client.get(f"/api/events/id/{evs[0].event_id}",
                                 headers=h)
            assert r.status == 200
            assert (await r.json())["deviceToken"] == "dr-1"
        finally:
            await client.close()

    asyncio.new_event_loop().run_until_complete(go())


def test_distributed_feed_and_command_delivery():
    """Outbound feed over per-shard rings + command delivery end to end on
    the mesh engine."""
    import asyncio
    import json as _json

    from sitewhere_tpu.commands.destinations import (
        CommandDestination,
        LocalDeliveryProvider,
        mqtt_topic_extractor,
    )
    from sitewhere_tpu.commands.encoders import JsonCommandExecutionEncoder
    from sitewhere_tpu.commands.model import CommandParameter, DeviceCommand, ParameterType
    from sitewhere_tpu.commands.routing import SingleChoiceCommandRouter
    from sitewhere_tpu.commands.service import CommandDeliveryService
    from sitewhere_tpu.parallel.distributed import DistributedFeedConsumer

    eng = DistributedEngine(small_config())
    eng.ingest_json_batch([meas_payload(f"fd-{i}", float(i))
                           for i in range(12)])
    eng.flush()
    feed = DistributedFeedConsumer(eng, "grp")
    evs = feed.poll()
    assert len(evs) == 12
    assert len({e.event_id for e in evs}) == 12
    assert {e.device_token for e in evs} == {f"fd-{i}" for i in range(12)}
    feed.commit(evs)
    assert feed.poll() == []

    # command delivery consumes the same per-shard rings
    svc = CommandDeliveryService(eng, SingleChoiceCommandRouter("local"))
    svc.registry.create(DeviceCommand(token="ping", device_type="default",
                                      name="ping"))
    provider = LocalDeliveryProvider()
    svc.add_destination(CommandDestination(
        "local", mqtt_topic_extractor(), JsonCommandExecutionEncoder(),
        provider))
    inv = svc.invoke("fd-3", "ping")
    eng.flush()

    async def pump():
        return await svc.pump()

    n = asyncio.new_event_loop().run_until_complete(pump())
    assert n == 1 and len(provider.delivered) == 1
    target, payload, system = provider.delivered[0]
    assert target == "fd-3" and not system


def test_query_events_by_assignment_scopes_to_one_assignment(engine):
    """ADVICE r2 (high): assignment-scoped queries must filter on the
    shard-local assignment row, not just the owning shard — two devices
    whose events land on the SAME shard must not leak into each other's
    assignment listing."""
    for i in range(2 * engine.n_shards):
        engine.register_device(f"aq-{i}", tenant="t1")
    engine.flush()
    asgs = [engine.list_assignments(device_token=f"aq-{i}")[0]
            for i in range(2 * engine.n_shards)]
    by_shard: dict[int, list] = {}
    for a in asgs:
        by_shard.setdefault(engine._split_gdid(a.id)[0], []).append(a)
    shard, pair = next((s, v) for s, v in by_shard.items() if len(v) >= 2)
    a0, a1 = pair[0], pair[1]
    engine.ingest_json_batch(
        [meas_payload(a0.device_token, 1.0 + i, ts_ms=1000 + i)
         for i in range(3)]
        + [meas_payload(a1.device_token, 2.0 + i, ts_ms=2000 + i)
           for i in range(2)],
        tenant="t1")
    engine.flush()
    r0 = engine.query_events(assignment_id=a0.id)
    r1 = engine.query_events(assignment_id=a1.id)
    assert r0["total"] == 3 and r1["total"] == 2
    assert all(e["assignmentId"] == a0.id for e in r0["events"])
    assert all(e["deviceToken"] == a0.device_token for e in r0["events"])
    # device+assignment combined filter still works
    both = engine.query_events(device_token=a0.device_token,
                               assignment_id=a0.id)
    assert both["total"] == 3
    # mismatched device/assignment shards -> empty
    other = next(a for a in asgs
                 if engine._split_gdid(a.id)[0] != shard)
    assert engine.query_events(device_token=a0.device_token,
                               assignment_id=other.id)["total"] == 0


def test_distributed_assignment_admin_parity(engine):
    """ADVICE r2 (medium): DistributedEngine must implement the Engine
    admin surface REST calls (update/delete/missing + get_event) so a
    distributed instance never 500s on those endpoints."""
    engine.register_device("adm-1", tenant="t1")
    a = engine.create_assignment("adm-1", token="adm-1:x", asset="pump")
    upd = engine.update_assignment("adm-1:x", asset="valve",
                                   metadata={"k": "v"})
    assert upd.asset == "valve" and upd.metadata == {"k": "v"}
    assert engine.get_assignment("adm-1:x").asset == "valve"

    miss = engine.mark_assignment_missing("adm-1:x")
    assert miss.status == "MISSING"
    # missing assignments stay active: events still expand to both
    engine.ingest_json_batch([meas_payload("adm-1", 7.0)], tenant="t1")
    out = engine.flush()
    assert out["persisted"] == 2

    assert engine.delete_assignment("adm-1:x") is True
    assert engine.get_assignment("adm-1:x") is None
    assert engine.delete_assignment("adm-1:x") is False


def test_distributed_get_event_roundtrip(engine):
    from sitewhere_tpu.parallel.distributed import DistributedFeedConsumer

    engine.ingest_json_batch([meas_payload(f"ge-{i}", 10.0 + i)
                              for i in range(6)])
    engine.flush()
    evs = DistributedFeedConsumer(engine, "ge-grp").poll()
    assert len(evs) == 6
    for src in evs:
        ev = engine.get_event(src.event_id)
        assert ev is not None
        assert ev["deviceToken"] == src.device_token
        assert ev["eventDateMs"] == src.ts_ms
        assert ev["measurements"] == src.measurements
    assert engine.get_event(-1) is None
    assert engine.get_event(10**9) is None
