"""Flight recorder: batch-lifecycle tracing, device-side counters, and
cross-rank traceparent propagation (PR 3).

The reference reconstructs a message's path from Istio/Zipkin spans; here
every ingest batch gets one ring-buffer lifecycle record (utils/flight.py)
whose trace id follows cross-rank forwards through the RPC frame's
``tp`` field, and the jit step accumulates a packed per-tenant counter
grid with zero extra host<->device syncs.
"""

import json

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType
from sitewhere_tpu.utils.flight import NULL_RECORD, FlightRecorder
from sitewhere_tpu.utils.tracing import (bind_traceparent,
                                         current_traceparent,
                                         new_traceparent, trace_id_of)


def _cfg(**kw):
    base = dict(device_capacity=64, token_capacity=128,
                assignment_capacity=128, store_capacity=1024,
                batch_capacity=16, channels=4)
    base.update(kw)
    return EngineConfig(**base)


def meas_payload(token, name="temp", value=1.0, i=0):
    return json.dumps({
        "deviceToken": token, "type": "DeviceMeasurements",
        "request": {"measurements": {name: value},
                    "eventDate": 1700000000000 + i}}).encode()


# ---------------------------------------------------------------- recorder
def test_recorder_wraparound():
    rec = FlightRecorder(capacity=4)
    ids = [rec.begin("ingest", n_payloads=i).trace_id for i in range(6)]
    # the two oldest records were evicted by the ring
    assert rec.records_of(ids[0]) == []
    assert rec.records_of(ids[1]) == []
    assert rec.records_of(ids[2]) != []
    assert rec.dropped == 2
    recent = rec.recent(10)
    assert len(recent) == 4
    # newest first
    assert [r["traceId"] for r in recent] == list(reversed(ids[2:]))
    assert len(rec) == 4


def test_recorder_disabled_is_noop():
    rec = FlightRecorder(capacity=4, enabled=False)
    r = rec.begin("ingest")
    assert r is NULL_RECORD and r.trace_id is None
    r.mark("decode")          # all no-ops
    r.add("k", 1)
    assert rec.recent(10) == [] and len(rec) == 0


def test_recorder_joins_traceparent():
    rec = FlightRecorder(capacity=4, rank=3)
    tp = new_traceparent(rank=3)
    r = rec.begin("ingest", traceparent=tp)
    assert r.trace_id == trace_id_of(tp)
    # malformed traceparent falls back to a fresh id, never crashes
    r2 = rec.begin("ingest", traceparent="garbage")
    assert r2.trace_id and len(r2.trace_id) == 32


def test_traceparent_context_binding():
    assert current_traceparent() is None
    tp = new_traceparent(rank=1)
    with bind_traceparent(tp):
        assert current_traceparent() == tp
        with bind_traceparent(None):        # no-op bind keeps context
            assert current_traceparent() == tp
    assert current_traceparent() is None


# ---------------------------------------------------------------- lifecycle
def test_engine_batch_lifecycle_record(tmp_path):
    eng = Engine(_cfg(wal_dir=str(tmp_path / "wal")))
    res = eng.ingest_json_batch(
        [meas_payload(f"fl-{i % 4}", i=i) for i in range(10)])
    assert res["trace_id"]
    eng.flush()
    trace = eng.get_trace(res["trace_id"])
    assert trace["records"], "ingest batch must leave a lifecycle record"
    rec = trace["records"][0]
    stages = rec["stagesUs"]
    # every lifecycle stage timestamped, including device-ready
    for name in ("decode", "wal_append", "commit", "dispatch",
                 "device_ready", "readback"):
        assert name in stages, f"missing stage {name}: {stages}"
    # stage ordering is physically monotone
    assert stages["decode"] <= stages["commit"] <= stages["dispatch"]
    assert stages["dispatch"] <= stages["device_ready"]
    assert rec["decoded"] == 10
    # recent_traces serves the same record
    assert any(r["traceId"] == res["trace_id"]
               for r in eng.recent_traces(10))
    # unknown ids resolve to an empty record list
    assert eng.get_trace("f" * 32)["records"] == []


def test_legacy_path_trace_survives_midingest_flush():
    """Copy-staging path (no arenas): a batch whose rows are ALL
    dispatched by mid-ingest buffer-fill flushes must still end with a
    complete lifecycle — the record joins the newest in-flight program
    instead of stranding with only decode/commit."""
    eng = Engine(_cfg(ingest_arenas=-1, batch_capacity=8))
    res = eng.ingest_json_batch(
        [meas_payload(f"lg-{i % 4}", i=i) for i in range(16)])
    eng.flush()
    rec = eng.get_trace(res["trace_id"])["records"][0]
    for name in ("decode", "commit", "dispatch", "device_ready",
                 "readback"):
        assert name in rec["stagesUs"], rec


def test_trace_id_spans_wal_less_engine():
    eng = Engine(_cfg())
    res = eng.ingest_json_batch([meas_payload("nw-1")])
    eng.flush()
    stages = eng.get_trace(res["trace_id"])["records"][0]["stagesUs"]
    assert "wal_append" not in stages      # no WAL configured
    assert "readback" in stages


# ---------------------------------------------------- device-side counters
def test_device_side_tenant_counters_accepted_and_dedup():
    eng = Engine(_cfg())
    eng.register_device("dc-1", tenant="acme")
    # two identical alternate ids in ONE batch: the step's in-batch
    # dedup lane must count the redelivery signature
    for _ in range(2):
        eng.process(DecodedRequest(
            type=RequestType.DEVICE_MEASUREMENT, device_token="dc-1",
            tenant="acme", measurements={"t": 1.0}, alternate_id="alt-1"))
    eng.process(DecodedRequest(
        type=RequestType.DEVICE_MEASUREMENT, device_token="dc-1",
        tenant="acme", measurements={"t": 2.0}))
    eng.flush()
    counters = eng.tenant_pipeline_counters()
    assert counters["acme"]["accepted"] == 3
    assert counters["acme"]["dedup_dropped"] == 1
    assert counters["acme"]["invalid"] == 0


def alt_payload(token, alt, value=1.0, i=0):
    return json.dumps({
        "deviceToken": token, "type": "DeviceMeasurements",
        "request": {"measurements": {"t": value}, "alternateId": alt,
                    "eventDate": 1700000000000 + i}}).encode()


def test_batch_path_extracts_alternate_id_for_dedup():
    """ISSUE 4 satellite: the native batch/arena decoders extract
    ``alternateId`` into the aux1 lane, so the device-side dedup counter
    works on the batch path — with the SAME counts as the per-request
    process() path over the same traffic (parity)."""
    def drive_batch(eng):
        eng.register_device("dc-b", tenant="acme")
        eng.ingest_json_batch(
            [alt_payload("dc-b", "alt-1", i=1),
             alt_payload("dc-b", "alt-1", i=2),     # in-batch redelivery
             alt_payload("dc-b", "alt-2", i=3)],
            tenant="acme")
        eng.flush()
        return eng.tenant_pipeline_counters()

    def drive_requests(eng):
        eng.register_device("dc-r", tenant="acme")
        for alt in ("alt-1", "alt-1", "alt-2"):
            eng.process(DecodedRequest(
                type=RequestType.DEVICE_MEASUREMENT, device_token="dc-r",
                tenant="acme", measurements={"t": 1.0}, alternate_id=alt))
        eng.flush()
        return eng.tenant_pipeline_counters()

    batch = drive_batch(Engine(_cfg()))
    req = drive_requests(Engine(_cfg()))
    assert batch["acme"]["dedup_dropped"] == 1
    assert batch["acme"] == req["acme"], (batch, req)


def test_alternate_id_query_spans_batch_rows():
    """Rows staged by the batch decoder resolve through the alternate-id
    query surface — engine.event_ids and the decoder's aux1 interner are
    the SAME table."""
    eng = Engine(_cfg())
    eng.ingest_json_batch([alt_payload(f"aq-{i}", f"alt-q{i}", i=i)
                           for i in range(4)])
    eng.flush()
    res = eng.query_events(alternate_id="alt-q2")
    assert res["total"] == 1
    assert res["events"][0]["deviceToken"] == "aq-2"
    assert eng.query_events(alternate_id="alt-missing")["total"] == 0


def test_device_side_counters_invalid_lane():
    eng = Engine(_cfg(auto_register=False))
    eng.ingest_json_batch([meas_payload("ghost-1")])
    eng.flush()
    counters = eng.tenant_pipeline_counters()
    assert counters["default"]["invalid"] == 1
    assert counters["default"]["accepted"] == 0


def test_device_side_geofence_counter():
    eng = Engine(_cfg())
    eng.set_geofence_zones([[(0.0, 0.0), (0.0, 10.0), (10.0, 10.0),
                             (10.0, 0.0)]])
    for lat, lon in ((5.0, 5.0), (50.0, 50.0)):
        eng.process(DecodedRequest(
            type=RequestType.DEVICE_LOCATION, device_token="geo-1",
            latitude=lat, longitude=lon))
    eng.flush()
    counters = eng.tenant_pipeline_counters()
    assert counters["default"]["geofence_hit"] == 1
    assert counters["default"]["accepted"] == 2
    # removing the zones freezes (not resets) the cumulative lane
    eng.set_geofence_zones([])
    eng.process(DecodedRequest(
        type=RequestType.DEVICE_LOCATION, device_token="geo-1",
        latitude=5.0, longitude=5.0))
    eng.flush()
    assert eng.tenant_pipeline_counters()["default"]["geofence_hit"] == 1


def test_counters_survive_scan_chunk_dispatch():
    """The packed grid accumulates identically through the K-lane scan
    program (dispatch-shape parity, like every other device counter)."""
    eng = Engine(_cfg(scan_chunk=2))
    eng.ingest_json_batch([meas_payload(f"sc-{i}", i=i) for i in range(8)])
    eng.flush()
    assert eng.tenant_pipeline_counters()["default"]["accepted"] == 8


def test_restore_tolerates_pre_upgrade_snapshot(tmp_path):
    """A snapshot written BEFORE the tenant_counters grid existed must
    still restore: the missing metrics leaf keeps its fresh zeros."""
    import numpy as np

    from sitewhere_tpu.utils.checkpoint import restore_engine, save_engine

    eng = Engine(_cfg())
    eng.register_device("cp-1")
    eng.flush()
    save_engine(eng, tmp_path / "snap")
    path = tmp_path / "snap" / "state.npz"
    data = dict(np.load(path))
    del data[".metrics.tenant_counters"]      # simulate the old format
    np.savez_compressed(path, **data)
    eng2 = restore_engine(tmp_path / "snap")
    assert eng2.get_device("cp-1") is not None
    assert eng2.tenant_pipeline_counters() == {}    # fresh zeros


# --------------------------------------------------------------- cross-rank
def test_cross_rank_traceparent_resolution(tmp_path):
    """A batch ingested at rank 0 whose devices are owned by rank 1
    leaves records on BOTH ranks under ONE trace id, and the trace
    resolves cluster-wide from either rank."""
    from tests.test_cluster import _close, _mk_cluster, meas, tokens_owned_by

    clusters, host, _ = _mk_cluster(tmp_path)
    c0, c1 = clusters
    try:
        remote = tokens_owned_by(1, 3, prefix="fl")      # owned by rank 1
        local = tokens_owned_by(0, 1, prefix="fl")       # owned by rank 0
        payloads = [meas(t, "temp", 1.0, 100 + i)
                    for i, t in enumerate(remote + local)]
        res = c0.ingest_json_batch(payloads)
        tid = res["trace_id"]
        assert tid
        c0.flush()
        for facade in (c0, c1):
            trace = facade.get_trace(tid)
            ranks = {r["rank"] for r in trace["records"]}
            assert ranks == {0, 1}, trace
            kinds = {(r["rank"], r["kind"]) for r in trace["records"]}
            assert (0, "route") in kinds      # the facade's routing leg
            assert (1, "ingest") in kinds     # the owner-side ingest
        # the owner-side record went through the full lifecycle
        owner = [r for r in c1.get_trace(tid)["records"]
                 if r["rank"] == 1 and r["kind"] == "ingest"][0]
        for name in ("decode", "commit", "dispatch", "readback"):
            assert name in owner["stagesUs"], owner
    finally:
        _close(clusters, host)
