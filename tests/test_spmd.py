"""Multi-chip SPMD store (ISSUE 16): the real engine sharded over the mesh.

The contract pinned here, on the virtual 8-device CPU mesh:

  * **store byte-identity** — each shard's event ring is byte-identical to
    a single-chip engine fed only that shard's substream (the slot router
    is the only difference between the two executions);
  * **query parity** — fused cross-shard query pages equal the single-chip
    pages (same rows, same order — including ts ties, which break by
    (shard, ring-position), matching single-chip arrival order because
    the router preserves per-device arrival order and a device lives on
    exactly one shard);
  * **metrics parity** — ``engine.metrics()`` dict-equal to single-chip
    with qos + devicewatch + tracing + rules all on;
  * **rule-fire parity** — the merged harvest emits exactly the
    single-chip alert key set (device-scoped rules; a group lives on one
    shard);
  * **zero steady-state recompiles / excess retraces** for the
    ``sharded.*`` SPMD families once warm;
  * **conservation** — the flow ledger balances through the sharded
    staging lanes.
"""

import json

import jax
import numpy as np
import pytest

from sitewhere_tpu.core.events import EpochBase
from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.parallel.placement import shard_for_token
from sitewhere_tpu.parallel.sharded import SpmdEngine
from sitewhere_tpu.rules import RulesManager
from sitewhere_tpu.rules import oracle as rules_oracle
from sitewhere_tpu.utils.conservation import build_ledger, check_conservation
from sitewhere_tpu.utils.devicewatch import WATCH

CFG = dict(device_capacity=64, token_capacity=128, assignment_capacity=128,
           store_capacity=2048, batch_capacity=32, channels=4,
           rule_groups=64, rollup_buckets=8, use_native=False)

RULESET = {
    "name": "spmd",
    "rules": [
        {"name": "hot", "kind": "threshold", "channel": "temp",
         "op": ">", "value": 90.0, "cooldownMs": 1000},
        {"name": "burst", "kind": "window", "agg": "count",
         "channel": "temp", "op": ">=", "value": 3, "windowMs": 2000,
         "where": {"channel": "temp", "op": ">", "value": 50.0}},
    ],
    "rollups": [{"name": "temp-1s", "channel": "temp",
                 "windowMs": 1000, "scope": "device"}],
}


class FixedEpoch(EpochBase):
    """Deterministic received_ms so both executions stamp identical rows."""

    def __init__(self, now_ms: int = 500_000):
        super().__init__(0.0)
        self._now = now_ms

    def now_ms(self) -> int:
        return self._now


def _meas(tok, value, ts, name="temp"):
    return json.dumps({
        "deviceToken": tok, "type": "DeviceMeasurement",
        "request": {"name": name, "value": value, "eventDate": ts},
    }).encode()


def _stream(n=120, devs=8, ties=False):
    """Deterministic stream. With ``ties=True`` every frame of ``devs``
    events shares one timestamp (exercises the cross-shard merge-tie
    contract); otherwise timestamps are unique (byte-exact page parity)."""
    out = []
    for i in range(n):
        d = i % devs
        ts = 1_000 + ((i // devs) * 100 if ties else i * 10)
        v = 96.5 if i % 11 == 0 else 20.0 + (i % 40) * 0.5
        if i % 23 == 0:
            v = 2.5
        out.append((f"sp-{d}", v, ts))
    return out


def _engines(n_shards, **kw):
    ref = Engine(EngineConfig(**{**CFG, **kw}))
    spmd = SpmdEngine(EngineConfig(**{**CFG, **kw}), n_shards=n_shards)
    for e in (ref, spmd):
        e.epoch = FixedEpoch()
    return ref, spmd


def _spmd(n_shards, scan_chunk=1, depth=0, arena=True, **kw):
    eng = SpmdEngine(
        EngineConfig(**{**CFG, "scan_chunk": scan_chunk,
                        "ingest_arenas": depth, **kw}),
        n_shards=n_shards, arena=arena)
    eng.epoch = FixedEpoch()
    return eng


def _run(engines, events, chunk=32):
    for lo in range(0, len(events), chunk):
        wire = [_meas(t, v, ts) for t, v, ts in events[lo:lo + chunk]]
        for e in engines:
            e.ingest_json_batch(wire)
            e.flush()


def _page(eng, **kw):
    """A query page with the shard-qualified assignment id canonicalized
    (different id spaces; the assignment is identified by its device)."""
    out = eng.query_events(**kw)
    return out["total"], [
        {k: v for k, v in ev.items() if k != "assignmentId"}
        for ev in out["events"]
    ]


# --- store byte-identity ----------------------------------------------------


@pytest.mark.parametrize("n_shards", [2, 4])
def test_store_byte_identical_to_per_shard_substreams(n_shards):
    _, spmd = _engines(n_shards)
    events = _stream()
    _run([spmd], events)
    spmd.barrier()
    spmd.drain()
    for s in range(n_shards):
        sub = [ev for ev in events
               if shard_for_token(ev[0], n_shards) == s]
        ref = Engine(EngineConfig(**CFG))
        ref.epoch = FixedEpoch()
        _run([ref], sub)
        ref.barrier()
        ref.drain()
        ref_store = jax.device_get(ref.state.store)
        spmd_store = jax.tree_util.tree_map(
            lambda x, _s=s: jax.device_get(x[_s]), spmd.state.store)
        for a, b in zip(jax.tree_util.tree_leaves(ref_store),
                        jax.tree_util.tree_leaves(spmd_store)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_single_shard_is_the_identity():
    ref, spmd = _engines(1)
    events = _stream(64)
    _run([ref, spmd], events)
    for e in (ref, spmd):
        e.barrier()
        e.drain()
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(ref.state.store)),
                    jax.tree_util.tree_leaves(jax.tree_util.tree_map(
                        lambda x: jax.device_get(x[0]), spmd.state.store))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --- query parity -----------------------------------------------------------


@pytest.mark.parametrize("n_shards", [1, 2, 4])
def test_query_pages_match_single_chip(n_shards):
    ref, spmd = _engines(n_shards)
    _run([ref, spmd], _stream())
    for kw in (
            dict(limit=200),                       # full page
            dict(limit=7),                         # truncated page
            dict(device_token="sp-3", limit=20),   # device filter
            dict(device_token="sp-3", since_ms=1_200, until_ms=1_800,
                 limit=20),                        # time window
            dict(limit=20, since_ms=1_300),
    ):
        assert _page(ref, **kw) == _page(spmd, **kw), kw


@pytest.mark.parametrize("n_shards", [2, 4])
def test_query_tie_order_is_the_documented_merge_contract(n_shards):
    """Cross-shard ts TIES: single-chip breaks ties by global arrival
    order, which the shards cannot reconstruct; the SPMD page contract is
    the deterministic merge key ``(-ts, shard, ring-rank)`` — within one
    timestamp, shard-major, each shard's rows in its local arrival order.
    Same row SET per timestamp as single-chip, pinned order."""
    ref, spmd = _engines(n_shards)
    events = _stream(ties=True)
    _run([ref, spmd], events)
    t_ref, page_ref = _page(ref, limit=200)
    t_spmd, page_spmd = _page(spmd, limit=200)
    assert t_ref == t_spmd == len(events)
    # per-timestamp row multisets match single-chip exactly
    def by_ts(page):
        out = {}
        for ev in page:
            out.setdefault(ev["eventDateMs"], []).append(
                tuple(sorted((k, str(v)) for k, v in ev.items())))
        return {ts: sorted(rows) for ts, rows in out.items()}
    assert by_ts(page_ref) == by_ts(page_spmd)
    # pinned order: newest-first frames; within a frame shard-major, and
    # within a shard the stream's arrival order
    expected = []
    frames = sorted({ts for _, _, ts in events}, reverse=True)
    for ts in frames:
        for s in range(n_shards):
            expected.extend(
                tok for tok, _, ts2 in events
                if ts2 == ts and shard_for_token(tok, n_shards) == s)
    assert [ev["deviceToken"] for ev in page_spmd] == expected


@pytest.mark.parametrize("n_shards", [2, 4])
def test_device_state_and_tenant_metrics_match(n_shards):
    ref, spmd = _engines(n_shards)
    _run([ref, spmd], _stream())
    for d in range(8):
        assert (ref.get_device_state(f"sp-{d}")
                == spmd.get_device_state(f"sp-{d}"))
    assert ref.tenant_metrics() == spmd.tenant_metrics()
    assert ref.tenant_pipeline_counters() == spmd.tenant_pipeline_counters()


# --- metrics parity with every observability plane on -----------------------


@pytest.mark.parametrize("n_shards", [2, 4])
def test_metrics_dict_equal_with_qos_tracing_rules_on(n_shards):
    ref, spmd = _engines(n_shards, qos=True, devicewatch=True,
                         span_sample=1.0)
    mgr_ref = RulesManager(ref)
    mgr_spmd = RulesManager(spmd)
    mgr_ref.load(RULESET)
    mgr_spmd.load(RULESET, precompile=False)
    _run([ref, spmd], _stream())
    a, b = ref.metrics(), spmd.metrics()
    # host-side flush cadence differs by construction (per-shard lanes
    # emit fixed [S, B] batches), so dispatch-shape counters are not part
    # of the parity contract — everything event-count-shaped is
    for k in ("processed", "found", "missed", "registered", "persisted",
              "reg_overflow", "channel_collisions", "staged",
              "rule_fires", "rules_active"):
        assert a[k] == b[k], (k, a[k], b[k])
    assert ({x["alternateId"] for x in mgr_ref.poll()}
            == {x["alternateId"] for x in mgr_spmd.poll()})


# --- rule-fire parity vs single-chip and the host oracle --------------------


@pytest.mark.parametrize("n_shards", [2, 4])
def test_rule_fires_match_single_chip_and_oracle(n_shards):
    ref, spmd = _engines(n_shards)
    mgr_ref = RulesManager(ref)
    mgr_spmd = RulesManager(spmd)
    mgr_ref.load(RULESET)
    mgr_spmd.load(RULESET, precompile=False)
    events = _stream()
    _run([ref, spmd], events)
    keys_ref = {a["alternateId"] for a in mgr_ref.poll()}
    keys_spmd = {a["alternateId"] for a in mgr_spmd.poll()}
    assert keys_ref == keys_spmd
    assert ref.metrics()["rule_fires"] == spmd.metrics()["rule_fires"]
    # and both equal the sequential host oracle
    ev = [{"ts": ts, "group": t, "value": v, "value_b": v}
          for t, v, ts in events]
    expected = set()
    for g, w in rules_oracle.threshold_fire_keys(ev, op=0, value=90.0,
                                                 cooldown_ms=1000):
        expected.add(f"swr:hot:{g}:{w}")
    for g, w in rules_oracle.window_fire_keys(ev, agg="count", op=1,
                                              value=3, window_ms=2000,
                                              where=(0, 50.0)):
        expected.add(f"swr:burst:{g}:{w}")
    assert keys_ref == expected
    # rollup read path folds per-shard tables to the same buckets
    ru_ref = mgr_ref.read_rollup("temp-1s", limit=100)
    ru_spmd = mgr_spmd.read_rollup("temp-1s", limit=100)
    assert sorted(map(tuple, (sorted(b.items()) for b in ru_ref["buckets"]))) \
        == sorted(map(tuple, (sorted(b.items())
                              for b in ru_spmd["buckets"])))


# --- devicewatch: zero excess retraces, zero steady-state recompiles --------


def test_spmd_families_zero_steady_state_recompiles():
    _, spmd = _engines(4)
    events = _stream(64)
    _run([spmd], events)
    spmd.query_events(device_token="sp-1", limit=20)   # warm the AOT round
    spmd.presence_sweep()
    pre = WATCH.compile_totals()
    pre_excess = WATCH.excess_total()
    _run([spmd], _stream(64))
    spmd.query_events(device_token="sp-2", limit=20)
    spmd.presence_sweep()
    assert WATCH.compile_totals() == pre
    assert WATCH.excess_total() == pre_excess


# --- arena ingest: cartesian parity matrix (ISSUE 17) -----------------------
#
# The stacked-arena batch path must be byte-identical to the v1 per-row
# router for every (mesh size, scan_chunk packing, pipeline depth) combo:
# same store bytes, same query pages and tie order, same event-count
# metrics, balanced conservation on every shard. Heavy combos are -m slow.

_MATRIX = [(n, k, d) for n in (1, 2, 4) for k in (1, 2) for d in (1, 2)]
_LIGHT = {(2, 1, 1), (2, 2, 2)}


@pytest.mark.parametrize(
    "n_shards,scan_chunk,depth",
    [pytest.param(*combo,
                  marks=() if combo in _LIGHT else pytest.mark.slow)
     for combo in _MATRIX])
def test_arena_matrix_byte_identity_and_parity(n_shards, scan_chunk, depth):
    arena = _spmd(n_shards, scan_chunk, depth)
    router = _spmd(n_shards, arena=False)      # v1 per-row router oracle
    ref = Engine(EngineConfig(**CFG))
    ref.epoch = FixedEpoch()
    events = _stream()
    _run([arena, router, ref], events)
    for e in (arena, router, ref):
        e.barrier()
        e.drain()
    # store byte-identity: every leaf of the stacked store
    for a, b in zip(
            jax.tree_util.tree_leaves(jax.device_get(arena.state.store)),
            jax.tree_util.tree_leaves(jax.device_get(router.state.store))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the batch path never takes the copy-staging router
    assert arena.host_counters.get("staged_copy_rows", 0) == 0
    assert arena.host_counters.get("arena_rows", 0) == len(events)
    # query-page parity vs single-chip (full, truncated, filtered)
    for kw in (dict(limit=200), dict(limit=7),
               dict(device_token="sp-3", limit=20)):
        assert _page(ref, **kw) == _page(arena, **kw), kw
    # metrics parity on everything event-count-shaped
    a, b = arena.metrics(), router.metrics()
    for k in ("processed", "found", "missed", "registered", "persisted",
              "reg_overflow", "channel_collisions", "staged"):
        assert a[k] == b[k], (k, a[k], b[k])
    # conservation balances through the stacked arena lanes
    assert check_conservation(build_ledger(arena)) == []


@pytest.mark.parametrize("n_shards,scan_chunk,depth",
                         [(2, 1, 1), pytest.param(2, 2, 2,
                                                  marks=pytest.mark.slow)])
def test_arena_tie_order_matches_router(n_shards, scan_chunk, depth):
    arena = _spmd(n_shards, scan_chunk, depth)
    router = _spmd(n_shards, arena=False)
    events = _stream(ties=True)
    _run([arena, router], events)
    assert _page(arena, limit=200) == _page(router, limit=200)


def test_arena_scan_chunk_retune_stays_byte_identical():
    arena = _spmd(2, scan_chunk=1, depth=2)
    router = _spmd(2, arena=False)
    events = _stream()
    half = len(events) // 2
    _run([arena, router], events[:half])
    applied = arena.set_ingest_tuning(scan_chunk=2)
    assert applied["scan_chunk"] == 2
    _run([arena, router], events[half:])
    for e in (arena, router):
        e.barrier()
        e.drain()
    for a, b in zip(
            jax.tree_util.tree_leaves(jax.device_get(arena.state.store)),
            jax.tree_util.tree_leaves(jax.device_get(router.state.store))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_qos_shed_then_recover_no_loss_on_spmd():
    """Per-tenant admission at the SPMD ingest edge: a flood sheds at the
    rate limiter, the client retries after Retry-After, and afterwards
    the persisted count equals the admitted count exactly — nothing an
    arena dispatch saw is lost or double-applied."""
    from sitewhere_tpu.utils.qos import AdmissionController, ManualClock

    spmd = _spmd(2, scan_chunk=2, depth=2, qos=True)
    clk = ManualClock()
    spmd.qos = AdmissionController(tenant_rates={"sr-t": 40.0},
                                   burst_s=1.0, clock=clk)
    frames = [[_meas(f"sp-{j}", 20.0 + i, 1_000 + i * 10 + j)
               for j in range(10)] for i in range(12)]
    admitted = sheds = 0
    backlog = list(frames)
    rounds = 0
    while backlog and rounds < 100:
        rounds += 1
        still = []
        for f in backlog:
            d = spmd.qos.admit("sr-t", len(f))
            if d.admitted:
                spmd.ingest_json_batch(f, "sr-t")
                admitted += len(f)
            else:
                sheds += 1
                still.append(f)
        backlog = still
        clk.advance(0.5)
    assert not backlog and sheds > 0      # the cycle actually shed
    spmd.flush()
    assert admitted == 120
    counters = spmd.tenant_pipeline_counters().get("sr-t", {})
    assert counters.get("accepted") == 120          # no loss
    assert counters.get("dedup_dropped", 0) == 0    # no double-apply
    assert spmd.host_counters.get("staged_copy_rows", 0) == 0


# --- conservation -----------------------------------------------------------


@pytest.mark.parametrize("n_shards", [2, 4])
def test_conservation_ledger_balances(n_shards):
    _, spmd = _engines(n_shards)
    _run([spmd], _stream())
    spmd.flush()
    ledger = build_ledger(spmd)
    assert check_conservation(ledger) == []


# --- admin plane over shards ------------------------------------------------


def test_admin_paths_shard_qualified():
    _, spmd = _engines(4)
    dids = [spmd.register_device(f"adm-{i}", tenant="acme")
            for i in range(12)]
    assert len(set(dids)) == 12
    info = spmd.create_assignment("adm-0", token="asn-1", asset="truck")
    assert info.device_token == "adm-0"
    spmd.update_assignment("asn-1", area="north")
    assert spmd.get_assignment("asn-1").area == "north"
    spmd.release_assignment("asn-1")
    assert spmd.get_assignment("asn-1").status == "RELEASED"
    spmd.update_device("adm-0", device_type="gateway")
    assert spmd.get_device("adm-0").device_type == "gateway"
    # same-shard parenting works; cross-shard is refused loudly
    by_shard: dict[int, list[str]] = {}
    for i in range(12):
        by_shard.setdefault(shard_for_token(f"adm-{i}", 4),
                            []).append(f"adm-{i}")
    groups = [g for g in by_shard.values() if len(g) >= 2]
    if groups:
        a, b = groups[0][0], groups[0][1]
        assert spmd.map_device(a, b).metadata["parentToken"] == b
    two = [g[0] for g in by_shard.values()]
    if len(two) >= 2:
        with pytest.raises(ValueError, match="share a shard"):
            spmd.map_device(two[0], two[1])


def test_presence_sweep_parity():
    ref, spmd = _engines(2)
    _run([ref, spmd], _stream(32))
    missing_at = 500_000 + int(EngineConfig(**CFG).presence_missing_s
                               * 1000) + 10_000
    for e in (ref, spmd):
        e.epoch._now = missing_at
    assert sorted(ref.presence_sweep()) == sorted(spmd.presence_sweep())
    assert ref.presence_sweep() == spmd.presence_sweep() == []


def test_unsupported_configs_are_refused():
    with pytest.raises(ValueError, match="archive"):
        SpmdEngine(EngineConfig(**{**CFG, "archive_dir": "/tmp/x"}),
                   n_shards=2)
    with pytest.raises(ValueError, match="fair_tenancy"):
        SpmdEngine(EngineConfig(**{**CFG, "fair_tenancy": True}),
                   n_shards=2)
    # scan_chunk > 1 is SUPPORTED since the packed arena path (ISSUE 17)
    assert SpmdEngine(EngineConfig(**{**CFG, "scan_chunk": 2}),
                      n_shards=2).config.scan_chunk == 2
    eng = SpmdEngine(EngineConfig(**CFG), n_shards=2)
    with pytest.raises(NotImplementedError):
        eng.search_device_states()
    with pytest.raises(NotImplementedError):
        eng.get_event("x")
