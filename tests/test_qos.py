"""Overload discipline (ISSUE 9): per-tenant admission control,
weighted-fair scheduling, load shedding, and the SLO-steered autotuner.

Pinned properties:
  * token-bucket admission is DETERMINISTIC under a seeded/manual clock
    (same clock trace => same decision trace);
  * weighted-fair queuing: 2:1 weights => ~2:1 admitted throughput under
    saturation, for both the ingest gate and query-round membership;
  * shed-then-recover: no admitted event is lost or double-applied
    across a shed/retry cycle, and the WAL holds exactly the admitted
    payloads;
  * a 429 surfaced for a forwarded batch lands in retry_app_rejects
    (never retry_transport_failures), defers by the owner's Retry-After,
    never poison-dead-letters, and delivers exactly once on recovery;
  * the full-metrics-dict equality across dispatch shapes still holds
    with QoS on (engine.metrics() carries NO QoS keys);
  * ArenaPool.acquire(timeout_s=...) raises a typed ArenaStallError on a
    wedged dispatch, which the engine translates to a shed;
  * loadgen's abusive-tenant knob stays seed-deterministic and
    OpenLoopResult reports per-tenant shed counts.
"""

import json
import threading
import time

import pytest

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.utils.qos import (AdmissionController, ManualClock,
                                     ShedError, WeightedFairGate,
                                     WFQPicker, admit_or_raise)


def _meas(token, seq=0, value=1.0):
    return json.dumps({
        "deviceToken": token, "type": "DeviceMeasurement",
        "request": {"name": "t", "value": value,
                    "metadata": {"seq": str(seq)}}}).encode()


def _small_cfg(**kw):
    base = dict(device_capacity=64, token_capacity=128,
                assignment_capacity=128, store_capacity=4096,
                batch_capacity=16, channels=4)
    base.update(kw)
    return EngineConfig(**base)


# --------------------------------------------------------------- buckets
def test_token_bucket_deterministic_under_manual_clock():
    """Same config + same clock trace => byte-identical decision trace
    (the chaos-replay property). Refill arithmetic is exact."""

    def trace():
        clk = ManualClock()
        ac = AdmissionController(tenant_rates={"qos-det": 10.0},
                                 burst_s=1.0, clock=clk)
        out = []
        for i in range(14):
            d = ac.admit("qos-det", 1)
            out.append((d.admitted, round(d.retry_after_s, 6), d.reason))
            if i == 11:
                clk.advance(0.35)
        return out

    t1, t2 = trace(), trace()
    assert t1 == t2
    # capacity = 10 tokens: 10 admits, then rate sheds with an exact
    # retry hint (1 token / 10 eps = 0.1s), then the 0.35s refill buys
    # exactly 3 more admits
    assert [a for a, _, _ in t1[:10]] == [True] * 10
    assert t1[10] == (False, 0.1, "rate")
    assert t1[11] == (False, 0.1, "rate")
    assert [a for a, _, _ in t1[12:]] == [True, True]
    # an oversized request (n > bucket capacity) admits against a FULL
    # bucket and goes into debt — the bucket can never hold n tokens, so
    # refusing it would 429-loop the caller forever on a retry hint that
    # waiting cannot satisfy; the debt throttles what follows instead
    # (long-run rate preserved)
    ac2 = AdmissionController(tenant_rates={"qos-det2": 10.0}, burst_s=1.0,
                              clock=ManualClock())
    assert ac2.admit("qos-det2", 25).admitted        # full bucket: debt
    d = ac2.admit("qos-det2", 1)                     # balance now -15
    assert not d.admitted and d.retry_after_s == pytest.approx(1.6)


def test_admission_saturation_valve_and_unlimited_default():
    backlog = {"n": 0}
    clk = ManualClock()
    ac = AdmissionController(shed_threshold=100,
                             backlog_fn=lambda: backlog["n"], clock=clk,
                             min_retry_after_s=0.07)
    # unlimited default rate: any volume admits while not saturated
    assert ac.admit("qos-sat", 10_000).admitted
    backlog["n"] = 100
    d = ac.admit("qos-sat", 1)
    assert (d.admitted, d.reason) == (False, "saturated")
    assert d.retry_after_s == pytest.approx(0.07)
    backlog["n"] = 99
    assert ac.admit("qos-sat", 1).admitted
    assert ac.shed_by_tenant["qos-sat"] == 1


def test_admit_or_raise_typed_shed():
    class H:
        qos = AdmissionController(tenant_rates={"qos-t": 1.0},
                                  burst_s=1.0, clock=ManualClock())

    admit_or_raise(H(), "qos-t", 1)
    with pytest.raises(ShedError) as ei:
        admit_or_raise(H(), "qos-t", 5)
    assert ei.value.reason == "rate" and ei.value.retry_after_s > 0
    admit_or_raise(object(), "qos-t", 99)   # no controller = no-op


# ------------------------------------------------------------------ WFQ
def test_wfq_gate_two_to_one_ratio_under_saturation():
    """2:1 weights => ~2:1 granted turns while both tenants always have
    a waiter (the gate itself is the scheduler, so the ratio is a
    property of the virtual-time rule, not the OS scheduler)."""
    gate = WeightedFairGate({"wfq-a": 2.0, "wfq-b": 1.0})
    stop = threading.Event()
    start = threading.Barrier(4)   # every tenant is contending from
                                   # grant #1 — no head start

    def hammer(tenant):
        start.wait()
        while not stop.is_set():
            with gate.turn(tenant, 1):
                # a non-trivial turn: the GIL must rotate so BOTH
                # tenants actually contend (a no-op body lets one
                # thread blast the whole budget in a single GIL slice)
                time.sleep(0.0005)
                if gate.grants.get("wfq-a", 0) + \
                        gate.grants.get("wfq-b", 0) >= 600:
                    stop.set()

    ts = [threading.Thread(target=hammer, args=(t,))
          for t in ("wfq-a", "wfq-b") for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    ratio = gate.grants["wfq-a"] / max(1, gate.grants["wfq-b"])
    assert 1.5 <= ratio <= 2.7, gate.grants


def test_wfq_gate_uncontended_is_immediate_and_idle_banks_nothing():
    gate = WeightedFairGate({"solo": 1.0})
    for _ in range(5):
        with gate.turn("solo"):
            pass
    # a fresh tenant entering later starts at the current virtual clock,
    # not at 0 — it may not starve the incumbent with banked silence
    with gate.turn("late"):
        pass
    assert gate.vtimes()["late"] >= gate._vnow - 1.0


def test_wfq_picker_exact_weighted_membership():
    p = WFQPicker({"qa": 2.0, "qb": 1.0})
    entries = ([{"tenant": "qa", "i": i} for i in range(8)]
               + [{"tenant": "qb", "i": i} for i in range(8)])
    sel, rest = p.pick(entries, 6)
    counts = {"qa": 0, "qb": 0}
    for e in sel:
        counts[e["tenant"]] += 1
    assert counts == {"qa": 4, "qb": 2}
    # FIFO within a tenant, rest preserves arrival order
    assert [e["i"] for e in sel if e["tenant"] == "qa"] == [0, 1, 2, 3]
    assert len(rest) == 10 and [e["i"] for e in rest
                                if e["tenant"] == "qb"] == list(range(2, 8))
    # a tenant alone gets the whole round regardless of weight
    sel2, rest2 = p.pick([{"tenant": "qb", "i": i} for i in range(4)], 3)
    assert len(sel2) == 3 and len(rest2) == 1


def test_query_batcher_wfq_round_membership():
    """With QoS on, an overflowing query round grants slots by weight
    instead of arrival order: a flooding tenant cannot fill every slot
    of the next round ahead of another tenant's single query."""
    eng = Engine(_small_cfg(qos=True, query_coalesce=4,
                            tenant_weights={"qf-a": 1.0, "qf-b": 1.0}))
    b = eng._query_batcher
    assert b._wfq is not None
    flood = [{"tenant": "qf-a", "i": i} for i in range(6)]
    other = [{"tenant": "qf-b", "i": 0}]
    sel, rest = b._wfq.pick(flood + other, 4)
    assert {"qf-b"} <= {e["tenant"] for e in sel}
    # tenant flows through query_events into the batcher entry
    captured = {}
    orig = b.run

    def spy(params, limit, archive=None, tenant=None, trace_id=None):
        captured["tenant"] = tenant
        return orig(params, limit, archive=archive, tenant=tenant,
                    trace_id=trace_id)

    b.run = spy
    eng.query_events(tenant="default", limit=5)
    assert captured["tenant"] == "default"


def test_engine_wfq_fairness_under_saturation():
    """Engine-level WFQ: tenants hammering batch ingest through the gate
    get admitted throughput ~ their 2:1 weights. The EXACT ratio rule is
    pinned deterministically at the gate level above; this test pins the
    WIRING (the gate really orders batch ingest) so the band tolerates
    OS-scheduler skew on a loaded box: two threads per tenant keep a
    waiter parked on both sides, and the run stops on a GRANT COUNT, not
    wall time, so a slow box still collects a meaningful sample."""
    eng = Engine(_small_cfg(qos=True,
                            tenant_weights={"ewf-a": 2.0, "ewf-b": 1.0},
                            batch_capacity=32))
    payloads = {t: [_meas(f"{t}-{i}") for i in range(8)]
                for t in ("ewf-a", "ewf-b")}
    stop = threading.Event()
    start = threading.Barrier(4)

    def hammer(tenant):
        start.wait()
        while not stop.is_set():
            eng.ingest_json_batch(payloads[tenant], tenant)
            g = eng._wfq_gate.grants
            if g.get("ewf-a", 0) + g.get("ewf-b", 0) >= 180:
                stop.set()

    ts = [threading.Thread(target=hammer, args=(t,))
          for t in ("ewf-a", "ewf-b") for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    eng.flush()
    g = eng._wfq_gate.grants
    ratio = g["ewf-a"] / max(1, g["ewf-b"])
    assert 1.3 <= ratio <= 3.5, g


# ------------------------------------------------- shed-then-recover
def test_shed_then_recover_no_loss_no_dup_wal_clean(tmp_path):
    """A shed/retry cycle loses nothing and double-applies nothing: the
    edge retries shed frames until admitted; afterwards the persisted
    count equals the admitted count exactly and the WAL holds exactly
    one record per admitted payload (shed frames never touch it)."""
    clk = ManualClock()
    eng = Engine(_small_cfg(qos=True, wal_dir=str(tmp_path / "wal"),
                            store_capacity=8192, batch_capacity=64))
    eng.qos = AdmissionController(tenant_rates={"sr-t": 40.0},
                                  burst_s=1.0, clock=clk)
    frames = [[_meas(f"sr-{j}", seq=i * 10 + j) for j in range(10)]
              for i in range(12)]
    admitted = sheds = 0
    backlog = list(frames)
    rounds = 0
    while backlog and rounds < 100:
        rounds += 1
        still = []
        for f in backlog:
            d = eng.qos.admit("sr-t", len(f))
            if d.admitted:
                eng.ingest_json_batch(f, "sr-t")
                admitted += len(f)
            else:
                sheds += 1
                still.append(f)   # the client retries after Retry-After
        backlog = still
        clk.advance(0.5)
    assert not backlog and sheds > 0     # the cycle actually shed
    eng.flush()
    assert admitted == 120
    counters = eng.tenant_pipeline_counters().get("sr-t", {})
    assert counters.get("accepted") == 120          # no loss
    assert counters.get("dedup_dropped", 0) == 0    # no double-apply
    # WAL clean: exactly one record per ADMITTED payload
    from sitewhere_tpu.utils.ingestlog import IngestLog

    eng.wal.sync()
    records = list(IngestLog(tmp_path / "wal", readonly=True).replay())
    assert len(records) == 120


def test_metrics_dict_equality_across_dispatch_shapes_with_qos():
    """The PR-2..5 parity pin extended: with QoS enabled, engine.metrics()
    must still be EQUAL across scan_chunk shapes — every QoS instrument
    lives in the Prometheus registry, none leak into metrics()."""
    def build(chunk):
        return Engine(_small_cfg(qos=True, scan_chunk=chunk,
                                 store_capacity=4096,
                                 tenant_rates={"mq-t": 1e9}))

    a, b = build(1), build(4)
    b.epoch = a.epoch
    payloads = [_meas(f"mq-{i % 10}", seq=i) for i in range(64)]
    for eng in (a, b):
        for lo in range(0, 64, 16):
            eng.ingest_json_batch(payloads[lo:lo + 16], "mq-t")
        eng.flush()
    assert a.metrics() == b.metrics()
    assert not any(k.startswith("qos") or "shed" in k
                   for k in a.metrics())


# ------------------------------------------------------- arena stall
def test_arena_pool_acquire_timeout_raises_typed_stall():
    from sitewhere_tpu.ingest.arena import ArenaPool, ArenaStallError

    class Wedged:
        def is_ready(self):
            return False

    pool = ArenaPool(2, rows=8, channels=2)
    a1 = pool.acquire()
    a2 = pool.acquire()
    pool.retire(a1, Wedged())
    pool.retire(a2, Wedged())
    t0 = time.monotonic()
    with pytest.raises(ArenaStallError):
        pool.acquire(timeout_s=0.05)
    assert time.monotonic() - t0 < 2.0
    assert pool.waits == 1
    # a wedged ticket that becomes ready is reclaimed normally
    class Ready:
        def is_ready(self):
            return True

    pool2 = ArenaPool(1, rows=8, channels=2)
    b1 = pool2.acquire()
    import numpy as np

    pool2.retire(b1, np.zeros(1))
    assert pool2.acquire(timeout_s=0.05) is b1


def test_engine_translates_arena_stall_to_shed():
    eng = Engine(_small_cfg(qos=True, arena_stall_timeout_s=0.02,
                            tenant_rates={}))
    if eng._arena_pool is None:
        pytest.skip("native arena path unavailable")
    from sitewhere_tpu.ingest.arena import ArenaStallError

    def stall(timeout_s=None):
        raise ArenaStallError("wedged (test)")

    eng._arena_fill = None
    eng._arena_pool.acquire = stall
    with pytest.raises(ShedError) as ei:
        eng.ingest_json_batch([_meas("st-0")], "st-t")
    assert ei.value.reason == "stall"
    assert eng._stall_sheds == 1
    assert eng.qos.shed_by_tenant.get("st-t") == 1


# ------------------------------------------------------------- loadgen
def test_loadgen_abusive_knob_deterministic_and_additive():
    from sitewhere_tpu.loadgen import (OpenLoopSpec, TenantLoad,
                                       build_open_loop_schedule,
                                       schedule_fingerprint)

    def spec(mult):
        return OpenLoopSpec(
            tenants=(TenantLoad("lg-a", 400.0, abusive_mult=mult,
                                abusive_period_s=0.4,
                                abusive_burst_s=0.2),),
            duration_s=1.0, frame_size=32, seed=7)

    s_base = build_open_loop_schedule(spec(1.0))
    s_abuse = build_open_loop_schedule(spec(3.0))
    # determinism: same spec => identical fingerprint, both shapes
    assert (schedule_fingerprint(s_base)
            == schedule_fingerprint(build_open_loop_schedule(spec(1.0))))
    assert (schedule_fingerprint(s_abuse)
            == schedule_fingerprint(build_open_loop_schedule(spec(3.0))))
    n_base = sum(len(op.payloads or ()) for op in s_base)
    n_abuse = sum(len(op.payloads or ()) for op in s_abuse)
    # bursts cover half the horizon at +2x rate => ~2x total volume
    assert n_abuse > 1.5 * n_base
    # the extra arrivals land INSIDE the burst windows only
    in_win = out_win = 0
    base_arrivals = set()
    for op in s_base:
        for a in op.arrivals or ():
            base_arrivals.add(a)
    for op in s_abuse:
        for a in op.arrivals or ():
            if a in base_arrivals:
                continue
            if (a % 0.4) < 0.2:
                in_win += 1
            else:
                out_win += 1
    assert in_win > 0 and out_win == 0


def test_open_loop_reports_per_tenant_sheds():
    from sitewhere_tpu.loadgen import (OpenLoopSpec, TenantLoad,
                                       build_open_loop_schedule,
                                       run_open_loop)

    eng = Engine(_small_cfg(qos=True, store_capacity=8192,
                            batch_capacity=64,
                            tenant_rates={"ol-noisy": 50.0},
                            qos_burst_s=2.0))   # capacity 100: the first
                                               # noisy frames admit, the
                                               # flood past them sheds
    sched = build_open_loop_schedule(OpenLoopSpec(
        tenants=(TenantLoad("ol-good", 300.0, n_devices=16),
                 TenantLoad("ol-noisy", 1500.0, n_devices=16)),
        duration_s=0.6, frame_size=32, seed=3))
    res = run_open_loop(eng, sched, checkpoint_frames=2,
                        time_scale=0.05)   # replay fast: admission uses
                                           # the real clock, so the
                                           # noisy offer is ~20x its cap
    noisy = res.per_tenant["ol-noisy"]
    good = res.per_tenant["ol-good"]
    assert noisy["shed"] > 0 and good["shed"] == 0
    assert res.shed_events == noisy["shed"]
    assert res.events == good["events"] + noisy["events"]
    # zero admitted loss: device-side accepted == admitted per tenant
    eng.flush()
    tpc = eng.tenant_pipeline_counters()
    assert tpc["ol-good"]["accepted"] == good["events"]
    assert tpc["ol-noisy"]["accepted"] == noisy["events"]


# ------------------------------------------------------ SLO autotuner
def test_decide_slo_policy_pure():
    from sitewhere_tpu.utils.autotune import decide_slo

    bounds = {"max_workers": 4, "max_depth": 4, "max_chunk": 8,
              "min_shed": 64, "max_shed": 4096}
    cur = {"ingest_workers": 1, "dispatch_depth": 1, "scan_chunk": 1,
           "shed_threshold": 1024}
    flat = {"decode_ms": 1.0, "wal_ms": 1.0, "dispatch_wait_ms": 1.0,
            "device_ms": 1.0}
    # dead band (hysteresis): no proposals between 0.5x and 1.25x
    assert decide_slo(45.0, 50.0, flat, cur, bounds) == []
    assert decide_slo(30.0, 50.0, flat, cur, bounds) == []
    # violating + decode-bound: widen fan-out FIRST, shed tightening is
    # queued behind it
    hot = {"decode_ms": 8.0, "wal_ms": 0.5, "dispatch_wait_ms": 0.5,
           "device_ms": 2.0}
    props = decide_slo(90.0, 50.0, hot, cur, bounds)
    assert props[0][0] == "ingest_workers" and props[0][1] == 2
    assert props[-1][0] == "shed_threshold" and props[-1][1] == 512
    # violating with no stage dominance: tighten the shed threshold
    props = decide_slo(90.0, 50.0, flat, cur, bounds)
    assert props[0][0] == "shed_threshold" and props[0][1] == 512
    # threshold never tightens below min_shed
    low = dict(cur, shed_threshold=64)
    assert decide_slo(90.0, 50.0, flat, low, bounds) == []
    # comfortable: relax the threshold (and nothing else)
    props = decide_slo(10.0, 50.0, flat, cur, bounds)
    assert props == [("shed_threshold", 2048, props[0][2])]
    # no p99 measurement yet: no action
    assert decide_slo(None, 50.0, flat, cur, bounds) == []


def test_slo_harvest_scoped_to_own_engine():
    """ISSUE 10 satellite, closing the PR-9 known limit: the SLO harvest
    stamps every swtpu_ingest_e2e series with the harvesting engine's
    engine=e<n> label and the autotuner's reader keeps only its OWN
    engine's series — so with TWO in-process engines sharing the
    process-global registry, engine A's steering can never act on
    engine B's tenants (before the scope, both engines shared the
    default-tenant series and A would have read B's p99)."""
    from sitewhere_tpu.utils.metrics import slo_metrics

    a = Engine(_small_cfg(autotune=True, slo_p99_target_ms=50.0))
    b = Engine(_small_cfg(autotune=True, slo_p99_target_ms=50.0))
    assert a.metrics_label != b.metrics_label
    # the leak scenario: the SAME (default) tenant, ingested into B only
    b.ingest_json_batch([_meas(f"scope-{i}", seq=i) for i in range(16)])
    b.flush()
    # B's reader sees its own window ...
    assert b._autotuner.slo_p99_ms() is not None
    # ... A's sees nothing: B's series live under B's engine label (A
    # harvests first inside slo_p99_ms — its own records only)
    assert a._autotuner.slo_p99_ms() is None
    hist = slo_metrics()["ingest_e2e"]
    assert hist.count(tenant="default", engine=b.metrics_label) >= 16
    assert hist.count(tenant="default", engine=a.metrics_label) == 0


def test_autotuner_slo_objective_steers_shed_threshold():
    """End to end: an engine with qos + autotune + a hopeless p99 target
    tightens its shed threshold from the real SLO histogram reading.
    The engine's own per-dispatch hook drives the evaluations
    (autotune_interval=1), and the violating branch relieves the
    measured bottleneck FIRST (which stage dominates depends on the
    box), so ingest rounds continue until the bounded
    workers/depth/chunk headroom is spent and the threshold tightens."""
    eng = Engine(_small_cfg(qos=True, autotune=True, autotune_interval=1,
                            slo_p99_target_ms=0.0001,
                            store_capacity=8192, batch_capacity=32))
    tuner = eng._autotuner
    assert tuner is not None and tuner.slo_target_ms == 0.0001
    before = eng.qos.shed_threshold
    for r in range(10):
        for i in range(4):
            eng.ingest_json_batch(
                [_meas(f"slo-{j}", seq=(r * 4 + i) * 16 + j)
                 for j in range(16)], "slo-tune-t")
            eng.flush()
        if eng.qos.shed_threshold < before:
            break
    sheds = [d for d in tuner.decisions
             if d["knob"] == "shed_threshold"]
    assert sheds and sheds[-1]["p99_ms"] > 0.0001
    assert eng.qos.shed_threshold < before
    # the threshold knob went through the set_ingest_tuning choke point
    assert eng.config.shed_threshold == eng.qos.shed_threshold


# ------------------------------------------------- cluster forwarding
def test_forward_shed_classifies_app_reject_and_recovers(tmp_path):
    """ISSUE 9 satellite: a 429 shed at the OWNER of a forwarded batch
    is honest end to end — the sender spills it with the owner's
    Retry-After (summary carries shed_deferred + retry_after_s), the
    retry pump counts it in retry_app_rejects (NEVER
    retry_transport_failures), it never poison-dead-letters, and once
    the owner's bucket refills the batch delivers exactly once."""
    from tests.test_forward import _close, _mk_forwarding_cluster
    from tests.test_cluster import meas, tokens_owned_by

    clusters, queues, regs, servers, host, ports = \
        _mk_forwarding_cluster(tmp_path)
    c0, c1 = clusters
    try:
        clk = ManualClock()
        c1.local.qos = AdmissionController(
            tenant_rates={"fs-t": 10.0}, burst_s=0.2, clock=clk,
            min_retry_after_s=0.01)
        c1.local.qos.admit("fs-t", 2)        # drain the owner's bucket
        remote = tokens_owned_by(1, 2, prefix="fsh")
        s = c0.ingest_json_batch(
            [meas(t, "t", 1.0, 100 + i) for i, t in enumerate(remote)],
            tenant="fs-t")
        # spilled for deferred redelivery, with the owner's hint
        assert s["spilled"] == 2 and s["shed_deferred"] == 2
        assert s["retry_after_s"] == pytest.approx(0.2)
        q = queues[0]
        q.app_reject_attempts = 2            # would poison fast if 429
                                             # counted toward the budget
        assert q.metrics()["forward_queue_depth"] == 1
        # within the deferral window the pump does not even attempt
        assert q.retry_once() == 0
        assert q.counters["retry_app_rejects"] == 0
        time.sleep(0.25)                     # deferral (real clock) over;
                                             # owner clock still frozen
        for _ in range(3):                   # >> app_reject_attempts
            q.retry_once()
            time.sleep(0.25)
        m = q.metrics()
        assert m["forward_retry_app_rejects"] >= 3
        assert m["forward_retry_transport_failures"] == 0
        assert m["forward_deadlettered_poison"] == 0    # 429 never poisons
        assert m["forward_queue_depth"] == 1
        # owner recovers: bucket refills on ITS clock, batch delivers
        clk.advance(5.0)
        time.sleep(0.25)
        assert q.retry_once() == 1
        c1.flush()
        for t in remote:
            assert c0.query_events(device_token=t)["total"] == 1, t
    finally:
        _close(clusters, regs, host)


def test_rpc_edge_shed_is_typed_429():
    """The instance RPC ingest edge sheds with a typed code=429 error
    frame carrying retryAfterS (the wire form of Retry-After)."""
    import asyncio

    from sitewhere_tpu.instance.instance import (InstanceConfig,
                                                 SiteWhereTpuInstance)
    from sitewhere_tpu.rpc.client import RpcClient
    from sitewhere_tpu.rpc.protocol import RpcError
    from sitewhere_tpu.rpc.server import build_instance_rpc, system_jwt

    inst = SiteWhereTpuInstance(InstanceConfig(engine=_small_cfg()))
    inst.engine.qos = AdmissionController(
        tenant_rates={"default": 10.0}, burst_s=0.1, clock=ManualClock())

    async def go():
        srv = build_instance_rpc(inst)
        port = await srv.start()
        cli = await RpcClient(port=port, tenant="default",
                              auth_token=system_jwt(inst)).connect()
        env = {"deviceToken": "rpc-shed-0", "type": "DeviceMeasurement",
               "request": {"name": "t", "value": 1.0}}
        assert (await cli.call("DeviceEventManagement.addDeviceEvent",
                               envelope=env))["accepted"]
        with pytest.raises(RpcError) as ei:
            await cli.call("DeviceEventManagement.addDeviceEvent", envelope=env)
        assert ei.value.code == 429
        assert ei.value.retry_after_s == pytest.approx(0.1)
        await cli.close()
        await srv.stop()

    asyncio.new_event_loop().run_until_complete(go())


def test_facade_local_shed_is_all_or_nothing(tmp_path):
    """A locally-owned sub-batch refused by the facade's bucket refuses
    the WHOLE mixed-ownership call with a typed ShedError BEFORE any
    forward leaves the rank — never a success summary that silently
    drops the local payloads while remote-owned ones of the same call
    spill for durable redelivery. The refused batch retries verbatim
    once the bucket refills, landing every event exactly once."""
    from tests.test_cluster import meas, tokens_owned_by
    from tests.test_forward import _close, _mk_forwarding_cluster

    clusters, queues, regs, servers, host, ports = \
        _mk_forwarding_cluster(tmp_path)
    c0, c1 = clusters
    try:
        clk = ManualClock()
        c0.local.qos = AdmissionController(
            tenant_rates={"lf-t": 10.0}, burst_s=0.2, clock=clk,
            min_retry_after_s=0.01)
        c0.local.qos.admit("lf-t", 2)        # drain the facade's bucket
        local = tokens_owned_by(0, 1, prefix="lsh")
        remote = tokens_owned_by(1, 1, prefix="lsh")
        batch = [meas(t, "t", 1.0, 100 + i)
                 for i, t in enumerate(local + remote)]
        with pytest.raises(ShedError) as ei:
            c0.ingest_json_batch(batch, tenant="lf-t")
        assert ei.value.retry_after_s == pytest.approx(0.1)
        # nothing applied, forwarded, or spilled: the caller owns the
        # retry of the full batch
        assert queues[0].metrics()["forward_queue_depth"] == 0
        c0.flush()
        c1.flush()
        for t in local + remote:
            assert c0.query_events(device_token=t)["total"] == 0, t
        # the bucket refills on the facade's clock; the same batch lands
        clk.advance(1.0)
        c0.ingest_json_batch(batch, tenant="lf-t")
        c0.flush()
        c1.flush()
        for t in local + remote:
            assert c0.query_events(device_token=t)["total"] == 1, t
    finally:
        _close(clusters, regs, host)


def test_facade_single_event_edge_admits_per_owner(tmp_path):
    """The REST edge over a cluster facade admits ONLY locally-owned
    devices against the local bucket (remote owners run their own
    admission), so remote-owned traffic never double-charges the edge
    rank — and a locally-owned shed still answers an explicit 429."""
    import asyncio
    import base64

    import aiohttp

    from sitewhere_tpu.engine import EngineConfig
    from sitewhere_tpu.instance.instance import (InstanceConfig,
                                                 SiteWhereTpuInstance)
    from sitewhere_tpu.web.rest import start_server
    from tests.test_cluster import tokens_owned_by
    from tests.test_forward import _close, _mk_forwarding_cluster

    clusters, queues, regs, servers, host, ports = \
        _mk_forwarding_cluster(tmp_path)
    c0, c1 = clusters
    loop = asyncio.new_event_loop()
    inst = SiteWhereTpuInstance(
        InstanceConfig(engine=EngineConfig()), engine=c0)
    clk = ManualClock()
    c0.local.qos = AdmissionController(
        tenant_rates={"default": 10.0}, burst_s=0.2, clock=clk,
        min_retry_after_s=0.01)
    c0.local.qos.admit("default", 2)         # drain the local bucket
    server = loop.run_until_complete(start_server(inst))
    base = f"http://127.0.0.1:{server.port}"
    session = aiohttp.ClientSession(loop=loop)
    try:
        async def token():
            basic = base64.b64encode(b"admin:password").decode()
            async with session.get(
                    f"{base}/api/authapi/jwt",
                    headers={"Authorization": f"Basic {basic}"}) as r:
                return (await r.json())["token"]

        jwt = loop.run_until_complete(token())
        hdr = {"Authorization": f"Bearer {jwt}"}
        body = {"type": "DeviceMeasurement",
                "request": {"name": "t", "value": 1.0}}

        async def post(tok):
            async with session.post(
                    f"{base}/api/devices/{tok}/events", json=body,
                    headers=hdr) as r:
                return r.status, await r.json()

        (local_tok,) = tokens_owned_by(0, 1, prefix="seo")
        (remote_tok,) = tokens_owned_by(1, 1, prefix="seo")
        admitted_before = c0.local.qos.admitted_events
        # remote-owned: forwarded to its owner untouched by the local
        # bucket (owner has no qos configured => admitted there)
        st, _ = loop.run_until_complete(post(remote_tok))
        assert st == 201
        assert c0.local.qos.admitted_events == admitted_before
        # locally-owned: the drained local bucket sheds explicitly
        st, resp = loop.run_until_complete(post(local_tok))
        assert st == 429
        assert resp["reason"] == "rate"
        assert c0.local.qos.shed_by_tenant["default"] == 1
        # refilled bucket: the same locally-owned post lands
        clk.advance(1.0)
        st, _ = loop.run_until_complete(post(local_tok))
        assert st == 201
    finally:
        loop.run_until_complete(session.close())
        loop.run_until_complete(server.cleanup())
        loop.close()
        _close(clusters, regs, host)


def test_rest_edge_sheds_429_with_retry_after(tmp_path):
    """The REST ingest edge answers a shed with 429 + a Retry-After
    header (integer-ceiled) and a machine-readable retryAfterS body —
    for both the single-event POST and the bulk batch endpoint."""
    import asyncio
    import base64

    import aiohttp

    from sitewhere_tpu.instance.instance import (InstanceConfig,
                                                 SiteWhereTpuInstance)
    from sitewhere_tpu.web.rest import start_server

    loop = asyncio.new_event_loop()
    inst = SiteWhereTpuInstance(InstanceConfig(engine=_small_cfg()))
    inst.engine.qos = AdmissionController(
        tenant_rates={"default": 4.0}, burst_s=0.5, clock=ManualClock())
    server = loop.run_until_complete(start_server(inst))
    base = f"http://127.0.0.1:{server.port}"
    session = aiohttp.ClientSession(loop=loop)
    try:
        async def token():
            basic = base64.b64encode(b"admin:password").decode()
            async with session.get(
                    f"{base}/api/authapi/jwt",
                    headers={"Authorization": f"Basic {basic}"}) as r:
                return (await r.json())["token"]

        jwt = loop.run_until_complete(token())
        hdr = {"Authorization": f"Bearer {jwt}"}
        body = {"type": "DeviceMeasurement",
                "request": {"name": "t", "value": 1.0}}

        async def post(path, payload):
            async with session.post(base + path, json=payload,
                                    headers=hdr) as r:
                return r.status, r.headers, await r.json()

        st, _, _ = loop.run_until_complete(
            post("/api/devices/rq-0/events", body))
        assert st == 201
        st, _, _ = loop.run_until_complete(
            post("/api/devices/rq-0/events", body))
        assert st == 201    # bucket capacity 2: both initial tokens spent
        st, headers, resp = loop.run_until_complete(
            post("/api/devices/rq-0/events", body))
        assert st == 429
        assert int(headers["Retry-After"]) >= 1
        assert resp["retryAfterS"] == pytest.approx(0.25)
        assert resp["reason"] == "rate"
        # bulk endpoint: an entirely shed batch answers 429 too
        rows = [json.loads(_meas(f"rq-b{i}")) for i in range(4)]
        st, headers, resp = loop.run_until_complete(
            post("/api/events/batch", rows))
        assert st == 429 and "Retry-After" in headers
    finally:
        loop.run_until_complete(session.close())
        loop.run_until_complete(server.cleanup())
        loop.close()


@pytest.mark.slow
def test_wfq_gate_ratio_stress():
    """Heavy variant: 4 tenants, 3:2:1:1 weights, 4 threads each."""
    gate = WeightedFairGate({"sa": 3.0, "sb": 2.0, "sc": 1.0, "sd": 1.0})
    stop = threading.Event()
    start = threading.Barrier(16)

    def hammer(tenant):
        start.wait()
        while not stop.is_set():
            with gate.turn(tenant, 1):
                time.sleep(0.0002)
                if sum(gate.grants.values()) >= 7000:
                    stop.set()

    ts = [threading.Thread(target=hammer, args=(t,))
          for t in ("sa", "sb", "sc", "sd") for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    g = gate.grants
    assert 1.2 <= g["sa"] / max(1, g["sb"]) <= 1.9, g
    assert 2.2 <= g["sa"] / max(1, g["sc"]) <= 4.0, g
