"""Unit tests for the sort/segment primitives (ops/segment.py)."""

import jax.numpy as jnp
import numpy as np

from sitewhere_tpu.ops.segment import (
    compact_valid_front,
    lex_argsort,
    scatter_argmax_mask,
    segment_ranks,
)


def test_lex_argsort_stable(rng):
    a = rng.integers(0, 5, 64).astype(np.int32)
    b = rng.integers(0, 5, 64).astype(np.int32)
    keys, perm = lex_argsort([jnp.asarray(a), jnp.asarray(b)])
    perm = np.asarray(perm)
    expect = np.lexsort((np.arange(64), b, a))
    np.testing.assert_array_equal(perm, expect)
    np.testing.assert_array_equal(np.asarray(keys[0]), a[expect])


def test_segment_ranks():
    ids = jnp.asarray(np.array([0, 0, 0, 2, 2, 5], np.int32))
    start, end = segment_ranks(ids)
    np.testing.assert_array_equal(np.asarray(start), [0, 1, 2, 0, 1, 0])
    np.testing.assert_array_equal(np.asarray(end), [2, 1, 0, 1, 0, 0])


def test_segment_ranks_single_run():
    ids = jnp.zeros(8, jnp.int32)
    start, end = segment_ranks(ids)
    np.testing.assert_array_equal(np.asarray(start), np.arange(8))
    np.testing.assert_array_equal(np.asarray(end), np.arange(8)[::-1])


def test_scatter_argmax_mask(rng):
    n, b = 10, 200
    seg = rng.integers(0, n, b).astype(np.int32)
    key = rng.integers(0, 4, b).astype(np.int32)  # many ties
    valid = rng.random(b) < 0.8
    seq = np.arange(b, dtype=np.int32)
    winner = np.asarray(
        scatter_argmax_mask(jnp.asarray(seg), jnp.asarray(key), jnp.asarray(seq),
                            jnp.asarray(valid), n)
    )
    for s in range(n):
        rows = [i for i in range(b) if seg[i] == s and valid[i]]
        if not rows:
            assert not winner[seg == s].any()
            continue
        best = max(rows, key=lambda i: (key[i], seq[i]))
        chosen = np.where(winner & (seg == s))[0]
        assert list(chosen) == [best]


def test_compact_valid_front(rng):
    valid = rng.random(50) < 0.5
    vals = np.arange(50, dtype=np.int32)
    n, perm = compact_valid_front(jnp.asarray(valid))
    perm = np.asarray(perm)
    n = int(n)
    assert n == valid.sum()
    # valid rows first, in stable (original) order
    np.testing.assert_array_equal(vals[perm][:n], vals[valid])
    np.testing.assert_array_equal(vals[perm][n:], vals[~valid])
