"""SigV4 signing vectors + SQS connector against a local SQS-shaped server.

The GET vector is AWS's published Signature Version 4 example (ListUsers on
IAM, 2015-08-30) — the expected signature string comes from the public AWS
documentation, which makes the signer independently verifiable.
"""

import asyncio
import json
import urllib.parse

import pytest

from sitewhere_tpu.connectors.aws import AwsCredentials, SqsConnector, sigv4_headers
from sitewhere_tpu.core.types import EventType
from sitewhere_tpu.outbound.feed import OutboundEvent

AWS_EXAMPLE_CREDS = AwsCredentials(
    access_key="AKIDEXAMPLE",
    secret_key="wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
    region="us-east-1",
)


def test_sigv4_matches_aws_published_example():
    headers = sigv4_headers(
        AWS_EXAMPLE_CREDS, "iam", "GET",
        "https://iam.amazonaws.com/?Action=ListUsers&Version=2010-05-08",
        b"",
        headers={"Content-Type":
                 "application/x-www-form-urlencoded; charset=utf-8"},
        amz_date="20150830T123600Z",
    )
    auth = headers["Authorization"]
    assert auth.startswith(
        "AWS4-HMAC-SHA256 Credential=AKIDEXAMPLE/20150830/us-east-1/iam/"
        "aws4_request, SignedHeaders=content-type;host;x-amz-date, ")
    assert auth.endswith(
        "Signature=5d672d79c15b13162d9279b0855cfba6789a8edb4c82c400e06b5924a6f2b5d7")


def test_sigv4_query_ordering_and_body_hash():
    h1 = sigv4_headers(AWS_EXAMPLE_CREDS, "sqs", "POST",
                       "https://sqs.us-east-1.amazonaws.com/123/q?b=2&a=1",
                       b"payload", amz_date="20250101T000000Z")
    h2 = sigv4_headers(AWS_EXAMPLE_CREDS, "sqs", "POST",
                       "https://sqs.us-east-1.amazonaws.com/123/q?a=1&b=2",
                       b"payload", amz_date="20250101T000000Z")
    assert h1["Authorization"] == h2["Authorization"]  # canonical ordering
    h3 = sigv4_headers(AWS_EXAMPLE_CREDS, "sqs", "POST",
                       "https://sqs.us-east-1.amazonaws.com/123/q?a=1&b=2",
                       b"other", amz_date="20250101T000000Z")
    assert h1["Authorization"] != h3["Authorization"]  # body is signed


def test_sigv4_literal_plus_and_encoded_sort():
    # literal '+' in a query value must be signed as %2B, not collapsed to a
    # space; and pair ordering must follow the ENCODED forms
    h_plus = sigv4_headers(AWS_EXAMPLE_CREDS, "s3", "GET",
                           "https://s3.amazonaws.com/b?tok=a+b",
                           b"", amz_date="20250101T000000Z")
    h_enc = sigv4_headers(AWS_EXAMPLE_CREDS, "s3", "GET",
                          "https://s3.amazonaws.com/b?tok=a%2Bb",
                          b"", amz_date="20250101T000000Z")
    h_space = sigv4_headers(AWS_EXAMPLE_CREDS, "s3", "GET",
                            "https://s3.amazonaws.com/b?tok=a%20b",
                            b"", amz_date="20250101T000000Z")
    assert h_plus["Authorization"] == h_enc["Authorization"]
    assert h_plus["Authorization"] != h_space["Authorization"]


def test_sqs_connector_requires_credentials():
    with pytest.raises(ValueError, match="access key"):
        SqsConnector("s", "", "sk", "https://q")
    with pytest.raises(ValueError, match="secret key"):
        SqsConnector("s", "ak", "", "https://q")
    with pytest.raises(ValueError, match="queue URL"):
        SqsConnector("s", "ak", "sk", "")


def test_sqs_connector_sends_signed_request():
    from aiohttp import web

    received = []

    async def handler(request: web.Request) -> web.Response:
        received.append({
            "auth": request.headers.get("Authorization", ""),
            "body": await request.text(),
        })
        return web.Response(
            text="<SendMessageResponse><MessageId>1</MessageId>"
                 "</SendMessageResponse>")

    ev = OutboundEvent(
        event_id=7, etype=EventType.ALERT, device_token="d-9",
        device_id=0, assignment_id=0, tenant="default", area_id=0, asset_id=0,
        ts_ms=1000, received_ms=1001, measurements={},
        values=[], aux0=0, aux1=0,
    )

    async def run():
        app = web.Application()
        app.router.add_post("/123456789/events", handler)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        conn = SqsConnector(
            "sqs", "AKIDEXAMPLE", "secret",
            f"http://127.0.0.1:{port}/123456789/events")
        try:
            await conn.process_event(ev)
        finally:
            await conn.on_stop()
            await runner.cleanup()

    asyncio.run(run())
    assert len(received) == 1
    assert received[0]["auth"].startswith("AWS4-HMAC-SHA256 Credential=AKIDEXAMPLE/")
    form = dict(urllib.parse.parse_qsl(received[0]["body"]))
    assert form["Action"] == "SendMessage"
    assert json.loads(form["MessageBody"])["deviceToken"] == "d-9"
