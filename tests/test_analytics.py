"""Fleet-scale historical analytics (ISSUE 19): the archive->device
batched scoring pipeline.

Parity discipline: the job's streamed/planned/trimmed/batch-filled
windows must score IDENTICALLY (bit-for-bit, same jitted program) to a
host numpy oracle that rebuilds each device's window from the raw
archive rows with per-device Python loops — over compressed and
uncompressed segments, gap-registered partitions, underfilled windows
and time-range clips. Emission mirrors the PR-12 rule-alert replay
discipline: dedup keys are the durable registry, so kill/recover and
standby promotion emit exactly the score alerts the dead owner never
shipped. The analytics-windows conservation equation is falsifiable.
"""

import json
from types import SimpleNamespace

import numpy as np
import pytest

from sitewhere_tpu.core.types import EventType
from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.models.analytics import (SCORE_KEY_PREFIX,
                                            AnalyticsJobSpec,
                                            AnalyticsManager)

# one jit-shape family for the whole module (W, C, M shared by every
# test -> the fill + score programs compile once per pytest process)
W, C, M = 8, 4, 8
MIN_FILL = 4

CFG = dict(device_capacity=64, token_capacity=256,
           assignment_capacity=128, store_capacity=64,
           batch_capacity=16, channels=C, archive_segment_rows=16)


def _engine(tmp_path, name="arch", **kw):
    cfg = dict(CFG, archive_dir=str(tmp_path / name), **kw)
    return Engine(EngineConfig(**cfg))


def _meas(eng, tok, ts_rel, vals):
    return json.dumps({
        "deviceToken": tok, "type": "DeviceMeasurements",
        "request": {"measurements": vals,
                    "eventDate": int(eng.epoch.base_unix_s * 1000)
                    + ts_rel}}).encode()


def _spy_ingest(eng):
    """Wrap ingest_json_batch, collecting every decoded envelope — the
    emission-capture idiom of tests/test_rules_replay.py's replica feed."""
    sent = []
    orig = eng.ingest_json_batch

    def spy(payloads, tenant="default", **kw):
        sent.extend(json.loads(p) for p in payloads)
        return orig(payloads, tenant, **kw)

    eng.ingest_json_batch = spy
    return sent


# --------------------------------------------------------------- oracle
def _fleet_rows(tid, tid_other, ids, oid):
    """Deterministic row set: 6 scoreable devices with overlapping time
    ranges, ids[4] underfilled below MIN_FILL, ids[5] underfilled but
    scoreable; plus decoy rows the job must drop (invalid, wrong etype,
    other-tenant device)."""
    rng = np.random.default_rng(11)
    rows = []
    counts = [16, 16, 12, 10, 3, 5]
    for d, n in enumerate(counts):
        for i in range(n):
            vmask = np.array([(i + d + k) % 4 != 0 for k in range(C)],
                             bool)
            if not vmask.any():
                vmask[0] = True
            rows.append(dict(
                etype=0, device=ids[d], tenant=tid,
                ts=1000 + i * 50 + d * 7,
                values=rng.standard_normal(C).astype(np.float32),
                vmask=vmask, valid=True))
    # decoys: invalid row, alert-typed row, other-tenant device
    rows.append(dict(etype=0, device=ids[0], tenant=tid, ts=5000,
                     values=np.ones(C, np.float32),
                     vmask=np.ones(C, bool), valid=False))
    rows.append(dict(etype=int(EventType.ALERT), device=ids[1],
                     tenant=tid, ts=5001, values=np.ones(C, np.float32),
                     vmask=np.ones(C, bool), valid=True))
    for i in range(6):
        rows.append(dict(etype=0, device=oid, tenant=tid_other,
                         ts=1000 + i * 50, values=np.ones(C, np.float32),
                         vmask=np.ones(C, bool), valid=True))
    rng.shuffle(rows)
    return rows, counts


def _append(arch, part, start, rows):
    """One handmade segment from row dicts (ring-slice shape)."""
    n = len(rows)
    sl = SimpleNamespace(
        etype=np.array([r["etype"] for r in rows], np.int64),
        device=np.array([r["device"] for r in rows], np.int64),
        assignment=np.full(n, part, np.int64),
        tenant=np.array([r["tenant"] for r in rows], np.int64),
        area=np.full(n, -1, np.int64),
        customer=np.full(n, -1, np.int64),
        asset=np.full(n, -1, np.int64),
        ts_ms=np.array([r["ts"] for r in rows], np.int64),
        received_ms=np.array([r["ts"] for r in rows], np.int64),
        values=np.stack([r["values"] for r in rows]),
        vmask=np.stack([r["vmask"] for r in rows]),
        aux=np.zeros((n, 2), np.int64),
        valid=np.array([r["valid"] for r in rows], bool))
    arch.append_segment(part, start, sl)


def _mk_handmade(tmp_path, compress):
    """Engine + handmade archive: part 0 starts at a REGISTERED GAP
    (migration padding, positions 0..16 never held data), part 1 at 0."""
    from sitewhere_tpu.utils.archive import EventArchive

    eng = Engine(EngineConfig(**CFG))
    ids = [eng.register_device(f"an-{d}") for d in range(6)]
    assert all(i is not None for i in ids)
    oid = eng.register_device("tz-0", tenant="t2")
    tid = eng.tenants.lookup("default")
    tid_other = eng.tenants.lookup("t2")
    assert tid >= 0 and tid_other >= 0 and tid != tid_other
    rows, counts = _fleet_rows(tid, tid_other, ids, oid)
    arch = EventArchive(tmp_path / ("c" if compress else "u"),
                        segment_rows=16, compress=compress)
    arch.register_gap(0, 0, 16)
    cuts = [0, 16, 32, 48, len(rows)]
    pos = {}
    starts = [16, 32, 48, 0]
    parts = [0, 0, 0, 1]
    for k in range(4):
        seg_rows = rows[cuts[k]:cuts[k + 1]]
        _append(arch, parts[k], starts[k], seg_rows)
        for j, r in enumerate(seg_rows):
            pos[id(r)] = (parts[k], starts[k] + j)
    eng.archive = arch
    for r in rows:
        r["pos"] = pos[id(r)]
    return eng, rows, counts, tid


def _oracle(mgr, eng, rows, tid, *, until_ms=None, threshold=None):
    """Per-device window rebuild with plain Python loops + the SAME
    jitted scorer, devices in id order padded to M — bit-identical input
    to the job's single batch, so scores must match exactly."""
    import jax.numpy as jnp

    by_dev = {}
    for r in rows:
        if not r["valid"] or r["etype"] != 0 or r["tenant"] != tid:
            continue
        if until_ms is not None and r["ts"] > until_ms:
            continue
        by_dev.setdefault(r["device"], []).append(r)
    devs = sorted(by_dev)
    data = np.zeros((M, W, C), np.float32)
    filled = np.zeros(M, np.int32)
    ends = {}
    for k, d in enumerate(devs):
        evs = sorted(by_dev[d], key=lambda r: (r["ts"], r["pos"]))
        ends[d] = evs[-1]["ts"]
        filled[k] = min(len(evs), W)
        for j, r in enumerate(evs[-W:]):
            data[k, W - min(len(evs), W) + j] = \
                np.where(r["vmask"], r["values"], 0.0)
    model, params, score_fn = mgr._model_bundle(W, C)
    scores, valid, _ = score_fn(model, params, jnp.asarray(data),
                                jnp.asarray(filled), jnp.int32(MIN_FILL))
    scores = np.asarray(scores)[:len(devs)]
    valid = np.asarray(valid)[:len(devs)]
    out = {}
    for k, d in enumerate(devs):
        tok = eng.devices[d].token
        out[d] = dict(token=tok, end=ends[d], score=float(scores[k]),
                      valid=bool(valid[k]))
    return out


@pytest.mark.parametrize("compress", [False, True])
def test_job_scores_match_host_oracle(tmp_path, compress):
    eng, rows, counts, tid = _mk_handmade(tmp_path, compress)
    mgr = AnalyticsManager(eng)
    oracle = _oracle(mgr, eng, rows, tid)
    valid_scores = sorted(o["score"] for o in oracle.values()
                          if o["valid"])
    thr = valid_scores[len(valid_scores) // 2]   # splits the fleet
    sent = _spy_ingest(eng)
    job = mgr.run_job(AnalyticsJobSpec(
        window=W, batch_devices=M, min_fill=MIN_FILL, threshold=thr,
        name="par"))
    assert job["state"] == "done" and job["error"] is None
    assert job["devices"] == 6
    assert job["planned"] == 6
    assert job["scored"] == sum(v["valid"] for v in oracle.values()) == 5
    assert job["skipped_underfilled"] == 1       # device 4: 3 < MIN_FILL
    # emitted alert set == oracle's strict threshold crossings, and the
    # .3f-formatted score in each message matches the oracle bit-for-bit
    want = {f"{SCORE_KEY_PREFIX}par:{o['token']}:{o['end']}":
            f"{o['score']:.3f}"
            for o in oracle.values() if o["valid"] and o["score"] > thr}
    got = {e["request"]["alternateId"]:
           e["request"]["message"].split()[3]
           for e in sent if e["type"] == "DeviceAlert"}
    assert want and got == want
    assert job["emitted"] == len(want) and job["suppressed"] == 0
    st = mgr.ledger_stage()
    assert st["planned"] == st["scored"] + st["skipped_underfilled"] \
        + st["cancelled"]


@pytest.mark.parametrize("compress", [False, True])
def test_time_range_clip_matches_oracle(tmp_path, compress):
    """until_ms clips each device's window mid-history: window ends,
    fill counts and the underfilled set all shift — and must match the
    oracle's clipped rebuild."""
    eng, rows, counts, tid = _mk_handmade(tmp_path, compress)
    mgr = AnalyticsManager(eng)
    cut = 1000 + 6 * 50                          # keeps ~7 rows/device
    oracle = _oracle(mgr, eng, rows, tid, until_ms=cut)
    sent = _spy_ingest(eng)
    job = mgr.run_job(AnalyticsJobSpec(
        window=W, batch_devices=M, min_fill=MIN_FILL, threshold=-1e9,
        until_ms=cut, name="rng"))
    assert job["state"] == "done"
    assert job["devices"] == len(oracle)
    want = {f"{SCORE_KEY_PREFIX}rng:{o['token']}:{o['end']}"
            for o in oracle.values() if o["valid"]}
    got = {e["request"]["alternateId"] for e in sent
           if e["type"] == "DeviceAlert"}
    assert got == want
    assert job["scored"] == sum(o["valid"] for o in oracle.values())
    assert job["skipped_underfilled"] == \
        sum(not o["valid"] for o in oracle.values())


def test_compressed_segments_byte_parity(tmp_path):
    """The codec round-trips bit-for-bit: a compressed archive's pushdown
    query equals the UNCOMPRESSED archive's frozen full-scan oracle
    (query_unpruned, untouched) field by field; compressed files hold
    packed members, cost less on disk, and decode into the cache at
    resident size."""
    from sitewhere_tpu.utils.archive import EventArchive

    eng = Engine(EngineConfig(**CFG))
    ids = [eng.register_device(f"an-{d}") for d in range(6)]
    oid = eng.register_device("tz-0", tenant="t2")
    tid = eng.tenants.lookup("default")
    rows, _ = _fleet_rows(tid, eng.tenants.lookup("t2"), ids, oid)
    archs = {}
    for compress in (False, True):
        a = EventArchive(tmp_path / ("bc" if compress else "bu"),
                         segment_rows=16, compress=compress)
        for k, lo in enumerate(range(0, len(rows), 16)):
            _append(a, 0, lo, rows[lo:lo + 16])
        archs[compress] = a
    total_u, rows_u = archs[False].query_unpruned(etype=0, tenant=tid,
                                                  limit=1000)
    total_c, rows_c = archs[True].query(etype=0, tenant=tid, limit=1000)
    assert total_c == total_u and len(rows_c) == len(rows_u) > 0
    for ru, rc in zip(rows_u, rows_c):
        assert ru.keys() == rc.keys()
        for k in ru:
            assert np.array_equal(np.asarray(ru[k]), np.asarray(rc[k])), k
    # on-disk members are packed and smaller; planner cost charges both
    for seg in archs[True].segments:
        with np.load(archs[True].dir / seg.path) as z:
            assert "valid__packed" in z.files and "valid" not in z.files
        assert 0 < seg.stats["enc_bytes"] < seg.stats["bytes"]
    # decoded columns land in the cache at RESIDENT (decoded) size
    arch = archs[True]
    seg = arch.segments[0]
    cols = arch._cols_or_drop(seg, ("valid", "values", "vmask"))
    decoded = sum(np.asarray(v).nbytes for v in cols.values())
    assert decoded > 0 and arch.cache.nbytes >= decoded


def test_engine_spool_job_rerun_suppresses_and_cancel_accounts(tmp_path):
    """End to end through the real ring->spool path (compressed): a
    re-run of the same job name emits nothing new, and a scope-limited
    run (max_batches) keeps the conservation equation exact."""
    eng = _engine(tmp_path, archive_compress=True)
    rng = np.random.default_rng(7)
    payloads = []
    for i in range(4 * CFG["store_capacity"]):
        payloads.append(_meas(eng, f"d-{i % 6}", 1000 + i,
                              {"c0": float(rng.standard_normal()),
                               "c1": float(rng.standard_normal())}))
    for lo in range(0, len(payloads), 16):
        eng.ingest_json_batch(payloads[lo:lo + 16])
    eng.flush()
    assert eng.archive.total_rows() > 0
    mgr = AnalyticsManager(eng)
    spec = AnalyticsJobSpec(window=W, batch_devices=M, min_fill=MIN_FILL,
                            threshold=-1e9, name="e2e")
    job = mgr.run_job(spec)
    assert job["state"] == "done" and job["devices"] == 6
    assert job["emitted"] == job["scored"] > 0
    eng.flush()
    q = eng.query_events(etype=EventType.ALERT, limit=200)
    assert q["total"] == job["emitted"]
    # recover sim: fresh manager on the same engine — interner resync
    # re-registers every shipped key, the re-run suppresses all of them
    mgr2 = AnalyticsManager(eng)
    job2 = mgr2.run_job(spec)
    assert job2["emitted"] == 0
    assert job2["suppressed"] == job["emitted"]
    # scope-limited run (max_batches): a completed partial job — only
    # the in-scope batch is planned, nothing lands in the cancelled sink
    job3 = mgr2.run_job(AnalyticsJobSpec(
        window=W, batch_devices=4, min_fill=MIN_FILL, threshold=-1e9,
        name="e2e-b", max_batches=1, emit=False))
    assert job3["state"] == "done"
    assert job3["planned"] == 4 and job3["cancelled"] == 0
    st = mgr2.ledger_stage()
    assert st["planned"] == st["scored"] + st["skipped_underfilled"] \
        + st["cancelled"]


def test_cancel_mid_run_lands_in_cancelled_sink(tmp_path):
    """A cancel landing between device batches routes every
    planned-but-unscored window into the cancelled sink — the equation
    stays exact for a job that died mid-pass (the killed-owner shape)."""
    eng = _engine(tmp_path, name="cx-arch")
    _prime_12_devices(eng)
    mgr = AnalyticsManager(eng)
    orig_emit = mgr._emit_batch

    def emit_then_cancel(job, *a, **kw):
        out = orig_emit(job, *a, **kw)
        job["cancel"].set()            # first harvest pulls the plug
        return out

    mgr._emit_batch = emit_then_cancel
    job = mgr.run_job(AnalyticsJobSpec(
        window=W, batch_devices=4, min_fill=MIN_FILL, threshold=-1e9,
        name="cx"))
    assert job["state"] == "cancelled"
    # 12 devices / m=4: batches 0+1 were in flight when the cancel hit,
    # batch 2 never ran — its 4 windows land in the cancelled sink
    assert job["planned"] == 12
    assert job["cancelled"] == 4
    assert job["scored"] + job["skipped_underfilled"] == 8
    st = mgr.ledger_stage()
    assert st["planned"] == st["scored"] + st["skipped_underfilled"] \
        + st["cancelled"]
    assert st["jobs_cancelled"] == 1


def test_conservation_equation_is_falsifiable(tmp_path):
    """The analytics-windows equation audits clean on a live engine and
    trips on a one-off perturbation of any term (the ISSUE 14
    falsifiability discipline)."""
    from sitewhere_tpu.utils.conservation import (build_ledger,
                                                  check_conservation)

    eng = _engine(tmp_path, name="fb-arch")
    _prime_12_devices(eng)
    mgr = AnalyticsManager(eng)
    mgr.run_job(AnalyticsJobSpec(window=W, batch_devices=M,
                                 min_fill=MIN_FILL, threshold=-1e9,
                                 name="fb", emit=False))
    eng.flush()
    base = build_ledger(eng)
    assert base["stages"]["analytics"]["planned"] == 12
    assert not check_conservation(base)

    def perturbed(key):
        led = json.loads(json.dumps(base))
        led["stages"]["analytics"][key] += 1
        return [v.equation for v in check_conservation(led)]

    for key in ("planned", "scored", "skipped_underfilled", "cancelled"):
        assert "analytics-windows" in perturbed(key), key


def _prime_12_devices(eng, n_each=10):
    rng = np.random.default_rng(3)
    for i in range(12 * n_each):
        eng.ingest_json_batch([_meas(
            eng, f"kr-{i % 12}", 1000 + i,
            {"c0": float(rng.standard_normal()),
             "c1": float(rng.standard_normal())})])
    eng.flush()


def test_kill_recover_emits_exactly_unshipped(tmp_path):
    """The chaos slice: the owner scores one device batch (8 of 12
    devices), ships those alerts, dies; snapshot + WAL replay rebuilds
    the engine over the SAME archive, a fresh manager re-runs the same
    job name — and emits exactly the 4 device windows the dead owner
    never shipped. Zero lost, zero duplicate, each alert in the store
    exactly once."""
    from sitewhere_tpu.utils.checkpoint import (replay_wal_into,
                                                restore_engine,
                                                save_engine)

    eng = _engine(tmp_path, name="kr-arch",
                  wal_dir=str(tmp_path / "wal"))
    save_engine(eng, tmp_path / "snap")
    _prime_12_devices(eng)
    mgr = AnalyticsManager(eng)
    # a BOUNDED range pins each device's window identity: the job's own
    # alert ingest advances the ring and spools more measurement rows,
    # so an open-ended re-run would legitimately see newer window ends
    spec = dict(window=W, batch_devices=M, min_fill=MIN_FILL,
                threshold=-1e9, until_ms=1103, name="kr")
    pre_sent = _spy_ingest(eng)
    job = mgr.run_job(AnalyticsJobSpec(**spec, max_batches=1))
    assert job["devices"] == 12 and job["planned"] == 8
    pre = {e["request"]["alternateId"] for e in pre_sent
           if e["type"] == "DeviceAlert"}
    assert len(pre) == job["emitted"] > 0
    eng.flush()
    eng.wal.sync()
    eng.wal.close()                    # "SIGKILL"
    del eng

    r2 = restore_engine(tmp_path / "snap")
    replay_wal_into(r2, 0, tmp_path / "wal")
    m2 = AnalyticsManager(r2)
    post_sent = _spy_ingest(r2)
    job2 = m2.run_job(AnalyticsJobSpec(**spec))
    post = {e["request"]["alternateId"] for e in post_sent
            if e["type"] == "DeviceAlert"}
    assert job2["state"] == "done" and job2["planned"] == 12
    assert post and not (pre & post), "duplicate score alert"
    assert job2["suppressed"] == len(pre)
    assert len(pre | post) == job2["scored"]
    r2.flush()
    q = r2.query_events(etype=EventType.ALERT, limit=200)
    assert q["total"] == len(pre | post)


def test_standby_promotion_emits_only_the_tail(tmp_path):
    """A standby receives the owner's full stream (score alerts
    included, replica-feed style) with emission OFF; promotion resyncs
    the shipped keys and the next run emits exactly the unshipped
    complement."""
    owner = _engine(tmp_path, name="own-arch")
    standby = _engine(tmp_path, name="sby-arch")
    standby.epoch = owner.epoch
    omgr = AnalyticsManager(owner)
    smgr = AnalyticsManager(standby, active=False)
    orig = owner.ingest_json_batch

    def forwarding(payloads, tenant="default", **kw):
        res = orig(payloads, tenant, **kw)
        standby.ingest_json_batch(list(payloads), tenant)
        return res

    owner.ingest_json_batch = forwarding
    _prime_12_devices(owner)
    standby.flush()
    spec = dict(window=W, batch_devices=M, min_fill=MIN_FILL,
                threshold=-1e9, until_ms=1103, name="sp")
    pre_sent = _spy_ingest(owner)
    job = omgr.run_job(AnalyticsJobSpec(**spec, max_batches=1))
    pre = {e["request"]["alternateId"] for e in pre_sent
           if e["type"] == "DeviceAlert"}
    assert len(pre) == job["emitted"] > 0
    standby.flush()
    # a passive (standby) run scores but ships nothing
    passive = smgr.run_job(AnalyticsJobSpec(
        window=W, batch_devices=M, min_fill=MIN_FILL, threshold=-1e9,
        name="sp-passive"))
    assert passive["scored"] > 0 and passive["emitted"] == 0
    # owner dies; promotion enables emission (the passive run's own
    # resync already registered the replayed keys — promote's
    # incremental rescan finds nothing new) and the next run emits only
    # the unshipped tail
    assert smgr.promote() == 0 and smgr.active
    post_sent = _spy_ingest(standby)
    job2 = smgr.run_job(AnalyticsJobSpec(**spec))
    post = {e["request"]["alternateId"] for e in post_sent
            if e["type"] == "DeviceAlert"}
    assert post and not (pre & post)
    assert job2["suppressed"] == len(pre)
    assert len(pre | post) == job2["scored"] == 12


# ----------------------------------------------------- rollup spill tier
def _rollup_engine(tmp_path, compress=True):
    from sitewhere_tpu.rules import RulesManager

    cfg = dict(device_capacity=256, token_capacity=512,
               assignment_capacity=512, store_capacity=4096,
               batch_capacity=32, channels=4, rule_groups=64,
               rollup_buckets=8, archive_dir=str(tmp_path / "ra"),
               archive_segment_rows=16, archive_compress=compress)
    eng = Engine(EngineConfig(**cfg))
    mgr = RulesManager(eng)
    mgr.load({"name": "t", "rules": [],
              "rollups": [{"name": "temp-1s", "channel": "temp",
                           "windowMs": 1000, "scope": "device"}]})
    base = int(eng.epoch.base_unix_s * 1000)
    payloads = [json.dumps({
        "deviceToken": f"r-{i % 4}", "type": "DeviceMeasurement",
        "request": {"name": "temp", "value": 10.0 + (i % 7) * 0.5,
                    "eventDate": base + i * 250}}).encode()
        for i in range(96)]
    for lo in range(0, 96, 32):
        eng.ingest_json_batch(payloads[lo:lo + 32])
        eng.flush()
    return eng, mgr


def test_rollup_spill_history_parity_and_idempotence(tmp_path):
    """Closed rollup windows spill through the archive (compressed
    segments under the rollups/ subdir): the spilled history reads back
    exactly the closed live windows, a respill is a no-op, and a FRESH
    manager over the same archive recovers the watermark from the
    segment zone maps — restart-safe, no double spill."""
    eng, mgr = _rollup_engine(tmp_path)
    live = mgr.read_rollup("temp-1s", limit=1000)
    live_map = {(b["group"], b["windowStartMs"]):
                (b["count"], b["sum"], b["min"], b["max"])
                for b in live["buckets"]}
    newest = max(ws for _, ws in live_map)
    out = mgr.spill_rollups(lag=1)
    assert out["spilled"] > 0 and out["rollups"] == 1
    assert mgr.spill_rollups(lag=1)["spilled"] == 0   # idempotent
    hist = mgr.read_rollup_history("temp-1s", limit=1000)
    hist_map = {(b["group"], b["windowStartMs"]):
                (b["count"], b["sum"], b["min"], b["max"])
                for b in hist["buckets"]}
    closed = {k: v for k, v in live_map.items() if k[1] <= newest - 1000}
    assert hist_map == closed and closed
    one = mgr.read_rollup_history("temp-1s", group="r-1", limit=1000)
    assert one["buckets"] and all(b["group"] == "r-1"
                                  for b in one["buckets"])
    # rollup segments live under rollups/ and inherit compression —
    # invisible to the MAIN archive's non-recursive recovery glob
    ra = mgr.rollup_archive()
    assert ra.dir.name == "rollups" and ra.total_rows() == out["spilled"]
    for seg in ra.segments:
        assert seg.stats["enc_bytes"] < seg.stats["bytes"]
    assert eng.archive.total_rows() >= 0
    assert not any("rollups" in s.path for s in eng.archive.segments)
    # restart: a fresh manager recovers the spill watermark from disk
    from sitewhere_tpu.rules import RulesManager

    m2 = RulesManager(eng)
    m2.load({"name": "t", "rules": [],
             "rollups": [{"name": "temp-1s", "channel": "temp",
                          "windowMs": 1000, "scope": "device"}]})
    assert m2.spill_rollups(lag=1)["spilled"] == 0


# ------------------------------------------------------ loadgen markers
def test_loadgen_analytics_markers_deterministic_and_resolved(tmp_path):
    from sitewhere_tpu.loadgen import (OpenLoopSpec, TenantLoad,
                                       build_open_loop_schedule,
                                       run_open_loop,
                                       schedule_fingerprint)

    tl = TenantLoad(tenant="default", rate_eps=400, n_devices=4,
                    analytics_every=4)
    spec = OpenLoopSpec(duration_s=0.4, tenants=(tl,), seed=7,
                        frame_size=16)
    s1, s2 = (build_open_loop_schedule(spec) for _ in range(2))
    assert schedule_fingerprint(s1) == schedule_fingerprint(s2)
    marks = [op for op in s1 if op.kind == "analytics"]
    assert marks and all(op.analytics["emit"] is False for op in marks)
    # knob off -> no markers (pre-knob schedules replay unchanged)
    s0 = build_open_loop_schedule(OpenLoopSpec(
        duration_s=0.4, tenants=(TenantLoad(tenant="default",
                                            rate_eps=400, n_devices=4),),
        seed=7, frame_size=16))
    assert all(op.kind != "analytics" for op in s0)
    # the driver resolves markers against engine.analytics_jobs; a plain
    # engine (no archive) skips them silently
    eng = _engine(tmp_path)
    AnalyticsManager(eng)
    res = run_open_loop(eng, s1, time_scale=0.01)
    assert res.scoring_jobs == len(marks)
    assert res.scoring_p50_ms is not None
    assert res.to_dict()["scoring_p99_ms"] is not None
    plain = Engine(EngineConfig(**CFG))
    res0 = run_open_loop(plain, s1, time_scale=0.01)
    assert res0.scoring_jobs == 0 and res0.scoring_p50_ms is None


def test_manager_status_and_cancel_surface(tmp_path):
    eng, rows, counts, tid = _mk_handmade(tmp_path, False)
    mgr = AnalyticsManager(eng)
    job = mgr.run_job(AnalyticsJobSpec(window=W, batch_devices=M,
                                       min_fill=MIN_FILL, emit=False,
                                       name="st"))
    st = mgr.status()
    assert st["active"] and st["jobs_started"] == 1
    row = mgr.status(job["id"])
    assert row["state"] == "done" and row["spec"]["name"] == "st"
    assert not mgr.cancel(job["id"])          # finished: not cancellable
    with pytest.raises(KeyError):
        mgr.status("aj-404")
    # unknown tenant -> empty done job, nothing planned
    empty = mgr.run_job(AnalyticsJobSpec(tenant="ghost", window=W,
                                         batch_devices=M, name="g"))
    assert empty["state"] == "done" and empty["devices"] == 0
