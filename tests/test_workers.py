"""Multi-worker host ingest: N decode processes feeding one engine.

VERDICT r2 item 4 / SURVEY §2.9 "multiple host ingest workers feeding a
fixed chip mesh". The decode runs in worker processes against worker-local
interners; the engine translates dictionary ids with numpy gathers and the
results must be indistinguishable from single-process ingest.
"""

import json

import numpy as np
import pytest

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.ingest.fast_decode import native_available

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native library unavailable")


def mini_engine(**kw) -> Engine:
    cfg = dict(device_capacity=256, token_capacity=512,
               assignment_capacity=512, store_capacity=4096,
               batch_capacity=64, channels=4)
    cfg.update(kw)
    return Engine(EngineConfig(**cfg))


def meas(eng, token, name, value, ts_rel):
    base = int(eng.epoch.base_unix_s * 1000)
    return json.dumps({
        "deviceToken": token, "type": "DeviceMeasurements",
        "request": {"measurements": {name: value},
                    "eventDate": base + ts_rel}}).encode()


def alert(token, atype, level):
    return json.dumps({
        "deviceToken": token, "type": "DeviceAlert",
        "request": {"type": atype, "level": level, "message": "x"}}).encode()


def test_pool_matches_single_process_ingest():
    from sitewhere_tpu.ingest.workers import DecodeWorkerPool

    eng_pool = mini_engine()
    eng_ref = mini_engine()
    eng_ref.epoch = eng_pool.epoch

    batches = [
        [meas(eng_pool, f"wk-{i % 8}", "temp", float(i), 1000 + i)
         for i in range(b * 16, b * 16 + 16)]
        for b in range(6)
    ]
    with DecodeWorkerPool(eng_pool, n_workers=2, max_msgs=64) as pool:
        for b in batches:
            pool.submit(b)
        pool.flush()
        assert pool.stats()["n_workers"] == 2
        assert pool.stats()["fallback_batches"] == 0
    eng_pool.flush()

    for b in batches:
        eng_ref.ingest_json_batch(b)
    eng_ref.flush()

    mp_, mr = eng_pool.metrics(), eng_ref.metrics()
    for k in ("found", "missed", "registered", "persisted"):
        assert mp_[k] == mr[k], (k, mp_, mr)
    for tok in {f"wk-{i}" for i in range(8)}:
        sp = eng_pool.get_device_state(tok)
        sr = eng_ref.get_device_state(tok)
        assert sp["measurements"]["temp"]["value"] == \
            sr["measurements"]["temp"]["value"]
        assert sp["event_counts"] == sr["event_counts"]


def test_pool_translates_names_and_alert_types():
    """Workers intern names/alert-types in a DIFFERENT order than the
    engine; lane permutation + alert-id translation must reconcile."""
    from sitewhere_tpu.ingest.workers import DecodeWorkerPool

    eng = mini_engine()
    # engine already knows some names in its own order
    eng.ingest_json_batch([meas(eng, "seed", "pressure", 1.0, 10),
                           meas(eng, "seed", "temp", 2.0, 11)])
    eng.flush()
    with DecodeWorkerPool(eng, n_workers=1, max_msgs=64) as pool:
        # worker sees temp FIRST (different local name order)
        pool.submit([meas(eng, "wn-1", "temp", 21.5, 100),
                     meas(eng, "wn-1", "pressure", 3.5, 101),
                     alert("wn-1", "overheat", 2)])
        pool.flush()
    eng.flush()
    st = eng.get_device_state("wn-1")
    assert st["measurements"]["temp"]["value"] == 21.5
    assert st["measurements"]["pressure"]["value"] == 3.5
    res = eng.query_events(device_token="wn-1",
                           etype=__import__("sitewhere_tpu.core.types",
                                            fromlist=["EventType"]).EventType.ALERT)
    assert res["total"] == 1
    assert res["events"][0]["alertType"] == "overheat"


def test_pool_registration_envelopes_flow_through():
    from sitewhere_tpu.ingest.workers import DecodeWorkerPool

    eng = mini_engine()
    reg = json.dumps({
        "deviceToken": "wr-1", "type": "RegisterDevice",
        "request": {"deviceTypeToken": "sensor",
                    "metadata": {"k": "v"}}}).encode()
    with DecodeWorkerPool(eng, n_workers=1, max_msgs=64) as pool:
        pool.submit([reg, meas(eng, "wr-1", "temp", 5.0, 50)])
        pool.flush()
    eng.flush()
    info = eng.get_device("wr-1")
    assert info is not None and info.device_type == "sensor"
    assert eng.get_device_state("wr-1")["measurements"]["temp"]["value"] == 5.0


def test_pool_wal_durability(tmp_path):
    """Batches ingested through the pool must be WAL-logged like the
    single-process path (crash recovery replays them)."""
    from sitewhere_tpu.ingest.workers import DecodeWorkerPool
    from sitewhere_tpu.utils.checkpoint import recover_engine

    eng = mini_engine(wal_dir=str(tmp_path / "wal"))
    eng.save = None  # unused
    with DecodeWorkerPool(eng, n_workers=1, max_msgs=64) as pool:
        pool.submit([meas(eng, "wd-1", "temp", 9.0, 500)])
        pool.flush()
    eng.flush()
    from sitewhere_tpu.utils.checkpoint import save_engine

    save_dir = tmp_path / "snap"
    # snapshot BEFORE more traffic; then one more pooled batch hits only WAL
    save_engine(eng, save_dir)
    with DecodeWorkerPool(eng, n_workers=1, max_msgs=64) as pool:
        pool.submit([meas(eng, "wd-1", "temp", 11.0, 600)])
        pool.flush()
    eng.flush()
    eng.wal.close()
    rec = recover_engine(save_dir, tmp_path / "wal")
    assert rec.get_device_state("wd-1")["measurements"]["temp"]["value"] == 11.0


def test_pool_lane_scatter_is_exact_with_shifted_lanes():
    """Review r3 repro: engine pre-interns names so a worker's first name
    maps to a DIFFERENT engine lane; the scatter must not let unmapped
    worker lanes clobber mapped engine lanes."""
    from sitewhere_tpu.ingest.workers import DecodeWorkerPool

    eng = mini_engine()
    # engine occupies lanes 0..2 through its own ingest path
    eng.ingest_json_batch([
        meas(eng, "seed", "n0", 1.0, 1), meas(eng, "seed", "n1", 1.0, 2),
        meas(eng, "seed", "n2", 1.0, 3)])
    eng.flush()
    with DecodeWorkerPool(eng, n_workers=1, max_msgs=64) as pool:
        # worker's first-ever name -> worker lane 0, engine lane 3
        pool.submit([meas(eng, "ls-1", "fresh", 7.5, 100)])
        pool.flush()
        assert pool.stats()["fallback_batches"] == 0
        assert pool.stats()["lane_conflicts"] == 0
    eng.flush()
    st = eng.get_device_state("ls-1")
    assert st["measurements"]["fresh"]["value"] == 7.5


def test_pool_location_rows_survive_shifted_lanes():
    """Advisor r3 (high): the lane permutation comes from measurement names
    only, but LOCATION rows carry lat/lon/elev in fixed lanes 0-2 — a
    shifted lane map must not scramble or drop coordinates."""
    from sitewhere_tpu.ingest.workers import DecodeWorkerPool

    eng = mini_engine()
    # engine pre-interns 3 names so the worker's first name lands on a
    # different engine lane (non-identity permutation)
    eng.ingest_json_batch([
        meas(eng, "seed", "n0", 1.0, 1), meas(eng, "seed", "n1", 1.0, 2),
        meas(eng, "seed", "n2", 1.0, 3)])
    eng.flush()
    base = int(eng.epoch.base_unix_s * 1000)
    loc = json.dumps({
        "deviceToken": "lg-1", "type": "DeviceLocation",
        "request": {"latitude": 42.25, "longitude": -71.5,
                    "elevation": 12.5, "eventDate": base + 100}}).encode()
    with DecodeWorkerPool(eng, n_workers=1, max_msgs=64) as pool:
        # force a non-identity lane map, then a location through it
        pool.submit([meas(eng, "lg-1", "fresh", 7.5, 99), loc])
        pool.flush()
        assert pool.stats()["fallback_batches"] == 0
    eng.flush()
    st = eng.get_device_state("lg-1")
    assert st["measurements"]["fresh"]["value"] == 7.5
    (rec,) = st["recent_locations"]
    assert rec["latitude"] == 42.25
    assert rec["longitude"] == -71.5
    assert rec["elevation"] == 12.5


def test_pool_falls_back_on_lane_conflict():
    """With more names than channels the worker's lane permutation can
    become ambiguous; the pool must detect it and fall back to exact
    engine-side decode rather than silently mis-lane values."""
    from sitewhere_tpu.ingest.workers import DecodeWorkerPool

    eng = mini_engine(channels=3)
    with DecodeWorkerPool(eng, n_workers=1, max_msgs=64) as pool:
        # engine interns "b" first, so worker name "c" (worker lane 2)
        # maps to engine lane 0 which belongs to worker lane 1 ("b") —
        # a non-injective lane map the pool must refuse to scatter through
        eng.ingest_json_batch([meas(eng, "seed", "b", 1.0, 1)])
        eng.flush()
        for i, name in enumerate(["a", "b", "c", "d"]):
            pool.submit([meas(eng, "lc-1", name, float(i), 10 + i)])
        pool.flush()
        stats = pool.stats()
    eng.flush()
    assert stats["lane_conflicts"] == 1
    assert stats["fallback_batches"] >= 1
    # the fallback path (engine-side decode) kept every event
    m = eng.metrics()
    assert m["persisted"] >= 5
    # ...and the degradation is VISIBLE in the engine metrics (VERDICT r3
    # weak #1), which is what /api/instance/metrics serves
    assert m["worker_fallback_batches"] == stats["fallback_batches"]


def test_pool_translates_alternate_ids():
    """Worker-local event-id interner ids translate to engine ids like
    tokens/alert types do: alternate-id queries resolve rows staged
    through the shared-memory pool."""
    from sitewhere_tpu.ingest.workers import DecodeWorkerPool

    eng = mini_engine()
    base = int(eng.epoch.base_unix_s * 1000)
    payloads = [json.dumps({
        "deviceToken": f"wp-{i}", "type": "DeviceMeasurements",
        "request": {"measurements": {"t": 1.0}, "alternateId": f"alt-w{i}",
                    "eventDate": base + i}}).encode() for i in range(8)]
    with DecodeWorkerPool(eng, n_workers=2, max_msgs=64) as pool:
        pool.submit(payloads)
        pool.flush()
    eng.flush()
    res = eng.query_events(alternate_id="alt-w3")
    assert res["total"] == 1
    assert res["events"][0]["deviceToken"] == "wp-3"


def test_pool_rejects_strict_channel_engines():
    from sitewhere_tpu.ingest.workers import DecodeWorkerPool

    eng = mini_engine(strict_channels=True)
    with pytest.raises(ValueError, match="strict_channels"):
        DecodeWorkerPool(eng, n_workers=1)


def test_pool_rejects_oversized_batches():
    from sitewhere_tpu.ingest.workers import DecodeWorkerPool

    eng = mini_engine()
    with DecodeWorkerPool(eng, n_workers=1, max_msgs=64,
                          max_bytes=256) as pool:
        with pytest.raises(ValueError, match="max_bytes"):
            pool.submit([meas(eng, "big-1", "temp", 1.0, 1)] * 8)
