"""Multi-process product runtime: DistributedEngine replicas + router.

VERDICT r3 missing #1: the product engine (string tokens, WAL, feeds,
REST) running across processes. These tests drive the cluster layer
in-process over real RPC sockets (two full DistributedEngines, two
authenticated RPC servers); the spawned 2-process job lives in
tests/test_cluster_demo.py / parallel/cluster_demo.py. Reference model:
KafkaOutboundConnectorHost.java:43-257 (replicas over partitioned
consumer groups) + DeviceStateRouter.java:62-72 (route into the owning
engine from any node).
"""

import asyncio
import json
import socket
import threading
import time

import pytest

from sitewhere_tpu.parallel.cluster import (ClusterConfig, ClusterEngine,
                                            build_cluster_rpc, owner_rank)
from sitewhere_tpu.parallel.distributed import DistributedConfig

# one shared epoch base for every rank (int32 relative-ms domain: the
# base must be near "now", and identical across the cluster)
BASE_S = float(int(time.time()))
BASE_MS = int(BASE_S * 1000)


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _jwt_headers(rest_port, timeout=10):
    """Admin Bearer headers for a rank's REST gateway (Basic -> JWT)."""
    import base64
    import urllib.request

    basic = base64.b64encode(b"admin:password").decode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{rest_port}/api/authapi/jwt",
        headers={"Authorization": f"Basic {basic}"})
    jwt = json.loads(urllib.request.urlopen(req, timeout=timeout).read())
    return {"Authorization": f"Bearer {jwt['token']}"}


def _engine_cfg(tmp_path=None, rank=0, **kw):
    cfg = dict(n_shards=2, device_capacity_per_shard=64,
               token_capacity_per_shard=128,
               assignment_capacity_per_shard=128,
               store_capacity_per_shard=512, channels=4,
               batch_capacity_per_shard=16)
    if tmp_path is not None:
        cfg["wal_dir"] = str(tmp_path / f"wal-r{rank}")
    cfg.update(kw)
    return DistributedConfig(**cfg)


class _ServerHost:
    """One background event loop hosting this test's RPC servers."""

    def __init__(self):
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self.loop.run_forever,
                                       daemon=True)
        self.thread.start()
        self.servers = []

    def start(self, srv, port):
        asyncio.run_coroutine_threadsafe(
            srv.start(port=port), self.loop).result(10)
        self.servers.append(srv)

    def stop(self, srv=None):
        targets = [srv] if srv is not None else list(self.servers)
        for s in targets:
            asyncio.run_coroutine_threadsafe(s.stop(), self.loop).result(10)
            self.servers.remove(s)

    def close(self):
        self.stop()
        self.loop.call_soon_threadsafe(self.loop.stop)
        self.thread.join(timeout=5)


def _mk_cluster(tmp_path=None, secret="cluster-secret"):
    """Two ranks, full engines, live RPC servers. Returns
    (clusters, host, ports)."""
    ports = _free_ports(2)
    peers = [f"127.0.0.1:{p}" for p in ports]
    clusters = []
    host = _ServerHost()
    for r in range(2):
        cc = ClusterConfig(rank=r, n_ranks=2, peers=peers, secret=secret,
                           epoch_base_unix_s=BASE_S,
                           engine=_engine_cfg(tmp_path, r),
                           connect_timeout_s=10.0)
        cluster = ClusterEngine(cc)
        host.start(build_cluster_rpc(cluster.local, secret), ports[r])
        clusters.append(cluster)
    return clusters, host, ports


def meas(token, name, value, ts_rel):
    return json.dumps({
        "deviceToken": token, "type": "DeviceMeasurements",
        "request": {"measurements": {name: value},
                    "eventDate": BASE_MS + ts_rel}}).encode()


def _close(clusters, host):
    for c in clusters:
        c.close()
    host.close()


def _mk_instances_with_command(clusters, command_token="ping"):
    """One SiteWhereTpuInstance per rank, the same command registered on
    every rank (the management broadcast recipe) and a local delivery
    destination each. Returns (instances, providers)."""
    from sitewhere_tpu.commands.destinations import (CommandDestination,
                                                     LocalDeliveryProvider,
                                                     mqtt_topic_extractor)
    from sitewhere_tpu.commands.encoders import JsonCommandExecutionEncoder
    from sitewhere_tpu.commands.model import DeviceCommand
    from sitewhere_tpu.engine import EngineConfig
    from sitewhere_tpu.instance.instance import (InstanceConfig,
                                                 SiteWhereTpuInstance)

    insts, providers = [], []
    for c in clusters:
        inst = SiteWhereTpuInstance(
            InstanceConfig(engine=EngineConfig()), engine=c)
        inst.command_registry.create(DeviceCommand(
            token=command_token, device_type="default",
            name=command_token))
        p = LocalDeliveryProvider()
        inst.commands.add_destination(CommandDestination(
            "default", mqtt_topic_extractor(),
            JsonCommandExecutionEncoder(), p))
        insts.append(inst)
        providers.append(p)
    return insts, providers


def tokens_owned_by(rank, n=4, n_ranks=2, prefix="cd"):
    out, i = [], 0
    while len(out) < n:
        t = f"{prefix}-{i}"
        if owner_rank(t, n_ranks) == rank:
            out.append(t)
        i += 1
    return out


def test_owner_rank_is_stable_and_covers_ranks():
    # documented FNV-1a: same string -> same rank, across calls and
    # processes; both ranks actually receive devices
    assert owner_rank("abc", 4) == owner_rank("abc", 4)
    seen = {owner_rank(f"t-{i}", 2) for i in range(32)}
    assert seen == {0, 1}


def test_cluster_mixed_ingest_queries_agree(tmp_path):
    clusters, host, _ = _mk_cluster(tmp_path)
    c0, c1 = clusters
    try:
        toks0 = tokens_owned_by(0, 3)
        toks1 = tokens_owned_by(1, 3)
        both = toks0 + toks1
        # EACH rank ingests a batch naming devices of BOTH ranks: the
        # router forwards raw payloads to owners (Kafka-producer analog)
        s0 = c0.ingest_json_batch(
            [meas(t, "temp", 10.0 + i, 1000 + i)
             for i, t in enumerate(both)])
        s1 = c1.ingest_json_batch(
            [meas(t, "temp", 20.0 + i, 2000 + i)
             for i, t in enumerate(both)])
        # summaries merge across local + forwarded legs
        assert s0["staged"] == s1["staged"] == 6
        assert s0["failed"] == 0
        c0.flush()

        # every accepted event is persisted exactly once, at its owner
        m0, m1 = c0.metrics(), c1.metrics()
        assert m0["persisted"] == 12, m0
        assert m1["persisted"] == 12, m1
        assert c0.local.metrics()["persisted"] + \
            c1.local.metrics()["persisted"] == 12

        # query ANY rank: identical merged listings, newest first
        q0 = c0.query_events(limit=50)
        q1 = c1.query_events(limit=50)
        assert q0["total"] == q1["total"] == 12
        key = [(e["deviceToken"], e["eventDateMs"]) for e in q0["events"]]
        assert key == [(e["deviceToken"], e["eventDateMs"])
                       for e in q1["events"]]
        assert key[0][1] == 2000 + 5  # newest-first across ranks

        # per-device filters and state route to the owner from either side
        for t in both:
            r0 = c0.query_events(device_token=t)
            r1 = c1.query_events(device_token=t)
            assert r0["total"] == r1["total"] == 2
            st0, st1 = c0.get_device_state(t), c1.get_device_state(t)
            assert st0 is not None
            assert st0["measurements"]["temp"]["value"] == \
                st1["measurements"]["temp"]["value"]
            # the later (rank-1-submitted) value won at the owner
            assert st0["measurements"]["temp"]["value"] >= 20.0
        # merged device view: every device visible from both ranks
        assert {i.token for i in c0.devices.values()} == set(both)
        assert {i.token for i in c1.devices.values()} == set(both)
    finally:
        _close(clusters, host)


def test_cluster_admin_routing(tmp_path):
    clusters, host, _ = _mk_cluster(tmp_path)
    c0, c1 = clusters
    try:
        remote_tok = tokens_owned_by(1, 1, prefix="adm")[0]
        # register via NON-owner: routed to the owner
        c0.register_device(remote_tok, "default", metadata={"k": "v"})
        assert c1.local.get_device(remote_tok) is not None
        assert c0.local.get_device(remote_tok) is None  # no local copy
        info0, info1 = c0.get_device(remote_tok), c1.get_device(remote_tok)
        assert info0 == info1 and info0.metadata == {"k": "v"}
        asg0 = c0.list_assignments(remote_tok)
        asg1 = c1.list_assignments(remote_tok)
        assert len(asg0) == len(asg1) == 1
        c0.update_device(remote_tok, metadata={"k": "w"})
        assert c1.get_device(remote_tok).metadata == {"k": "w"}
        with pytest.raises(KeyError):
            c0.update_device("adm-ghost-" + remote_tok)
        # delete is a soft-deactivate on both engines (parity with the
        # single-node Engine): routed call returns True, unknown False
        assert c0.delete_device(remote_tok) is True
        assert c0.delete_device("adm-never-existed") is False
    finally:
        _close(clusters, host)


def test_cluster_event_ids_route_from_any_rank(tmp_path):
    clusters, host, _ = _mk_cluster(tmp_path)
    c0, c1 = clusters
    try:
        tok = tokens_owned_by(1, 1, prefix="ids")[0]   # rank 1 owns it
        feed = c1.make_feed_consumer("cluster-ids")
        c0.ingest_json_batch([meas(tok, "t", 5.5, 700)])
        c0.flush()
        (rec,) = feed.poll()
        assert rec.event_id % 2 == 1        # cluster id encodes rank 1
        ev0 = c0.get_event(rec.event_id)
        ev1 = c1.get_event(rec.event_id)
        assert ev0 is not None
        assert ev0["eventDateMs"] == ev1["eventDateMs"] == 700
        assert ev0["eventId"] == rec.event_id
        # tenant scoping still applies through the routed path
        assert c0.get_event(rec.event_id, tenant="default") is not None
        c1.local.tenants.intern("other")
        assert c0.get_event(rec.event_id, tenant="other") is None
    finally:
        _close(clusters, host)


def test_cluster_rest_identical_from_any_rank(tmp_path):
    """The VERDICT done-bar: REST-level queries return identical results
    regardless of which rank serves them."""
    from aiohttp.test_utils import TestClient, TestServer

    from sitewhere_tpu.engine import EngineConfig
    from sitewhere_tpu.instance.instance import (InstanceConfig,
                                                 SiteWhereTpuInstance)
    from sitewhere_tpu.web.rest import make_app

    clusters, host, _ = _mk_cluster(tmp_path)
    c0, c1 = clusters
    try:
        insts = [SiteWhereTpuInstance(
            InstanceConfig(engine=EngineConfig()), engine=c)
            for c in clusters]
        toks = tokens_owned_by(0, 2, prefix="rr") + \
            tokens_owned_by(1, 2, prefix="rr")
        c0.ingest_json_batch(
            [meas(t, "temp", float(i), 500 + i) for i, t in enumerate(toks)])
        c1.ingest_json_batch(
            [meas(t, "hum", 50.0 + i, 800 + i) for i, t in enumerate(toks)])
        c0.flush()

        async def drive():
            out = []
            for inst in insts:
                async with TestClient(TestServer(make_app(inst))) as cl:
                    jwt = inst.jwt.generate(
                        "admin", inst.users.authorities_for(
                            inst.users.users["admin"]))
                    h = {"Authorization": f"Bearer {jwt}"}
                    r = await cl.get("/api/events?pageSize=50", headers=h)
                    assert r.status == 200, await r.text()
                    listing = await r.json()
                    states = {}
                    for t in toks:
                        rs = await cl.get(f"/api/devices/{t}/state",
                                          headers=h)
                        assert rs.status == 200, await rs.text()
                        states[t] = await rs.json()
                    rd = await cl.get("/api/devices?pageSize=50", headers=h)
                    assert rd.status == 200
                    devices = await rd.json()
                    out.append((listing, states, devices))
            return out

        (l0, s0, d0), (l1, s1, d1) = asyncio.new_event_loop()\
            .run_until_complete(drive())
        assert l0["total"] == l1["total"] == 8
        assert [(e["deviceToken"], e["eventDateMs"])
                for e in l0["events"]] == \
               [(e["deviceToken"], e["eventDateMs"]) for e in l1["events"]]
        assert s0 == s1
        assert {d["token"] for d in d0["results"]} == \
               {d["token"] for d in d1["results"]} == set(toks)
    finally:
        _close(clusters, host)


def test_cluster_rank_crash_recovery(tmp_path):
    """Kill-and-recover one rank: its WAL replays at restart, peers
    reconnect, and pre-crash history serves from either rank (the
    reference leans on Kafka offsets + k8s restarts; SURVEY §5.4/5.5)."""
    from sitewhere_tpu.parallel.distributed import recover_distributed

    secret = "crash-secret"
    ports = _free_ports(2)
    peers = [f"127.0.0.1:{p}" for p in ports]
    host = _ServerHost()
    clusters = []
    servers = []
    for r in range(2):
        cc = ClusterConfig(rank=r, n_ranks=2, peers=peers, secret=secret,
                           epoch_base_unix_s=BASE_S,
                           engine=_engine_cfg(tmp_path, r),
                           connect_timeout_s=10.0)
        cluster = ClusterEngine(cc)
        srv = build_cluster_rpc(cluster.local, secret)
        host.start(srv, ports[r])
        clusters.append(cluster)
        servers.append(srv)
    c0, c1 = clusters
    try:
        tok = tokens_owned_by(1, 1, prefix="cr")[0]
        c0.ingest_json_batch([meas(tok, "t", 1.0, 100)])
        c0.flush()
        snap = tmp_path / "snap-r1"
        c1.local.save(snap)
        # post-snapshot traffic lands only in rank 1's WAL
        c0.ingest_json_batch([meas(tok, "t", 2.0, 200)])
        c0.flush()

        # --- crash rank 1: server down, engine dropped un-closed --------
        host.stop(servers[1])
        c1.local.wal.close()
        c1.close()

        # --- restart: recover from snapshot + WAL tail ------------------
        rec = recover_distributed(snap, tmp_path / "wal-r1")
        rec.epoch = c1.epoch
        cc1 = ClusterConfig(rank=1, n_ranks=2, peers=peers, secret=secret,
                            epoch_base_unix_s=BASE_S,
                            connect_timeout_s=10.0)
        c1b = ClusterEngine(cc1, local=rec)
        host.start(build_cluster_rpc(rec, secret), ports[1])
        clusters[1] = c1b

        # the recovered rank has BOTH events; rank 0 reconnects and serves
        # the full history (peer client rides out the restart)
        q1 = c1b.query_events(device_token=tok)
        assert q1["total"] == 2, q1
        q0 = c0.query_events(device_token=tok)
        assert q0["total"] == 2, q0
        assert [e["eventDateMs"] for e in q0["events"]] == [200, 100]
        st = c0.get_device_state(tok)
        assert st["measurements"]["t"]["value"] == 2.0
        # and the cluster stays writable through the recovered rank
        c0.ingest_json_batch([meas(tok, "t", 3.0, 300)])
        c0.flush()
        assert c1b.query_events(device_token=tok)["total"] == 3
    finally:
        _close(clusters, host)


def test_cluster_rpc_rejects_unauthenticated(tmp_path):
    from sitewhere_tpu.rpc.client import RpcClient
    from sitewhere_tpu.rpc.protocol import RpcError

    clusters, host, ports = _mk_cluster(tmp_path)
    try:
        async def go():
            anon = await RpcClient(port=ports[0]).connect()
            try:
                with pytest.raises(RpcError) as ei:
                    await anon.call("Cluster.metrics")
                assert ei.value.code == 401
            finally:
                await anon.close()
            wrong = RpcClient(
                port=ports[0],
                auth_token=__import__(
                    "sitewhere_tpu.parallel.cluster",
                    fromlist=["cluster_system_jwt"]
                ).cluster_system_jwt("wrong-secret"))
            with pytest.raises(RpcError) as ei:
                await wrong.connect()
            assert ei.value.code == 401

        asyncio.new_event_loop().run_until_complete(go())
    finally:
        _close(clusters, host)


def test_cluster_presence_sweep_spans_ranks(tmp_path):
    """One sweep trigger marks stale devices MISSING on every rank (the
    reference's DevicePresenceManager runs per engine; the cluster
    surface reaches all of them from any node)."""
    clusters, host, _ = _mk_cluster(tmp_path)
    c0, c1 = clusters
    try:
        for c in clusters:
            c.local.config.presence_missing_s = 0.0
        toks = tokens_owned_by(0, 2, prefix="pw") + \
            tokens_owned_by(1, 2, prefix="pw")
        c0.ingest_json_batch(
            [meas(t, "t", 1.0, 10 + i) for i, t in enumerate(toks)])
        c0.flush()
        missing = c0.presence_sweep()
        assert set(missing) == set(toks)
        for t in toks:
            assert c1.get_device_state(t)["presence"] == "MISSING"
    finally:
        _close(clusters, host)


def test_instance_rpc_serves_cluster_from_any_rank(tmp_path):
    """The deployment recipe: build_instance_rpc over a cluster-backed
    instance routes through the facade, so the full-family control plane
    answers identically no matter which rank hosts it."""
    from sitewhere_tpu.engine import EngineConfig
    from sitewhere_tpu.instance.instance import (InstanceConfig,
                                                 SiteWhereTpuInstance)
    from sitewhere_tpu.rpc.client import RpcClient
    from sitewhere_tpu.rpc.server import build_instance_rpc, system_jwt

    clusters, host, _ = _mk_cluster(tmp_path)
    c0, c1 = clusters
    try:
        insts = [SiteWhereTpuInstance(
            InstanceConfig(engine=EngineConfig()), engine=c)
            for c in clusters]
        toks = tokens_owned_by(0, 2, prefix="ir") + \
            tokens_owned_by(1, 2, prefix="ir")
        c0.ingest_json_batch(
            [meas(t, "t", float(i), 100 + i) for i, t in enumerate(toks)])
        c0.flush()

        async def drive(inst):
            srv = build_instance_rpc(inst)
            port = await srv.start()
            cli = await RpcClient(port=port, tenant="default",
                                  auth_token=system_jwt(inst)).connect()
            try:
                listing = await cli.call("DeviceManagement.listDevices")
                states = {t: await cli.call("DeviceState.getDeviceState",
                                            token=t) for t in toks}
                evs = await cli.call(
                    "DeviceEventManagement.listDeviceEvents", pageSize=50)
                return ({d["token"] for d in listing["results"]},
                        states, evs["total"])
            finally:
                await cli.close()
                await srv.stop()

        loop = asyncio.new_event_loop()
        try:
            r0 = loop.run_until_complete(drive(insts[0]))
            r1 = loop.run_until_complete(drive(insts[1]))
        finally:
            loop.close()
        assert r0[0] == r1[0] == set(toks)
        assert r0[1] == r1[1]
        assert r0[2] == r1[2] == 4
    finally:
        _close(clusters, host)


def test_protocol_edge_routes_across_cluster(tmp_path):
    """The ingest edge (event sources -> decoder -> engine.process) on one
    rank forwards each decoded request to its owning rank — a device can
    publish to ANY rank's broker, like producing to any Kafka broker."""
    from sitewhere_tpu.engine import EngineConfig
    from sitewhere_tpu.ingest.decoders import JsonDeviceRequestDecoder
    from sitewhere_tpu.ingest.sources import (InboundEventSource,
                                              InMemoryEventReceiver)
    from sitewhere_tpu.instance.instance import (InstanceConfig,
                                                 SiteWhereTpuInstance)

    clusters, host, _ = _mk_cluster(tmp_path)
    c0, c1 = clusters
    try:
        inst0 = SiteWhereTpuInstance(
            InstanceConfig(engine=EngineConfig()), engine=c0)
        recv = InMemoryEventReceiver()
        inst0.event_sources.add_source(
            InboundEventSource("edge", JsonDeviceRequestDecoder(), [recv]))
        toks = tokens_owned_by(0, 2, prefix="pe") + \
            tokens_owned_by(1, 2, prefix="pe")
        for i, t in enumerate(toks):
            recv.submit(meas(t, "temp", 30.0 + i, 400 + i))
        c0.flush()
        # every event landed at its owner; both ranks agree
        for c in clusters:
            assert c.query_events(limit=50)["total"] == 4
        for t in tokens_owned_by(1, 2, prefix="pe"):
            assert c1.local.get_device(t) is not None
            assert c0.local.get_device(t) is None
            st = c0.get_device_state(t)
            assert st["measurements"]["temp"]["value"] >= 30.0
    finally:
        _close(clusters, host)


@pytest.mark.slow
def test_two_process_product_job_with_crash_recovery():
    """The VERDICT r3 done-bar, process-level: two OS processes each run
    a DistributedEngine (string tokens, WAL, feeds) + REST; both ingest
    mixed batches; REST agrees from either rank; rank 1 is killed with
    WAL-tail-only events and must recover and serve full history.

    Marked slow: 3 subprocesses x cold jax compiles need more cores than
    the 2-core CI container has — the cross-rank metrics fan-out trips
    its 45s RPC window while a peer compiles under its engine lock, and
    the 300s demo budget can't absorb that plus phase-2 recovery. Runs
    in full (non-tier-1) mode and on real driver hosts."""
    from sitewhere_tpu.parallel.cluster_demo import spawn_cluster_demo

    lines = spawn_cluster_demo(devices_per_proc=2)
    assert sum(ln.startswith("CLUSTER_OK") for ln in lines) == 3
    assert any("phase=2" in ln for ln in lines)
    assert any(ln.startswith("CLUSTER_RECOVERED") and "replayed_total=3"
               in ln for ln in lines)
    assert all("rest_agree=1" in ln for ln in lines if "phase=1" in ln)


def test_cluster_event_search_spans_ranks(tmp_path):
    """Each rank's connector indexes ITS partition; the embedded search
    fans out so /api/search/events answers identically (and completely)
    from any rank — all replicas feeding one Solr, reference-style."""
    from sitewhere_tpu.engine import EngineConfig
    from sitewhere_tpu.instance.instance import (InstanceConfig,
                                                 SiteWhereTpuInstance)

    clusters, host, _ = _mk_cluster(tmp_path)
    c0, c1 = clusters
    try:
        insts = [SiteWhereTpuInstance(
            InstanceConfig(engine=EngineConfig()), engine=c)
            for c in clusters]
        toks = tokens_owned_by(0, 2, prefix="se") + \
            tokens_owned_by(1, 2, prefix="se")
        c0.ingest_json_batch(
            [meas(t, "temp", float(i), 300 + i) for i, t in enumerate(toks)])
        c0.flush()
        # each rank's connector indexes its OWN feed partition
        loop = asyncio.new_event_loop()
        try:
            for inst in insts:
                loop.run_until_complete(inst.pump_outbound())
        finally:
            loop.close()
        # rank-local indexes are partial...
        assert 0 < len(insts[0].search_index.search("*:*")) < 4
        # ...but the cluster surface is complete and identical from both
        d0 = c0.search_events("*:*", 50)
        d1 = c1.search_events("*:*", 50)
        assert len(d0) == len(d1) == 4
        assert [d["deviceToken"] for d in d0] == \
               [d["deviceToken"] for d in d1]
        only_r1 = tokens_owned_by(1, 1, prefix="se")[0]
        hits = c0.search_events(f"deviceToken:{only_r1}", 10)
        assert len(hits) == 1 and hits[0]["deviceToken"] == only_r1
        # backdated events rank by EVENT time even when a rank's top-N
        # by arrival would drop them (review r4): tiny max_results
        top = c0.search_events("*:*", 1)
        assert top[0]["eventDateMs"] == max(
            d["eventDateMs"] for d in c0.search_events("*:*", 50))
        # ...and the instance's "embedded" PROVIDER is the cluster-wide
        # one, so the REST tier needs no engine-topology branch
        p0 = insts[0].search.get("embedded")
        p1 = insts[1].search.get("embedded")
        assert [d["deviceToken"] for d in p0.search("*:*", 50)] == \
               [d["deviceToken"] for d in p1.search("*:*", 50)]
        assert len(p0.search("*:*", 50)) == 4
        # provider INFO describes the cluster corpus (what search()
        # actually searches), not the local slice (VERDICT r4 weak #6)
        assert p0.info.docs == p1.info.docs == 4
        assert p0.info.provider_id == "embedded"
        # ...while each rank's raw index still reports its partition
        assert insts[0].search_index.info.docs < 4
    finally:
        _close(clusters, host)


def test_merged_devices_by_id_get_is_explicitly_local(tmp_path):
    """Device ids are rank-scoped: the dict-shaped ``get`` on the merged
    view silently aliased across ranks (VERDICT r4 weak #2) — by-id
    lookups must be explicitly local (get_local / local_device_info) or
    token-routed (get_device)."""
    from sitewhere_tpu.engine import local_device_info

    clusters, host, _ = _mk_cluster(tmp_path)
    c0, c1 = clusters
    try:
        toks = tokens_owned_by(0, 1, prefix="md") + \
            tokens_owned_by(1, 1, prefix="md")
        c0.ingest_json_batch([meas(t, "t", float(i), 20 + i)
                              for i, t in enumerate(toks)])
        for c in clusters:
            c.flush()
        with pytest.raises(TypeError, match="rank-local"):
            c0.devices.get(0)
        lid0, info0 = next(iter(c0.local.devices.items()))
        assert c0.devices.get_local(lid0).token == info0.token
        # the shared helper reads the local mirror on BOTH surfaces
        assert local_device_info(c0, lid0).token == info0.token
        assert local_device_info(c0.local, lid0).token == info0.token
        assert local_device_info(c0, 10_000) is None
        # fan-out surfaces still span the cluster
        assert len(c0.devices) == 2
        assert {i.token for i in c0.devices.values()} == set(toks)
    finally:
        _close(clusters, host)


def test_cluster_search_fails_loudly_without_peer_index(tmp_path):
    """A peer serving Cluster.searchEvents without an attached index must
    fail the merge, not silently shrink it to one rank's partition."""
    from sitewhere_tpu.search.index import EventSearchIndex

    clusters, host, _ = _mk_cluster(tmp_path)
    c0, c1 = clusters
    try:
        c0.attach_search_index(EventSearchIndex())   # rank 1: none
        with pytest.raises(RuntimeError, match="rank 1"):
            c0.search_events("*:*", 10)
        # and with no LOCAL index the facade signals fallback, not failure
        assert c1.search_events("*:*", 10) is None
    finally:
        _close(clusters, host)


def test_cluster_command_invocation_delivers_at_owning_rank(tmp_path):
    """The downlink over the cluster: an invocation accepted at ANY rank
    routes to the device's owner, persists there, and THAT rank's
    delivery pump encodes + delivers it (the reference's command chain:
    REST anywhere -> event-management partition -> the partition
    consumer's destinations). Command definitions follow the management
    deployment recipe (created on every rank)."""
    from sitewhere_tpu.commands.destinations import (CommandDestination,
                                                     LocalDeliveryProvider,
                                                     mqtt_topic_extractor)
    from sitewhere_tpu.commands.encoders import JsonCommandExecutionEncoder
    from sitewhere_tpu.commands.model import (CommandParameter,
                                              DeviceCommand, ParameterType)
    from sitewhere_tpu.engine import EngineConfig
    from sitewhere_tpu.instance.instance import (InstanceConfig,
                                                 SiteWhereTpuInstance)

    clusters, host, _ = _mk_cluster(tmp_path)
    c0, c1 = clusters
    try:
        insts = [SiteWhereTpuInstance(
            InstanceConfig(engine=EngineConfig()), engine=c)
            for c in clusters]
        providers = []
        for inst in insts:   # the broadcast recipe: same command + a
            inst.command_registry.create(DeviceCommand(  # local dest on
                token="reboot", device_type="default",   # every rank
                name="reboot",
                parameters=(CommandParameter("delay", ParameterType.INT64,
                                             required=True),)))
            p = LocalDeliveryProvider()
            providers.append(p)
            inst.commands.add_destination(CommandDestination(
                "default", mqtt_topic_extractor(),
                JsonCommandExecutionEncoder(), p))
        remote_tok = tokens_owned_by(1, 1, prefix="cmd")[0]
        local_tok = tokens_owned_by(0, 1, prefix="cmd")[0]
        c0.register_device(remote_tok, "default")
        c0.register_device(local_tok, "default")
        # a LOCAL invocation first: its id and the routed one must live
        # in disjoint (rank-tagged) id spaces — no history collisions
        inv_local = insts[0].commands.invoke(local_tok, "reboot",
                                             {"delay": 1})
        # invoke at the NON-owner rank
        inv = insts[0].commands.invoke(remote_tok, "reboot",
                                       {"delay": 5})
        assert inv.invocation_id != inv_local.invocation_id
        assert inv.invocation_id % 2 == 1       # owner rank 1's id space
        assert inv_local.invocation_id % 2 == 0
        c0.flush()
        loop = asyncio.new_event_loop()
        try:
            # rank 0's pump delivers only ITS partition (the local inv)...
            assert loop.run_until_complete(insts[0].commands.pump()) == 1
            # ...rank 1's pump delivers the routed one from ITS feed
            assert loop.run_until_complete(insts[1].commands.pump()) == 1
        finally:
            loop.close()
        assert len(providers[1].delivered) == 1
        assert len(providers[0].delivered) == 1
        assert insts[0].commands.get_invocation(
            inv_local.invocation_id).device_token == local_tok
        _target, payload, _system = providers[1].delivered[0]
        assert b"reboot" in payload
        assert insts[1].commands.undelivered == []
        # the invocation EVENT persisted at the owner and is visible
        # cluster-wide; both ranks' history carries the same owner id
        from sitewhere_tpu.core.types import EventType
        q = c0.query_events(device_token=remote_tok,
                            etype=EventType.COMMAND_INVOCATION)
        assert q["total"] == 1
        assert insts[0].commands.get_invocation(inv.invocation_id) \
            is not None
        assert insts[1].commands.get_invocation(inv.invocation_id) \
            is not None
        # a device ack (COMMAND_RESPONSE naming the invocation) lands at
        # the owner; responses_for answers identically from BOTH ranks
        c0.ingest_json_batch([json.dumps({
            "deviceToken": remote_tok, "type": "Acknowledge",
            "request": {"originatingEventId": str(inv.invocation_id),
                        "response": "done",
                        "eventDate": BASE_MS + 999}}).encode()])
        c0.flush()
        r0 = insts[0].commands.responses_for(inv.invocation_id)
        r1 = insts[1].commands.responses_for(inv.invocation_id)
        assert len(r0) == len(r1) == 1
        assert r0[0]["originatingEventId"] == str(inv.invocation_id)
        # ...and no cross-talk with the local invocation's responses
        assert insts[0].commands.responses_for(
            inv_local.invocation_id) == []
        # raw interner-id filters are refused at the cluster surface
        with pytest.raises(ValueError, match="rank-local"):
            c0.query_events(aux0=3)
        # direct wrong-rank staging stays LOUD, never silent
        with pytest.raises(NotImplementedError, match="owned by rank"):
            with c0.lock:
                c0._stage_row(1, c0.local.tokens.intern(remote_tok), 0,
                              0, 0, None, None, -1, -1)
    finally:
        _close(clusters, host)


def test_cluster_feed_commit_does_not_skip_events(tmp_path):
    """Review r4 repro: ClusterFeed translates ids on poll, so commit
    must UNTRANSLATE them — otherwise each commit over-advances ~n_ranks
    x and silently skips undelivered invocations. Four invocations with
    interleaved telemetry, pumping after each, must all deliver."""
    clusters, host, _ = _mk_cluster(tmp_path)
    c0, c1 = clusters
    try:
        insts, _providers = _mk_instances_with_command(clusters)
        tok = tokens_owned_by(1, 1, prefix="fc")[0]
        c0.register_device(tok, "default")
        loop = asyncio.new_event_loop()
        try:
            delivered = 0
            for i in range(4):
                insts[0].commands.invoke(tok, "ping")
                # interleaved telemetry widens the feed between commits
                c0.ingest_json_batch([meas(tok, "t", float(i), 50 + i)])
                c0.flush()
                delivered += loop.run_until_complete(
                    insts[1].commands.pump())
            delivered += loop.run_until_complete(insts[1].commands.pump())
        finally:
            loop.close()
        assert delivered == 4, delivered
        assert insts[1].commands._pending == {}
    finally:
        _close(clusters, host)


def test_batch_command_operation_spans_cluster(tmp_path):
    """A batch command created at ONE rank fans its per-device
    invocations across the cluster: local devices deliver locally,
    remote ones route to their owner's pump (the reference's
    batch-operations -> command chain over partitioned topics)."""
    from sitewhere_tpu.commands.destinations import (CommandDestination,
                                                     LocalDeliveryProvider,
                                                     mqtt_topic_extractor)
    from sitewhere_tpu.commands.encoders import JsonCommandExecutionEncoder
    from sitewhere_tpu.commands.model import DeviceCommand
    from sitewhere_tpu.engine import EngineConfig
    from sitewhere_tpu.instance.instance import (InstanceConfig,
                                                 SiteWhereTpuInstance)

    clusters, host, _ = _mk_cluster(tmp_path)
    c0, c1 = clusters
    try:
        insts, providers = _mk_instances_with_command(clusters)
        toks = tokens_owned_by(0, 2, prefix="bat") + \
            tokens_owned_by(1, 2, prefix="bat")
        for t in toks:
            c0.register_device(t, "default")
        insts[0].batch.create_operation("bat-1", "InvokeCommand", toks,
                                        parameters={"commandToken": "ping"})
        loop = asyncio.new_event_loop()
        try:
            op = loop.run_until_complete(
                insts[0].batch.process_operation("bat-1"))
            assert op.counts()["SUCCEEDED"] == 4
            c0.flush()
            n1 = loop.run_until_complete(insts[1].commands.pump())
        finally:
            loop.close()
        # rank 0's pump ran inside the batch handler; rank 1 delivers its
        # routed half from its own feed
        assert len(providers[0].delivered) == 2
        assert n1 == 2 and len(providers[1].delivered) == 2
        assert insts[0].commands.undelivered == []
        assert insts[1].commands.undelivered == []
    finally:
        _close(clusters, host)


def test_invocation_readable_from_third_rank(tmp_path):
    """GET /api/invocations/{id} must answer from a rank that is NEITHER
    originator nor owner: the rank-tagged id routes the lookup to its
    owning rank (review r4 — invisible at n_ranks=2)."""
    from sitewhere_tpu.commands.model import (CommandParameter,
                                              DeviceCommand, ParameterType)
    from sitewhere_tpu.engine import EngineConfig
    from sitewhere_tpu.instance.instance import (InstanceConfig,
                                                 SiteWhereTpuInstance)

    ports = _free_ports(3)
    peers = [f"127.0.0.1:{p}" for p in ports]
    host = _ServerHost()
    clusters = []
    for r in range(3):
        cc = ClusterConfig(rank=r, n_ranks=3, peers=peers, secret="i3",
                           epoch_base_unix_s=BASE_S,
                           engine=_engine_cfg(None, r, n_shards=1),
                           connect_timeout_s=10.0)
        c = ClusterEngine(cc)
        host.start(build_cluster_rpc(c.local, "i3"), ports[r])
        clusters.append(c)
    try:
        insts = [SiteWhereTpuInstance(
            InstanceConfig(engine=EngineConfig()), engine=c)
            for c in clusters]
        for inst in insts:
            inst.command_registry.create(DeviceCommand(
                token="ping", device_type="default", name="ping"))
        tok = tokens_owned_by(1, 1, n_ranks=3, prefix="inv3")[0]
        clusters[0].register_device(tok, "default")
        inv = insts[0].commands.invoke(tok, "ping")
        assert inv.invocation_id % 3 == 1     # owner rank 1's id space
        # rank 2 saw nothing locally; the lookup routes to the owner
        got = insts[2].commands.get_invocation(inv.invocation_id)
        assert got is not None
        assert got.device_token == tok and got.command_token == "ping"
        assert insts[2].commands.get_invocation(999_999 * 3 + 1) is None
    finally:
        _close(clusters, host)


def test_cluster_rank_count_reshard_by_wal_replay(tmp_path):
    """Rank-count elasticity: ownership is token-hash % n_ranks, so
    changing the rank count re-partitions devices. Replaying every old
    rank's WAL through a FRESH 3-rank cluster migrates the whole history,
    each event exactly once to its new owner."""
    from sitewhere_tpu.parallel.cluster import reshard_cluster

    # --- old 2-rank cluster with per-rank WALs -------------------------
    clusters, host, _ = _mk_cluster(tmp_path / "old")
    c0, c1 = clusters
    toks = tokens_owned_by(0, 3, n_ranks=2) + tokens_owned_by(1, 3,
                                                              n_ranks=2)
    try:
        c0.ingest_json_batch(
            [meas(t, "temp", float(i), 600 + i) for i, t in enumerate(toks)])
        c1.ingest_json_batch(
            [meas(t, "temp", 50.0 + i, 900 + i) for i, t in enumerate(toks)])
        c0.flush()
        want = c0.query_events(limit=50)
        want_states = {t: c0.get_device_state(t)["measurements"]
                       for t in toks}
    finally:
        for c in clusters:
            c.local.wal.close()
        _close(clusters, host)

    # --- fresh 3-rank cluster, replay both old WALs --------------------
    ports = _free_ports(3)
    peers = [f"127.0.0.1:{p}" for p in ports]
    host3 = _ServerHost()
    new = []
    for r in range(3):
        cc = ClusterConfig(rank=r, n_ranks=3, peers=peers, secret="rs3",
                           epoch_base_unix_s=BASE_S,
                           engine=_engine_cfg(tmp_path / "new", r),
                           connect_timeout_s=10.0)
        c = ClusterEngine(cc)
        host3.start(build_cluster_rpc(c.local, "rs3"), ports[r])
        new.append(c)
    try:
        n_replayed = reshard_cluster(
            new[0], [tmp_path / "old" / "wal-r0", tmp_path / "old" / "wal-r1"])
        assert n_replayed == 12
        got = new[0].query_events(limit=50)
        assert got["total"] == want["total"] == 12
        assert [(e["deviceToken"], e["eventDateMs"]) for e in got["events"]] \
            == [(e["deviceToken"], e["eventDateMs"]) for e in want["events"]]
        for t in toks:
            assert new[1].get_device_state(t)["measurements"] == \
                want_states[t]
            # ownership re-partitioned under n_ranks=3: the device mirror
            # lives ONLY at its new owner
            owner = owner_rank(t, 3)
            for r in range(3):
                has = new[r].local.get_device(t) is not None
                assert has == (r == owner), (t, r, owner)
        # the new cluster's own WALs carry the migrated history — each
        # record re-logged at its NEW owner (count rank 2's wal directly;
        # the merged metric would pass even with empty WALs)
        assert new[2].local.metrics()["persisted"] == \
            2 * sum(owner_rank(t, 3) == 2 for t in toks)
        for c in new:
            c.local.wal.close()
        from sitewhere_tpu.utils.ingestlog import IngestLog

        wal2 = IngestLog(tmp_path / "new" / "wal-r2", readonly=True)
        n_logged = sum(1 for _ in wal2.replay())
        wal2.close()
        assert n_logged == 2 * sum(owner_rank(t, 3) == 2 for t in toks)
        assert n_logged > 0
        # pruned source WALs are refused, never silently partial
        from sitewhere_tpu.parallel.cluster import replay_wal_through

        pruned = tmp_path / "pruned-wal"
        pruned.mkdir()
        (pruned / "segment-00000003.log").write_bytes(b"SWAL1\n")
        with pytest.raises(ValueError, match="pruned"):
            replay_wal_through(new[0], pruned)
    finally:
        _close(new, host3)


def test_envelope_round_trip():
    """envelope_from_request is the exact inverse of request_from_envelope
    for every routed request type (the cross-rank single-event wire)."""
    from sitewhere_tpu.ingest.decoders import (envelope_from_request,
                                               request_from_envelope)

    envs = [
        {"deviceToken": "d", "type": "DeviceMeasurement", "tenant": "t1",
         "request": {"measurements": {"a": 1.5, "b": 2.0},
                     "eventDate": 1234, "alternateId": "alt-1"}},
        {"deviceToken": "d", "type": "DeviceLocation",
         "request": {"latitude": 1.5, "longitude": -2.5,
                     "elevation": 3.0}},
        {"deviceToken": "d", "type": "DeviceAlert",
         "request": {"type": "overheat", "level": "Error",
                     "message": "hot"}},
        {"deviceToken": "d", "type": "Acknowledge",
         "request": {"originatingEventId": "oe-1", "response": "ok"}},
        {"deviceToken": "d", "type": "DeviceStateChange",
         "request": {"attribute": "fw", "type": "upgrade",
                     "previousState": "1", "newState": "2"}},
        {"deviceToken": "d", "type": "RegisterDevice",
         "request": {"deviceTypeToken": "sensor",
                     "metadata": {"k": "v"}}},
    ]
    for env in envs:
        req = request_from_envelope(env)
        req.tenant = env.get("tenant", "default")
        rt = request_from_envelope(envelope_from_request(req))
        for f in ("type", "device_token", "event_ts_ms", "measurements",
                  "latitude", "longitude", "elevation", "alert_type",
                  "alert_level", "alert_message", "originating_event_id",
                  "response", "attribute", "state_type", "previous_state",
                  "new_state", "alternate_id", "extras", "metadata"):
            assert getattr(rt, f) == getattr(req, f), (env["type"], f)


def test_native_route_matches_python_partitioner():
    """The native batch router (swtpu_route_pylist) and its Python port
    (native/route_fallback.py) must agree payload-for-payload — a
    divergence would send a device's events to a rank that registers it
    under a second identity. Covers: precedence (deviceToken over
    hardwareId), last-duplicate-key-wins, empty/numeric/null tokens,
    broken and TRUNCATED JSON, trailing garbage, control characters in
    strings, escapes (incl. surrogate pairs and non-BMP raw UTF-8),
    >2048-byte tokens, and overlong/surrogate/invalid-UTF-8 binary
    tokens."""
    import json as _json

    from sitewhere_tpu.ingest.decoders import (encode_binary_request,
                                               request_from_envelope)
    from sitewhere_tpu.native.binding import route_payloads
    from sitewhere_tpu.native.route_fallback import (route_binary_payload,
                                                     route_json_payload)
    from sitewhere_tpu.parallel.cluster import owner_rank

    n_ranks = 5
    long_tok = "L" * 3000
    payloads = [
        _json.dumps({"deviceToken": f"dev-{i}", "type": "DeviceMeasurement",
                     "request": {"name": "t", "value": 1.0}}).encode()
        for i in range(40)
    ] + [
        b'{"hardwareId": "hw-7", "type": "DeviceMeasurement"}',
        b'{"deviceToken": "", "hardwareId": "hw-8"}',
        b'{"deviceToken": 12345}',
        b'{"deviceToken": null, "hardwareId": "hw-9"}',
        b'{"type": "DeviceMeasurement"}',
        b'{broken json',
        b'[1,2,3]',
        _json.dumps({"deviceToken": 'esc"tok\\en'}).encode(),
        _json.dumps({"deviceToken": "télémetre"}).encode(),
        b'{"deviceToken": "first", "deviceToken": "second"}',
        b'{"deviceToken": "keep", "deviceToken": 42}',
        _json.dumps({"deviceToken": "dt-wins",
                     "hardwareId": "hw-loses"}).encode(),
        # review repros: token extracted, then the envelope goes bad
        b'{"deviceToken": "x", "request": {"na',       # truncated mid-doc
        b'{"deviceToken": "x"} garbage',               # trailing garbage
        b'{"deviceToken": "a\nb"}',                    # raw control char
        b'{"a": "c\rd", "deviceToken": "y"}',          # ctrl in other string
        # surrogate pair: escaped and raw forms of the same token
        b'{"deviceToken": "\\ud83d\\ude00-dev"}',
        '{"deviceToken": "\U0001F600-dev"}'.encode(),
        b'{"deviceToken": "\\ud83d lonely"}',          # lone high surrogate
        ('{"deviceToken": "%s"}' % long_tok).encode(),  # > vbuf cap
        ('{"a": 1.5e3, "deviceToken": "after-num", "b": true,'
         ' "c": null, "d": [1, {"x": "y"}]}').encode(),
    ]
    ranks = route_payloads(payloads, n_ranks)
    if ranks is None:
        pytest.skip("native list router unavailable")
    for i, p in enumerate(payloads):
        want = route_json_payload(p, n_ranks)
        assert int(ranks[i]) == want, (i, p[:60], int(ranks[i]), want)
    # the escaped and raw forms of the same non-BMP token route together
    i_esc = payloads.index(b'{"deviceToken": "\\ud83d\\ude00-dev"}')
    i_raw = payloads.index('{"deviceToken": "\U0001F600-dev"}'.encode())
    assert int(ranks[i_esc]) == int(ranks[i_raw]) >= 0
    # plain tokens still match the string-level owner_rank contract
    assert int(ranks[0]) == owner_rank("dev-0", n_ranks)
    # >512-byte tokens intern to their 512-byte prefix, so two tokens
    # sharing that prefix are ONE device to the decoder — the router
    # must send both to the same rank
    twins = [('{"deviceToken": "%s"}' % ("P" * 512 + sfx)).encode()
             for sfx in ("-a", "-b")]
    tr = route_payloads(twins, n_ranks)
    assert int(tr[0]) == int(tr[1]) >= 0
    assert route_json_payload(twins[0], n_ranks) == int(tr[0])

    bp = [encode_binary_request(request_from_envelope({
            "deviceToken": f"bt-{i}", "type": "DeviceMeasurement",
            "request": {"measurements": {"x": 1.0}}})) for i in range(20)]
    bp += [b"", b"\x02\x01\x00\x00", b"\x01\x01\x05\x00ab",
           b"\x01\x01\x02\x00\xff\xfe" + b"\x00" * 8,
           b"\x01\x01\x03\x00\xed\xa0\x80" + b"\x00" * 8,   # surrogate
           b"\x01\x01\x03\x00\xe0\x80\x80" + b"\x00" * 8,   # overlong
           b"\x01\x01\x04\x00\xf4\x90\x80\x80" + b"\x00" * 8,  # >U+10FFFF
           b"\x01\x01\x04\x00\xf0\x9f\x98\x80" + b"\x00" * 8]  # valid emoji
    br = route_payloads(bp, n_ranks, binary=True)
    for i, p in enumerate(bp):
        want = route_binary_payload(p, n_ranks)
        assert int(br[i]) == want, (i, p[:30], int(br[i]), want)
    assert int(br[-1]) >= 0          # valid 4-byte UTF-8 routes
    assert int(br[-2]) == int(br[-3]) == int(br[-4]) == -1


def test_surrogate_pair_tokens_intern_identically():
    """Escaped (\\ud83d\\ude00) and raw UTF-8 forms of a non-BMP token
    must decode to the SAME device — CESU-8 interning would split one
    physical device into two identities."""
    import json as _json

    from sitewhere_tpu.engine import Engine, EngineConfig
    from sitewhere_tpu.ingest.fast_decode import native_available

    if not native_available():
        pytest.skip("native library unavailable")
    eng = Engine(EngineConfig(
        device_capacity=32, token_capacity=64, assignment_capacity=64,
        store_capacity=512, batch_capacity=8, channels=4))
    tok = "\U0001F600-dev"
    base = int(eng.epoch.base_unix_s * 1000)
    raw = _json.dumps({"deviceToken": tok, "type": "DeviceMeasurement",
                       "request": {"name": "t", "value": 1.0,
                                   "eventDate": base + 1}},
                      ensure_ascii=False).encode()
    esc = _json.dumps({"deviceToken": tok, "type": "DeviceMeasurement",
                       "request": {"name": "t", "value": 2.0,
                                   "eventDate": base + 2}},
                      ensure_ascii=True).encode()
    assert b"\\ud83d" in esc and b"\\u" not in raw
    res = eng.ingest_json_batch([raw, esc])
    assert res["failed"] == 0
    eng.flush()
    assert eng.metrics()["registered"] == 1   # ONE device, not two
    st = eng.get_device_state(tok)
    assert st["measurements"]["t"]["value"] == 2.0


def test_binary_token_of():
    from sitewhere_tpu.ingest.decoders import (binary_token_of,
                                               encode_binary_request,
                                               request_from_envelope)

    req = request_from_envelope({
        "deviceToken": "bin-7", "type": "DeviceMeasurement",
        "request": {"measurements": {"t": 1.0}}})
    assert binary_token_of(encode_binary_request(req)) == "bin-7"
    assert binary_token_of(b"") is None
    assert binary_token_of(b"\xff\x01\x02\x00xx") is None


def test_cluster_engine_refuses_epoch_base_drift(tmp_path):
    # ADVICE r4: a recovered engine carries the epoch base its snapshot/
    # WAL were written under — a drifted configured base must raise, not
    # silently shift every stored relative timestamp
    from sitewhere_tpu.core.events import EpochBase
    from sitewhere_tpu.parallel.distributed import DistributedEngine

    eng = DistributedEngine(_engine_cfg(tmp_path))
    eng.epoch = EpochBase(BASE_S - 3600.0)   # snapshot written an hour ago
    cc = ClusterConfig(rank=0, n_ranks=1, peers=["127.0.0.1:1"],
                       secret="s", epoch_base_unix_s=BASE_S)
    with pytest.raises(ValueError, match="epoch base"):
        ClusterEngine(cc, local=eng)
    # matching base is accepted (the recover_distributed path)
    cc_ok = ClusterConfig(rank=0, n_ranks=1, peers=["127.0.0.1:1"],
                          secret="s", epoch_base_unix_s=BASE_S - 3600.0)
    ClusterEngine(cc_ok, local=eng).close()


def test_sync_peer_mints_fresh_token_per_connection(tmp_path):
    # ADVICE r4 (medium): a token minted once at engine construction
    # expires after 24h and every later reconnect 401s permanently —
    # the peer must call the token FACTORY on each connection attempt
    from sitewhere_tpu.parallel.cluster import (_SyncPeer,
                                                cluster_system_jwt)
    from sitewhere_tpu.parallel.distributed import DistributedEngine

    secret = "mint-secret"
    eng = DistributedEngine(_engine_cfg(tmp_path))
    host = _ServerHost()
    [port] = _free_ports(1)
    mints = []

    def factory():
        mints.append(1)
        return cluster_system_jwt(secret)

    srv = build_cluster_rpc(eng, secret)
    host.start(srv, port)
    peer = _SyncPeer(f"127.0.0.1:{port}", factory, timeout_s=10.0)
    try:
        assert peer.call("Cluster.deviceCount") == 0
        assert len(mints) == 1
        # server restart = the crash-recovery reconnect path: a SECOND
        # mint must happen (a cached token would be stale by then)
        host.stop(srv)
        srv2 = build_cluster_rpc(eng, secret)
        host.start(srv2, port)
        assert peer.call("Cluster.deviceCount") == 0
        assert len(mints) == 2
    finally:
        peer.close()
        host.close()


def test_sync_peer_timeout_reconnects_cleanly(tmp_path):
    # ADVICE r4: a slow peer used to leak a pending future on the shared
    # client with no reconnect — the next caller reused a connection in
    # an indeterminate state. A timeout must cancel + reconnect.
    from sitewhere_tpu.parallel.cluster import (_SyncPeer,
                                                cluster_system_jwt)
    from sitewhere_tpu.parallel.distributed import DistributedEngine

    secret = "slow-secret"
    eng = DistributedEngine(_engine_cfg(tmp_path))
    host = _ServerHost()
    [port] = _free_ports(1)
    srv = build_cluster_rpc(eng, secret)

    async def slow():
        await asyncio.sleep(3.0)
        return {"ok": True}

    srv.register("Test.slow", slow)
    host.start(srv, port)
    peer = _SyncPeer(f"127.0.0.1:{port}",
                     lambda: cluster_system_jwt(secret), timeout_s=1.0)
    peer.grace_s = 0.2   # result window 1.2s < the 3s handler
    try:
        t0 = time.monotonic()
        with pytest.raises(TimeoutError, match="indeterminate"):
            # times out and is NOT auto-retried (the peer may still be
            # executing it — a retry would double-execute non-idempotent
            # RPCs); the in-flight future is cancelled, not leaked
            peer.call("Test.slow")
        assert time.monotonic() - t0 < 30.0
        peer.grace_s = 30.0
        # the shared peer still works: fresh connection, no stale
        # pending future consuming the next response off the wire
        assert peer.call("Cluster.deviceCount") == 0
    finally:
        peer.close()
        host.close()


def test_run_rank_validates_wiring_before_serving(tmp_path):
    """A mis-composed rank must fail at STARTUP with every problem
    listed — not at the first cross-rank RPC (VERDICT r4 item 5)."""
    from sitewhere_tpu.parallel.rank_runtime import (RankConfig,
                                                     RankWiringError,
                                                     run_rank)

    # no WAL on a durable rank + truncated peers list: both reported
    cc = ClusterConfig(rank=1, n_ranks=2, peers=["127.0.0.1:1"],
                       secret="s", epoch_base_unix_s=BASE_S,
                       engine=_engine_cfg())   # no wal_dir
    with pytest.raises(RankWiringError) as ei:
        run_rank(RankConfig(cluster=cc))
    msg = str(ei.value)
    assert "WAL" in msg and "peers list has 1" in msg


def test_run_rank_boots_a_serving_rank_from_one_config(tmp_path):
    """run_rank composes engine + cluster RPC + REST + pumps; the public
    health route reports readiness; ingest->query->search work; stop()
    tears it all down."""
    import urllib.request

    from sitewhere_tpu.parallel.rank_runtime import RankConfig, run_rank

    [rpc_port] = _free_ports(1)
    cc = ClusterConfig(rank=0, n_ranks=1, peers=[f"127.0.0.1:{rpc_port}"],
                       secret="s", epoch_base_unix_s=BASE_S,
                       engine=_engine_cfg(tmp_path))
    rt = run_rank(RankConfig(cluster=cc))
    try:
        assert rt.rest_port and rt.rest_port > 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{rt.rest_port}/api/instance/health",
                timeout=10) as r:
            h = json.loads(r.read())
        assert h["status"] == "UP" and h["ready"] is True
        assert h["rank"] == 0 and h["nRanks"] == 1
        assert h["recovered"] is False
        rt.cluster.ingest_json_batch([meas("rr-1", "t", 5.0, 100)])
        rt.cluster.flush()
        q = rt.cluster.query_events(device_token="rr-1")
        assert q["total"] == 1
        rt.pump_outbound()   # search connector indexes the partition
        assert len(rt.instance.search_index.search("*:*")) == 1
        # observability surfaces: the cluster page + rank-labeled
        # Prometheus series (single rank: by_rank has one entry)
        hdr = _jwt_headers(rt.rest_port)
        req = urllib.request.Request(
            f"http://127.0.0.1:{rt.rest_port}/api/instance/cluster",
            headers=hdr)
        cs = json.loads(urllib.request.urlopen(req, timeout=10).read())
        assert cs["rank"] == 0 and cs["ranks"]["0"]["status"] == "UP"
        assert "entities" in cs   # replication gauges ride the page
        req = urllib.request.Request(
            f"http://127.0.0.1:{rt.rest_port}"
            "/api/instance/metrics/prometheus", headers=hdr)
        text = urllib.request.urlopen(req, timeout=10).read().decode()
        assert 'rank="0"' in text and 'rank="all"' in text
        assert "swtpu_engine_persisted" in text
    finally:
        rt.stop()


def test_assignments_administered_from_any_rank(tmp_path):
    """Assignment CRUD routes across the cluster: create routes by the
    device's owner, by-token reads/updates/release resolve the owning
    rank from ANY rank (Assignments.java REST any-node semantics —
    previously these fell through to the serving rank's local engine)."""
    clusters, host, _ = _mk_cluster(tmp_path)
    c0, c1 = clusters
    try:
        remote = tokens_owned_by(1, 1, prefix="asg")[0]   # owned by r1
        c0.register_device(remote, "default")
        # create at the NON-owner rank: routes to rank 1
        a = c0.create_assignment(remote, token="asg-A", asset="truck-7")
        assert a.device_token == remote and a.asset == "truck-7"
        assert c1.local.get_assignment("asg-A") is not None
        assert c0.local.get_assignment("asg-A") is None
        # by-token read + update + missing + release from EITHER rank
        assert c0.get_assignment("asg-A").asset == "truck-7"
        assert c1.get_assignment("asg-A").asset == "truck-7"
        upd = c0.update_assignment("asg-A", area="yard")
        assert upd.area == "yard"
        assert c1.get_assignment("asg-A").area == "yard"
        m = c0.mark_assignment_missing("asg-A")
        assert m.status == "MISSING"
        rel = c0.release_assignment("asg-A")
        assert rel.status == "RELEASED"
        # delete resolves the owner too; unknown tokens are False
        assert c0.delete_assignment("asg-A") is True
        assert c0.get_assignment("asg-A") is None
        assert c1.delete_assignment("asg-A") is False
        with pytest.raises(KeyError):
            c0.update_assignment("asg-A", area="x")
    finally:
        _close(clusters, host)


def test_cluster_metrics_carry_rank_attribution(tmp_path):
    """metrics() keeps the cluster-merged sums AND reports by_rank, so
    an operator can see WHICH rank is hot (VERDICT r4 item 7 — a pure
    sum hides every imbalance); cluster_status() is the topology/health
    page behind /api/instance/cluster."""
    clusters, host, _ = _mk_cluster(tmp_path)
    c0, c1 = clusters
    try:
        toks = tokens_owned_by(0, 3, prefix="mr") + \
            tokens_owned_by(1, 1, prefix="mr")
        c0.ingest_json_batch([meas(t, "t", float(i), 40 + i)
                              for i, t in enumerate(toks)])
        c0.flush()
        m = c0.metrics()
        assert m["persisted"] == 4
        assert set(m["by_rank"]) == {"0", "1"}
        assert sum(r["persisted"] for r in m["by_rank"].values()) == 4
        # the imbalance is visible: rank 0 owns 3 of the 4 devices
        assert m["by_rank"]["0"]["persisted"] == 3
        # per-tenant counts exist on the mesh engine too (the Prometheus
        # per-tenant series; Engine.tenant_metrics parity)
        tm = c0.local.tenant_metrics()
        assert tm["default"]["MEASUREMENT"] == 3
        # entity-replication gauges ride each rank's schema when attached
        s = c0.cluster_status()
        assert s["clustered"] is True and s["rank"] == 0
        assert s["ranks"]["0"]["local"] and s["ranks"]["0"]["status"] == "UP"
        assert s["ranks"]["1"]["status"] == "UP"
        assert s["ranks"]["0"]["devices"] == 3
        assert s["ranks"]["1"]["devices"] == 1
        # the same page from the other rank agrees on topology
        s1 = c1.cluster_status()
        assert s1["rank"] == 1 and s1["nRanks"] == 2
        assert s1["ranks"]["0"]["devices"] == 3
    finally:
        _close(clusters, host)


def test_run_rank_three_rank_cluster_from_one_config(tmp_path):
    """The operator story at N=3: three ranks from the SAME config shape,
    administered once, ingesting anywhere, reading identically everywhere
    (run_rank generality beyond the 2-rank demo)."""
    from sitewhere_tpu.parallel.rank_runtime import RankConfig, run_rank

    n = 3
    ports = _free_ports(n)
    peers = [f"127.0.0.1:{p}" for p in ports]
    rts = []
    try:
        for r in range(n):
            cc = ClusterConfig(
                rank=r, n_ranks=n, peers=peers, secret="three",
                epoch_base_unix_s=BASE_S,
                engine=_engine_cfg(tmp_path, rank=r))
            rts.append(run_rank(RankConfig(
                cluster=cc, entity_sync_interval_s=3600.0)))

        # one admin call, at rank 0 only
        rts[0].instance.device_management.create_device_type(
            "tri-type", "Triple")
        rts[0].replicator.drain_pushes()
        for rt in rts:
            assert "tri-type" in rt.instance.device_management.device_types

        # ingest at rank 1 a batch whose tokens are owned by ALL ranks
        toks = [tokens_owned_by(r, 2, n_ranks=n, prefix="tri") for r in range(n)]
        flat = [t for per in toks for t in per]
        batch = [meas(t, "temp", 10.0 + i, 100 + i)
                 for i, t in enumerate(flat)]
        s = rts[1].cluster.ingest_json_batch(batch)
        assert s.get("failed", 0) == 0 and s.get("spilled", 0) == 0
        for rt in rts:
            rt.cluster.flush()
        # every rank answers every token identically (owner-routed reads)
        for rt in rts:
            for t in flat:
                q = rt.cluster.query_events(device_token=t)
                assert q["total"] == 1, (t, q)
            assert len(rt.cluster.devices) == len(flat)

        # cluster-wide search agrees from any rank
        for rt in rts:
            rt.pump_outbound()
        hits = {len(rt.instance.search.get("embedded").search("*:*"))
                for rt in rts}
        assert hits == {len(flat)}

        # the cluster status page at rank 2 sees all three ranks UP
        import urllib.request
        req = urllib.request.Request(
            f"http://127.0.0.1:{rts[2].rest_port}/api/instance/cluster",
            headers=_jwt_headers(rts[2].rest_port))
        cs = json.loads(urllib.request.urlopen(req, timeout=10).read())
        assert cs["nRanks"] == 3
        assert {r for r, v in cs["ranks"].items()
                if v["status"] == "UP"} == {"0", "1", "2"}
    finally:
        for rt in rts:
            try:
                rt.stop()
            except Exception:
                pass   # one rank's teardown must not strand the rest
