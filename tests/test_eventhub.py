"""Partitioned event-hub tests: partition-key routing, processor-host batch
delivery, checkpoint/resume, multi-host partition splitting, receiver +
connector (sources/azure/EventHubInboundEventReceiver.java parity)."""

import asyncio
import json

from sitewhere_tpu.core.types import EventType
from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.ingest.decoders import JsonDeviceRequestDecoder
from sitewhere_tpu.ingest.eventhub import (
    CheckpointStore,
    EventHub,
    EventHubEventReceiver,
    EventProcessorHost,
)
from sitewhere_tpu.ingest.sources import EventSourcesManager, InboundEventSource
from sitewhere_tpu.outbound.feed import OutboundEvent


def test_partition_key_stability_and_round_robin():
    hub = EventHub("telemetry", partition_count=4)
    a1 = hub.send(b"1", partition_key="dev-a")
    a2 = hub.send(b"2", partition_key="dev-a")
    assert a1.partition_id == a2.partition_id  # stable hash per key
    assert a2.sequence_number == a1.sequence_number + 1
    pids = {hub.send(b"x").partition_id for _ in range(4)}
    assert pids == {0, 1, 2, 3}  # keyless round-robin covers all partitions


def test_processor_host_batches_and_checkpoints(tmp_path):
    hub = EventHub("telemetry", partition_count=2)
    store = CheckpointStore(tmp_path / "ckpt.json")
    for i in range(12):
        hub.send(b"m%d" % i, partition_key=f"k{i}")

    got: list[bytes] = []

    async def run_host():
        host = EventProcessorHost(hub, "$Default", store, checkpoint_every=5)
        host.on_events = lambda pid, batch: got.extend(ev.body for ev in batch)
        await host.register()
        await asyncio.sleep(0.2)
        await host.unregister()

    asyncio.run(run_host())
    assert sorted(got) == sorted(b"m%d" % i for i in range(12))
    # checkpoints persisted: sum of checkpointed offsets covers all but the
    # sub-checkpoint_every tail of each partition
    total_ckpt = sum(store.get("$Default", p, hub.epoch) for p in range(2))
    assert total_ckpt >= 12 - 2 * 4

    # a NEW host with a NEW store file handle resumes from the checkpoint,
    # not from zero
    store2 = CheckpointStore(tmp_path / "ckpt.json")
    resumed: list[bytes] = []

    async def run_resumed():
        host = EventProcessorHost(hub, "$Default", store2, checkpoint_every=5)
        host.on_events = lambda pid, batch: resumed.extend(ev.body for ev in batch)
        await host.register()
        await asyncio.sleep(0.2)
        await host.unregister()

    asyncio.run(run_resumed())
    assert len(resumed) == 12 - total_ckpt


def test_two_hosts_split_partitions():
    hub = EventHub("telemetry", partition_count=4)
    seen = {1: set(), 2: set()}

    async def run():
        h1 = EventProcessorHost(hub, "grp")
        h2 = EventProcessorHost(hub, "grp")
        h1.on_events = lambda pid, batch: seen[1].add(pid)
        h2.on_events = lambda pid, batch: seen[2].add(pid)
        await h1.register()
        await h2.register()
        for i in range(32):
            hub.send(b"x", partition_key=f"k{i}")
        await asyncio.sleep(0.3)
        await h1.unregister()
        await h2.unregister()

    asyncio.run(run())
    assert seen[1] and seen[2]
    assert not (seen[1] & seen[2])  # disjoint ownership
    assert seen[1] | seen[2] == {0, 1, 2, 3}


def test_retention_trims_and_reader_ages_out():
    hub = EventHub("small", partition_count=1, retention=5)
    for i in range(12):
        hub.send(b"m%d" % i, partition_key="k")
    assert hub.end_offset(0) == 12
    # only the last 5 retained; a reader from 0 ages out to offset 7
    batch = hub.read(0, 0, 100)
    assert [e.body for e in batch] == [b"m7", b"m8", b"m9", b"m10", b"m11"]
    assert batch[0].offset == 7


def test_checkpoint_clamped_to_fresh_hub(tmp_path):
    """A persisted checkpoint from a previous log generation must not
    swallow the new run's first events: epochs differ, so resume from 0."""
    store = CheckpointStore(tmp_path / "c.json")
    store.checkpoint("$Default", 0, 10, epoch="previous-run-epoch")

    hub = EventHub("fresh", partition_count=1)
    got: list[bytes] = []

    async def run():
        host = EventProcessorHost(hub, "$Default",
                                  CheckpointStore(tmp_path / "c.json"))
        host.on_events = lambda pid, batch: got.extend(e.body for e in batch)
        await host.register()
        hub.send(b"first", partition_key="k")
        await asyncio.sleep(0.2)
        await host.unregister()

    asyncio.run(run())
    assert got == [b"first"]


def test_eventhub_receiver_end_to_end():
    hub = EventHub("ingest", partition_count=3)

    async def run():
        engine = Engine(EngineConfig(
            device_capacity=64, token_capacity=128, assignment_capacity=128,
            store_capacity=4096, batch_capacity=16, channels=4,
        ))
        mgr = EventSourcesManager(
            on_event_request=engine.process,
            on_registration_request=engine.process,
        )
        recv = EventHubEventReceiver(hub)
        mgr.add_source(InboundEventSource("hub", JsonDeviceRequestDecoder(), [recv]))
        await mgr.initialize()
        await mgr.start()
        try:
            for i in range(10):
                hub.send(json.dumps({
                    "deviceToken": f"hub-{i}", "type": "DeviceMeasurement",
                    "request": {"name": "t", "value": float(i)},
                }).encode(), partition_key=f"hub-{i}")
            await asyncio.sleep(0.3)
        finally:
            await mgr.stop()
        engine.flush()
        assert engine.metrics()["registered"] == 10
        assert engine.metrics()["persisted"] == 10

    asyncio.run(run())


def test_eventhub_connector():
    from sitewhere_tpu.connectors.impl import EventHubConnector

    hub = EventHub("out", partition_count=2)
    ev = OutboundEvent(
        event_id=1, etype=EventType.MEASUREMENT, device_token="d-1",
        device_id=0, assignment_id=0, tenant="default", area_id=0, asset_id=0,
        ts_ms=1000, received_ms=1001, measurements={"temp": 20.5},
        values=[20.5], aux0=0, aux1=0,
    )

    asyncio.run(EventHubConnector("hub", hub).process_event(ev))
    bodies = [e for p in range(hub.partition_count)
              for e in hub.read(p, 0, 100)]
    assert len(bodies) == 1
    assert json.loads(bodies[0].body)["deviceToken"] == "d-1"
    assert bodies[0].partition_key == "d-1"
