"""Shard heat & skew observability plane (ISSUE 18).

The contract pinned here, on the virtual 8-device CPU mesh:

  * **heat determinism** — the EWMA tracker never reads a clock; a
    seeded (grid, slot_rows, now_s) sequence replays to byte-identical
    heat maps, the first harvest primes baselines at zero heat, dt of
    one half-life halves a quiet cell EXACTLY, and counter regressions
    (restore) clamp to zero instead of going negative;
  * **skew discipline** — per-dispatch max/mean index, reset-on-scrape
    HWM (the PR-11 arena-HWM rule), and the two-consecutive-audit
    confirmation before a sustained-skew escalation (the PR-13
    conservation-auditor rule);
  * **per-shard conservation** — the ledger's new ``spmd-shard-flow``
    equation balances on a drained engine, per-shard lanes sum EXACTLY
    to the folded device stage, and perturbing one per-shard lane is a
    Violation (falsifiability);
  * **attribution** — a deliberately skewed two-tenant stream fingers
    the hot tenant in the (shard, tenant) heat map AND the hot token's
    placement slot as top-1;
  * **dispatch-shape pin** — exercising the whole plane leaves
    ``engine.metrics()`` dict-equal across ``scan_chunk`` retunes and
    free of heat/skew keys (the plane stays OUT, like every plane
    before it);
  * **surfaces** — scrape-time Prometheus export (lint-clean),
    ``spmd_heat_payload`` duck-typing ({"spmd": False} on single-chip),
    the debug bundle's "spmd" section, the ``decide_balance`` heat
    input (byte-identical policy when absent — the PR-15 pure-function
    pin), and the spmd.* flight spans surviving the offline
    trace2perfetto converter (smoke-invoked as a subprocess).
"""

import json
import subprocess
import sys

import numpy as np
import pytest

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.parallel.placement import (PlacementMap, decide_balance,
                                              slot_for_token)
from sitewhere_tpu.utils.conservation import build_ledger, check_conservation
from sitewhere_tpu.utils.metrics import MetricsRegistry, export_engine_metrics
from sitewhere_tpu.utils.shardobs import (ShardHeatTracker, heat_map_doc,
                                          spmd_heat_payload)
from sitewhere_tpu.utils.tracing import debug_bundle, timeline_events
from tests.test_spmd import CFG, FixedEpoch, _meas, _run, _spmd, _stream


def _grid(n_shards, n_buckets, accepted, invalid=None):
    """A synthetic cumulative tenant counter grid [S, T, 4] in
    TENANT_COUNTER_LANES order (accepted, dedup, geofence, invalid)."""
    g = np.zeros((n_shards, n_buckets, 4), np.int64)
    g[..., 0] = accepted
    if invalid is not None:
        g[..., 3] = invalid
    return g


# ===================================================================
# ShardHeatTracker unit pins
# ===================================================================

def test_first_harvest_primes_baselines_at_zero_heat():
    tr = ShardHeatTracker(2, 16)
    tr.harvest(_grid(2, 4, 100), np.zeros(16, np.int64), now_s=0.0)
    assert tr.harvests == 1
    assert tr.heat_grid is not None and not tr.heat_grid.any()
    assert not tr.slot_heat.any()
    assert tr.top_slots() == []


def test_heat_ewma_deterministic_and_halflife_exact():
    """Same (grid, slot_rows, now_s) sequence -> byte-identical maps;
    a quiet half-life halves heat EXACTLY (alpha = 1 - 0.5**(dt/hl))."""
    def replay():
        tr = ShardHeatTracker(2, 16, halflife_s=10.0)
        slots = np.zeros(16, np.int64)
        tr.harvest(_grid(2, 4, 0), slots, now_s=0.0)
        g = _grid(2, 4, 0)
        g[0, 1, 0] = 50                       # 50 ev in 1 s on (0, 1)
        s2 = slots.copy()
        s2[3] = 50
        tr.harvest(g, s2, now_s=1.0)
        tr.harvest(g, s2, now_s=3.5)          # quiet interval decays
        return tr

    a, b = replay(), replay()
    assert np.array_equal(a.heat_grid, b.heat_grid)
    assert np.array_equal(a.slot_heat, b.slot_heat)
    assert a.heat_grid[0, 1] > 0 and a.heat_grid[1, 1] == 0

    tr = ShardHeatTracker(1, 4, halflife_s=10.0)
    tr.harvest(_grid(1, 2, 0), np.zeros(4, np.int64), now_s=0.0)
    g = _grid(1, 2, 0)
    g[0, 0, 0] = 40
    tr.harvest(g, np.zeros(4, np.int64), now_s=10.0)
    warm = float(tr.heat_grid[0, 0])
    tr.harvest(g, np.zeros(4, np.int64), now_s=20.0)   # one quiet halflife
    assert float(tr.heat_grid[0, 0]) == warm * 0.5


def test_heat_counts_invalid_lane_and_clamps_regressions():
    """Heat is OFFERED load (accepted + invalid — garbage heats a shard
    like good rows do), and a counter regression (snapshot restore)
    clamps the delta to zero instead of producing negative heat."""
    tr = ShardHeatTracker(1, 4)
    tr.harvest(_grid(1, 2, 10, invalid=5), np.zeros(4, np.int64), 0.0)
    tr.harvest(_grid(1, 2, 14, invalid=11), np.zeros(4, np.int64), 1.0)
    assert float(tr.heat_grid[0, 0]) > 0
    tr2 = ShardHeatTracker(1, 4)
    tr2.harvest(_grid(1, 2, 100), np.zeros(4, np.int64), 0.0)
    tr2.harvest(_grid(1, 2, 7), np.zeros(4, np.int64), 1.0)  # went backwards
    assert float(tr2.heat_grid[0, 0]) == 0.0
    assert (tr2.heat_grid >= 0).all() and (tr2.slot_heat >= 0).all()


def test_dispatch_skew_index_and_hwm_reset_on_take():
    tr = ShardHeatTracker(4, 32)
    assert tr.note_dispatch([8, 0, 0, 0]) == 4.0
    assert tr.note_dispatch([2, 2, 2, 2]) == 1.0
    assert tr.note_dispatch([0, 0, 0, 0]) == 1.0     # empty = balanced
    assert tr.skew_hwm == 4.0                        # peek keeps the peak
    assert tr.take_skew_hwm() == 4.0                 # take resets...
    assert tr.take_skew_hwm() == 1.0                 # ...to the live index
    assert tr.dispatches == 3


def test_skew_escalation_needs_two_consecutive_audits():
    """One hot audit is a suspect, not a verdict; recovery between
    audits clears the suspicion (the PR-13 confirmation rule)."""
    tr = ShardHeatTracker(4, 32, skew_threshold=4.0)
    tr.note_dispatch([8, 0, 0, 0])                   # index 4.0: breach
    assert tr.audit_skew() is False                  # suspect only
    assert tr.audit_skew() is True                   # confirmed
    assert tr.sustained_total == 1
    # a PERSISTENT breach re-arms and escalates every other audit —
    # bounded noise, never a double-count within one confirmation
    assert tr.audit_skew() is False
    assert tr.audit_skew() is True
    assert tr.sustained_total == 2
    tr.note_dispatch([2, 2, 2, 2])                   # recovered
    assert tr.audit_skew() is False                  # suspicion cleared
    tr.note_dispatch([8, 0, 0, 0])
    assert tr.audit_skew() is False                  # must re-confirm
    assert tr.sustained_total == 2


def test_top_slots_hottest_first_quiet_omitted():
    tr = ShardHeatTracker(2, 16)
    tr.slot_heat[3] = 5.0
    tr.slot_heat[11] = 9.0
    tr.slot_heat[0] = 1.5
    assert tr.top_slots(2) == [(11, 9.0), (3, 5.0)]
    assert [s for s, _ in tr.top_slots()] == [11, 3, 0]


# ===================================================================
# Per-shard conservation (the spmd-shard-flow equation)
# ===================================================================

@pytest.mark.parametrize("n_shards", [2, 4])
def test_shard_flow_conservation_balances_and_is_falsifiable(n_shards):
    eng = _spmd(n_shards)
    _run([eng], _stream(n=96))
    eng.barrier()
    eng.drain()
    led = build_ledger(eng)
    assert not check_conservation(led)
    sp = led["stages"]["spmd"]
    assert sp["shards"] == n_shards and sp["counting"]
    dev = led["stages"]["device"]
    for lane in ("processed", "accepted", "invalid"):
        assert sum(r[lane] for r in sp["perShard"]) == dev[lane]
    # drained: routed == dispatched, zero backlog, work on every shard
    for row in sp["perShard"]:
        assert row["backlog_rows"] == 0
        assert row["routed_rows"] == row["dispatched_rows"] > 0
    # falsifiability (the PR-13 discipline): one per-shard lane off by
    # one breaks BOTH the partition and the fold-sum identity
    bad = json.loads(json.dumps(led))
    bad["stages"]["spmd"]["perShard"][0]["accepted"] += 1
    vs = check_conservation(bad)
    assert len(vs) == 2
    assert {v.equation for v in vs} == {"spmd-shard-flow"}
    bad2 = json.loads(json.dumps(led))
    bad2["stages"]["spmd"]["perShard"][-1]["dispatched_rows"] -= 1
    assert any(v.equation == "spmd-shard-flow"
               for v in check_conservation(bad2))


def test_shard_flow_mid_flight_backlog_is_the_legal_slack():
    eng = _spmd(2)
    wire = [_meas(f"sp-{i % 8}", 30.0, 1_000 + i * 10) for i in range(16)]
    eng.ingest_json_batch(wire)                       # staged, NOT flushed
    led = build_ledger(eng)
    assert not check_conservation(led)
    sp = led["stages"]["spmd"]
    assert sum(r["backlog_rows"] for r in sp["perShard"]) == 16
    assert all(r["dispatched_rows"] == 0 for r in sp["perShard"])
    eng.flush()
    eng.drain()
    assert not check_conservation(build_ledger(eng))


def test_single_chip_ledger_has_no_spmd_stage():
    eng = Engine(EngineConfig(**CFG))
    eng.epoch = FixedEpoch()
    _run([eng], _stream(n=32))
    led = build_ledger(eng)
    assert "spmd" not in led["stages"]
    assert not check_conservation(led)


# ===================================================================
# Heat attribution on the mesh engine
# ===================================================================

def test_heat_fingers_hot_tenant_and_hot_slot():
    """A stream where one tenant's one token carries 8x the rows: the
    (shard, tenant) heat map's hottest cell names THAT tenant and the
    top-1 slot is THAT token's placement slot (the bench hotspot leg's
    oracle, deterministic here via the injected clock)."""
    eng = _spmd(2)
    eng.harvest_shard_heat(now_s=0.0)                 # prime baselines
    hot_tok, n_hot = "blaze-7", 64
    hot = [_meas(hot_tok, 21.0, 1_000 + i) for i in range(n_hot)]
    cold = [_meas(f"cold-{i}", 21.0, 1_000 + i) for i in range(8)]
    for lo in range(0, n_hot, 16):
        eng.ingest_json_batch(hot[lo:lo + 16], tenant="blaze")
        eng.flush()
    eng.ingest_json_batch(cold, tenant="quiet")
    eng.flush()
    eng.drain()
    tracker = eng.harvest_shard_heat(now_s=1.0)
    doc = heat_map_doc(tracker, eng.tenants)
    cells = [(eps, ten) for cells in doc.values()
             for ten, eps in cells.items()]
    assert max(cells)[1] == "blaze"
    by_tenant = {}
    for eps, ten in cells:
        by_tenant[ten] = by_tenant.get(ten, 0.0) + eps
    assert by_tenant["blaze"] > 4 * by_tenant["quiet"]
    top = tracker.top_slots()
    assert top and top[0][0] == slot_for_token(hot_tok, eng.n_shards)
    # the full document serves the same story
    payload = spmd_heat_payload(eng, now_s=2.0)
    assert payload["spmd"] is True
    assert payload["flow"]["perShard"] and payload["heat"]
    assert payload["slots"]["topK"][0]["slot"] == top[0][0]
    assert payload["skew"]["dispatches"] == tracker.dispatches > 0


def test_staged_hwm_reset_on_scrape_sees_drained_pileup():
    """The swtpu_shard_staged_rows blind-spot fix: a pileup that drained
    BEFORE the scrape still shows in the HWM take; the take resets."""
    eng = _spmd(2)
    wire = [_meas(f"sp-{i % 8}", 30.0, 1_000 + i * 10) for i in range(24)]
    eng.ingest_json_batch(wire)
    eng.flush()
    eng.drain()                                       # backlog is 0 now
    hwm = eng.take_shard_staged_hwm()
    assert sum(hwm) == 24 and all(h > 0 for h in hwm)
    assert eng.take_shard_staged_hwm() == [0, 0]      # reset on take


# ===================================================================
# Dispatch-shape pin: the plane stays OUT of engine.metrics()
# ===================================================================

@pytest.mark.parametrize("n_shards", [1, 2, 4])
def test_metrics_dict_unchanged_with_heat_plane_exercised(n_shards):
    """engine.metrics() is pinned dict-equal across scan_chunk retunes
    WITH the whole plane exercised between ingests — heat, skew, flow
    and HWM surfaces add zero keys and change zero values (the known
    limit: posture lives on shard_flow/spmd_heat, never metrics())."""
    a = _spmd(n_shards, scan_chunk=1)
    b = _spmd(n_shards, scan_chunk=2)
    events = _stream(n=64)
    clock = iter(range(100))
    for lo in range(0, len(events), 16):
        wire = [_meas(t, v, ts) for t, v, ts in events[lo:lo + 16]]
        for e in (a, b):
            e.ingest_json_batch(wire)
            e.flush()
            e.harvest_shard_heat(now_s=float(next(clock)))
            e.shard_flow()
            e.take_shard_staged_hwm()
            e.spmd_heat()
    for e in (a, b):
        e.barrier()
        e.drain()
    ma, mb = a.metrics(), b.metrics()
    assert ma == mb
    assert not any("heat" in k or "skew" in k or "slot" in k
                   for k in ma)


# ===================================================================
# Surfaces: exposition, payload duck-typing, bundle, placement input
# ===================================================================

def test_heat_series_export_at_scrape_and_lint():
    from tests.test_metrics_exposition import lint_prometheus

    eng = _spmd(2)
    wire = [_meas(f"sx-{i % 8}", float(i), 1_000 + i) for i in range(24)]
    eng.ingest_json_batch(wire)
    eng.flush()
    eng.drain()
    reg = MetricsRegistry()
    export_engine_metrics(eng, reg)                   # primes baselines
    eng.ingest_json_batch(wire)
    eng.flush()
    eng.drain()
    reg = MetricsRegistry()
    export_engine_metrics(eng, reg)
    text = reg.expose_text()
    lint_prometheus(text)
    lbl = eng.metrics_label
    for s in ("0", "1"):
        assert (f'swtpu_shard_staged_rows_hwm{{engine="{lbl}",shard="{s}"}}'
                in text)
        for lane in ("processed", "accepted", "routed_rows",
                     "dispatched_rows", "backlog_rows"):
            assert (f'swtpu_shard_flow_rows{{engine="{lbl}",'
                    f'lane="{lane}",shard="{s}"}}' in text)
    assert f'swtpu_shard_heat{{engine="{lbl}"' in text
    assert f'swtpu_slot_heat_topk{{engine="{lbl}"' in text
    assert f'swtpu_spmd_skew{{engine="{lbl}"}}' in text
    assert f'swtpu_spmd_skew_hwm{{engine="{lbl}"}}' in text
    # single-chip engines export NONE of the plane
    reg1 = MetricsRegistry()
    export_engine_metrics(Engine(EngineConfig(**CFG)), reg1)
    t1 = reg1.expose_text()
    assert "swtpu_shard_flow_rows" not in t1
    assert "swtpu_shard_heat" not in t1
    assert "swtpu_spmd_skew" not in t1


def test_spmd_heat_payload_duck_types_single_chip():
    assert spmd_heat_payload(Engine(EngineConfig(**CFG))) == {"spmd": False}
    assert spmd_heat_payload(object()) == {"spmd": False}


def test_decide_balance_heat_input_and_purity_pin():
    """slot_heat steers the peel toward the MEASURED hottest of the hot
    tenant's slots; None (and {}) keep the decision byte-identical to
    the PR-15 policy (slots[0]) — the pure-function pin."""
    m = PlacementMap.initial(2, slots_per_rank=2)      # slots 0..3
    pmap = m.with_moves({1: 0})       # rank 0 holds 3 slots, rank 1 one
    kw = dict(tenant_p99_ms={"hot": 900.0}, tenant_rank={"hot": 0},
              tenant_slots={"hot": [0, 2]}, pmap=pmap,
              p99_target_ms=250.0)
    base = decide_balance(**kw)
    assert base == [(0, 1)]
    assert decide_balance(**kw, slot_heat=None) == base
    assert decide_balance(**kw, slot_heat={}) == base
    assert decide_balance(**kw, slot_heat={2: 9.0, 0: 1.0}) == [(2, 1)]
    assert decide_balance(**kw, slot_heat={0: 9.0, 2: 1.0}) == base
    # unmeasured slots read heat 0.0; ties break to the lowest slot id
    assert decide_balance(**kw, slot_heat={99: 5.0}) == base


# ===================================================================
# SPMD flight spans + offline converter
# ===================================================================

def test_spmd_flight_spans_and_trace2perfetto_roundtrip(tmp_path):
    """SPMD ingest flights expose the route/scatter lifecycle as
    spmd.* child spans with the skew breadcrumbs on the root event;
    single-chip span derivation is untouched, and the offline
    trace2perfetto converter survives the new names (smoke-invoked as
    a subprocess, the ISSUE 11 discipline)."""
    eng = _spmd(2)
    wire = [_meas(f"fl-{i % 8}", 25.0, 1_000 + i) for i in range(16)]
    eng.ingest_json_batch(wire)
    eng.flush()
    eng.drain()
    rec = next(r for r in eng.flight.recent(kind="ingest")
               if "route" in (r.get("stagesUs") or {}))
    events = timeline_events(eng, rec["traceId"])
    names = {e["name"] for e in events}
    assert {"ingest.spmd.route", "ingest.spmd.scatter",
            "ingest.spmd.commit"} <= names
    root = next(e for e in events if e["name"] == "ingest")
    assert "shard_rows" in root["args"] and "skew" in root["args"]
    assert len(root["args"]["shard_rows"].split("/")) == 2

    bundle = debug_bundle(eng)
    assert bundle["spmd"]["spmd"] is True             # the new section
    assert bundle["spmd"]["flow"]["perShard"]
    path = tmp_path / "bundle.json"
    path.write_text(json.dumps(bundle))
    out = tmp_path / "trace.perfetto.json"
    r = subprocess.run(
        [sys.executable, "scripts/trace2perfetto.py", str(path),
         "--trace", rec["traceId"], "-o", str(out)],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    doc = json.loads(out.read_text())
    xs = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert "ingest.spmd.route" in xs and "ingest.spmd.scatter" in xs

    # a single-chip flight record derives NO spmd.* spans
    sc = Engine(EngineConfig(**CFG))
    sc.epoch = FixedEpoch()
    sc.ingest_json_batch(wire)
    sc.flush()
    screc = sc.flight.recent(kind="ingest")[0]
    scnames = {e["name"]
               for e in timeline_events(sc, screc["traceId"])}
    assert not any(n.startswith("ingest.spmd.") for n in scnames)
