"""Persistent-connection wire edge tests (ISSUE 20): the batched socket
edge feeding staging arenas.

Pinned contracts:

* **WireBatcher** — arrival-window accumulation, size/deadline adaptive
  flush, (tenant, wire-format) run splitting in arrival order, WAL-gated
  ack callbacks, arena-stall shed (``on_stall``), barrier acks.
* **MQTT 3.1.1 server codec under adversarial framing** — byte-at-a-time
  fragmented reads across varint remaining-length boundaries, QoS 1
  duplicate redelivery (no double ingest, ack regenerated), QoS 2
  park/release, oversized-frame rejection, keepalive timeout.
* **SWP framing** — handshake validation, cumulative durable acks, shed
  codes with Retry-After, oversized-frame error records.
* **Byte-identity** — frames through the batched wire path produce a
  store byte-identical to direct ``ingest_json_batch`` calls with the
  same batch boundaries, for ``Engine`` AND ``SpmdEngine`` at
  ``scan_chunk`` 1 and 2, metrics dict-equal, conservation clean.
* **Conservation "wire" stage** — the disposition equation balances and
  is falsifiable (a one-frame perturbation is a Violation).
* **Observability split** — ``swtpu_wire_*`` series exist only at scrape
  time; ``engine.metrics()`` keys are identical with and without an
  edge attached (dispatch-shape equality pin).
"""

import asyncio
import dataclasses
import json
import struct
import threading
import types

import jax
import numpy as np
import pytest

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.core.events import EpochBase
from sitewhere_tpu.ingest.decoders import JsonDeviceRequestDecoder
from sitewhere_tpu.ingest.dedup import AlternateIdDeduplicator
from sitewhere_tpu.ingest.mqtt import (
    CONNACK,
    DISCONNECT,
    PINGREQ,
    PINGRESP,
    PUBACK,
    PUBCOMP,
    PUBLISH,
    PUBREC,
    PUBREL,
    encode_connect,
    encode_packet,
    encode_publish,
    read_packet,
)
from sitewhere_tpu.ingest.sources import (
    EventSourcesManager,
    InboundEventSource,
    InMemoryEventReceiver,
)
from sitewhere_tpu.ingest.wire_edge import (
    SWP_ACK,
    SWP_ERR,
    SWP_MAGIC,
    SWP_SHED,
    AltIdRing,
    WireBatcher,
    WireEdge,
    WireEdgeConfig,
    aggregate_wire_snapshot,
    extract_alternate_id,
)
from sitewhere_tpu.utils.conservation import build_ledger, check_conservation

W_CFG = dict(device_capacity=64, token_capacity=128, assignment_capacity=128,
             store_capacity=2048, batch_capacity=32, channels=4)


class FixedEpoch(EpochBase):
    """Deterministic received_ms so paired executions stamp identical rows."""

    def __init__(self, now_ms: int = 500_000):
        super().__init__(0.0)
        self._now = now_ms

    def now_ms(self) -> int:
        return self._now


class FakeEngine:
    """Engine facade for protocol tests: records batch-ingest calls, no
    jax. ``qos=None`` admits everything (utils/qos.admit_or_raise)."""

    def __init__(self):
        self.qos = None
        self.wal = None
        self.wire_edges = []
        self.json_batches: list[tuple[list[bytes], str]] = []
        self.binary_batches: list[tuple[list[bytes], str]] = []

    def ingest_json_batch(self, payloads, tenant="default", **kw):
        self.json_batches.append((list(payloads), tenant))
        return {"rows": len(payloads)}

    def ingest_binary_batch(self, payloads, tenant="default", **kw):
        self.binary_batches.append((list(payloads), tenant))
        return {"rows": len(payloads)}


class _DenyAll:
    """QoS gate refusing every admission (forces the shed paths)."""

    def admit(self, tenant, n):
        return types.SimpleNamespace(admitted=False, retry_after_s=0.25,
                                     reason="rate")


def _payload(i, dev=6):
    return json.dumps({
        "deviceToken": f"wd-{i % dev}", "type": "DeviceMeasurement",
        "request": {"name": "temp", "value": 20.0 + i,
                    "eventDate": 1_000 + 10 * i},
    }).encode()


def _alt_payload(alt, i=0):
    return json.dumps({
        "deviceToken": "wd-0", "type": "DeviceMeasurement",
        "request": {"name": "temp", "value": 1.0 + i, "eventDate": 1_000,
                    "alternateId": alt},
    }).encode()


# --- alternate-id byte scan --------------------------------------------------


def test_extract_alternate_id_variants():
    assert extract_alternate_id(_alt_payload("m-7")) == "m-7"
    assert extract_alternate_id(b'{"alternateId" \t:\n "a b"}') == "a b"
    assert extract_alternate_id(b'{"alternateId": "q\\"x"}') == 'q"x'
    assert extract_alternate_id(_payload(0)) is None          # key absent
    assert extract_alternate_id(b'{"alternateId": 12}') is None   # non-str
    assert extract_alternate_id(b'{"alternateId": "open') is None  # truncated
    assert extract_alternate_id(b'{"alternateId"}') is None   # no colon


def test_alt_id_ring_bounded_fifo():
    ring = AltIdRing(capacity=3)
    for x in ("a", "b", "c"):
        ring.add(x)
    assert all(ring.seen(x) for x in ("a", "b", "c"))
    ring.add("d")                       # evicts "a" (FIFO)
    assert not ring.seen("a")
    assert ring.seen("d") and ring.seen("b")
    ring.add("b")                       # re-add of a member is a no-op
    ring.add("e")                       # evicts "b" (original position)
    assert not ring.seen("b")


# --- WireBatcher -------------------------------------------------------------


def test_batcher_size_flush_and_run_splitting():
    eng = FakeEngine()
    b = WireBatcher(eng, flush_rows=64, auto=False)
    # arrival order: t1 json, t1 json, t2 json, t1 binary, t1 binary
    b.add(b"a", tenant="t1")
    b.add(b"b", tenant="t1")
    b.add(b"c", tenant="t2")
    b.add(b"x", tenant="t1", binary=True)
    b.add(b"y", tenant="t1", binary=True)
    assert b.pending == 5
    assert b.flush() == 5
    assert b.pending == 0
    # one engine call per (tenant, format) run, arrival order preserved
    assert eng.json_batches == [([b"a", b"b"], "t1"), ([b"c"], "t2")]
    assert eng.binary_batches == [([b"x", b"y"], "t1")]
    c = b.counters()
    assert c["rows_submitted"] == 5
    assert c["flushes"] == c["flushes_drain"] == 1
    assert c["flush_rows_sum"] == 5
    b.close()


def test_batcher_auto_size_threshold():
    eng = FakeEngine()
    b = WireBatcher(eng, flush_rows=4, flush_interval_s=30.0, auto=True)
    done = threading.Event()
    for i in range(4):
        b.add(b"p%d" % i, on_durable=done.set if i == 3 else None)
    assert done.wait(5.0), "size-threshold flush never fired"
    assert eng.json_batches == [([b"p0", b"p1", b"p2", b"p3"], "default")]
    assert b.counters()["flushes_size"] == 1
    b.close()


def test_batcher_auto_deadline_flush():
    """Sub-threshold arrival windows drain at the deadline — the fix for
    the flusher never arming its timer on the first frame."""
    eng = FakeEngine()
    b = WireBatcher(eng, flush_rows=100, flush_interval_s=0.05, auto=True)
    acked = []
    for i in range(3):
        b.add(b"d%d" % i, on_durable=lambda i=i: acked.append(i))
    deadline_fired = threading.Event()
    b.add_barrier(deadline_fired.set)
    assert deadline_fired.wait(5.0), "deadline flush never fired"
    assert eng.json_batches == [([b"d0", b"d1", b"d2"], "default")]
    assert acked == [0, 1, 2]           # ack order == ingest order
    assert b.counters()["flushes_deadline"] >= 1
    b.close()


def test_batcher_shed_withholds_acks():
    eng = FakeEngine()
    from sitewhere_tpu.utils.qos import ShedError

    def raise_shed(payloads, tenant="default", **kw):
        raise ShedError("arena stall", tenant=tenant, retry_after_s=0.1,
                        reason="stall")
    eng.ingest_json_batch = raise_shed
    b = WireBatcher(eng, flush_rows=64, auto=False)
    acks, stalls = [], []
    b.add(b"s0", on_durable=lambda: acks.append(0),
          on_stall=lambda e: stalls.append(e))
    assert b.flush() == 0
    # the frame was never staged: ack withheld, stall surfaced, counted
    assert acks == []
    assert len(stalls) == 1 and stalls[0].reason == "stall"
    assert b.counters()["frames_stalled"] == 1
    b.close()


def test_batcher_closed_raises():
    b = WireBatcher(FakeEngine(), auto=False)
    b.close()
    with pytest.raises(RuntimeError):
        b.add(b"late")
    with pytest.raises(RuntimeError):
        b.add_barrier(lambda: None)


# --- sources: batched submit API (satellite) --------------------------------


def test_source_routes_through_batched_submit():
    eng = FakeEngine()
    batcher = WireBatcher(eng, flush_rows=64, auto=False)
    mgr = EventSourcesManager(on_event_request=lambda r: None,
                              batcher=batcher)
    recv = InMemoryEventReceiver()
    src = InboundEventSource("batched", JsonDeviceRequestDecoder(), [recv])
    mgr.add_source(src)
    # a batchable decoder (wire_tag) inherits the manager's batcher
    assert src.batcher is batcher
    fired = []
    for i in range(3):
        recv.submit(_payload(i), on_durable=lambda i=i: fired.append(i))
    # payloads ride the arrival window by reference — no per-event
    # decode, no per-event engine call, acks gated on the flush
    assert src.batched_count == 3 and src.decoded_count == 0
    assert batcher.pending == 3 and eng.json_batches == [] and fired == []
    batcher.flush()
    assert eng.json_batches == [([_payload(0), _payload(1), _payload(2)],
                                 "default")]
    assert fired == [0, 1, 2]
    batcher.close()


def test_source_per_payload_path_acks_synchronously():
    eng = FakeEngine()
    got = []
    mgr = EventSourcesManager(on_event_request=got.append)
    recv = InMemoryEventReceiver()
    mgr.add_source(InboundEventSource("plain", JsonDeviceRequestDecoder(),
                                      [recv]))
    fired = []
    recv.submit(_payload(0), on_durable=lambda: fired.append("ok"))
    assert len(got) == 1 and fired == ["ok"]
    # decode failure still releases the sender (dead letter, then ack)
    recv.submit(b"not json", on_durable=lambda: fired.append("dlq"))
    assert fired == ["ok", "dlq"]


def test_source_batcher_dedup_mutually_exclusive():
    with pytest.raises(ValueError):
        InboundEventSource("x", JsonDeviceRequestDecoder(),
                           [InMemoryEventReceiver()],
                           deduplicator=AlternateIdDeduplicator(),
                           batcher=WireBatcher(FakeEngine(), auto=False))


# --- MQTT server: adversarial framing ---------------------------------------


def _edge_cfg(**kw):
    base = dict(mqtt_port=0, tcp_port=None, flush_rows=1,
                flush_interval_s=0.01)
    base.update(kw)
    return WireEdgeConfig(**base)


async def _mqtt_connect(port, keepalive=0, fragment=False):
    r, w = await asyncio.open_connection("127.0.0.1", port)
    pkt = encode_connect("t-client", keepalive=keepalive)
    if fragment:
        for i in range(len(pkt)):
            w.write(pkt[i:i + 1])
            await w.drain()
            await asyncio.sleep(0.001)
    else:
        w.write(pkt)
        await w.drain()
    ptype, _, body = await asyncio.wait_for(read_packet(r), 10)
    assert ptype == CONNACK and body == b"\x00\x00"
    return r, w


def test_mqtt_fragmented_frames_across_varint_boundary():
    """Byte-at-a-time delivery of CONNECT and of a PUBLISH whose
    remaining length needs a 2-byte varint (>127) must frame exactly as
    contiguous delivery would."""
    eng = FakeEngine()

    async def run():
        edge = WireEdge(eng, _edge_cfg())
        await edge.start()
        try:
            r, w = await _mqtt_connect(edge.mqtt_port, fragment=True)
            payload = _payload(0) + b" " * 160     # force 2-byte varint
            pkt = encode_publish("swtpu/default/events", payload, qos=1,
                                 packet_id=3)
            assert len(pkt) > 129                  # varint spans 2 bytes
            for i in range(len(pkt)):
                w.write(pkt[i:i + 1])
                await w.drain()
                await asyncio.sleep(0.0005)
            ptype, _, body = await asyncio.wait_for(read_packet(r), 10)
            assert ptype == PUBACK
            assert int.from_bytes(body[:2], "big") == 3
            w.close()
        finally:
            await edge.stop()

    asyncio.run(run())
    assert eng.json_batches == [([_payload(0) + b" " * 160], "default")]


def test_mqtt_qos1_duplicate_redelivery_no_double_ingest():
    """QoS 1 redelivery of an alternateId-bearing frame (lost PUBACK)
    regenerates the ack WITHOUT a second ingest."""
    eng = FakeEngine()
    snap = {}

    async def run():
        edge = WireEdge(eng, _edge_cfg())
        await edge.start()
        try:
            r, w = await _mqtt_connect(edge.mqtt_port)
            dup = _alt_payload("alt-42")
            for pid in (7, 8):          # second offer = DUP redelivery
                w.write(encode_publish("swtpu/default/events", dup,
                                       qos=1, packet_id=pid))
                await w.drain()
                ptype, _, body = await asyncio.wait_for(read_packet(r), 10)
                assert ptype == PUBACK  # both offers acked...
                assert int.from_bytes(body[:2], "big") == pid
            w.close()
            snap.update(edge.snapshot())
        finally:
            await edge.stop()

    asyncio.run(run())
    # ...but exactly one ingest reached the engine
    assert eng.json_batches == [([_alt_payload("alt-42")], "default")]
    assert snap["frames_received"] == 2
    assert snap["frames_admitted"] == 1
    assert snap["frames_duplicate"] == 1


def test_mqtt_qos2_park_release_single_ingest():
    eng = FakeEngine()

    async def run():
        edge = WireEdge(eng, _edge_cfg())
        await edge.start()
        try:
            r, w = await _mqtt_connect(edge.mqtt_port)
            pub = encode_publish("swtpu/default/events", _payload(1),
                                 qos=2, packet_id=9)
            w.write(pub)
            await w.drain()
            ptype, _, body = await asyncio.wait_for(read_packet(r), 10)
            assert ptype == PUBREC
            # redelivered PUBLISH with the same pid replaces the parked
            # copy — never a second ingest
            w.write(pub)
            await w.drain()
            ptype, _, _ = await asyncio.wait_for(read_packet(r), 10)
            assert ptype == PUBREC
            w.write(encode_packet(PUBREL, 2, (9).to_bytes(2, "big")))
            await w.drain()
            ptype, _, body = await asyncio.wait_for(read_packet(r), 10)
            assert ptype == PUBCOMP
            assert int.from_bytes(body[:2], "big") == 9
            w.close()
        finally:
            await edge.stop()

    asyncio.run(run())
    assert eng.json_batches == [([_payload(1)], "default")]


def test_mqtt_oversized_frame_rejected_before_body():
    eng = FakeEngine()
    snap = {}

    async def run():
        edge = WireEdge(eng, _edge_cfg(max_frame_bytes=64))
        await edge.start()
        try:
            r, w = await _mqtt_connect(edge.mqtt_port)
            w.write(encode_publish("swtpu/default/events", b"z" * 256,
                                   qos=1, packet_id=1))
            await w.drain()
            # server drops the connection without reading the body
            assert await asyncio.wait_for(r.read(16), 10) == b""
            snap.update(edge.snapshot())
        finally:
            await edge.stop()

    asyncio.run(run())
    assert snap["frames_invalid"] == 1
    assert eng.json_batches == []


def test_mqtt_keepalive_timeout_disconnects():
    eng = FakeEngine()
    snap = {}

    async def run():
        edge = WireEdge(eng, _edge_cfg(keepalive_grace=0.3))
        await edge.start()
        try:
            r, w = await _mqtt_connect(edge.mqtt_port, keepalive=1)
            # a PINGREQ inside the window keeps the session alive
            w.write(encode_packet(PINGREQ, 0, b""))
            await w.drain()
            ptype, _, _ = await asyncio.wait_for(read_packet(r), 10)
            assert ptype == PINGRESP
            # then silence past 1.5x-style grace: server must hang up
            assert await asyncio.wait_for(r.read(16), 10) == b""
            snap.update(edge.snapshot())
        finally:
            await edge.stop()

    asyncio.run(run())
    assert snap["keepalive_timeouts"] == 1
    assert snap["connections_live"] == 0


def test_mqtt_shed_withholds_puback_and_disconnects():
    eng = FakeEngine()
    eng.qos = _DenyAll()
    snap = {}

    async def run():
        edge = WireEdge(eng, _edge_cfg())
        await edge.start()
        try:
            r, w = await _mqtt_connect(edge.mqtt_port)
            w.write(encode_publish("swtpu/default/events", _payload(0),
                                   qos=1, packet_id=5))
            await w.drain()
            # no PUBACK ever — the connection closes so the client's
            # redelivery loop backs off
            assert await asyncio.wait_for(r.read(16), 10) == b""
            snap.update(edge.snapshot())
        finally:
            await edge.stop()

    asyncio.run(run())
    assert snap["frames_shed"] == 1
    assert snap["frames_admitted"] == 0
    assert snap["backpressure_events"] == 1
    assert eng.json_batches == []


# --- SWP server --------------------------------------------------------------


async def _swp_connect(port, tenant=b"default", fmt=b"json"):
    r, w = await asyncio.open_connection("127.0.0.1", port)
    w.write(SWP_MAGIC + b" " + tenant + b" " + fmt + b"\n")
    await w.drain()
    return r, w


async def _swp_rec(r, timeout=10):
    code, val = struct.unpack("!BI", await asyncio.wait_for(
        r.readexactly(5), timeout))
    return code, val


def test_swp_cumulative_durable_acks():
    eng = FakeEngine()

    async def run():
        edge = WireEdge(eng, WireEdgeConfig(
            mqtt_port=None, tcp_port=0, flush_rows=64,
            flush_interval_s=5.0))
        await edge.start()
        try:
            r, w = await _swp_connect(edge.tcp_port)
            for i in range(3):
                p = _payload(i)
                w.write(struct.pack("!I", len(p)) + p)
            w.write(struct.pack("!I", 0))      # flush hint
            await w.drain()
            acked = 0
            while acked < 3:
                code, acked = await _swp_rec(r)
                assert code == SWP_ACK
            w.close()
        finally:
            await edge.stop()

    asyncio.run(run())
    # one arrival window -> ONE engine call for all three frames
    assert eng.json_batches == [([_payload(0), _payload(1), _payload(2)],
                                 "default")]


def test_swp_bad_handshake_and_oversize():
    eng = FakeEngine()
    snaps = []

    async def run():
        edge = WireEdge(eng, WireEdgeConfig(
            mqtt_port=None, tcp_port=0, max_frame_bytes=64))
        await edge.start()
        try:
            r, w = await asyncio.open_connection("127.0.0.1", edge.tcp_port)
            w.write(b"NOTSWP default json\n")
            await w.drain()
            code, val = await _swp_rec(r)
            assert code == SWP_ERR and val == 64
            w.close()
            r, w = await _swp_connect(edge.tcp_port)
            w.write(struct.pack("!I", 4096))   # oversized length prefix
            await w.drain()
            code, val = await _swp_rec(r)
            assert code == SWP_ERR and val == 64
            w.close()
            snaps.append(edge.snapshot())
        finally:
            await edge.stop()

    asyncio.run(run())
    assert snaps[0]["frames_invalid"] == 2
    assert eng.json_batches == []


def test_swp_shed_code_carries_retry_after():
    eng = FakeEngine()
    eng.qos = _DenyAll()

    async def run():
        edge = WireEdge(eng, WireEdgeConfig(mqtt_port=None, tcp_port=0))
        await edge.start()
        try:
            r, w = await _swp_connect(edge.tcp_port)
            p = _payload(0)
            w.write(struct.pack("!I", len(p)) + p)
            await w.drain()
            code, retry_ms = await _swp_rec(r)
            assert code == SWP_SHED
            assert retry_ms == 250             # _DenyAll's 0.25s
            w.close()
        finally:
            await edge.stop()

    asyncio.run(run())
    assert eng.json_batches == []


# --- byte-identity vs the direct batch-ingest path ---------------------------


def _make_engines(kind, scan_chunk):
    if kind == "engine":
        mk = lambda: Engine(EngineConfig(**W_CFG))
    else:
        from sitewhere_tpu.parallel.sharded import SpmdEngine

        mk = lambda: SpmdEngine(
            EngineConfig(**{**W_CFG, "scan_chunk": scan_chunk}), n_shards=2)
    a, b = mk(), mk()
    for e in (a, b):
        e.epoch = FixedEpoch()
    return a, b


def _settle(e):
    e.flush()
    for fn in ("barrier", "drain"):
        m = getattr(e, fn, None)
        if m is not None:
            m()


def _assert_store_identical(a, b):
    sa, sb = jax.device_get(a.state.store), jax.device_get(b.state.store)
    for f in dataclasses.fields(sa):
        va, vb = getattr(sa, f.name), getattr(sb, f.name)
        assert np.array_equal(np.asarray(va), np.asarray(vb)), \
            f"store field {f.name} diverged"


@pytest.mark.parametrize("kind,scan_chunk", [
    ("engine", None), ("spmd", 1), ("spmd", 2),
])
def test_wire_batched_path_byte_identical(kind, scan_chunk):
    """Frames through the wire batcher == direct ingest_json_batch with
    the same batch boundaries: identical store bytes, identical
    metrics() dict, conservation clean — Engine and SpmdEngine, packed
    and unpacked scan."""
    a, b = _make_engines(kind, scan_chunk)
    batcher = WireBatcher(a, flush_rows=16, auto=False)
    payloads = [_payload(i) for i in range(48)]
    for lo in range(0, len(payloads), 16):
        chunk = payloads[lo:lo + 16]
        for p in chunk:
            batcher.add(p)
        batcher.flush()                 # same split as the oracle call
        b.ingest_json_batch(chunk)
    _settle(a)
    _settle(b)
    _assert_store_identical(a, b)
    assert a.metrics() == b.metrics()
    for e in (a, b):
        assert check_conservation(build_ledger(e)) == []
    batcher.close()


def test_swp_socket_byte_identical_and_conservation():
    """End-to-end: live SWP frames -> edge -> arena path, vs the oracle's
    direct batch calls. Also pins the conservation "wire" stage (present
    and falsifiable while the edge is attached) and the dispatch-shape
    equality of metrics() with an edge attached."""
    a, b = _make_engines("engine", None)
    payloads = [_payload(i) for i in range(32)]
    wire_violations = []
    perturbed = []

    async def run():
        edge = WireEdge(a, WireEdgeConfig(
            mqtt_port=None, tcp_port=0, flush_rows=16,
            flush_interval_s=5.0))
        await edge.start()
        r, w = await _swp_connect(edge.tcp_port)
        acked = 0
        for lo in range(0, len(payloads), 16):
            chunk = payloads[lo:lo + 16]
            for p in chunk:
                w.write(struct.pack("!I", len(p)) + p)
            w.write(struct.pack("!I", 0))      # flush hint: drain now
            await w.drain()
            while acked < lo + 16:             # ack barrier: same batch
                code, acked = await _swp_rec(r, timeout=60)  # split as
                assert code == SWP_ACK                       # the oracle
            b.ingest_json_batch(chunk)
        w.close()
        # an invalid frame (bad handshake) must balance too: it counts as
        # received AND invalid, not invalid-only (which would permanently
        # violate wire-frames for every malformed client)
        r2, w2 = await asyncio.open_connection("127.0.0.1", edge.tcp_port)
        w2.write(b"NOTSWP default json\n")
        await w2.drain()
        code, _ = await _swp_rec(r2)
        assert code == SWP_ERR
        w2.close()
        assert edge.snapshot()["frames_invalid"] == 1
        # conservation audits run while the edge is attached
        _settle(a)
        wire_violations.extend(check_conservation(build_ledger(a)))
        ledger = build_ledger(a)
        assert "wire" in ledger["stages"]
        # falsifiability: one phantom frame must be a Violation
        edge.frames_received += 1
        perturbed.extend(check_conservation(build_ledger(a)))
        edge.frames_received -= 1
        await edge.stop()

    asyncio.run(run())
    _settle(b)
    _assert_store_identical(a, b)
    # dispatch-shape equality pin: wire series never leak into metrics()
    assert a.metrics() == b.metrics()
    assert not any("wire" in k for k in a.metrics())
    assert wire_violations == []
    assert any(v.equation == "wire-frames" for v in perturbed)


# --- observability plane -----------------------------------------------------


def test_wire_scrape_series_only_with_edge_attached():
    from sitewhere_tpu.utils.metrics import MetricsRegistry, export_wire_metrics

    eng = FakeEngine()

    async def run():
        edge = WireEdge(eng, WireEdgeConfig(mqtt_port=None, tcp_port=0,
                                            flush_rows=64,
                                            flush_interval_s=5.0))
        await edge.start()
        try:
            r, w = await _swp_connect(edge.tcp_port)
            for i in range(2):
                p = _payload(i)
                w.write(struct.pack("!I", len(p)) + p)
            w.write(struct.pack("!I", 0))
            await w.drain()
            acked = 0
            while acked < 2:
                _, acked = await _swp_rec(r)
            reg = MetricsRegistry()
            export_wire_metrics(eng, reg)
            text = reg.expose_text()
            assert 'swtpu_wire_frames_total{disposition="admitted"} 2' in text
            assert 'swtpu_wire_frames_total{disposition="received"} 2' in text
            assert "swtpu_wire_connections_live 1" in text
            assert "swtpu_wire_rows_submitted_total 2" in text
            assert "swtpu_wire_flush_occupancy_pct" in text
            w.close()
        finally:
            await edge.stop()

    asyncio.run(run())
    # no edge attached -> the exporter emits nothing
    reg2_engine = FakeEngine()
    from sitewhere_tpu.utils.metrics import MetricsRegistry as _MR
    from sitewhere_tpu.utils.metrics import export_wire_metrics as _ex

    reg2 = _MR()
    _ex(reg2_engine, reg2)
    assert "swtpu_wire" not in reg2.expose_text()
    assert aggregate_wire_snapshot(reg2_engine) is None


def test_batcher_on_staged_fires_only_on_success():
    """on_staged (the dedup-ring commit point) fires for staged frames
    only — a shed run's hook never fires."""
    eng = FakeEngine()
    from sitewhere_tpu.utils.qos import ShedError

    calls = {"n": 0}

    def shed_once(payloads, tenant="default", **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise ShedError("arena stall", tenant=tenant,
                            retry_after_s=0.1, reason="stall")
        eng.json_batches.append((list(payloads), tenant))
        return {"rows": len(payloads)}
    eng.ingest_json_batch = shed_once
    b = WireBatcher(eng, flush_rows=64, auto=False)
    staged = []
    b.add(b"s0", on_staged=lambda: staged.append(0))
    b.flush()
    assert staged == []                 # stalled: no commit
    b.add(b"s0", on_staged=lambda: staged.append(1))
    b.flush()
    assert staged == [1]                # staged: committed
    b.close()


def test_shed_frame_leaves_no_dedup_entry_redelivery_reingested():
    """A frame shed at admission must NOT poison the dedup ring: the
    client's redelivery (same alternateId) is re-admitted and ingested,
    never acked as a duplicate of an ingest that didn't happen."""
    eng = FakeEngine()
    eng.qos = _DenyAll()
    snap = {}

    async def run():
        edge = WireEdge(eng, WireEdgeConfig(
            mqtt_port=None, tcp_port=0, flush_rows=1,
            flush_interval_s=0.01))
        await edge.start()
        try:
            r, w = await _swp_connect(edge.tcp_port)
            p = _alt_payload("shed-1")
            w.write(struct.pack("!I", len(p)) + p)
            await w.drain()
            code, _ = await _swp_rec(r)
            assert code == SWP_SHED
            eng.qos = None              # pressure clears; client resends
            w.write(struct.pack("!I", len(p)) + p)
            await w.drain()
            code, acked = await _swp_rec(r)
            assert code == SWP_ACK and acked == 1
            w.close()
            snap.update(edge.snapshot())
        finally:
            await edge.stop()

    asyncio.run(run())
    assert eng.json_batches == [([_alt_payload("shed-1")], "default")]
    assert snap["frames_shed"] == 1
    assert snap["frames_admitted"] == 1
    assert snap["frames_duplicate"] == 0


def test_stalled_frame_leaves_no_dedup_entry_redelivery_reingested():
    """Same ack-without-ingest hole via the other path: admitted but the
    run STALLS (arena shed inside the engine call). The redelivery must
    ingest; the ring committed nothing for the stalled frame."""
    from sitewhere_tpu.utils.qos import ShedError

    eng = FakeEngine()
    calls = {"n": 0}

    def stall_once(payloads, tenant="default", **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise ShedError("arena stall", tenant=tenant,
                            retry_after_s=0.05, reason="stall")
        eng.json_batches.append((list(payloads), tenant))
        return {"rows": len(payloads)}
    eng.ingest_json_batch = stall_once
    snap = {}

    async def run():
        edge = WireEdge(eng, WireEdgeConfig(
            mqtt_port=None, tcp_port=0, flush_rows=1,
            flush_interval_s=0.01))
        await edge.start()
        try:
            r, w = await _swp_connect(edge.tcp_port)
            p = _alt_payload("stall-1")
            w.write(struct.pack("!I", len(p)) + p)
            await w.drain()
            code, _ = await _swp_rec(r)
            assert code == SWP_SHED     # stall surfaced, ack withheld
            w.write(struct.pack("!I", len(p)) + p)
            await w.drain()
            code, acked = await _swp_rec(r)
            assert code == SWP_ACK and acked == 1
            w.close()
            snap.update(edge.snapshot())
        finally:
            await edge.stop()

    asyncio.run(run())
    assert eng.json_batches == [([_alt_payload("stall-1")], "default")]
    assert snap["frames_stalled"] == 1
    assert snap["frames_duplicate"] == 0
    assert snap["frames_admitted"] == 2     # both offers were admitted


def test_dedup_key_scoped_by_tenant_and_device():
    """The ring keys by (tenant, deviceToken, alternateId) — the repo's
    established dedup triple. An alternateId reused across tenants or
    devices is NOT a duplicate; only the full triple dedups."""
    eng = FakeEngine()
    snap = {}

    def _pay(dev, alt):
        return json.dumps({
            "deviceToken": dev, "type": "DeviceMeasurement",
            "request": {"name": "temp", "value": 1.0, "eventDate": 1_000,
                        "alternateId": alt},
        }).encode()

    async def run():
        edge = WireEdge(eng, _edge_cfg())
        await edge.start()
        try:
            r, w = await _mqtt_connect(edge.mqtt_port)
            offers = [
                ("swtpu/t1/events", _pay("wd-0", "seq-1")),   # ingests
                ("swtpu/t2/events", _pay("wd-0", "seq-1")),   # other tenant
                ("swtpu/t1/events", _pay("wd-1", "seq-1")),   # other device
                ("swtpu/t1/events", _pay("wd-0", "seq-1")),   # true dup
            ]
            for pid, (topic, payload) in enumerate(offers, start=1):
                w.write(encode_publish(topic, payload, qos=1,
                                       packet_id=pid))
                await w.drain()
                ptype, _, body = await asyncio.wait_for(read_packet(r), 10)
                assert ptype == PUBACK
                assert int.from_bytes(body[:2], "big") == pid
            w.close()
            snap.update(edge.snapshot())
        finally:
            await edge.stop()

    asyncio.run(run())
    assert snap["frames_admitted"] == 3
    assert snap["frames_duplicate"] == 1
    assert [t for _, t in eng.json_batches] == ["t1", "t2", "t1"]


def test_mqtt_qos2_shed_release_withholds_pubcomp_until_ingest():
    """QoS 2 exactly-once under shed: a PUBREL whose released frame is
    shed must NOT complete on the client's PUBREL retransmission — the
    payload re-parks, PUBCOMP stays withheld until a release actually
    stages. PUBCOMP therefore implies ingest."""
    eng = FakeEngine()
    eng.qos = _DenyAll()

    async def run():
        edge = WireEdge(eng, _edge_cfg())
        await edge.start()
        try:
            r, w = await _mqtt_connect(edge.mqtt_port)
            w.write(encode_publish("swtpu/default/events", _payload(2),
                                   qos=2, packet_id=11))
            await w.drain()
            ptype, _, _ = await asyncio.wait_for(read_packet(r), 10)
            assert ptype == PUBREC
            rel = encode_packet(PUBREL, 2, (11).to_bytes(2, "big"))
            # release is shed twice; neither may produce a PUBCOMP
            for _ in range(2):
                w.write(rel)
                await w.drain()
                with pytest.raises(asyncio.TimeoutError):
                    await asyncio.wait_for(read_packet(r), 0.3)
            eng.qos = None          # pressure clears
            w.write(rel)
            await w.drain()
            ptype, _, body = await asyncio.wait_for(read_packet(r), 10)
            assert ptype == PUBCOMP
            assert int.from_bytes(body[:2], "big") == 11
            # pid settled: one more PUBREL is a true duplicate -> re-comp
            w.write(rel)
            await w.drain()
            ptype, _, _ = await asyncio.wait_for(read_packet(r), 10)
            assert ptype == PUBCOMP
            w.close()
        finally:
            await edge.stop()

    asyncio.run(run())
    # exactly ONE ingest despite four PUBRELs
    assert eng.json_batches == [([_payload(2)], "default")]


def test_aggregate_multi_edge_peak_and_occupancy():
    """Multi-edge aggregation: counters sum, but connections_peak is a
    max and flush occupancy a capacity-weighted mean — two edges at 80%
    report 80%, not 160%."""
    eng = FakeEngine()
    cfg = WireEdgeConfig(mqtt_port=None, tcp_port=None, flush_rows=100)
    e1, e2 = WireEdge(eng, cfg), WireEdge(eng, cfg)
    eng.wire_edges = [e1, e2]
    for edge, peak, flushes, rows in ((e1, 5, 10, 800), (e2, 3, 10, 800)):
        edge.connections_peak = peak
        edge.frames_received = edge.frames_admitted = rows
        b = edge.batchers[0]
        b.flushes_drain = flushes
        b.flush_rows_sum = b.rows_submitted = rows
    total = aggregate_wire_snapshot(eng)
    assert total["connections_peak"] == 5           # max, not 8
    assert total["flush_occupancy_pct"] == 80.0     # weighted, not 160
    assert total["frames_received"] == 1600          # counters still sum
    assert total["flushes"] == 20
    for e in (e1, e2):
        e.batchers[0].close()


def test_wire_snapshot_disposition_balance():
    """Every disposition path in one session: the snapshot's own terms
    satisfy the wire-frames equation the ledger checks."""
    eng = FakeEngine()
    snap = {}

    async def run():
        edge = WireEdge(eng, _edge_cfg(max_frame_bytes=4096))
        await edge.start()
        try:
            r, w = await _mqtt_connect(edge.mqtt_port)
            dup = _alt_payload("bal-1")
            w.write(encode_publish("swtpu/default/events", dup, qos=1,
                                   packet_id=1))
            await w.drain()
            # PUBACK implies the frame staged (ring committed) — only
            # then is a redelivery classified duplicate
            ptype, _, _ = await asyncio.wait_for(read_packet(r), 10)
            assert ptype == PUBACK
            w.write(encode_publish("swtpu/default/events", dup, qos=1,
                                   packet_id=2))          # duplicate
            w.write(encode_publish("swtpu/default/events", _payload(3),
                                   qos=1, packet_id=3))   # admitted
            await w.drain()
            for _ in range(2):
                ptype, _, _ = await asyncio.wait_for(read_packet(r), 10)
                assert ptype == PUBACK
            w.write(encode_packet(DISCONNECT, 0, b""))
            await w.drain()
            w.close()
            snap.update(edge.snapshot())
        finally:
            await edge.stop()

    asyncio.run(run())
    assert snap["frames_received"] == (
        snap["frames_admitted"] + snap["frames_shed"]
        + snap["frames_invalid"] + snap["frames_duplicate"])
    assert snap["frames_admitted"] == (
        snap["rows_submitted"] + snap["frames_stalled"] + snap["pending"])
    assert snap["frames_duplicate"] == 1
