"""Multi-process (DCN-side) execution: the system running as 2 processes.

VERDICT r2 item 3: bring up jax.distributed on the CPU backend across two
processes, use multihost.local_shard_ids + assemble_stacked_batch, ingest
from both hosts, and assert global metrics/queries agree. The reference
analog is horizontally scaled replicas over partitioned consumer groups
(KafkaOutboundConnectorHost.java:43-257).

The job runs in SUBPROCESSES (each rank owns its own jax runtime); this
file only spawns and checks them, so the in-process CPU-mesh conftest
fixture is untouched.
"""

import jax
import pytest

from sitewhere_tpu.parallel.multihost_demo import spawn_two_process_demo

# jax 0.4.x CPU backend: "Multiprocess computations aren't implemented on
# the CPU backend" — the cross-process CPU collective path arrived later,
# so this test can only run on newer runtimes (or real accelerators)
_multiprocess_cpu = pytest.mark.skipif(
    tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5),
    reason="CPU-backend multiprocess collectives need jax >= 0.5")


@_multiprocess_cpu
def test_two_process_job_agrees_on_global_state():
    lines = spawn_two_process_demo(devices_per_proc=4)
    assert len(lines) == 2
    by_rank = sorted(lines)
    assert "rank=0/2" in by_rank[0] and "rank=1/2" in by_rank[1]
    # both ranks computed identical global totals over the 8-shard mesh
    tail0 = by_rank[0].split("persisted=")[1]
    tail1 = by_rank[1].split("persisted=")[1]
    assert tail0 == tail1
    # 3 steps x 8 events x 8 shards, all visible and all marked missing by
    # the mesh-wide presence sweep
    assert "persisted=192" in by_rank[0] and "store_valid=192" in by_rank[0]
    assert "missing=64" in by_rank[0]
    # disjoint shard ownership: rank 0 owns 0-3, rank 1 owns 4-7
    assert "shards=[0, 1, 2, 3]" in by_rank[0]
    assert "shards=[4, 5, 6, 7]" in by_rank[1]
