"""Durable cross-rank forwarding (VERDICT r4 missing #2).

Reference model: the ingest edge hands events to a durable partitioned
Kafka topic (DecodedEventsProducer.java:17-28) — a consumer replica being
down never loses data. Here unreachable-owner sub-batches spill to a
CRC'd per-peer disk queue, retry in the background, dead-letter after a
budget, and redeliveries are suppressed by an owner-side forward-id
registry (parallel/forward.py)."""

import json
import time

import pytest

from sitewhere_tpu.parallel.cluster import (ClusterConfig, ClusterEngine,
                                            build_cluster_rpc)
from sitewhere_tpu.parallel.distributed import DistributedEngine
from sitewhere_tpu.parallel.forward import ForwardQueue, SpillRegistry
from tests.test_cluster import (BASE_S, _engine_cfg, _free_ports,
                                _ServerHost, meas, tokens_owned_by)


def _mk_forwarding_cluster(tmp_path, connect_timeout_s=2.0):
    """Two ranks with durable forwarding attached; rank 1's RPC server is
    returned so tests can stop/restart it (the 'owner goes down' lever)."""
    ports = _free_ports(2)
    peers = [f"127.0.0.1:{p}" for p in ports]
    host = _ServerHost()
    clusters, queues, regs, servers = [], [], [], []
    for r in range(2):
        cc = ClusterConfig(rank=r, n_ranks=2, peers=peers,
                           secret="fwd-secret", epoch_base_unix_s=BASE_S,
                           engine=_engine_cfg(tmp_path, r),
                           connect_timeout_s=connect_timeout_s)
        c = ClusterEngine(cc)
        q = ForwardQueue(c, tmp_path / f"fwd-r{r}", retry_budget_s=300.0)
        reg = SpillRegistry(tmp_path / f"fwd-r{r}" / "registry")
        c.attach_forwarding(q, reg)
        srv = build_cluster_rpc(c.local, "fwd-secret")
        host.start(srv, ports[r])
        clusters.append(c)
        queues.append(q)
        regs.append(reg)
        servers.append(srv)
    return clusters, queues, regs, servers, host, ports


def _close(clusters, regs, host):
    for c in clusters:
        c.close()
    for reg in regs:
        reg.close()
    host.close()


def test_down_owner_spills_instead_of_raising_and_redelivers(tmp_path):
    """THE done-criterion: owner goes down mid-ingest, the batch is NOT
    lost and ingest_json_batch does not raise mid-batch; after the owner
    restarts, retry delivers everything exactly once."""
    clusters, queues, regs, servers, host, ports = \
        _mk_forwarding_cluster(tmp_path)
    c0, c1 = clusters
    try:
        local = tokens_owned_by(0, 2, prefix="fw")
        remote = tokens_owned_by(1, 2, prefix="fw")
        both = local + remote
        # warm path first: forwarding works while the owner is up
        s = c0.ingest_json_batch([meas(t, "t", 1.0, 100 + i)
                                  for i, t in enumerate(both)])
        assert s.get("staged") == 4 and "spilled" not in s
        # ---- owner rank 1 goes DOWN ----------------------------------
        host.stop(servers[1])
        s2 = c0.ingest_json_batch([meas(t, "t", 2.0, 200 + i)
                                   for i, t in enumerate(both)])
        # local share applied, remote share spilled — no exception, no
        # partial-batch loss
        assert s2["staged"] == 2 and s2["spilled"] == 2, s2
        m = queues[0].metrics()
        assert m["forward_queue_depth"] == 1
        assert m["forward_spilled_payloads"] == 2
        assert m["forward_queue_oldest_ms"] >= 0
        # retry while still down: stays queued, order preserved
        assert queues[0].retry_once() == 0
        assert queues[0].metrics()["forward_queue_depth"] == 1
        # ---- owner restarts (same engine, same port) -----------------
        srv1b = build_cluster_rpc(c1.local, "fwd-secret")
        host.start(srv1b, ports[1])
        assert queues[0].retry_once() == 1
        assert queues[0].metrics()["forward_queue_depth"] == 0
        c0.flush()
        # zero loss: every device has both rounds, exactly once
        for t in both:
            q = c0.query_events(device_token=t)
            assert q["total"] == 2, (t, q)
        # conservation (ISSUE 14): the forward-queue equation balances
        # through the spill/redeliver cycle — spilled == redelivered +
        # deadlettered + depth, and the rest of the sender's ledger too
        from sitewhere_tpu.utils.conservation import (build_ledger,
                                                      check_conservation)

        led = build_ledger(c0)
        assert not check_conservation(led)
        assert led["stages"]["forward"] == {
            "spilled_batches": 1, "redelivered_batches": 1,
            "deadlettered_batches": 0, "rerouted_batches": 0,
            "queue_depth": 0, "open_circuits": 0}
    finally:
        _close(clusters, regs, host)


def test_redelivery_is_suppressed_by_forward_registry(tmp_path):
    """A retry after a LOST RESPONSE (owner applied, sender never heard)
    must not double-ingest: the owner's registry remembers applied
    forward ids — across an owner registry restart too."""
    clusters, queues, regs, servers, host, ports = \
        _mk_forwarding_cluster(tmp_path)
    c0, c1 = clusters
    try:
        remote = tokens_owned_by(1, 1, prefix="dup")[0]
        payloads = [meas(remote, "t", 5.0, 500)]
        fid = c0._next_fid()
        import base64

        b64 = [base64.b64encode(p).decode() for p in payloads]
        s1 = c0._peer(1).call("Cluster.ingestForward", fid=fid,
                              payloads=b64, tenant="default",
                              encoding="json")
        assert s1["staged"] == 1
        # the "response was lost" replay: same fid again
        s2 = c0._peer(1).call("Cluster.ingestForward", fid=fid,
                              payloads=b64, tenant="default",
                              encoding="json")
        assert s2 == {"duplicate_forward": 1}
        # registry survives a restart (reload from its append log)
        regs[1].close()
        reg1b = SpillRegistry(tmp_path / "fwd-r1" / "registry")
        c1.attach_forwarding(queues[1], reg1b)
        regs[1] = reg1b
        s3 = c0._peer(1).call("Cluster.ingestForward", fid=fid,
                              payloads=b64, tenant="default",
                              encoding="json")
        assert s3 == {"duplicate_forward": 1}
        c0.flush()
        assert c0.query_events(device_token=remote)["total"] == 1
    finally:
        _close(clusters, regs, host)


def test_retry_budget_moves_to_deadletter_not_drops(tmp_path):
    clusters, queues, regs, servers, host, ports = \
        _mk_forwarding_cluster(tmp_path)
    c0 = clusters[0]
    try:
        host.stop(servers[1])
        remote = tokens_owned_by(1, 1, prefix="dl")[0]
        s = c0.ingest_json_batch([meas(remote, "t", 9.0, 900)])
        assert s.pop("trace_id", None)   # every ingest is traced
        assert s == {"spilled": 1}
        queues[0].retry_budget_s = 0.0   # budget exhausted immediately
        time.sleep(0.01)
        assert queues[0].retry_once() == 0
        m = queues[0].metrics()
        assert m["forward_deadlettered_batches"] == 1
        assert m["forward_queue_depth"] == 0
        # the data is preserved on disk, not dropped
        dl = list((tmp_path / "fwd-r0" / "deadletter").glob("*.json"))
        assert len(dl) == 1
        rec = json.loads(json.loads(dl[0].read_bytes())["body"])
        assert rec["kind"] == "json" and len(rec["payloads"]) == 1
    finally:
        _close(clusters, regs, host)


def test_envelope_forwarding_spills_and_redelivers(tmp_path):
    """The single-envelope path (process/protocol edges) gets the same
    durability as batches."""
    from sitewhere_tpu.ingest.decoders import request_from_envelope

    clusters, queues, regs, servers, host, ports = \
        _mk_forwarding_cluster(tmp_path)
    c0, c1 = clusters
    try:
        remote = tokens_owned_by(1, 1, prefix="env")[0]
        env = {"deviceToken": remote, "type": "DeviceMeasurements",
               "request": {"measurements": {"t": 3.0},
                           "eventDate": int(BASE_S * 1000) + 300}}
        host.stop(servers[1])
        req = request_from_envelope(env)
        req.tenant = "default"
        c0.process(req)                 # spills, does not raise
        assert queues[0].metrics()["forward_queue_depth"] == 1
        srv1b = build_cluster_rpc(c1.local, "fwd-secret")
        host.start(srv1b, ports[1])
        assert queues[0].retry_once() == 1
        c1.flush()
        assert c1.query_events(device_token=remote)["total"] == 1
    finally:
        _close(clusters, regs, host)


def test_circuit_breaker_spills_fast_after_first_failure(tmp_path):
    """After one failed forward, later batches to the same peer spill
    immediately (no per-batch connect timeout); the first successful
    retry closes the circuit and normal forwarding resumes."""
    clusters, queues, regs, servers, host, ports = \
        _mk_forwarding_cluster(tmp_path, connect_timeout_s=1.0)
    c0, c1 = clusters
    try:
        remote = tokens_owned_by(1, 1, prefix="cb")[0]
        host.stop(servers[1])
        s = c0.ingest_json_batch([meas(remote, "t", 1.0, 100)])
        assert s.pop("trace_id", None)   # every ingest is traced
        assert s == {"spilled": 1}
        assert queues[0].circuit_open(1)
        t0 = time.monotonic()
        s2 = c0.ingest_json_batch([meas(remote, "t", 2.0, 101)])
        fast = time.monotonic() - t0
        assert s2.pop("trace_id", None)
        assert s2 == {"spilled": 1}
        assert fast < 0.5, f"open circuit should spill instantly ({fast}s)"
        srv1b = build_cluster_rpc(c1.local, "fwd-secret")
        host.start(srv1b, ports[1])
        assert queues[0].retry_once() == 2
        assert not queues[0].circuit_open(1)
        # circuit closed: live forwarding again (not spilling)
        s3 = c0.ingest_json_batch([meas(remote, "t", 3.0, 102)])
        assert s3.get("staged") == 1 and "spilled" not in s3
        c0.flush()
        assert c0.query_events(device_token=remote)["total"] == 3
    finally:
        _close(clusters, regs, host)


def test_cluster_status_reports_down_peer_via_circuit(tmp_path):
    """With forwarding attached, a dead peer shows DOWN on the cluster
    status page WITHOUT the scrape paying a connect timeout (the open
    circuit answers), and the durability gauges ride along."""
    clusters, queues, regs, servers, host, ports = \
        _mk_forwarding_cluster(tmp_path, connect_timeout_s=1.0)
    c0 = clusters[0]
    try:
        remote = tokens_owned_by(1, 1, prefix="st")[0]
        host.stop(servers[1])
        c0.ingest_json_batch([meas(remote, "t", 1.0, 100)])  # trips circuit
        t0 = time.monotonic()
        s = c0.cluster_status()
        assert time.monotonic() - t0 < 0.5   # no connect attempt
        assert s["ranks"]["1"]["status"] == "DOWN"
        assert "circuit" in s["ranks"]["1"]["reason"]
        assert s["forwarding"]["forward_queue_depth"] == 1
        assert s["forwarding"]["forward_open_circuits"] == 1
        # per-rank metrics schema includes the forward gauges
        from sitewhere_tpu.parallel.cluster import local_rank_metrics

        lm = local_rank_metrics(c0.local)
        assert lm["forward_queue_depth"] == 1
        # the metrics surface DEGRADES, never 500s: the down rank shows
        # unreachable and the merged sums cover the live ranks only
        m = c0.metrics()
        assert m["by_rank"]["1"] == {"unreachable": 1,
                                     "reason": "forward circuit open"}
        assert m["by_rank"]["0"]["persisted"] == 0   # spilled, not local
        assert m["forward_queue_oldest_ms"] >= 0     # max-merged age
    finally:
        _close(clusters, regs, host)


def test_poison_batch_does_not_block_the_queue(tmp_path):
    """ISSUE 6 satellite: a deterministic owner-side reject (RpcError)
    must NOT head-of-line-block the batches spilled behind it — they
    deliver on the same pass, and the poison file dead-letters after K
    attempts instead of wedging the pump for the transport budget."""
    clusters, queues, regs, servers, host, ports = \
        _mk_forwarding_cluster(tmp_path)
    c0, c1 = clusters
    try:
        q = queues[0]
        q.app_reject_attempts = 3
        remote = tokens_owned_by(1, 1, prefix="poison")[0]
        # poison first (envelope the owner deterministically rejects),
        # a GOOD batch queued behind it
        q.spill(1, "envelope", "default", c0._next_fid(),
                envelope={"garbage": True})
        q.spill(1, "json", "default", c0._next_fid(),
                payloads=[meas(remote, "t", 1.0, 100)])
        # one pass: the good batch delivers DESPITE the poison ahead
        assert q.retry_once() == 1
        m = q.metrics()
        assert m["forward_retry_app_rejects"] == 1
        assert m["forward_retry_transport_failures"] == 0
        assert m["forward_queue_depth"] == 1   # only the poison remains
        c1.flush()
        assert c1.query_events(device_token=remote)["total"] == 1
        # after K=3 total attempts the poison dead-letters (preserved)
        assert q.retry_once() == 0
        assert q.retry_once() == 0
        m = q.metrics()
        assert m["forward_deadlettered_poison"] == 1
        assert m["forward_queue_depth"] == 0
        assert len(list((tmp_path / "fwd-r0" / "deadletter")
                        .glob("spill-*.json"))) == 1
    finally:
        _close(clusters, regs, host)


def test_transport_failures_still_preserve_order(tmp_path):
    """The poison fix must not weaken the transport contract: while the
    peer is DOWN, retry stops at the first file (order preserved), and
    both failure classes count separately."""
    clusters, queues, regs, servers, host, ports = \
        _mk_forwarding_cluster(tmp_path, connect_timeout_s=1.0)
    c0 = clusters[0]
    try:
        host.stop(servers[1])
        remote = tokens_owned_by(1, 2, prefix="ord")
        for i, t in enumerate(remote):
            c0.ingest_json_batch([meas(t, "t", float(i), 100 + i)])
        q = queues[0]
        assert q.metrics()["forward_queue_depth"] == 2
        assert q.retry_once() == 0          # down: nothing skips ahead
        m = q.metrics()
        assert m["forward_retry_transport_failures"] >= 1
        assert m["forward_retry_app_rejects"] == 0
        assert m["forward_queue_depth"] == 2
    finally:
        _close(clusters, regs, host)


def test_post_horizon_redelivery_rejected_not_reapplied(tmp_path):
    """ISSUE 6 satellite: the dedup registry's capacity is an explicit
    HORIZON — a redelivery older than the eviction watermark can no
    longer be proven un-applied, so it dead-letters (+counter) instead
    of silently double-applying; the watermark survives a restart."""
    import base64

    clusters, queues, regs, servers, host, ports = \
        _mk_forwarding_cluster(tmp_path)
    c0, c1 = clusters
    try:
        regs[1].close()
        small = SpillRegistry(tmp_path / "small-reg", capacity=4)
        c1.attach_forwarding(queues[1], small)
        regs[1] = small
        remote = tokens_owned_by(1, 1, prefix="hz")[0]

        def fwd(fid, ts_rel):
            p = base64.b64encode(meas(remote, "t", 1.0, ts_rel)).decode()
            return c0._peer(1).call("Cluster.ingestForward", fid=fid,
                                    payloads=[p], tenant="default",
                                    encoding="json")

        fids = [f"0-{1000 + i}-{i}" for i in range(7)]
        for i, fid in enumerate(fids):
            assert fwd(fid, 100 + i)["staged"] == 1
        # capacity 4 of 7: three evictions -> watermark at the newest
        # evicted fid's clock
        assert small.horizon_ns == 1002
        # post-horizon redelivery: REJECTED + preserved, never re-applied
        s = fwd(fids[0], 100)
        assert s == {"stale_forward": 1}
        assert small.metrics()["forward_stale_rejects"] == 1
        assert len(list((tmp_path / "small-reg" / "deadletter")
                        .glob("stale-*.json"))) == 1
        # an in-horizon redelivery still suppresses as a duplicate
        assert fwd(fids[-1], 106) == {"duplicate_forward": 1}
        c1.flush()
        assert c1.query_events(device_token=remote)["total"] == 7
        # the watermark is persistent state
        small.close()
        reopened = SpillRegistry(tmp_path / "small-reg", capacity=4)
        assert reopened.horizon_ns == 1002
        assert reopened.check(fids[0]) == "stale"
        regs[1] = reopened
        c1.attach_forwarding(queues[1], reopened)
    finally:
        _close(clusters, regs, host)
