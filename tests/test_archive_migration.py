"""Archive topology migration: history survives elastic reshard.

VERDICT r3 missing #2 — the reference's event history lives in
topology-agnostic external stores and survives any scaling event
(InfluxDbDeviceEventManagement.java:63-161). Here the archive is
partition-stamped, so a reshard must MIGRATE it: re-partition every
archived row under the new shard count and lift the new rings' positions
above the migrated history so ring + archive stay non-overlapping.
"""

import json

import pytest

from sitewhere_tpu.parallel.distributed import (DistributedConfig,
                                                DistributedEngine,
                                                restore_distributed)
from sitewhere_tpu.parallel.reshard import reshard_snapshot


def _mk(tmp_path, n_shards=4, store=64):
    return DistributedEngine(DistributedConfig(
        n_shards=n_shards, device_capacity_per_shard=64,
        token_capacity_per_shard=256, assignment_capacity_per_shard=256,
        store_capacity_per_shard=store, channels=4,
        batch_capacity_per_shard=16,
        archive_dir=str(tmp_path / "arch"), archive_segment_rows=8))


def _meas(eng, token, value, ts_rel):
    base = int(eng.epoch.base_unix_s * 1000)
    return json.dumps({
        "deviceToken": token, "type": "DeviceMeasurements",
        "request": {"measurements": {"m": value},
                    "eventDate": base + ts_rel}}).encode()


def _fill(eng, n_devices=24, rounds=40):
    """Ingest far past ring capacity so early history is archive-only."""
    for r in range(rounds):
        eng.ingest_json_batch(
            [_meas(eng, f"mig-{d}", float(r), r * 100 + d)
             for d in range(n_devices)])
        if r % 8 == 7:
            eng.flush_async()
    eng.flush()


def test_archive_migrates_through_reshard(tmp_path):
    eng = _mk(tmp_path)
    _fill(eng)
    want_total = eng.query_events(limit=1)["total"]
    assert want_total == 24 * 40
    # the first rounds live only in the archive by now
    early = eng.query_events(since_ms=0, until_ms=399, limit=200)
    assert early["total"] == 24 * 4
    early_key = [(e["deviceToken"], e["eventDateMs"])
                 for e in early["events"]]
    per_dev = eng.query_events(device_token="mig-3", limit=100)
    assert per_dev["total"] == 40

    eng.save(tmp_path / "snap")
    stats = reshard_snapshot(tmp_path / "snap", tmp_path / "resnap", 2,
                             archive_dir=tmp_path / "arch",
                             archive_dst=tmp_path / "arch2")
    mig = stats["archive_migration"]
    assert mig["migrated_rows"] > 0
    assert mig["dropped_unmapped_rows"] == 0

    eng2 = restore_distributed(tmp_path / "resnap")
    assert eng2.n_shards == 2
    # no loss, no duplicates — the headline invariant
    assert eng2.query_events(limit=1)["total"] == want_total
    # pre-reshard history answers identically (order + contents)
    early2 = eng2.query_events(since_ms=0, until_ms=399, limit=200)
    assert early2["total"] == early["total"]
    assert [(e["deviceToken"], e["eventDateMs"])
            for e in early2["events"]] == early_key
    # per-device history intact across the device-id renumbering
    assert eng2.query_events(device_token="mig-3", limit=100)["total"] == 40
    assert eng2.get_device_state("mig-3")["measurements"]["m"]["value"] \
        == 39.0

    # the resharded engine keeps WRITING through the migrated archive:
    # new events spill without colliding with migrated positions
    for r in range(40, 48):
        eng2.ingest_json_batch(
            [_meas(eng2, f"mig-{d}", float(r), r * 100 + d)
             for d in range(24)])
    eng2.flush()
    assert eng2.archive.lost_rows == 0
    assert eng2.query_events(limit=1)["total"] == want_total + 24 * 8
    assert eng2.query_events(device_token="mig-3", limit=100)["total"] == 48


def test_reshard_to_one_shard_preserves_overflow_in_archive(tmp_path):
    """4 rings -> 1 ring cannot hold everything: the overflow rows that a
    bare reshard would drop must land in the migrated archive instead."""
    eng = _mk(tmp_path)
    _fill(eng, n_devices=16, rounds=24)
    want_total = eng.query_events(limit=1)["total"]
    eng.save(tmp_path / "snap")
    stats = reshard_snapshot(tmp_path / "snap", tmp_path / "resnap", 1,
                             archive_dir=tmp_path / "arch",
                             archive_dst=tmp_path / "arch2")
    assert stats["archive_migration"]["preserved_overflow_rows"] > 0
    eng2 = restore_distributed(tmp_path / "resnap")
    assert eng2.query_events(limit=1)["total"] == want_total
    assert eng2.query_events(device_token="mig-5",
                             limit=100)["total"] == 24


def test_plain_reshard_keeps_archive_dir(tmp_path):
    """Review r4: a reshard WITHOUT migration must not silently disable
    the retention tier — the original archive_dir carries through (its
    old-topology files retire on reopen; fresh spill continues)."""
    eng = _mk(tmp_path, n_shards=2)
    _fill(eng, n_devices=8, rounds=12)
    eng.save(tmp_path / "snap")
    reshard_snapshot(tmp_path / "snap", tmp_path / "resnap", 1)
    eng2 = restore_distributed(tmp_path / "resnap")
    assert eng2.config.archive_dir == str(tmp_path / "arch")
    assert eng2.archive is not None
    # old-topology files were retired, not misread
    assert list((tmp_path / "arch").glob("retired-*"))


def test_feed_replay_counts_no_phantom_loss_over_migration_gap(tmp_path):
    """Review r4: the padding gap [H, bump*acap) never held data; a
    replaying consumer must skip it WITHOUT counting lag_lost."""
    eng = _mk(tmp_path)
    _fill(eng)
    want_total = eng.query_events(limit=1)["total"]
    eng.save(tmp_path / "snap")
    reshard_snapshot(tmp_path / "snap", tmp_path / "resnap", 2,
                     archive_dir=tmp_path / "arch",
                     archive_dst=tmp_path / "arch2")
    eng2 = restore_distributed(tmp_path / "resnap")
    feed = eng2.make_feed_consumer("gap-replay", max_batch=256)
    seen = 0
    while True:
        recs = feed.poll()
        if not recs:
            break
        seen += len(recs)
        feed.commit(recs)
    assert seen == want_total, (seen, want_total)
    assert feed.lag_lost == 0


def test_migration_refuses_foreign_archive(tmp_path):
    eng = _mk(tmp_path, n_shards=2)
    _fill(eng, n_devices=8, rounds=12)
    eng.save(tmp_path / "snap")
    # the archive carries a mesh/2x1 stamp; a 4-shard snapshot would
    # misread its partition indices — refused, never retired/migrated
    eng4 = _mk(tmp_path / "other", n_shards=4)
    _fill(eng4, n_devices=8, rounds=12)
    eng4.save(tmp_path / "snap4")
    with pytest.raises(ValueError, match="topology"):
        reshard_snapshot(tmp_path / "snap4", tmp_path / "re4", 2,
                         archive_dir=tmp_path / "arch",
                         archive_dst=tmp_path / "arch-bad")


def test_migrated_history_serves_over_rest(tmp_path):
    """The VERDICT done-bar: pre-reshard history through the REST event
    listings after an 8->4-style topology change."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from sitewhere_tpu.engine import EngineConfig
    from sitewhere_tpu.instance.instance import (InstanceConfig,
                                                 SiteWhereTpuInstance)
    from sitewhere_tpu.web.rest import make_app

    eng = _mk(tmp_path)
    _fill(eng)
    eng.save(tmp_path / "snap")
    reshard_snapshot(tmp_path / "snap", tmp_path / "resnap", 2,
                     archive_dir=tmp_path / "arch",
                     archive_dst=tmp_path / "arch2")
    eng2 = restore_distributed(tmp_path / "resnap")
    inst = SiteWhereTpuInstance(InstanceConfig(engine=EngineConfig()),
                                engine=eng2)

    async def drive():
        async with TestClient(TestServer(make_app(inst))) as cl:
            jwt = inst.jwt.generate("admin", inst.users.authorities_for(
                inst.users.users["admin"]))
            h = {"Authorization": f"Bearer {jwt}"}
            r = await cl.get("/api/events?sinceMs=0&untilMs=399&pageSize=200",
                             headers=h)
            assert r.status == 200, await r.text()
            listing = await r.json()
            r = await cl.get("/api/devices/mig-7/events?pageSize=100",
                             headers=h)
            assert r.status == 200, await r.text()
            dev = await r.json()
            return listing, dev

    listing, dev = asyncio.new_event_loop().run_until_complete(drive())
    assert listing["total"] == 24 * 4          # pre-reshard earliest rounds
    assert dev["total"] == 40
