"""Test harness: run everything on a virtual 8-device CPU mesh.

Mirrors SURVEY.md §4's test plan: unit kernels vs numpy oracles, single-process
integration with in-memory ingest, and multi-chip sharding validated with
``--xla_force_host_platform_device_count`` CPU emulation (ICI collectives run
without hardware).
"""

import os

# The interpreter may have already imported jax (sitecustomize registers the
# TPU plugin at startup), so env vars alone are too late — update jax config
# directly before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax < 0.5 has no jax_num_cpu_devices; the XLA_FLAGS fallback above
    # already forces the 8-device host platform
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
