"""Import hygiene (ISSUE 11 satellite): offline tooling stays jax-free.

Generalizes the PR-10 "trace2perfetto imports without jax" pin: every
module under ``scripts/`` plus ``sitewhere_tpu/utils/metrics.py`` (the
exposition/lint layer offline tools build on) must import in a
subprocess where importing jax RAISES — an accidental module-level jax
import in offline tooling would force the full accelerator runtime onto
laptops and CI boxes that only want to convert a trace or lint an
exposition."""

import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent

_DRIVER = r"""
import importlib.util
import sys

class _JaxBlocker:
    def find_spec(self, name, path=None, target=None):
        if name == "jax" or name.startswith("jax."):
            raise ImportError(
                f"BLOCKED: offline module tried to import {name!r}")
        return None

sys.meta_path.insert(0, _JaxBlocker())

failures = []
for kind, target in [t.split("=", 1) for t in sys.argv[1:]]:
    try:
        if kind == "file":
            spec = importlib.util.spec_from_file_location(
                "offline_under_test", target)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        else:
            importlib.import_module(target)
    except BaseException as e:          # incl. SystemExit from argparse
        if isinstance(e, SystemExit):
            continue                     # a CLI main() guard fired: fine
        failures.append(f"{target}: {type(e).__name__}: {e}")
print("\n".join(failures))
sys.exit(1 if failures else 0)
"""


def test_offline_modules_import_with_jax_blocked():
    scripts = sorted((REPO / "scripts").glob("*.py"))
    assert scripts, "scripts/ has no modules to check"
    # the SPMD bench leg (ISSUE 16) runs as a bench.py SUBPROCESS and
    # keeps everything above main() stdlib-only — pin that it stays in
    # this sweep so a module-level jax import can't sneak in
    assert any(p.name == "bench_spmd.py" for p in scripts)
    targets = [f"file={p}" for p in scripts]
    targets.append("mod=sitewhere_tpu.utils.metrics")
    # the conservation checker (ISSUE 14): offline tooling evaluates
    # ledger documents (bench_diff, debug-bundle triage) without jax
    targets.append("mod=sitewhere_tpu.utils.conservation")
    # the shard heat tracker (ISSUE 18): heat/skew documents are
    # numpy + stdlib — the engine hands in plain host arrays
    targets.append("mod=sitewhere_tpu.utils.shardobs")
    # the fleet-analytics job manager (ISSUE 19): module level is
    # numpy + stdlib — jax, the window-fill op and the model stack
    # import lazily inside the job thread, so the REST/RPC job surface
    # and the conservation stage exist on accelerator-free boxes
    targets.append("mod=sitewhere_tpu.models.analytics")
    res = subprocess.run(
        [sys.executable, "-c", _DRIVER, *targets],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, (
        "offline module(s) grew a jax import:\n"
        f"{res.stdout}\n{res.stderr}")
