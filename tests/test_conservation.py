"""Event conservation ledger & audit plane (ISSUE 14).

Covers the tentpole's contract ends:
  * the ledger balances (zero violations) on live, shed-then-recover,
    and kill/recover-replayed engines — the chaos-gated guarantees;
  * the checker is FALSIFIABLE: a deliberately broken ledger (injected
    off-by-one per stage) must produce a Violation naming the equation;
  * the auditor escalates only violations that survive two consecutive
    audits, into ``swtpu_conservation_violation_total``;
  * the REST/cluster surfaces serve the ledger document;
  * the metrics() dispatch-shape equality pin holds with the ledger on.
"""

import json

import numpy as np
import pytest

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.utils.conservation import (ConservationAuditor,
                                              FlowLedger, Violation,
                                              build_ledger,
                                              check_conservation,
                                              conservation_payload)


def _cfg(**kw):
    base = dict(device_capacity=256, token_capacity=512,
                assignment_capacity=512, store_capacity=4096,
                batch_capacity=64, channels=4)
    base.update(kw)
    return EngineConfig(**base)


def _meas(tok: str, seq: int, value: float = 20.0) -> bytes:
    return json.dumps({
        "deviceToken": tok, "type": "DeviceMeasurements",
        "request": {"measurements": {"temp": value}, "eventDate": seq},
    }).encode()


def _pay(lo: int, n: int, devs: int = 7) -> list[bytes]:
    return [_meas(f"cv-{i % devs}", 1_000_000 + i) for i in range(lo, lo + n)]


# ------------------------------------------------------------- balance
def test_ledger_balances_live_and_quiesced():
    eng = Engine(_cfg())
    eng.ingest_json_batch(_pay(0, 150))
    # mid-flight: the staging equation's slack term (backlog) absorbs
    # the staged-but-undispatched rows
    led = build_ledger(eng)
    assert not check_conservation(led)
    ing = led["stages"]["ingest"]
    assert ing["staged_rows"] == 150
    assert ing["staged_rows"] == ing["dispatched_rows"] + ing["backlog_rows"]
    eng.flush()
    led = build_ledger(eng)
    assert not check_conservation(led)
    dev = led["stages"]["device"]
    assert dev["processed"] == 150
    assert dev["accepted"] + dev["invalid"] == dev["processed"]
    assert led["lag"]["staged_backlog_rows"] == 0
    assert led["watermarks"]["dispatched_rows"] == 150


def test_ledger_balances_across_dispatch_shapes_and_metrics_pin():
    """scan_chunk 1 vs 2 over the same stream: both ledgers balance,
    the flow totals agree (padding lanes never count), and the
    engine.metrics() equality pin holds with the ledger ON."""
    a = Engine(_cfg(scan_chunk=1))
    b = Engine(_cfg(scan_chunk=2))
    b.epoch = a.epoch
    for lo in range(0, 192, 64):
        for e in (a, b):
            e.ingest_json_batch(_pay(lo, 64))
    a.flush()
    b.flush()
    la, lb = build_ledger(a), build_ledger(b)
    assert not check_conservation(la) and not check_conservation(lb)
    assert la["stages"]["ingest"] == lb["stages"]["ingest"]
    assert a.metrics() == b.metrics()


def test_wal_balance_and_watermarks(tmp_path):
    eng = Engine(_cfg(wal_dir=str(tmp_path / "wal")))
    eng.ingest_json_batch(_pay(0, 100))
    eng.flush()
    led = build_ledger(eng)
    assert not check_conservation(led)
    w = led["stages"]["wal"]
    assert w["appended_seq"] >= 1
    assert led["watermarks"]["wal_appended"] == w["appended_seq"]
    assert led["lag"]["wal_durable_lag"] >= 0
    eng.wal.sync()
    led = build_ledger(eng)
    assert led["lag"]["wal_durable_lag"] == 0


# ------------------------------------------------------- falsifiability
def test_injected_off_by_one_produces_violation():
    """The checker itself must be falsifiable: perturbing each stage of
    a balanced ledger by one must trip exactly the matching equation."""
    eng = Engine(_cfg(qos=True, tenant_rates={"t": 10_000.0}))
    eng.qos.admit("t", 10)
    eng.ingest_json_batch(_pay(0, 10), tenant="t")
    eng.flush()
    base = build_ledger(eng)
    assert not check_conservation(base)

    def perturbed(mutate):
        led = json.loads(json.dumps(base))   # deep copy
        mutate(led["stages"])
        return [v.equation for v in check_conservation(led)]

    assert "staging-balance" in perturbed(
        lambda s: s["ingest"].__setitem__(
            "staged_rows", s["ingest"]["staged_rows"] + 1))
    assert "device-processed" in perturbed(
        lambda s: s["device"].__setitem__(
            "processed", s["device"]["processed"] - 1))
    assert "device-disposition" in perturbed(
        lambda s: s["device"].__setitem__(
            "accepted", s["device"]["accepted"] + 1))
    assert "edge-admission" in perturbed(
        lambda s: s["edge"].__setitem__("shed", s["edge"]["shed"] + 1))
    # a violation carries the evaluated sides for the structured log
    led = json.loads(json.dumps(base))
    led["stages"]["ingest"]["staged_rows"] += 1
    v = check_conservation(led)[0]
    assert isinstance(v, Violation) and v.lhs == v.rhs + 1
    assert v.to_dict()["equation"] == "staging-balance"


def test_forward_and_replication_equations_pure():
    """The cross-rank equations evaluate over any ledger document — no
    engine required (the checker is pure)."""
    led = {"stages": {
        "forward": {"spilled_batches": 5, "redelivered_batches": 3,
                    "deadlettered_batches": 1, "queue_depth": 1,
                    "open_circuits": 0},
        "replication": {"feed_seq": 7, "published": 7,
                        "acked": {"1": 6}, "buffer": 1},
    }}
    assert not check_conservation(led)
    led["stages"]["forward"]["redelivered_batches"] = 2
    assert [v.equation for v in check_conservation(led)] == [
        "forward-queue"]
    led["stages"]["forward"]["redelivered_batches"] = 3
    led["stages"]["replication"]["acked"]["1"] = 9   # acked past seq
    assert [v.equation for v in check_conservation(led)] == [
        "replication-feed"]
    led["stages"]["replication"]["acked"]["1"] = 6
    led["stages"]["replication"]["published"] = 6
    assert [v.equation for v in check_conservation(led)] == [
        "replication-feed"]


def test_archive_spill_equation():
    led = {"stages": {"archive": {
        "parts": {"0": {"head": 100, "spilled": 64, "capacity": 128}},
        "rows": 64, "lost_rows": 0, "expired_rows": 0}}}
    assert not check_conservation(led)
    # spill cursor ahead of the ring head = corruption
    led["stages"]["archive"]["parts"]["0"]["spilled"] = 101
    assert [v.equation for v in check_conservation(led)] == [
        "archive-spill"]
    # unspilled backlog beyond capacity is only legal when counted
    led["stages"]["archive"]["parts"]["0"].update(spilled=0, head=200)
    assert [v.equation for v in check_conservation(led)] == [
        "archive-spill"]
    led["stages"]["archive"]["lost_rows"] = 72
    assert not check_conservation(led)


# ------------------------------------------------------- chaos: recover
def test_kill_recover_wal_replay_ledger_balances(tmp_path):
    """PR-6 discipline, continuously measured: snapshot, ingest through
    WAL (archive spilling), SIGKILL (del), restore + replay — the
    recovered engine's ledger must balance over the replayed rows (the
    restore rebases the device counters the snapshot carried)."""
    from sitewhere_tpu.utils.checkpoint import (replay_wal_into,
                                                restore_engine,
                                                save_engine)

    cfg = _cfg(store_capacity=2048, batch_capacity=32,
               wal_dir=str(tmp_path / "wal"),
               archive_dir=str(tmp_path / "arch"),
               archive_segment_rows=64)
    eng = Engine(cfg)
    save_engine(eng, tmp_path / "snap")
    eng.ingest_json_batch(_pay(0, 300))
    eng.flush()
    assert not check_conservation(build_ledger(eng))
    eng.wal.sync()
    eng.wal.close()
    del eng                      # "SIGKILL"
    r2 = restore_engine(tmp_path / "snap")
    replay_wal_into(r2, 0, tmp_path / "wal")
    led = build_ledger(r2)
    assert not check_conservation(led)
    ing = led["stages"]["ingest"]
    assert ing["staged_rows"] == 300 and ing["dispatched_rows"] == 300
    assert led["stages"]["device"]["processed"] == 300
    arch = led["stages"]["archive"]
    assert arch["lost_rows"] == 0
    for part in arch["parts"].values():
        assert part["spilled"] <= part["head"]


def test_mid_stream_snapshot_restore_rebases(tmp_path):
    """Restoring a snapshot that already carries device history: the
    baseline must absorb it, so the recovered ledger balances over what
    THIS process replayed — not the pre-crash totals."""
    from sitewhere_tpu.utils.checkpoint import (replay_wal_into,
                                                restore_engine,
                                                save_engine)

    eng = Engine(_cfg(wal_dir=str(tmp_path / "wal")))
    eng.ingest_json_batch(_pay(0, 100))
    eng.flush()
    save_engine(eng, tmp_path / "snap")        # snapshot mid-history
    eng.ingest_json_batch(_pay(100, 60))
    eng.flush()
    eng.wal.sync()
    eng.wal.close()
    del eng
    r2 = restore_engine(tmp_path / "snap")
    assert r2.ledger.baseline["processed"] == 100
    # replay everything (after_cursor 0 predates the watermark): the
    # idempotent pipeline re-applies, the ledger counts the replay
    replay_wal_into(r2, 0, tmp_path / "wal")
    led = build_ledger(r2)
    assert not check_conservation(led)
    assert led["stages"]["ingest"]["staged_rows"] == 160


# --------------------------------------------------- chaos: shed cycles
def test_shed_then_recover_ledger_balances():
    """PR-9 discipline, continuously measured: a shed/retry cycle shows
    up in the edge stage (offered == admitted + shed) and never
    unbalances the staging/device equations."""
    from sitewhere_tpu.utils.qos import ManualClock

    clk = ManualClock()
    eng = Engine(_cfg(qos=True))
    from sitewhere_tpu.utils.qos import AdmissionController

    eng.qos = AdmissionController(tenant_rates={"sv": 40.0},
                                  burst_s=1.0, clock=clk)
    frames = [_pay(i * 10, 10) for i in range(12)]
    backlog = list(frames)
    sheds = 0
    rounds = 0
    while backlog and rounds < 100:
        rounds += 1
        still = []
        for f in backlog:
            if eng.qos.admit("sv", len(f)).admitted:
                eng.ingest_json_batch(f, "sv")
            else:
                sheds += 1
                still.append(f)
        backlog = still
        clk.advance(0.5)
    assert not backlog and sheds > 0
    eng.flush()
    led = build_ledger(eng)
    assert not check_conservation(led)
    edge = led["stages"]["edge"]
    assert edge["admitted"] == 120
    assert edge["offered"] == edge["admitted"] + edge["shed"]
    assert led["stages"]["device"]["accepted"] == 120


# ------------------------------------------------------- rules equation
def test_rules_harvest_equation_balances():
    from sitewhere_tpu.rules import RuleSet, RulesManager

    eng = Engine(_cfg(channels=8, rule_groups=64, rollup_buckets=8))
    m = RulesManager(eng)
    m.load(RuleSet.parse({
        "name": "cv",
        "rules": [{"name": "hot", "kind": "threshold", "channel": "temp",
                   "op": ">", "value": 90.0, "cooldownMs": 1000}],
        "rollups": [{"name": "r", "channel": "temp", "windowMs": 2000,
                     "scope": "device"}]}), precompile=False)
    base = int(eng.epoch.base_unix_s * 1000)
    eng.ingest_json_batch([
        json.dumps({"deviceToken": f"rv-{i % 4}",
                    "type": "DeviceMeasurements",
                    "request": {"measurements": {
                        "temp": 95.0 if i % 11 == 0 else 20.0},
                        "eventDate": base + i * 10}}).encode()
        for i in range(200)])
    eng.flush()
    alerts = m.poll()
    assert alerts
    eng.flush()
    led = build_ledger(eng, m)
    assert not check_conservation(led)
    r = led["stages"]["rules"]
    assert r["harvested"] == r["emitted"] + r["suppressed"] + r["skipped"]
    assert r["fires"] >= r["harvested"] - r["pending"]
    assert "rollup_window_id" in led["watermarks"]
    # falsifiability on the rules equation too
    led["stages"]["rules"]["emitted"] += 1
    assert "rules-harvest" in [v.equation
                               for v in check_conservation(led)]


# -------------------------------------------------------------- auditor
def test_auditor_confirms_on_second_read_and_counts():
    from sitewhere_tpu.utils.metrics import MetricsRegistry

    reg = MetricsRegistry()
    eng = Engine(_cfg())
    eng.ingest_json_batch(_pay(0, 64))
    eng.flush()
    aud = ConservationAuditor(eng, interval_s=60.0, registry=reg)
    assert eng.conservation_auditor is aud    # attached for the scrape
    _, v = aud.audit()
    assert not v and aud.audits == 1
    # inject a persistent imbalance straight into the ledger counters
    eng.ledger.counters["staged_rows"] += 3
    _, v1 = aud.audit()
    assert v1 and aud.confirmed_total == 0    # first read: suspect only
    _, v2 = aud.audit()
    assert v2 and aud.confirmed_total == 1    # second read: escalated
    c = reg.counter("swtpu_conservation_violation_total", "")
    assert c.value(equation="staging-balance") == 1.0
    # a transient imbalance (gone by the next audit) never escalates
    eng.ledger.counters["staged_rows"] -= 3
    _, v3 = aud.audit()
    assert not v3 and aud.confirmed_total == 1


def test_auditor_thread_lifecycle():
    import time

    eng = Engine(_cfg())
    eng.ingest_json_batch(_pay(0, 32))
    eng.flush()
    aud = ConservationAuditor(eng, interval_s=0.02)
    aud.start()
    deadline = time.monotonic() + 5.0
    while aud.audits < 3 and time.monotonic() < deadline:
        time.sleep(0.02)
    aud.stop()
    assert aud.audits >= 3 and aud.confirmed_total == 0
    assert aud.last_ledger is not None and not aud.last_violations


# ------------------------------------------------------------ surfaces
def test_conservation_payload_and_flow_export():
    from sitewhere_tpu.utils.metrics import MetricsRegistry
    from sitewhere_tpu.utils.conservation import (
        export_conservation_metrics)

    eng = Engine(_cfg())
    eng.ingest_json_batch(_pay(0, 64))
    eng.flush()
    doc = conservation_payload(eng)
    assert doc["balanced"] and doc["violations"] == []
    assert doc["ledger"]["stages"]["ingest"]["staged_rows"] == 64
    aud = ConservationAuditor(eng, interval_s=60.0)
    aud.audit()
    doc = conservation_payload(eng)
    assert doc["auditor"]["audits"] == 1
    reg = MetricsRegistry()
    export_conservation_metrics(eng, reg)
    lbl = eng.metrics_label
    g = reg.gauge("swtpu_flow_rows", "")
    assert g.value(stage="staged", engine=lbl) == 64.0
    assert g.value(stage="dispatched", engine=lbl) == 64.0
    assert reg.gauge("swtpu_conservation_violations", "").value(
        engine=lbl) == 0.0


def test_ledger_disabled_engine_skips_counting_checks():
    eng = Engine(_cfg(conservation=False))
    assert isinstance(eng.ledger, FlowLedger) and not eng.ledger.enabled
    eng.ingest_json_batch(_pay(0, 32))
    eng.flush()
    led = build_ledger(eng)
    # counting off: the staging equations are skipped, device-internal
    # disposition still checks (and balances)
    assert not check_conservation(led)
    assert led["stages"]["ingest"]["counting"] is False
    assert led["stages"]["ingest"]["staged_rows"] == 0


def test_rest_conservation_endpoint():
    """The REST document end to end (aiohttp test client against
    make_app, the exposition-lint test's instance recipe)."""
    aiohttp = pytest.importorskip("aiohttp")
    import asyncio

    from sitewhere_tpu.instance.instance import (InstanceConfig,
                                                 SiteWhereTpuInstance)
    from sitewhere_tpu.web.rest import make_app, start_server

    inst = SiteWhereTpuInstance(InstanceConfig(
        engine=EngineConfig(
            device_capacity=64, token_capacity=128,
            assignment_capacity=128, store_capacity=1024,
            batch_capacity=16, channels=4),
        conservation_audit_s=0.05))
    inst.engine.ingest_json_batch(_pay(0, 12, devs=3))
    inst.engine.flush()

    loop = asyncio.new_event_loop()
    try:
        server = loop.run_until_complete(start_server(inst))
        assert inst.conservation_auditor._thread is not None

        async def fetch():
            import base64

            async with aiohttp.ClientSession() as s:
                basic = base64.b64encode(b"admin:password").decode()
                async with s.get(
                        f"http://127.0.0.1:{server.port}/api/authapi/jwt",
                        headers={"Authorization": f"Basic {basic}"}) as r:
                    token = (await r.json())["token"]
                url = (f"http://127.0.0.1:{server.port}"
                       "/api/instance/conservation")
                async with s.get(url, headers={
                        "Authorization": f"Bearer {token}"}) as r:
                    return r.status, await r.json()

        status, doc = loop.run_until_complete(fetch())
        assert status == 200
        assert doc["balanced"] is True
        assert doc["ledger"]["stages"]["ingest"]["staged_rows"] == 12
        assert "auditor" in doc
        loop.run_until_complete(server.cleanup())
        assert inst.conservation_auditor._thread is None
    finally:
        loop.close()


def test_debug_bundle_carries_conservation_section():
    from sitewhere_tpu.utils.tracing import debug_bundle

    eng = Engine(_cfg())
    eng.ingest_json_batch(_pay(0, 16))
    eng.flush()
    bundle = debug_bundle(eng)
    assert bundle["conservation"]["balanced"] is True
    assert (bundle["conservation"]["ledger"]["stages"]["ingest"]
            ["staged_rows"] == 16)
