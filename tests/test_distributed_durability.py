"""DistributedEngine durability: snapshot/restore, WAL crash recovery, and
elastic N->M resharding on the virtual CPU mesh."""

import json

import numpy as np
import pytest

from sitewhere_tpu.parallel.distributed import (
    DistributedConfig,
    DistributedEngine,
    recover_distributed,
    restore_distributed,
)
from sitewhere_tpu.parallel.reshard import reshard_snapshot


def cfg(**kw) -> DistributedConfig:
    base = dict(
        n_shards=4,
        device_capacity_per_shard=64,
        token_capacity_per_shard=128,
        assignment_capacity_per_shard=128,
        store_capacity_per_shard=256,
        channels=4,
        batch_capacity_per_shard=64,
    )
    base.update(kw)
    return DistributedConfig(**base)


def meas(token: str, value: float, ts_ms: int | None = None) -> bytes:
    req = {"deviceToken": token, "type": "DeviceMeasurements",
           "request": {"measurements": {"m": value}}}
    if ts_ms is not None:
        req["request"]["eventDate"] = ts_ms
    return json.dumps(req).encode()


def fill_engine(eng: DistributedEngine, n: int = 24) -> None:
    base_ms = int(eng.epoch.base_unix_s * 1000)
    eng.ingest_json_batch(
        [meas(f"d-{i}", float(i), ts_ms=base_ms + i * 100) for i in range(n)])
    eng.register_device("adm-0", tenant="acme", area="plant")
    eng.create_assignment("adm-0", token="adm-0:x", asset="press")
    eng.flush()


def event_key_set(eng: DistributedEngine) -> set:
    evs = eng.query_events(limit=200)["events"]
    return {(e["deviceToken"], e["type"], e["eventDateMs"]) for e in evs}


def test_snapshot_restore_roundtrip(tmp_path):
    eng = DistributedEngine(cfg())
    fill_engine(eng)
    before_events = event_key_set(eng)
    before_state = eng.get_device_state("d-5")
    eng.save(tmp_path / "snap")

    eng2 = restore_distributed(tmp_path / "snap")
    assert event_key_set(eng2) == before_events
    assert eng2.get_device_state("d-5") == before_state
    assert eng2.get_device("adm-0").tenant == "acme"
    assert eng2.get_assignment("adm-0:x").asset == "press"
    m1, m2 = eng.metrics(), eng2.metrics()
    assert m1["persisted"] == m2["persisted"]
    # the restored engine keeps ingesting: same token maps to same device
    eng2.ingest_json_batch([meas("d-5", 99.0)])
    out = eng2.flush()
    assert out["found"] == 1 and out["registered"] == 0


def test_wal_crash_recovery(tmp_path):
    wal_dir = tmp_path / "wal"
    eng = DistributedEngine(cfg(wal_dir=str(wal_dir)))
    fill_engine(eng, n=16)
    eng.save(tmp_path / "snap")
    # post-snapshot traffic: only the WAL has it (explicit eventDate so the
    # replayed rows are byte-identical; dateless events re-stamp on replay)
    base_ms = int(eng.epoch.base_unix_s * 1000)
    eng.ingest_json_batch([meas(f"late-{i}", 50.0 + i, ts_ms=base_ms + 5000 + i)
                           for i in range(8)])
    eng.flush()
    expected = event_key_set(eng)
    n_persisted = eng.metrics()["persisted"]
    eng.wal.close()   # crash

    eng2 = recover_distributed(tmp_path / "snap")
    assert eng2.metrics()["persisted"] == n_persisted
    assert event_key_set(eng2) == expected
    assert eng2.get_device_state("late-3")["measurements"]["m"]["value"] == 53.0


def test_unknown_tenant_matches_nothing():
    """A tenant name the engine has never seen must return ZERO events —
    not every tenant's events (isolation regression guard)."""
    eng = DistributedEngine(cfg())
    eng.ingest_json_batch([meas("t-0", 1.0)], tenant="acme")
    eng.flush()
    assert eng.query_events(tenant="acme")["total"] == 1
    assert eng.query_events(tenant="no-such-tenant")["total"] == 0


def test_recovery_from_preserved_wal_copy(tmp_path):
    """recover_distributed(wal_dir=forensic copy) must not write into the
    copy, and the recovered engine must not adopt it as the live WAL."""
    import shutil

    eng = DistributedEngine(cfg(wal_dir=str(tmp_path / "wal")))
    eng.save(tmp_path / "snap")
    base_ms = int(eng.epoch.base_unix_s * 1000)
    eng.ingest_json_batch([meas(f"w-{i}", float(i), ts_ms=base_ms + i)
                           for i in range(6)])
    eng.flush()
    eng.wal.close()
    shutil.copytree(tmp_path / "wal", tmp_path / "copy")
    listing = sorted(p.name for p in (tmp_path / "copy").iterdir())
    # strip wal_dir from the snapshot config so recovery must use the copy
    import json as _json
    hostp = tmp_path / "snap" / "host_distributed.json"
    h = _json.loads(hostp.read_text())
    h["config"]["wal_dir"] = None
    hostp.write_text(_json.dumps(h))

    eng2 = recover_distributed(tmp_path / "snap", wal_dir=tmp_path / "copy")
    assert eng2.metrics()["persisted"] == 6
    # byte-identical copy: no new segment, no appended records
    assert sorted(p.name for p in (tmp_path / "copy").iterdir()) == listing
    assert eng2.wal is None   # forensic copy never becomes the live log


@pytest.mark.parametrize("m_new", [2, 8])
def test_reshard_preserves_state(tmp_path, m_new):
    eng = DistributedEngine(cfg())
    fill_engine(eng)
    eng.ingest_json_batch([meas("d-3", 7.5)])   # second event for one device
    eng.flush()
    before_events = event_key_set(eng)
    before_states = {t: eng.get_device_state(t)
                     for t in ("d-0", "d-3", "d-11", "adm-0")}
    for st in before_states.values():
        st.pop("shard", None)
    before_metrics = eng.metrics()
    eng.save(tmp_path / "snap")

    reshard_snapshot(tmp_path / "snap", tmp_path / "resnap", m_new)
    eng2 = restore_distributed(tmp_path / "resnap")
    assert eng2.n_shards == m_new
    assert event_key_set(eng2) == before_events
    for tok, st in before_states.items():
        st2 = eng2.get_device_state(tok)
        st2.pop("shard", None)
        assert st2 == st, tok
    m2 = eng2.metrics()
    for k in ("processed", "found", "missed", "registered", "persisted"):
        assert m2[k] == before_metrics[k], k
    # assignments survive with device linkage
    a = eng2.get_assignment("adm-0:x")
    assert a is not None and a.device_token == "adm-0" and a.asset == "press"
    # devices keep flowing after the reshard (routing uses the new mesh)
    eng2.ingest_json_batch([meas("d-3", 8.5), meas("fresh-0", 1.0)])
    out = eng2.flush()
    assert out["found"] == 2 and out["registered"] == 1
    st = eng2.get_device_state("d-3")
    assert st["measurements"]["m"]["value"] == 8.5
    assert st["event_counts"]["MEASUREMENT"] == 3


def test_reshard_ring_overflow(tmp_path):
    """Merging 4 shards into 1 can exceed the per-shard ring: the newest
    events must survive, oldest drop (live-ring overwrite semantics)."""
    eng = DistributedEngine(cfg(store_capacity_per_shard=64,
                                batch_capacity_per_shard=16))
    base_ms = int(eng.epoch.base_unix_s * 1000)
    eng.ingest_json_batch(
        [meas(f"ov-{i % 16}", float(i), ts_ms=base_ms + i * 10)
         for i in range(128)])
    eng.flush()
    eng.save(tmp_path / "snap")
    reshard_snapshot(tmp_path / "snap", tmp_path / "one", 1)
    eng2 = restore_distributed(tmp_path / "one")
    res = eng2.query_events(limit=64)
    assert res["total"] == 64   # one 64-slot ring
    kept_ts = {e["eventDateMs"] for e in res["events"]}
    # the newest event overall (relative ts 127*10) must be retained
    assert max(kept_ts) == 1270
