"""Long-term retention tier: ring-segment spill + transparent query merge.

The VERDICT r2 acceptance test: ingest 4x ring capacity, then query events
from the first quarter by date range and get them back — single-node and
distributed. Matches the reference's unbounded external-DB history
(InfluxDbDeviceEventManagement.java:63-161 date-range search).
"""

import json

import numpy as np
import pytest

from sitewhere_tpu.core.types import EventType
from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.utils.archive import EventArchive


def meas(eng: Engine, token: str, value: float, ts_rel: int) -> bytes:
    """Payload with eventDate at engine-relative millisecond ``ts_rel``
    (wire carries absolute unix ms; queries use the relative domain)."""
    base = int(eng.epoch.base_unix_s * 1000)
    return json.dumps({
        "deviceToken": token,
        "type": "DeviceMeasurements",
        "request": {"measurements": {"temp": value}, "eventDate": base + ts_rel},
    }).encode()


SMALL_CFG = dict(
    device_capacity=64, token_capacity=128, assignment_capacity=128,
    store_capacity=64, channels=4, batch_capacity=16,
    archive_segment_rows=16,
)


def small_engine(tmp_path, **kw) -> Engine:
    cfg = dict(SMALL_CFG, archive_dir=str(tmp_path / "arch"))
    cfg.update(kw)
    return Engine(EngineConfig(**cfg))


def test_ingest_4x_capacity_then_query_first_quarter(tmp_path):
    eng = small_engine(tmp_path)
    n = 4 * 64
    for i in range(n):
        eng.ingest_json_batch([meas(eng, f"d-{i % 8}", float(i), 1000 + i)])
    eng.flush()

    # ring holds only the newest <=64 rows; the rest must be on disk
    assert eng.archive.total_rows() >= n - 64 - eng.archive.segment_rows
    assert eng.archive.lost_rows == 0

    # date-range query over the FIRST quarter — long gone from the ring
    res = eng.query_events(since_ms=1000, until_ms=1000 + 63, limit=64)
    assert res["total"] == 64
    assert len(res["events"]) == 64
    assert res["events"][0]["eventDateMs"] == 1063   # newest first
    assert res["events"][-1]["eventDateMs"] == 1000
    # values decoded from the archived columns
    by_ts = {e["eventDateMs"]: e for e in res["events"]}
    assert by_ts[1005]["measurements"]["temp"] == pytest.approx(5.0)
    assert by_ts[1005]["deviceToken"] == "d-5"

    # unfiltered total covers the full history (ring + archive, no overlap)
    res = eng.query_events(limit=10)
    assert res["total"] == n

    # device filter reaches archived rows
    res = eng.query_events(device_token="d-3", since_ms=1000,
                           until_ms=1000 + 63, limit=64)
    assert res["total"] == 8
    assert all(e["deviceToken"] == "d-3" for e in res["events"])


def test_archive_tenant_and_type_filters(tmp_path):
    eng = small_engine(tmp_path)
    for i in range(128):
        eng.ingest_json_batch([meas(eng, "t-1", float(i), 2000 + i)],
                              tenant="acme")
    eng.flush()
    res = eng.query_events(tenant="acme", since_ms=2000, until_ms=2031,
                           limit=64)
    assert res["total"] == 32
    res = eng.query_events(tenant="ghost", since_ms=2000, until_ms=2031)
    assert res["total"] == 0
    res = eng.query_events(etype=EventType.LOCATION, since_ms=2000,
                           until_ms=2031)
    assert res["total"] == 0


def test_archive_index_rebuild_after_manifest_loss(tmp_path):
    eng = small_engine(tmp_path)
    for i in range(128):
        eng.ingest_json_batch([meas(eng, "r-1", float(i), 3000 + i)])
    eng.flush()
    n_rows = eng.archive.total_rows()
    assert n_rows > 0
    # crash between segment rename and manifest rewrite: manifest gone
    (tmp_path / "arch" / "index.json").unlink()
    arch = EventArchive(tmp_path / "arch", segment_rows=16)
    assert arch.total_rows() == n_rows
    assert arch.spilled(0) == eng.archive.spilled(0)


def test_archive_append_idempotent(tmp_path):
    eng = small_engine(tmp_path)
    for i in range(128):
        eng.ingest_json_batch([meas(eng, "i-1", float(i), 4000 + i)])
    eng.flush()
    before = eng.archive.total_rows()
    spilled = eng.archive.spilled(0)
    # WAL-replay style re-spool of an already-archived range is a no-op
    eng._rows_since_spool = 10**9
    eng._spool()
    assert eng.archive.total_rows() == before
    assert eng.archive.spilled(0) == spilled


def test_archive_respects_limit_and_merge_order(tmp_path):
    eng = small_engine(tmp_path)
    for i in range(4 * 64):
        eng.ingest_json_batch([meas(eng, "m-1", float(i), 5000 + i)])
    eng.flush()
    res = eng.query_events(limit=300)
    # limit caps the page; total still counts everything
    assert res["total"] == 256
    assert len(res["events"]) == 256 if 256 <= 300 else 300
    ts = [e["eventDateMs"] for e in res["events"]]
    assert ts == sorted(ts, reverse=True)
    assert ts[0] == 5000 + 255


# ---------------------------------------------------------------- distributed
def test_distributed_ingest_4x_capacity_then_query_first_quarter(tmp_path):
    from sitewhere_tpu.parallel.distributed import (
        DistributedConfig,
        DistributedEngine,
    )

    eng = DistributedEngine(DistributedConfig(
        n_shards=4, device_capacity_per_shard=64, token_capacity_per_shard=128,
        assignment_capacity_per_shard=128, store_capacity_per_shard=64,
        channels=4, batch_capacity_per_shard=16,
        archive_dir=str(tmp_path / "darch"), archive_segment_rows=16))
    base = int(eng.epoch.base_unix_s * 1000)

    def pay(token, value, ts_rel):
        return json.dumps({
            "deviceToken": token, "type": "DeviceMeasurements",
            "request": {"measurements": {"temp": value},
                        "eventDate": base + ts_rel}}).encode()

    # 4x the AGGREGATE ring capacity, over enough devices that every shard
    # wraps several times
    n = 4 * 4 * 64
    for i in range(0, n, 32):
        eng.ingest_json_batch([
            pay(f"da-{j % 16}", float(j), 1000 + j)
            for j in range(i, i + 32)])
    eng.flush()
    assert eng.archive.lost_rows == 0
    assert eng.archive.total_rows() > 0

    # first-quarter date range, long evicted from every shard's ring
    res = eng.query_events(since_ms=1000, until_ms=1000 + 255, limit=256)
    assert res["total"] == 256
    ts = [e["eventDateMs"] for e in res["events"]]
    assert ts == sorted(ts, reverse=True)
    assert ts[0] == 1255 and ts[-1] == 1000
    by_ts = {e["eventDateMs"]: e for e in res["events"]}
    assert by_ts[1005]["deviceToken"] == "da-5"
    assert by_ts[1005]["measurements"]["temp"] == pytest.approx(5.0)

    # full-history totals agree (ring + archive, no overlap)
    assert eng.query_events(limit=10)["total"] == n

    # device filter scoped to the owning shard's partitions
    res = eng.query_events(device_token="da-3", since_ms=1000,
                           until_ms=1000 + 255, limit=256)
    assert res["total"] == 16
    assert all(e["deviceToken"] == "da-3" for e in res["events"])

    m = eng.metrics()
    assert m["archived_rows"] == eng.archive.total_rows()


def test_archive_with_scan_chunks_loses_nothing(tmp_path):
    """Review r3: spool accounting must happen at DISPATCH time — with
    scan_chunk>1 a staged batch advances the ring only when its chunk
    dispatches, and rows must still spill before overwrite."""
    eng = small_engine(tmp_path, batch_capacity=4, scan_chunk=2)
    for i in range(4 * 64):
        eng.ingest_json_batch([meas(eng, f"sc-{i % 4}", float(i), 6000 + i)])
    eng.flush()
    assert eng.archive.lost_rows == 0
    res = eng.query_events(since_ms=6000, until_ms=6063, limit=64)
    assert res["total"] == 64


def test_get_event_falls_back_to_archive(tmp_path):
    """Review r3: /api/events/id/{id} must agree with query_events about
    archived history — by-id lookups follow evicted rows to disk."""
    eng = small_engine(tmp_path)
    feed = eng.make_feed_consumer("arch-feed")
    eng.ingest_json_batch([meas(eng, "ge-1", 1.5, 7000)])
    eng.flush()
    first = feed.poll()[0]
    assert eng.get_event(first.event_id)["eventDateMs"] == 7000
    # wrap the ring several times; the first event now lives only on disk
    for i in range(4 * 64):
        eng.ingest_json_batch([meas(eng, "ge-1", float(i), 7100 + i)])
    eng.flush()
    ev = eng.get_event(first.event_id)
    assert ev is not None
    assert ev["eventDateMs"] == 7000
    assert ev["measurements"]["temp"] == pytest.approx(1.5)
    # never-written ids still miss
    assert eng.get_event(10**9) is None


def test_archive_ignores_partial_tmp_file(tmp_path):
    eng = small_engine(tmp_path)
    for i in range(128):
        eng.ingest_json_batch([meas(eng, "tf-1", float(i), 8000 + i)])
    eng.flush()
    n_rows = eng.archive.total_rows()
    # crash mid-write: a truncated temp file must not poison recovery
    (tmp_path / "arch" / "seg-p0000-o99999999999999-n16.npz.tmp").write_bytes(
        b"\x50\x4b\x03\x04 truncated")
    arch = EventArchive(tmp_path / "arch", segment_rows=16)
    assert arch.total_rows() == n_rows
    assert not list((tmp_path / "arch").glob("*.npz.tmp"))


def test_distributed_get_event_falls_back_to_archive(tmp_path):
    from sitewhere_tpu.parallel.distributed import (
        DistributedConfig,
        DistributedEngine,
        DistributedFeedConsumer,
    )

    eng = DistributedEngine(DistributedConfig(
        n_shards=4, device_capacity_per_shard=64, token_capacity_per_shard=128,
        assignment_capacity_per_shard=128, store_capacity_per_shard=64,
        channels=4, batch_capacity_per_shard=16,
        archive_dir=str(tmp_path / "dga"), archive_segment_rows=16))
    base = int(eng.epoch.base_unix_s * 1000)

    def pay(token, value, ts_rel):
        return json.dumps({
            "deviceToken": token, "type": "DeviceMeasurements",
            "request": {"measurements": {"temp": value},
                        "eventDate": base + ts_rel}}).encode()

    feed = DistributedFeedConsumer(eng, "dga-feed")
    eng.ingest_json_batch([pay("dg-1", 2.5, 9000)])
    eng.flush()
    first = feed.poll()[0]
    for i in range(4 * 4 * 64):
        eng.ingest_json_batch([pay(f"dg-{i % 8}", float(i), 9100 + i)])
    eng.flush()
    ev = eng.get_event(first.event_id)
    assert ev is not None and ev["eventDateMs"] == 9000
    assert ev["deviceToken"] == "dg-1"
    assert ev["measurements"]["temp"] == pytest.approx(2.5)


def test_feed_consumer_replays_from_archive(tmp_path):
    """A lagging feed consumer must replay evicted rows from the archive
    tier instead of dropping them (Kafka-consumer at-least-once past ring
    wrap; reference consumers read older log segments)."""
    eng = small_engine(tmp_path)
    feed = eng.make_feed_consumer("lagger", max_batch=64)
    n = 4 * 64
    for i in range(n):
        eng.ingest_json_batch([meas(eng, f"fr-{i % 4}", float(i), 1000 + i)])
    eng.flush()
    # consumer never polled while the ring wrapped 4x: replay EVERYTHING
    seen = []
    while True:
        evs = feed.poll()
        if not evs:
            break
        seen.extend(evs)
        feed.commit(evs)
    assert len(seen) == n
    assert feed.lag_lost == 0
    ts = [e.ts_ms for e in seen]
    assert ts == sorted(ts)              # replay preserves log order
    assert ts[0] == 1000 and ts[-1] == 1000 + n - 1
    assert len({e.event_id for e in seen}) == n
    # values survived the disk round trip
    assert seen[5].measurements["temp"] == pytest.approx(5.0)


def test_distributed_feed_replays_from_archive(tmp_path):
    from sitewhere_tpu.parallel.distributed import (
        DistributedConfig,
        DistributedEngine,
        DistributedFeedConsumer,
    )

    eng = DistributedEngine(DistributedConfig(
        n_shards=4, device_capacity_per_shard=64, token_capacity_per_shard=128,
        assignment_capacity_per_shard=128, store_capacity_per_shard=64,
        channels=4, batch_capacity_per_shard=16,
        archive_dir=str(tmp_path / "dfr"), archive_segment_rows=16))
    base = int(eng.epoch.base_unix_s * 1000)
    feed = DistributedFeedConsumer(eng, "dlag", max_batch=64)

    def pay(token, value, ts_rel):
        return json.dumps({
            "deviceToken": token, "type": "DeviceMeasurements",
            "request": {"measurements": {"temp": value},
                        "eventDate": base + ts_rel}}).encode()

    n = 4 * 4 * 64
    for lo in range(0, n, 32):
        eng.ingest_json_batch([pay(f"df-{j % 16}", float(j), 1000 + j)
                               for j in range(lo, lo + 32)])
    eng.flush()
    seen = []
    while True:
        evs = feed.poll()
        if not evs:
            break
        seen.extend(evs)
        feed.commit(evs)
    assert len(seen) == n
    assert feed.lag_lost == 0
    assert len({e.event_id for e in seen}) == n
    assert {e.device_token for e in seen} == {f"df-{i}" for i in range(16)}


def test_feed_without_archive_still_counts_lag(tmp_path):
    eng = Engine(EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=64, channels=4, batch_capacity=16))
    feed = eng.make_feed_consumer("nolag")
    for i in range(128):
        eng.ingest_json_batch([meas(eng, "na-1", float(i), 1000 + i)])
    eng.flush()
    evs = feed.poll()
    # ring holds the newest 64 rows; the 64 evicted ones are genuinely
    # lost without an archive tier and must be accounted
    assert len(evs) == 64
    assert feed.lag_lost == 64


def test_feed_replay_is_at_least_once(tmp_path):
    """Review r3: replayed events must be REDELIVERED until commit() —
    poll() advancing offsets would make the archive path at-most-once."""
    eng = small_engine(tmp_path)
    feed = eng.make_feed_consumer("alo", max_batch=32)
    for i in range(256):
        eng.ingest_json_batch([meas(eng, "alo-1", float(i), 1000 + i)])
    eng.flush()
    first = feed.poll()
    assert len(first) == 32
    # handler "crashed": no commit — the same events come back
    again = feed.poll()
    assert [e.event_id for e in again] == [e.event_id for e in first]
    feed.commit(again)
    nxt = feed.poll()
    assert nxt and nxt[0].event_id not in {e.event_id for e in first}
    assert feed.lag_lost == 0


def test_feed_replay_resumes_after_recorded_gap(tmp_path):
    """Review r3: a recorded-loss gap must cost exactly the gap — archived
    segments AFTER the gap still replay."""
    eng = small_engine(tmp_path)
    for i in range(256):
        eng.ingest_json_batch([meas(eng, "gap-1", float(i), 1000 + i)])
    eng.flush()
    # fabricate a hole: delete the archive segments covering [32, 64)
    removed = 0
    for seg in list(eng.archive.segments):
        if 32 <= seg.start < 64:
            (tmp_path / "arch" / seg.path).unlink()
            eng.archive.segments.remove(seg)
            removed += seg.count
    eng.archive._reindex()
    eng.archive._row_cache = None
    assert removed == 32
    feed = eng.make_feed_consumer("gappy", max_batch=512)
    seen = []
    while True:
        evs = feed.poll()
        if not evs:
            break
        seen.extend(evs)
        feed.commit(evs)
    assert feed.lag_lost == 32            # exactly the hole
    assert len(seen) == 256 - 32          # everything else delivered
    ts = [e.ts_ms for e in seen]
    assert ts == sorted(ts)
    assert 1000 + 40 not in ts and 1000 + 100 in ts


def test_archive_retention_policy_expires_oldest(tmp_path):
    """Bounded retention (reference: INFLUX_RETENTION_POLICY override) —
    the oldest whole segments expire; recent history stays queryable."""
    # cap = ring (64) + 64 rows of history beyond it
    eng = small_engine(tmp_path, archive_max_rows=128)
    for i in range(4 * 64):
        eng.ingest_json_batch([meas(eng, "rp-1", float(i), 1000 + i)])
    eng.flush()
    arch = eng.archive
    # per-partition archived rows bounded; expiries counted as policy
    assert sum(s.count for s in arch.segments) <= 128 + arch.segment_rows
    assert arch.expired_rows > 0
    assert arch.lost_rows == 0
    # evicted-but-retained rows still resolve; expired ones are gone
    res = eng.query_events(since_ms=1000 + 128, until_ms=1000 + 191,
                           limit=64)
    assert res["total"] == 64
    res = eng.query_events(since_ms=1000, until_ms=1063, limit=64)
    assert res["total"] == 0
    # only the policy-retained segment files remain on disk
    n_files = len(list((tmp_path / "arch").glob("seg-*.npz")))
    assert n_files == len(arch.segments)


def test_gap_skip_never_commits_past_uncommitted_replay(tmp_path):
    """Review r3: hitting a gap mid-poll must NOT advance the offset past
    events replayed earlier in the same poll but not yet committed."""
    eng = small_engine(tmp_path)
    for i in range(256):
        eng.ingest_json_batch([meas(eng, "gc-1", float(i), 1000 + i)])
    eng.flush()
    for seg in list(eng.archive.segments):
        if 32 <= seg.start < 64:
            (tmp_path / "arch" / seg.path).unlink()
            eng.archive.segments.remove(seg)
    eng.archive._reindex()
    eng.archive._row_cache = None
    feed = eng.make_feed_consumer("crashy", max_batch=512)
    first = feed.poll()               # replays [0,32) then stops at gap
    assert len(first) == 32
    # handler crash: no commit -> exact redelivery, offset untouched
    again = feed.poll()
    assert [e.event_id for e in again] == [e.event_id for e in first]
    assert feed.offsets[0] == 0
    feed.commit(again)
    # now the gap is at the committed offset: it may be skipped
    rest = []
    while True:
        evs = feed.poll()
        if not evs:
            break
        rest.extend(evs)
        feed.commit(evs)
    assert feed.lag_lost == 32
    assert len(first) + len(rest) == 256 - 32


def test_archive_survives_snapshot_recovery(tmp_path):
    """Archived history must still serve after distributed snapshot + WAL
    crash recovery (same topology re-attaches the archive)."""
    from sitewhere_tpu.parallel.distributed import (
        DistributedConfig,
        DistributedEngine,
        recover_distributed,
    )

    cfg = DistributedConfig(
        n_shards=4, device_capacity_per_shard=64, token_capacity_per_shard=128,
        assignment_capacity_per_shard=128, store_capacity_per_shard=64,
        channels=4, batch_capacity_per_shard=16,
        archive_dir=str(tmp_path / "ra"), archive_segment_rows=16,
        wal_dir=str(tmp_path / "wal"))
    eng = DistributedEngine(cfg)
    base = int(eng.epoch.base_unix_s * 1000)

    def pay(token, value, ts_rel):
        return json.dumps({
            "deviceToken": token, "type": "DeviceMeasurements",
            "request": {"measurements": {"temp": value},
                        "eventDate": base + ts_rel}}).encode()

    n = 2 * 4 * 64
    for lo in range(0, n, 32):
        eng.ingest_json_batch([pay(f"rs-{j % 8}", float(j), 1000 + j)
                               for j in range(lo, lo + 32)])
    eng.flush()
    eng.save(tmp_path / "snap")
    eng.wal.close()
    rec = recover_distributed(tmp_path / "snap")
    # first-half history (evicted from every ring) still resolves
    res = rec.query_events(since_ms=1000, until_ms=1000 + n // 2 - 1,
                           limit=16)
    assert res["total"] == n // 2
    assert rec.archive.total_rows() > 0


def test_archive_retired_on_topology_change(tmp_path):
    """After an elastic reshard, the old archive's partition indices no
    longer mean the same (shard, arena) — it must be RETIRED, never
    misread under the new mesh."""
    from sitewhere_tpu.utils.archive import EventArchive

    arch4 = EventArchive(tmp_path / "topo", segment_rows=4,
                     topology="mesh/4x1")
    import types

    cols = types.SimpleNamespace(**{
        c: np.zeros((4, 4) if c in ("values", "vmask") else (4, 2)
                    if c == "aux" else 4,
                    np.float32 if c == "values" else
                    bool if c in ("vmask", "valid") else np.int32)
        for c in ("etype", "device", "assignment", "tenant", "area",
                  "customer", "asset", "ts_ms", "received_ms", "values",
                  "vmask", "aux", "valid")})
    arch4.append_segment(3, 0, cols)
    assert arch4.total_rows() == 4

    # same topology re-opens and keeps the data
    again = EventArchive(tmp_path / "topo", segment_rows=4,
                     topology="mesh/4x1")
    assert again.total_rows() == 4

    # different topology retires it
    arch2 = EventArchive(tmp_path / "topo", segment_rows=4,
                     topology="mesh/2x1")
    assert arch2.total_rows() == 0
    assert arch2.spilled(3) == 0
    retired = list((tmp_path / "topo").glob("retired-mesh-4x1*"))
    assert len(retired) == 1
    assert list(retired[0].glob("seg-*.npz"))


def test_topology_check_covers_manifestless_and_equal_count(tmp_path):
    """Review r3: (a) segments carry their OWN topology stamp, so a
    manifest-less dir can't smuggle old-topology partitions past the
    check; (b) the stamp is the full shape, so single/2 vs mesh/2x1
    (equal partition COUNTS) still retires."""
    import types

    from sitewhere_tpu.utils.archive import EventArchive

    def cols(n=4):
        return types.SimpleNamespace(**{
            c: np.zeros((n, 4) if c in ("values", "vmask") else (n, 2)
                        if c == "aux" else n,
                        np.float32 if c == "values" else
                        bool if c in ("vmask", "valid") else np.int32)
            for c in ("etype", "device", "assignment", "tenant", "area",
                      "customer", "asset", "ts_ms", "received_ms",
                      "values", "vmask", "aux", "valid")})

    a1 = EventArchive(tmp_path / "t", segment_rows=4, topology="single/2")
    a1.append_segment(1, 0, cols())
    # (b) equal partition count, different shape -> retired
    a2 = EventArchive(tmp_path / "t", segment_rows=4, topology="mesh/2x1")
    assert a2.total_rows() == 0
    assert list((tmp_path / "t").glob("retired-single-2*"))

    # (a) manifest-less: write a segment, delete index.json, reopen under
    # a different topology — the per-segment stamp still blocks adoption
    a2.append_segment(0, 0, cols())
    (tmp_path / "t" / "index.json").unlink()
    a3 = EventArchive(tmp_path / "t", segment_rows=4, topology="mesh/8x1")
    assert a3.total_rows() == 0
    # and the same topology WITHOUT a manifest still rebuilds fine
    a4 = EventArchive(tmp_path / "t", segment_rows=4, topology="mesh/8x1")
    a4.append_segment(0, 0, cols())
    (tmp_path / "t" / "index.json").unlink()
    a5 = EventArchive(tmp_path / "t", segment_rows=4, topology="mesh/8x1")
    assert a5.total_rows() == 4


def _cols(n=4, ts0=0):
    import types

    out = types.SimpleNamespace(**{
        c: np.zeros((n, 4) if c in ("values", "vmask") else (n, 2)
                    if c == "aux" else n,
                    np.float32 if c == "values" else
                    bool if c in ("vmask", "valid") else np.int32)
        for c in ("etype", "device", "assignment", "tenant", "area",
                  "customer", "asset", "ts_ms", "received_ms", "values",
                  "vmask", "aux", "valid")})
    out.ts_ms = np.arange(ts0, ts0 + n, dtype=np.int32)
    out.valid = np.ones(n, bool)
    return out


def test_archive_compaction_merges_small_segments(tmp_path):
    """VERDICT r3 weak #2: many small spill files merge into
    O(rows/target) files; positions (by-id lookups, replay cursors)
    survive; a reopened archive sees the compacted layout."""
    from sitewhere_tpu.utils.archive import EventArchive

    arch = EventArchive(tmp_path / "c", segment_rows=4, topology="mesh/2x1")
    for part in (0, 1):
        for k in range(12):   # 12 four-row segments per partition
            arch.append_segment(part, k * 4, _cols(4, ts0=k * 4))
    assert len(arch.segments) == 24
    before = arch.get_row(1, 17)
    stats = arch.compact(target_rows=16)
    # 48 rows/part at target 16 -> 3 merged files per part
    assert stats["files_now"] == 6 and stats["files_removed"] == 24
    assert len(list((tmp_path / "c").glob("seg-*.npz"))) == 6
    assert arch.total_rows() == 96
    after = arch.get_row(1, 17)
    assert after is not None
    assert int(after["ts_ms"]) == int(before["ts_ms"])
    # idempotent: a second pass has nothing to merge
    assert arch.compact(target_rows=16)["merged_segments"] == 0
    # reopen: the compacted layout loads and queries unchanged
    again = EventArchive(tmp_path / "c", segment_rows=4,
                         topology="mesh/2x1")
    assert again.total_rows() == 96
    assert int(again.get_row(1, 17)["ts_ms"]) == int(before["ts_ms"])
    total, rows = again.query(since_ms=4, until_ms=7, limit=50)
    assert total == 8   # 4 rows per partition in that window


def test_compaction_crash_leftovers_swept_on_load(tmp_path):
    """A crash between the merged-file rename and the source deletes
    leaves covered sources; the next open sweeps them instead of
    double-counting."""
    from sitewhere_tpu.utils.archive import EventArchive

    arch = EventArchive(tmp_path / "x", segment_rows=4, topology="s/1")
    for k in range(4):
        arch.append_segment(0, k * 4, _cols(4, ts0=k * 4))
    names = [s.path for s in arch.segments]
    arch.compact(target_rows=16)
    merged = arch.segments[0].path
    # simulate the crash: restore one source file next to the merged one
    src = tmp_path / "x" / names[1]
    import shutil

    shutil.copy(tmp_path / "x" / merged, tmp_path / "x" / "backup.npz")
    arch2 = EventArchive(tmp_path / "x", segment_rows=4, topology="s/1")
    assert arch2.total_rows() == 16
    # now actually plant a covered leftover and reopen
    with np.load(tmp_path / "x" / merged) as z:
        sub = {k: (v[:4] if getattr(v, "ndim", 0) else v)
               for k, v in z.items()}
    sub["start"] = np.int64(0)
    with open(src, "wb") as f:
        np.savez(f, **sub)
    (tmp_path / "x" / "index.json").unlink()
    arch3 = EventArchive(tmp_path / "x", segment_rows=4, topology="s/1")
    assert arch3.total_rows() == 16          # not 20: leftover dropped
    assert not src.exists()                  # ...and deleted


def test_disk_usage_and_purge_retired(tmp_path):
    from sitewhere_tpu.utils.archive import EventArchive

    a1 = EventArchive(tmp_path / "d", segment_rows=4, topology="mesh/4x1")
    a1.append_segment(0, 0, _cols(4))
    u = a1.disk_usage()
    assert u["live_segments"] == 1 and u["live_bytes"] > 0
    assert u["retired_bytes"] == 0
    # topology change retires the history; usage reports it; purge frees
    a2 = EventArchive(tmp_path / "d", segment_rows=4, topology="mesh/2x1")
    u = a2.disk_usage()
    assert u["live_segments"] == 0
    assert u["retired_files"] >= 1 and u["retired_bytes"] > 0
    freed = a2.purge_retired()
    assert freed == u["retired_bytes"]
    assert a2.disk_usage()["retired_bytes"] == 0
    assert not list((tmp_path / "d").glob("retired-*"))


def test_unstamped_segments_adopted_by_topology_aware_open(tmp_path):
    """Advisor r3 (low): an archive opened with topology=None stamps
    segments with an empty string; a later topology-aware open must treat
    that like a missing/None stamp (adopt), matching the manifest-level
    null-stamp semantics — not retire them as a foreign topology."""
    import types

    from sitewhere_tpu.utils.archive import EventArchive

    def cols(n=4):
        return types.SimpleNamespace(**{
            c: np.zeros((n, 4) if c in ("values", "vmask") else (n, 2)
                        if c == "aux" else n,
                        np.float32 if c == "values" else
                        bool if c in ("vmask", "valid") else np.int32)
            for c in ("etype", "device", "assignment", "tenant", "area",
                      "customer", "asset", "ts_ms", "received_ms",
                      "values", "vmask", "aux", "valid")})

    a0 = EventArchive(tmp_path / "u", segment_rows=4, topology=None)
    a0.append_segment(0, 0, cols())
    # manifest-less reopen forces the file-level stamp path
    (tmp_path / "u" / "index.json").unlink()
    a1 = EventArchive(tmp_path / "u", segment_rows=4, topology="mesh/4x1")
    assert a1.total_rows() == 4
    assert not list((tmp_path / "u").glob("retired-*"))


def test_archived_history_serves_over_rest(tmp_path):
    """The REST event listings transparently include archived history —
    the user-visible version of the unbounded date-range search."""
    import asyncio
    import base64

    from aiohttp.test_utils import TestClient, TestServer

    from sitewhere_tpu.instance.instance import (
        InstanceConfig,
        SiteWhereTpuInstance,
    )
    from sitewhere_tpu.web.rest import make_app

    inst = SiteWhereTpuInstance(InstanceConfig(engine=EngineConfig(
        **SMALL_CFG, archive_dir=str(tmp_path / "ra"))))
    eng = inst.engine
    for i in range(256):
        eng.ingest_json_batch([meas(eng, f"rr-{i % 4}", float(i), 1000 + i)])
    eng.flush()

    async def go():
        client = TestClient(TestServer(make_app(inst)))
        await client.start_server()
        try:
            basic = base64.b64encode(b"admin:password").decode()
            r = await client.get("/api/authapi/jwt",
                                 headers={"Authorization": f"Basic {basic}"})
            h = {"Authorization": f"Bearer {(await r.json())['token']}"}
            # full-history total through the generic listing
            r = await client.get("/api/events", headers=h)
            assert (await r.json())["total"] == 256
            # device listing reaches the archived first quarter
            r = await client.get(
                "/api/devices/rr-1/events",
                params={"sinceMs": "1000", "untilMs": "1063",
                        "pageSize": "64"}, headers=h)
            body = await r.json()
            assert body["total"] == 16
            assert all(e["deviceToken"] == "rr-1" for e in body["events"])
            # by-id lookup follows an evicted event to disk
            feed = eng.make_feed_consumer("rest-arch")
            first = feed.poll()[0]
            r = await client.get(f"/api/events/id/{first.event_id}",
                                 headers=h)
            assert r.status == 200
            assert (await r.json())["eventDateMs"] == 1000
            # archive observability + maintenance endpoints (admin)
            r = await client.get("/api/instance/metrics", headers=h)
            m = await r.json()
            assert m["archive"]["rows"] > 0
            assert m["archive"]["live_bytes"] > 0
            files_before = m["archive"]["live_segments"]
            r = await client.post("/api/instance/archive/compact",
                                  json={"targetRows": 64}, headers=h)
            assert r.status == 200, await r.text()
            stats = await r.json()
            assert stats["files_now"] < files_before
            # compaction preserved the archived history end-to-end
            r = await client.get(
                "/api/devices/rr-1/events",
                params={"sinceMs": "1000", "untilMs": "1063",
                        "pageSize": "64"}, headers=h)
            assert (await r.json())["total"] == 16
            r = await client.post("/api/instance/archive/purge-retired",
                                  headers=h)
            assert r.status == 200
            assert (await r.json())["freedBytes"] == 0  # nothing retired
        finally:
            await client.close()

    asyncio.run(go())
    # engine-level date-range agreement for the same instance
    res = eng.query_events(device_token="rr-1", since_ms=1000,
                           until_ms=1063, limit=64)
    assert res["total"] == 16


def test_archive_age_based_retention(tmp_path):
    """Event-time retention horizon: segments whose newest event trails
    the partition's newest by more than max_age_ms expire."""
    eng = small_engine(tmp_path, archive_max_age_ms=100)
    for i in range(4 * 64):
        eng.ingest_json_batch([meas(eng, "ag-1", float(i), 1000 + i)])
    eng.flush()
    arch = eng.archive
    assert arch.expired_rows > 0
    # everything inside the horizon (newest ts 1255, horizon 1155) that
    # is already evicted from the ring still resolves...
    res = eng.query_events(since_ms=1160, until_ms=1191, limit=64)
    assert res["total"] == 32
    # ...while history beyond the horizon is gone
    assert eng.query_events(since_ms=1000, until_ms=1063)["total"] == 0
    # retained archive segments all end within the horizon
    newest = max(s.ts_max for s in arch.segments)
    assert all(s.ts_max >= newest - 100 for s in arch.segments)


def test_age_retention_sweeps_backfilled_segments(tmp_path):
    """Review r3: the age horizon must come from surviving segments and
    sweep ALL of them — a backfilled (out-of-order event time) segment
    behind a fresher head still expires."""
    import types

    from sitewhere_tpu.utils.archive import EventArchive

    def cols(ts_vals):
        n = len(ts_vals)
        d = {c: np.zeros((n, 4) if c in ("values", "vmask") else (n, 2)
                         if c == "aux" else n,
                         np.float32 if c == "values" else
                         bool if c in ("vmask", "valid") else np.int32)
             for c in ("etype", "device", "assignment", "tenant", "area",
                       "customer", "asset", "ts_ms", "received_ms",
                       "values", "vmask", "aux", "valid")}
        d["ts_ms"][:] = ts_vals
        d["valid"][:] = True
        return types.SimpleNamespace(**d)

    arch = EventArchive(tmp_path / "bk", segment_rows=2, max_age_ms=50,
                        topology="single/1")
    arch.append_segment(0, 0, cols([300, 300]))   # live
    arch.append_segment(0, 2, cols([100, 100]))   # backfill, past horizon
    arch.append_segment(0, 4, cols([310, 310]))   # live again
    # horizon = 310 - 50 = 260: the backfilled middle segment expires even
    # though a fresher segment precedes it in write order
    starts = sorted(s.start for s in arch.segments)
    assert starts == [0, 4]
    assert arch.expired_rows == 2


def test_spill_watermark_survives_tail_expiry(tmp_path):
    """Review r3: age-expiring the newest-POSITION segment must not
    regress spilled() — the spooler would otherwise re-spill and
    re-expire the same rows forever (and miscount them as lost)."""
    import types

    from sitewhere_tpu.utils.archive import EventArchive

    def cols(ts_vals):
        n = len(ts_vals)
        d = {c: np.zeros((n, 4) if c in ("values", "vmask") else (n, 2)
                         if c == "aux" else n,
                         np.float32 if c == "values" else
                         bool if c in ("vmask", "valid") else np.int32)
             for c in ("etype", "device", "assignment", "tenant", "area",
                       "customer", "asset", "ts_ms", "received_ms",
                       "values", "vmask", "aux", "valid")}
        d["ts_ms"][:] = ts_vals
        d["valid"][:] = True
        return types.SimpleNamespace(**d)

    arch = EventArchive(tmp_path / "wm", segment_rows=2, max_age_ms=50,
                        topology="single/1")
    arch.append_segment(0, 0, cols([300, 300]))
    # backfilled TAIL segment: newest position, oldest event time -> it
    # expires immediately, but the watermark must stay at 4
    arch.append_segment(0, 2, cols([100, 100]))
    assert arch.expired_rows == 2
    assert arch.spilled(0) == 4
    before = arch.expired_rows
    # an idempotent re-append of the same range must not churn
    arch.append_segment(0, 2, cols([100, 100]))
    assert arch.spilled(0) == 4
    # the watermark survives a reopen (persisted in the manifest)
    again = EventArchive(tmp_path / "wm", segment_rows=2, max_age_ms=50,
                         topology="single/1")
    assert again.spilled(0) == 4
