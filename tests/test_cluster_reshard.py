"""Snapshot+archive rank-count migration (VERDICT r4 missing #3).

The replay-based ``reshard_cluster`` is O(all history) and refuses pruned
WALs; ``migrate_cluster_snapshots`` re-partitions live snapshots and
row-copies archives, with only the post-snapshot WAL tails re-decoded.
THE done-criterion: prune the WALs first and the migrated cluster still
serves IDENTICAL query results."""

import json
import time

import pytest

from sitewhere_tpu.parallel.cluster import (ClusterConfig, ClusterEngine,
                                            build_cluster_rpc, owner_rank)
from sitewhere_tpu.parallel.cluster_reshard import (migrate_cluster_snapshots,
                                                    replay_wal_tails)
from sitewhere_tpu.parallel.distributed import (DistributedConfig,
                                                recover_distributed)
from tests.test_cluster import BASE_MS, BASE_S, _free_ports, _ServerHost

CH = ("temp", "hum", "psi")


def _cfg(tmp_path, tag, rank):
    return DistributedConfig(
        n_shards=2, device_capacity_per_shard=64,
        token_capacity_per_shard=128, assignment_capacity_per_shard=128,
        store_capacity_per_shard=64, channels=4,
        batch_capacity_per_shard=8, archive_segment_rows=8,
        wal_dir=str(tmp_path / f"{tag}-wal-r{rank}"),
        archive_dir=str(tmp_path / f"{tag}-arch-r{rank}"))


def _mk(tmp_path, tag, n_ranks, locals_=None):
    ports = _free_ports(n_ranks)
    peers = [f"127.0.0.1:{p}" for p in ports]
    host = _ServerHost()
    clusters = []
    for r in range(n_ranks):
        cc = ClusterConfig(rank=r, n_ranks=n_ranks, peers=peers,
                           secret=f"{tag}-secret",
                           epoch_base_unix_s=BASE_S,
                           engine=_cfg(tmp_path, tag, r),
                           connect_timeout_s=10.0)
        c = ClusterEngine(cc, local=locals_[r] if locals_ else None)
        host.start(build_cluster_rpc(c.local, f"{tag}-secret"), ports[r])
        clusters.append(c)
    return clusters, host


def _tokens(n_old, n, prefix):
    """n tokens per OLD rank, chosen so the NEW 3-rank partitioning also
    spreads (any tokens do — ownership is just a hash)."""
    out, i = {r: [] for r in range(n_old)}, 0
    while any(len(v) < n for v in out.values()):
        t = f"{prefix}-{i}"
        r = owner_rank(t, n_old)
        if len(out[r]) < n:
            out[r].append(t)
        i += 1
    return [t for r in range(n_old) for t in out[r]]


def _meas(token, pairs, ts_rel, alt=None):
    req = {"measurements": dict(pairs), "eventDate": BASE_MS + ts_rel}
    if alt:
        req["alternateId"] = alt
    return json.dumps({"deviceToken": token, "type": "DeviceMeasurements",
                       "request": req}).encode()


def _loc(token, lat, lon, ts_rel):
    return json.dumps({
        "deviceToken": token, "type": "DeviceLocation",
        "request": {"latitude": lat, "longitude": lon, "elevation": 5.0,
                    "eventDate": BASE_MS + ts_rel}}).encode()


def _alert(token, atype, level, ts_rel):
    return json.dumps({
        "deviceToken": token, "type": "DeviceAlert",
        "request": {"type": atype, "level": level, "message": "m",
                    "eventDate": BASE_MS + ts_rel}}).encode()


def _norm(events):
    """Topology-independent event identity: ids/assignment ids live in
    rank-local spaces and legitimately change across a migration."""
    out = []
    for e in events:
        out.append((e["deviceToken"], e["type"], e["eventDateMs"],
                    e.get("measurements"), e.get("latitude"),
                    e.get("longitude"), e.get("alertType"),
                    e.get("level"), e.get("attribute"),
                    e.get("stateChange")))
    return out


def test_pruned_wal_snapshot_archive_migration_identical_queries(tmp_path):
    old, old_host = _mk(tmp_path, "old", 2)
    toks = _tokens(2, 3, "mig")
    news = None
    new_host = None
    try:
        # devices with metadata; one extra assignment with an asset
        for i, t in enumerate(toks):
            old[0].register_device(t, "default", area=f"area-{i % 2}",
                                   customer="acme")
        old[0].create_assignment(toks[0], token="mig-asg",
                                 asset="truck-1")
        # lane-order divergence: rank 0 interns temp->hum, rank 1
        # interns hum->temp (the migration must realign by NAME)
        r0_toks = [t for t in toks if owner_rank(t, 2) == 0]
        r1_toks = [t for t in toks if owner_rank(t, 2) == 1]
        old[0].ingest_json_batch([_meas(r0_toks[0], [("temp", 1.0)], 0)])
        old[1].local.ingest_json_batch(
            [_meas(r1_toks[0], [("hum", 2.0)], 1)])
        # bulk history: overflow the tiny rings into the archive
        batch = []
        for i in range(40):
            for j, t in enumerate(toks):
                ts = 10 + i * len(toks) + j
                if i % 7 == 3:
                    batch.append(_loc(t, 45.0 + i, -122.0 - j, ts))
                elif i % 11 == 5:
                    batch.append(_alert(t, "overheat" if j % 2 else
                                        "lowbatt", 2, ts))
                else:
                    batch.append(_meas(
                        t, [(CH[(i + j) % 3], float(i))], ts))
        old[0].ingest_json_batch(batch)
        # alternate ids + state changes ride the per-request path (the
        # envelope decoder interns them into event_ids — the aux lanes
        # whose interner ids the migration must remap)
        from sitewhere_tpu.ingest.decoders import request_from_envelope

        req = request_from_envelope(json.loads(_meas(
            toks[0], [("temp", 7.0)], 4000, alt=f"alt-{toks[0]}-1")))
        req.tenant = "default"
        old[1].process(req)    # routes to the owner
        sc = request_from_envelope({
            "deviceToken": toks[1], "type": "DeviceStateChange",
            "request": {"attribute": "fw", "type": "upgrade",
                        "previousState": "1", "newState": "2",
                        "eventDate": BASE_MS + 4001}})
        sc.tenant = "default"
        old[0].process(sc)
        old[0].flush()

        # ---- snapshot, rotate + PRUNE the WALs, then a live tail -----
        snaps = []
        for r, c in enumerate(old):
            d = tmp_path / f"snap-r{r}"
            c.local.save(d)
            snaps.append(d)
            c.local.wal._seg_index += 1
            c.local.wal._open_segment()   # tail lands in a new segment
            pruned = c.local.wal.prune(keep_segments=1)
            assert pruned >= 1            # the snapshot-covered span is GONE
        tail = [_meas(t, [("temp", 99.5)], 5000 + i)
                for i, t in enumerate(toks)]
        old[1].ingest_json_batch(tail)
        old[0].flush()

        # reference answers from the OLD live cluster
        ref_all = old[0].query_events(limit=500)
        ref_dev = {t: old[0].query_events(device_token=t, limit=500)
                   for t in toks}
        ref_state = {t: old[0].get_device_state(t) for t in toks}
        ref_alt = old[0].query_events(alternate_id=f"alt-{toks[0]}-1",
                                      limit=10)
        # toks[0] carries TWO active assignments, so the event expanded
        # to two rows — the premise is presence, not a fixed count
        assert ref_alt["total"] == 2
        from sitewhere_tpu.core.types import EventType

        ref_sc = old[1].query_events(device_token=toks[1],
                                     etype=int(EventType.STATE_CHANGE),
                                     limit=10)
        assert ref_sc["total"] == 1
        ref_asg = old[0].get_assignment("mig-asg")

        # ---- migrate 2 -> 3 ranks off the snapshots + archives -------
        stats = migrate_cluster_snapshots(
            snaps, 3, tmp_path / "new",
            old_archive_dirs=[tmp_path / "old-arch-r0",
                              tmp_path / "old-arch-r1"])
        assert sum(s["devices"] for s in stats["targets"]) == len(toks)
        assert sum(s["archive_rows"] for s in stats["targets"]) > 0
        # all three targets actually own devices (hash spreads)
        assert all(s["devices"] > 0 for s in stats["targets"])

        locals_ = [recover_distributed(
            tmp_path / "new" / f"rank-{t}" / "snapshot",
            tmp_path / f"new-wal-r{t}") for t in range(3)]
        news, new_host = _mk(tmp_path, "new", 3, locals_=locals_)

        # ---- O(tail) finish: replay ONLY the pruned WALs' tails ------
        replayed = replay_wal_tails(news[0], snaps,
                                    [tmp_path / "old-wal-r0",
                                     tmp_path / "old-wal-r1"])
        assert replayed == len(toks)      # just the post-snapshot batch

        # ---- identical answers from any new rank ---------------------
        for c in news:
            got_all = c.query_events(limit=500)
            assert got_all["total"] == ref_all["total"]
            assert _norm(got_all["events"]) == _norm(ref_all["events"])
        for t in toks:
            got = news[1].query_events(device_token=t, limit=500)
            assert got["total"] == ref_dev[t]["total"], t
            assert _norm(got["events"]) == _norm(ref_dev[t]["events"]), t
            st_old, st_new = ref_state[t], news[2].get_device_state(t)
            assert st_new["measurements"] == st_old["measurements"], t
            assert st_new["presence"] == st_old["presence"], t
            info = news[0].get_device(t)
            assert info.area == f"area-{toks.index(t) % 2}"
            assert info.customer == "acme"
        # alternate-id lookups cross the interner remap
        got_alt = news[0].query_events(alternate_id=f"alt-{toks[0]}-1",
                                       limit=10)
        assert got_alt["total"] == ref_alt["total"] == 2
        assert _norm(got_alt["events"]) == _norm(ref_alt["events"])
        # state-change aux0 (event_ids interner) crossed the remap too
        got_sc = news[0].query_events(device_token=toks[1],
                                      etype=int(EventType.STATE_CHANGE),
                                      limit=10)
        assert got_sc["total"] == 1
        assert _norm(got_sc["events"]) == _norm(ref_sc["events"])
        assert got_sc["events"][0]["attribute"] == "fw"
        # assignments survive with associations intact
        a = news[0].get_assignment("mig-asg")
        assert a is not None and a.asset == ref_asg.asset == "truck-1"
        assert a.device_token == toks[0]
    finally:
        for c in old:
            c.close()
        old_host.close()
        if news is not None:
            for c in news:
                c.close()
            new_host.close()


def test_replay_wal_tails_validates_everything_before_replaying(tmp_path):
    """Satellite regression (ISSUE 15): a missing/None WAL dir (or an
    unreadable snapshot manifest) must fail LOUDLY with NOTHING
    replayed — never mid-loop with earlier ranks' tails already in the
    new cluster — while a WAL dir pruned to zero segments is a legal
    zero-record tail."""

    class _Probe:
        """Stands in for the live cluster: records every replay call."""

        def __init__(self):
            self.calls = []

        def ingest_json_batch(self, payloads, tenant="default"):
            self.calls.append(("json", len(payloads), tenant))
            return {"staged": len(payloads)}

        def ingest_binary_batch(self, payloads, tenant="default"):
            self.calls.append(("binary", len(payloads), tenant))
            return {"staged": len(payloads)}

        def flush(self):
            self.calls.append(("flush",))
            return {}

    def _snap(i, cursor=0):
        d = tmp_path / f"snap-{i}"
        d.mkdir(parents=True, exist_ok=True)
        (d / "host_distributed.json").write_text(
            json.dumps({"store_cursor": cursor}))
        return d

    def _wal_with_records(i, payloads):
        """A real (tiny) WAL so the happy path replays records."""
        from sitewhere_tpu.engine import WAL_JSON
        from sitewhere_tpu.utils.ingestlog import IngestLog

        d = tmp_path / f"wal-{i}"
        wal = IngestLog(d)
        for p in payloads:
            wal.append(WAL_JSON + b"default\x00" + p)
        wal.flush()
        wal.close()
        return d

    probe = _Probe()
    good_wal = _wal_with_records(0, [b'{"deviceToken":"a"}'] * 3)
    empty_wal = tmp_path / "wal-empty"
    empty_wal.mkdir()

    # missing WAL dir: raises up front, rank 0's perfectly good tail is
    # NOT half-applied first
    with pytest.raises(ValueError, match="does not exist"):
        replay_wal_tails(probe, [_snap(0), _snap(1)],
                         [good_wal, tmp_path / "nope"])
    assert probe.calls == []
    # a None entry is refused the same way
    with pytest.raises(ValueError, match="None"):
        replay_wal_tails(probe, [_snap(0)], [None])
    assert probe.calls == []
    # unreadable snapshot manifest: same contract
    bare = tmp_path / "snap-bare"
    bare.mkdir()
    with pytest.raises(ValueError, match="manifest"):
        replay_wal_tails(probe, [bare], [good_wal])
    assert probe.calls == []
    # count mismatch is a usage error, not a silent zip-truncation
    with pytest.raises(ValueError, match="one WAL tail per"):
        replay_wal_tails(probe, [_snap(0), _snap(1)], [good_wal])

    # happy path: a pruned-to-nothing (empty) dir is a zero-record
    # tail and the good rank's records replay
    n = replay_wal_tails(probe, [_snap(0), _snap(1)],
                         [good_wal, empty_wal])
    assert n == 3
    assert ("flush",) in probe.calls
    assert sum(c[1] for c in probe.calls if c[0] == "json") == 3
