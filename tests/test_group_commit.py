"""Group-commit WAL (ISSUE 4 tentpole, pillar 2).

Durability semantics under the commit thread: appends buffer in user
space and a dedicated thread writes + fsyncs once per quiescent window,
so a crash loses exactly the un-fsynced tail. The engine gates every
device dispatch on its batch's durability watermark, which is the whole
guarantee: a DISPATCHED batch's payloads can never be absent from a
replayed log, no matter where the crash lands between buffered append
and fsync. And the fsyncs must actually amortize — fewer fsyncs than
append groups at steady state — or the design bought nothing.
"""

import pathlib
import threading

import numpy as np
import pytest

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.loadgen import generate_measurements_message
from sitewhere_tpu.utils.ingestlog import _FSYNC_HIST, IngestLog

SMALL = dict(device_capacity=1 << 10, token_capacity=1 << 11,
             assignment_capacity=1 << 11, store_capacity=1 << 12,
             batch_capacity=256)


def _payload_batch(b, n=32):
    return [generate_measurements_message(f"gc-{i % 20}", b * 1000 + i)
            for i in range(n)]


# ------------------------------------------------------------ amortization
def test_group_commit_fewer_fsyncs_than_batches(tmp_path):
    """Steady state: several ingest batches land between dispatches, so
    one commit fsync covers several append groups — asserted both on the
    log's own counters and on the swtpu_wal_fsync_seconds histogram
    (the operator-visible amortization proof)."""
    eng = Engine(EngineConfig(**SMALL, wal_dir=str(tmp_path / "wal")))
    assert eng.wal.group_commit
    hist_before = _FSYNC_HIST.count()
    n_batches = 16
    for b in range(n_batches):
        eng.ingest_json_batch(_payload_batch(b))
    eng.flush()
    assert eng.wal.commit_groups == n_batches
    assert eng.wal.fsyncs < n_batches, \
        (eng.wal.fsyncs, "no amortization happened")
    assert _FSYNC_HIST.count() - hist_before == eng.wal.fsyncs
    # durability covered everything that was appended
    assert eng.wal.durable_seq == n_batches
    records = list(IngestLog(tmp_path / "wal", readonly=True).replay())
    assert len(records) == n_batches * 32
    eng.wal.close()


# ------------------------------------------------------------ crash safety
def test_crash_between_append_and_fsync_never_loses_dispatched(tmp_path):
    """At every dispatch, snapshot what a MACHINE crash would leave
    behind (files truncated to the fsync'd watermark — the user-space
    buffer and un-fsynced tail are gone) and replay it: every payload of
    every batch dispatched so far must be present."""
    wal_dir = tmp_path / "wal"
    eng = Engine(EngineConfig(**SMALL, wal_dir=str(wal_dir)))
    if eng._arena_pool is None:
        pytest.skip("native arena path unavailable")
    real_step = eng._step
    dispatched_rows = []
    snapshots = []

    def checking_step(state, batch):
        n_valid = int(np.sum(np.asarray(batch.valid)))
        dispatched_rows.append(n_valid)
        snapshots.append((sum(dispatched_rows), eng.wal.durable_view()))
        return real_step(state, batch)

    eng._step = checking_step
    for b in range(10):
        eng.ingest_json_batch(_payload_batch(b, n=96))
    eng.flush()
    assert sum(dispatched_rows) == 960
    assert len(snapshots) >= 3
    for rows_so_far, view in snapshots:
        crash_dir = tmp_path / f"crash-{rows_so_far}"
        crash_dir.mkdir()
        for name, nbytes in view.items():
            data = (wal_dir / name).read_bytes()[:nbytes]
            pathlib.Path(crash_dir / name).write_bytes(data)
        survived = list(IngestLog(crash_dir, readonly=True).replay())
        assert len(survived) >= rows_so_far, \
            f"crash after {rows_so_far} dispatched rows lost records " \
            f"({len(survived)} survived)"
    eng.wal.close()


def test_fsync_failure_blocks_dispatch_fail_stop(tmp_path):
    """Fail injection between buffered append and fsync: the dispatch
    gate must refuse (loudly) rather than dispatch an un-durable batch,
    and the log stays poisoned (fail-stop — a later commit must never
    retroactively claim durability for lost frames)."""
    eng = Engine(EngineConfig(**SMALL, wal_dir=str(tmp_path / "wal")))

    def boom():
        raise OSError("injected fsync failure")

    eng.wal._commit_hook = boom
    dispatches_before = eng._arena_dispatches
    with pytest.raises(Exception) as ei:
        for b in range(8):
            eng.ingest_json_batch(_payload_batch(b, n=96))
        eng.flush()
    assert "WAL" in str(ei.value) or "fsync" in str(ei.value)
    assert eng._arena_dispatches == dispatches_before, \
        "a batch was dispatched without durability"
    # poisoned: further appends refuse too
    with pytest.raises(RuntimeError):
        eng.wal.append_many([b"x"], b"\x01t\x00")
    eng.wal.close()


# ----------------------------------------------------- watermark semantics
def test_watermark_rides_group_commit_in_order(tmp_path):
    """A watermark buffered between two groups must land between them on
    disk: replay with a snapshot cursor at the watermark skips exactly
    the records before it."""
    log = IngestLog(tmp_path / "wal", group_commit=True)
    log.append_many([b"a1", b"a2"])
    log.append_watermark(50)
    log.append_many([b"b1", b"b2"])
    log.sync()
    log.close()
    replayed = list(IngestLog(tmp_path / "wal", readonly=True).replay())
    assert replayed == [b"a1", b"a2", b"b1", b"b2"]
    # snapshot covers cursor 50: records before the watermark are skipped
    after = list(IngestLog(tmp_path / "wal",
                           readonly=True).replay(after_cursor=60))
    assert after == [b"b1", b"b2"]
    # snapshot older than the watermark: everything replays
    before = list(IngestLog(tmp_path / "wal",
                            readonly=True).replay(after_cursor=10))
    assert before == [b"a1", b"a2", b"b1", b"b2"]


def test_watermark_wrap_across_segment_rotation(tmp_path):
    """Segment rotation under group commit: the watermark and its
    surrounding records stay ordered across the segment boundary, the
    sealed segment is fsync'd before the new one opens, and replay
    honors the watermark exactly as in the single-segment case."""
    log = IngestLog(tmp_path / "wal", segment_bytes=256, group_commit=True)
    first = [f"pre-{i}".encode() * 8 for i in range(8)]
    for p in first:
        log.append(p)
        log.flush()             # force commits so rotation interleaves
    log.append_watermark(100)
    tail = [f"post-{i}".encode() * 8 for i in range(8)]
    for p in tail:
        log.append(p)
    log.sync()
    segs = sorted((tmp_path / "wal").glob("segment-*.log"))
    assert len(segs) >= 2, "rotation never happened"
    view = log.durable_view()
    for s in segs:
        assert view[s.name] == s.stat().st_size   # everything durable
    log.close()
    assert list(IngestLog(tmp_path / "wal", readonly=True).replay()) == \
        first + tail
    assert list(IngestLog(tmp_path / "wal",
                          readonly=True).replay(after_cursor=150)) == tail


# ------------------------------------------------------------- concurrency
def test_concurrent_appenders_one_commit_each_group(tmp_path):
    """Several threads appending concurrently: every group becomes
    durable, replay sees every record exactly once, and the commit count
    stays below the group count (they share fsyncs)."""
    log = IngestLog(tmp_path / "wal", group_commit=True,
                    group_window_s=0.005)
    n_threads, n_groups = 4, 12

    def appender(t):
        for g in range(n_groups):
            log.append_many([f"t{t}-g{g}-r{r}".encode() for r in range(5)])

    threads = [threading.Thread(target=appender, args=(t,))
               for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    log.sync()
    assert log.fsyncs < n_threads * n_groups
    log.close()
    replayed = list(IngestLog(tmp_path / "wal", readonly=True).replay())
    assert sorted(replayed) == sorted(
        f"t{t}-g{g}-r{r}".encode()
        for t in range(n_threads) for g in range(n_groups)
        for r in range(5))


def test_wait_durable_seq_zero_is_immediate(tmp_path):
    log = IngestLog(tmp_path / "wal", group_commit=True)
    log.wait_durable(0)          # nothing appended: no block, no error
    seq = log.append_many([b"only"])
    log.wait_durable(seq)
    assert log.durable_seq >= seq
    log.close()


def test_empty_append_group_does_not_hang(tmp_path):
    """append_many([]) adds no records, so its ticket must be the PRIOR
    group's — a fresh sequence here would never wake the commit thread
    and the gate would time out."""
    log = IngestLog(tmp_path / "wal", group_commit=True)
    seq0 = log.append_many([b"a"])
    log.wait_durable(seq0)
    seq = log.append_many([])
    assert seq == seq0
    log.wait_durable(seq, timeout=5)    # must return immediately
    log.flush()                         # ditto
    log.close()


def test_durable_view_reports_nothing_before_first_commit(tmp_path):
    """Before any commit, nothing is fsync'd — not even the segment
    magic header, which sits in the user-space write buffer. A crash
    'now' leaves a 0-byte file and durable_view must say so."""
    log = IngestLog(tmp_path / "wal", group_commit=True,
                    group_window_s=5.0)
    assert all(v == 0 for v in log.durable_view().values())
    log.close()


def test_group_commit_off_preserves_inline_contract(tmp_path):
    """wal_group_commit=False keeps the PR-2 behavior: appends write +
    flush inline, the gate is a no-op, and no commit thread exists."""
    eng = Engine(EngineConfig(**SMALL, wal_dir=str(tmp_path / "wal"),
                              wal_group_commit=False))
    assert not eng.wal.group_commit
    for b in range(4):
        eng.ingest_json_batch(_payload_batch(b))
    eng.flush()
    records = list(IngestLog(tmp_path / "wal", readonly=True).replay())
    assert len(records) == 4 * 32
    assert eng.wal.fsyncs == 0    # fsync stays the operator's sync() call
    eng.wal.close()
