"""Tenant config hot-reload: swap a decoder at runtime, next ingest uses it.

VERDICT r2 item 6: a POST/watch path that rebuilds a tenant's component
graph (sources/decoders/filters/destinations) live — reference: ZooKeeper
config watch + EventSourcesParser.java:50-126, README "Centralized
Configuration Management".
"""

import asyncio
import base64
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from sitewhere_tpu.config import apply_tenant_config, reload_tenant_config
from sitewhere_tpu.engine import EngineConfig
from sitewhere_tpu.instance.instance import InstanceConfig, SiteWhereTpuInstance
from sitewhere_tpu.web.rest import make_app

SCRIPT = """
from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType

def decode(payload, metadata):
    return [DecodedRequest(type=RequestType.DEVICE_MEASUREMENT,
                           device_token=payload.decode(),
                           measurements={"swapped": 42.0})]
"""


def mini_instance() -> SiteWhereTpuInstance:
    return SiteWhereTpuInstance(InstanceConfig(engine=EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=4096, batch_capacity=16, channels=4)))


def json_payload(token: str) -> bytes:
    return json.dumps({"deviceToken": token, "type": "DeviceMeasurement",
                       "request": {"name": "t", "value": 7.0}}).encode()


V1_CFG = {
    "eventSources": [
        {"id": "in", "type": "inmemory", "decoder": {"type": "json"}},
    ],
}


def scripted_cfg(script_path) -> dict:
    return {
        "eventSources": [
            {"id": "in", "type": "inmemory",
             "decoder": {"type": "scripted", "script": str(script_path)}},
        ],
    }


def test_reload_swaps_decoder_live(tmp_path):
    inst = mini_instance()
    apply_tenant_config(inst, V1_CFG)
    inst.event_sources.sources["in"].receivers[0].submit(json_payload("hr-1"))
    inst.engine.flush()
    assert inst.engine.get_device_state("hr-1")["measurements"]["t"]["value"] == 7.0

    (tmp_path / "dec.py").write_text(SCRIPT)
    asyncio.new_event_loop().run_until_complete(
        reload_tenant_config(inst, scripted_cfg(tmp_path / "dec.py")))

    # the source id survived the swap; the NEXT ingest decodes via script
    src = inst.event_sources.sources["in"]
    src.receivers[0].submit(b"hr-2")
    inst.engine.flush()
    st = inst.engine.get_device_state("hr-2")
    assert st["measurements"]["swapped"]["value"] == 42.0
    # exactly one source registered (old one detached)
    assert list(inst.event_sources.sources) == ["in"]
    assert sum(1 for c in inst.event_sources.children) == 1


def test_reload_validates_before_teardown(tmp_path):
    from sitewhere_tpu.config import ConfigError

    inst = mini_instance()
    apply_tenant_config(inst, V1_CFG)
    with pytest.raises(ConfigError):
        asyncio.new_event_loop().run_until_complete(reload_tenant_config(
            inst, {"eventSources": [{"id": "in", "type": "bogus"}]}))
    # the old graph is still serving
    inst.event_sources.sources["in"].receivers[0].submit(json_payload("hr-3"))
    inst.engine.flush()
    assert inst.engine.get_device_state("hr-3") is not None


def test_reload_over_rest_and_get_configuration(tmp_path):
    inst = mini_instance()
    apply_tenant_config(inst, V1_CFG)
    (tmp_path / "dec.py").write_text(SCRIPT)

    async def go():
        client = TestClient(TestServer(make_app(inst)))
        await client.start_server()
        try:
            basic = base64.b64encode(b"admin:password").decode()
            r = await client.get("/api/authapi/jwt",
                                 headers={"Authorization": f"Basic {basic}"})
            h = {"Authorization": f"Bearer {(await r.json())['token']}"}
            url = ("/api/microservices/event-sources/tenants/default"
                   "/configuration")
            r = await client.get(url, headers=h)
            body = await r.json()
            assert r.status == 200
            assert body["configuration"] == V1_CFG
            # live hot-reload over POST
            r = await client.post(url, json={
                "configuration": scripted_cfg(tmp_path / "dec.py")},
                headers=h)
            assert r.status == 200
            assert (await r.json())["summary"]["eventSources"] == ["in"]
            # bad config -> 400, old graph intact
            r = await client.post(url, json={
                "configuration": {"eventSources": [
                    {"id": "in", "type": "bogus"}]}}, headers=h)
            assert r.status == 400
            r = await client.get(url, headers=h)
            assert (await r.json())["configuration"] == \
                scripted_cfg(tmp_path / "dec.py")
        finally:
            await client.close()

    asyncio.new_event_loop().run_until_complete(go())
    # decoder actually swapped
    inst.event_sources.sources["in"].receivers[0].submit(b"hr-4")
    inst.engine.flush()
    assert inst.engine.get_device_state("hr-4")["measurements"]["swapped"]["value"] == 42.0


def test_config_file_watcher(tmp_path):
    import os

    from sitewhere_tpu.config import TenantConfigWatcher

    inst = mini_instance()
    cfg_file = tmp_path / "tenant.json"
    cfg_file.write_text(json.dumps(V1_CFG))
    apply_tenant_config(inst, cfg_file)
    watcher = TenantConfigWatcher(inst, cfg_file)

    async def drive():
        # first check adopts the already-applied startup config silently
        assert await watcher.check() is False
        (tmp_path / "dec.py").write_text(SCRIPT)
        cfg_file.write_text(json.dumps(scripted_cfg(tmp_path / "dec.py")))
        os.utime(cfg_file)   # defeat coarse mtime granularity
        assert await watcher.check() is True
        assert await watcher.check() is False   # no change -> no reload

    asyncio.new_event_loop().run_until_complete(drive())
    inst.event_sources.sources["in"].receivers[0].submit(b"hr-5")
    inst.engine.flush()
    assert inst.engine.get_device_state("hr-5")["measurements"]["swapped"]["value"] == 42.0


def test_reload_is_tenant_scoped(tmp_path):
    """Review r3: reloading tenant B must not clobber or tear down tenant
    A's recorded graph."""
    inst = mini_instance()
    apply_tenant_config(inst, V1_CFG, tenant="default")
    loop = asyncio.new_event_loop()
    loop.run_until_complete(reload_tenant_config(inst, {
        "eventSources": [{"id": "acme-in", "type": "inmemory",
                          "decoder": {"type": "json"}}]}, tenant="acme"))
    # both graphs live, both records present and distinct
    assert set(inst.event_sources.sources) == {"in", "acme-in"}
    assert inst.tenant_configs["default"]["summary"]["eventSources"] == ["in"]
    assert inst.tenant_configs["acme"]["summary"]["eventSources"] == ["acme-in"]
    # reloading default touches only default's components
    loop.run_until_complete(reload_tenant_config(inst, V1_CFG,
                                                 tenant="default"))
    assert set(inst.event_sources.sources) == {"in", "acme-in"}


def test_reload_rejects_id_collisions_before_teardown():
    from sitewhere_tpu.config import ConfigError

    inst = mini_instance()
    apply_tenant_config(inst, V1_CFG, tenant="default")
    loop = asyncio.new_event_loop()
    # duplicate ids inside one config
    with pytest.raises(ConfigError, match="duplicate"):
        loop.run_until_complete(reload_tenant_config(inst, {
            "eventSources": [
                {"id": "x", "type": "inmemory", "decoder": {"type": "json"}},
                {"id": "x", "type": "inmemory", "decoder": {"type": "json"}},
            ]}, tenant="acme"))
    # collision with ANOTHER tenant's live source
    with pytest.raises(ConfigError, match="already in use"):
        loop.run_until_complete(reload_tenant_config(inst, {
            "eventSources": [{"id": "in", "type": "inmemory",
                              "decoder": {"type": "json"}}]}, tenant="acme"))
    # default's graph untouched by either rejection
    assert set(inst.event_sources.sources) == {"in"}
    inst.event_sources.sources["in"].receivers[0].submit(json_payload("tc-1"))
    inst.engine.flush()
    assert inst.engine.get_device_state("tc-1") is not None


def test_reload_teardown_detaches_destinations():
    inst = mini_instance()
    cfg = dict(V1_CFG)
    cfg["commandRouting"] = {
        "destinations": [{"id": "d1", "type": "local",
                          "encoder": {"type": "json"}}]}
    apply_tenant_config(inst, cfg)
    n_children = len(inst.commands.children)
    loop = asyncio.new_event_loop()
    for _ in range(3):
        loop.run_until_complete(reload_tenant_config(inst, cfg))
    # children must not accumulate across reloads
    assert len(inst.commands.children) == n_children
    assert list(inst.commands.destinations) == ["d1"]


def test_scripting_and_config_endpoints_require_admin(tmp_path):
    inst = mini_instance()
    apply_tenant_config(inst, V1_CFG)
    inst.users.create_user("viewer", "pw", roles=["user"])

    async def go():
        client = TestClient(TestServer(make_app(inst)))
        await client.start_server()
        try:
            basic = base64.b64encode(b"viewer:pw").decode()
            r = await client.get("/api/authapi/jwt",
                                 headers={"Authorization": f"Basic {basic}"})
            h = {"Authorization": f"Bearer {(await r.json())['token']}"}
            sb = "/api/microservices/event-sources/tenants/default/scripting"
            r = await client.post(f"{sb}/scripts", json={
                "id": "evil", "content": "import os"}, headers=h)
            assert r.status == 403
            r = await client.get(f"{sb}/scripts", headers=h)
            assert r.status == 403
            r = await client.post(
                "/api/microservices/event-sources/tenants/default"
                "/configuration", json={"configuration": V1_CFG}, headers=h)
            assert r.status == 403
        finally:
            await client.close()

    asyncio.new_event_loop().run_until_complete(go())


def test_reload_retires_stale_router():
    """Review r3: dropping commandRouting from a tenant's config must not
    leave the old router aimed at torn-down destinations."""
    from sitewhere_tpu.commands.routing import NoOpCommandRouter

    inst = mini_instance()
    cfg = dict(V1_CFG)
    cfg["commandRouting"] = {
        "router": {"type": "single-choice", "destination": "d1"},
        "destinations": [{"id": "d1", "type": "local",
                          "encoder": {"type": "json"}}]}
    apply_tenant_config(inst, cfg)
    installed = inst.commands.router
    loop = asyncio.new_event_loop()
    # new config without commandRouting: destinations AND router retire
    loop.run_until_complete(reload_tenant_config(inst, V1_CFG))
    assert inst.commands.destinations == {}
    assert isinstance(inst.commands.router, NoOpCommandRouter)
    assert inst.commands.router is not installed
    # a config WITH routing installs its own router again
    loop.run_until_complete(reload_tenant_config(inst, cfg))
    assert not isinstance(inst.commands.router, NoOpCommandRouter)
    assert list(inst.commands.destinations) == ["d1"]
