"""Cluster-replicated management-entity plane (VERDICT r4 missing #1).

Reference model: every replica of a service shares one per-tenant DB —
a device type created via any node is instantly usable by all replicas
(RdbDeviceManagement.java:127-159). Here entity mutations ship their
post-state over the authenticated cluster RPC with per-origin sequences,
a CRC'd journal for crash recovery, and pull anti-entropy for ranks that
were down during a push (parallel/entity_sync.py).
"""

import asyncio
import json
import time

import pytest

from sitewhere_tpu.engine import EngineConfig
from sitewhere_tpu.instance.instance import InstanceConfig, SiteWhereTpuInstance
from sitewhere_tpu.parallel.entity_sync import (EntityReplicator, from_state,
                                                to_state)
from tests.test_cluster import (BASE_MS, BASE_S, _free_ports, _mk_cluster,
                                meas, tokens_owned_by)


def _mk_replicated(tmp_path, with_logs=True):
    """Two ranks with instances + attached replicators over live RPC."""
    clusters, host, ports = _mk_cluster(tmp_path)
    insts, reps = [], []
    for i, c in enumerate(clusters):
        inst = SiteWhereTpuInstance(
            InstanceConfig(engine=EngineConfig()), engine=c)
        rep = EntityReplicator(
            c, inst,
            log_dir=str(tmp_path / f"elog-r{i}") if with_logs else None)
        rep.attach()
        rep.register_rpc(host.servers[i])
        insts.append(inst)
        reps.append(rep)
    return clusters, insts, reps, host


def _close_all(clusters, reps, host):
    for rep in reps:
        rep.close()
    for c in clusters:
        c.close()
    host.close()


def test_entity_plane_replicates_from_any_rank(tmp_path):
    """THE done-criterion: rank 0 creates a device type + command +
    schedule; rank 1 ingests a device of that type, routes that command,
    and fires that schedule — with no per-rank admin."""
    from sitewhere_tpu.commands.destinations import (CommandDestination,
                                                     LocalDeliveryProvider,
                                                     mqtt_topic_extractor)
    from sitewhere_tpu.commands.encoders import JsonCommandExecutionEncoder
    from sitewhere_tpu.commands.model import DeviceCommand

    clusters, insts, reps, host = _mk_replicated(tmp_path)
    c0, c1 = clusters
    try:
        # ---- rank 0 administers EVERYTHING, exactly once --------------
        insts[0].device_management.create_device_type("sensor-x",
                                                      "Sensor X")
        insts[0].command_registry.create(DeviceCommand(
            token="calibrate", device_type="sensor-x", name="calibrate"))
        # schedule whose token is OWNED by rank 1 (fires there only)
        sched_tok = tokens_owned_by(1, 1, prefix="sch")[0]
        insts[0].scheduler.register_executor("test", lambda job: None)
        insts[1].scheduler.register_executor("test", lambda job: None)
        insts[0].scheduler.create_schedule(sched_tok, "every-min",
                                           "Simple", interval_s=60)
        insts[0].scheduler.create_job("job-1", sched_tok, "test", {})
        reps[0].drain_pushes()   # pushes are async (off the admin thread)

        # ---- rank 1 uses all three with no admin of its own -----------
        # the type validates against rank 1's OWN (replicated) store
        dev = tokens_owned_by(0, 1, prefix="ent")[0]   # owned by rank 0
        insts[1].device_management.create_device(dev, "sensor-x")
        assert c0.get_device(dev).device_type == "sensor-x"
        # the command definition replicated: invoke at rank 1 routes to
        # the owner (rank 0), whose pump delivers it
        p0 = LocalDeliveryProvider()
        insts[0].commands.add_destination(CommandDestination(
            "default", mqtt_topic_extractor(),
            JsonCommandExecutionEncoder(), p0))
        inv = insts[1].commands.invoke(dev, "calibrate", {})
        assert inv.invocation_id % 2 == 0      # rank 0's id space
        c1.flush()
        loop = asyncio.new_event_loop()
        try:
            fired0 = loop.run_until_complete(insts[0].scheduler.fire_due())
            fired1 = loop.run_until_complete(insts[1].scheduler.fire_due())
            pumped = loop.run_until_complete(insts[0].commands.pump())
        finally:
            loop.close()
        assert pumped == 1 and len(p0.delivered) == 1
        # the replicated schedule fires at its OWNER rank only — not N
        # times across the cluster
        assert (fired0, fired1) == (0, 1)
        job1 = insts[1].scheduler.jobs.get("job-1")
        assert job1.fired_count == 1
        # listings agree from both ranks (meta ids/timestamps shipped)
        dt0 = insts[0].device_management.device_types.get("sensor-x")
        dt1 = insts[1].device_management.device_types.get("sensor-x")
        assert to_state(dt0) == to_state(dt1)
    finally:
        _close_all(clusters, reps, host)


def test_closure_updates_groups_and_alarm_enums_replicate(tmp_path):
    """The REST tier's closure-based PUT handlers, group membership, and
    enum-bearing entities all replicate as POST-state."""
    clusters, insts, reps, host = _mk_replicated(tmp_path)
    c0, c1 = clusters
    try:
        dm0, dm1 = insts[0].device_management, insts[1].device_management
        dm0.create_device_type("gw", "Gateway")
        reps[0].drain_pushes()
        # closure update (what rest.py _store_update does)
        dm1.device_types.update(
            "gw", lambda t: setattr(t, "description", "edge gateway"))
        reps[1].drain_pushes()
        assert dm0.device_types.get("gw").description == "edge gateway"
        # groups + membership (elements ship as one replicated value)
        dev = tokens_owned_by(0, 1, prefix="grp")[0]
        c1.register_device(dev, "gw")
        dm0.create_group("fleet", "Fleet", roles=["prod"])
        reps[0].drain_pushes()
        els = dm1.add_group_elements("fleet", [{"device": dev,
                                                "roles": ["prod"]}])
        reps[1].drain_pushes()
        assert [e.device_token for e in dm0.group_elements("fleet")] == [dev]
        assert dm0.expand_group_devices("fleet") == [dev]
        dm0.remove_group_element("fleet", els[0].element_id)
        reps[0].drain_pushes()
        assert dm1.group_elements("fleet") == []
        # alarms carry an Enum; ack at the OTHER rank round-trips it
        dm0.create_alarm("al-1", dev, "overheat")
        reps[0].drain_pushes()
        a = dm1.acknowledge_alarm("al-1")
        reps[1].drain_pushes()
        from sitewhere_tpu.management.device_management import AlarmState

        assert dm0.alarms.get("al-1").state is AlarmState.ACKNOWLEDGED
        assert a.acknowledged_ms is not None
        # deletes replicate too
        dm1.device_types.delete("gw")
        reps[1].drain_pushes()
        assert "gw" not in dm0.device_types
    finally:
        _close_all(clusters, reps, host)


def test_users_and_tenants_replicate(tmp_path):
    """A user created at rank 0 logs in at rank 1 (only the PBKDF2 hash
    crosses the wire); a tenant created at rank 0 exists at rank 1 with
    its dataset-seeded entities and its engine lane interned."""
    clusters, insts, reps, host = _mk_replicated(tmp_path)
    try:
        insts[0].users.create_user("operator", "s3cret", roles=["user"])
        reps[0].drain_pushes()
        u = insts[1].users.authenticate("operator", "s3cret")
        assert u.username == "operator"
        # plaintext never entered any op
        for rep in reps:
            for ops in rep._ops_by_origin.values():
                for op in ops:
                    assert "s3cret" not in json.dumps(op)
        # role catalogs replicate
        insts[1].users.create_role("auditor", ["VIEW_SERVER_INFORMATION"])
        reps[1].drain_pushes()
        assert "auditor" in insts[0].users.roles
        # tenant + dataset bootstrap: the SEEDED entities arrive as their
        # own ops; the tenant lane interns on the peer engine
        insts[0].tenants.create_tenant("acme", "Acme",
                                       dataset_template="construction")
        reps[0].drain_pushes()
        t1 = insts[1].tenants.tenants.get("acme")
        assert t1.bootstrap_state == "Bootstrapped"
        assert "acme-excavator" in insts[1].device_management.device_types
        # the tenant LANE interned on the peer engine (ingest under
        # tenant "acme" resolves there without any per-rank admin)
        assert clusters[1].local.tenants.lookup("acme") is not None
    finally:
        _close_all(clusters, reps, host)


def test_recovery_replay_and_anti_entropy(tmp_path):
    """A SIGKILL'd rank replays its entity journal on restart; a rank
    that was DOWN during pushes converges via one anti-entropy pull."""
    clusters, insts, reps, host = _mk_replicated(tmp_path)
    c0, c1 = clusters
    try:
        dm0 = insts[0].device_management
        dm0.create_device_type("dur", "Durable")
        dm0.create_area_type("region", "Region")
        dm0.create_area("west", "region", "West")
        insts[0].assets.create_asset_type("truck", "Truck")
        reps[0].drain_pushes()
        n_ops = sum(len(v) for v in reps[0]._ops_by_origin.values())
        assert n_ops >= 4

        # ---- crash-restart rank 0's entity plane (journal replay) -----
        reps[0].close()
        inst0b = SiteWhereTpuInstance(
            InstanceConfig(engine=EngineConfig()), engine=c0)
        rep0b = EntityReplicator(c0, inst0b,
                                 log_dir=str(tmp_path / "elog-r0"))
        rep0b.attach()
        assert "dur" in inst0b.device_management.device_types
        assert "west" in inst0b.device_management.areas
        assert "truck" in inst0b.assets.asset_types
        assert rep0b.vector == reps[0].vector
        reps[0] = rep0b

        # ---- a rank that missed pushes pulls the backlog --------------
        inst1b = SiteWhereTpuInstance(
            InstanceConfig(engine=EngineConfig()), engine=c1)
        rep1b = EntityReplicator(c1, inst1b, log_dir=None)   # fresh, empty
        rep1b.attach()
        assert "dur" not in inst1b.device_management.device_types
        pulled = rep1b.sync_from_peers(best_effort=False)
        assert pulled >= n_ops
        assert "dur" in inst1b.device_management.device_types
        assert "west" in inst1b.device_management.areas
        reps[1].close()
        reps[1] = rep1b
    finally:
        _close_all(clusters, reps, host)


def test_lww_converges_under_any_delivery_order(tmp_path):
    """Concurrent writes to the same entity converge to the same value on
    every rank regardless of delivery order: last-writer-wins on
    (ts, origin)."""
    from sitewhere_tpu.management.device_management import DeviceType
    from sitewhere_tpu.management.entities import EntityMeta

    clusters, insts, reps, host = _mk_replicated(tmp_path, with_logs=False)
    try:
        def op(origin, seq, ts, name):
            state = to_state(DeviceType(
                meta=EntityMeta(id=7, token="lww", created_ms=1.0,
                                updated_ms=ts),
                name=name))
            return {"origin": origin, "seq": seq, "ts": ts,
                    "action": "upsert", "kind": "device-type",
                    "token": "lww", "state": state}

        older = op(2, 1, 1000.0, "old-name")
        newer = op(3, 1, 2000.0, "new-name")
        # rank 0 sees newer first, rank 1 sees older first (apply_op =
        # raw push delivery; apply_batch would sort)
        reps[0].apply_op(newer)
        reps[0].apply_op(older)
        reps[1].apply_op(older)
        reps[1].apply_op(newer)
        n0 = insts[0].device_management.device_types.get("lww").name
        n1 = insts[1].device_management.device_types.get("lww").name
        assert n0 == n1 == "new-name"
        assert reps[0].counters["lww_skipped"] == 1
    finally:
        _close_all(clusters, reps, host)


def test_codec_roundtrips_nested_and_enum_fields():
    from sitewhere_tpu.commands.model import (CommandParameter,
                                              DeviceCommand, ParameterType)
    from sitewhere_tpu.management.device_management import Zone
    from sitewhere_tpu.management.entities import EntityMeta

    cmd = DeviceCommand(
        token="set", device_type="dt", name="set",
        parameters=(CommandParameter("level", ParameterType.INT64, True),))
    back = from_state(DeviceCommand, to_state(cmd))
    assert back == cmd and isinstance(back.parameters, tuple)
    assert back.parameters[0].type is ParameterType.INT64

    z = Zone(meta=EntityMeta(id=1, token="z", created_ms=1, updated_ms=2),
             name="z", area_token="a",
             bounds=[(1.0, 2.0), (3.0, 4.0), (5.0, 6.0)])
    zb = from_state(Zone, to_state(z))
    assert zb.bounds == [(1.0, 2.0), (3.0, 4.0), (5.0, 6.0)]
    assert isinstance(zb.bounds[0], tuple)


def test_concurrent_creates_never_collide_on_ids(tmp_path):
    """Rank-namespaced id allocation: two ranks creating DIFFERENT
    entities concurrently must never mint the same id — a replicated
    upsert would silently clobber the other rank's entity in _by_id."""
    clusters, insts, reps, host = _mk_replicated(tmp_path, with_logs=False)
    try:
        dm0, dm1 = insts[0].device_management, insts[1].device_management
        # both creates land in the same "next" slot before either push
        dm0.create_device_type("cc-a", "A")
        dm1.create_device_type("cc-b", "B")
        reps[0].drain_pushes()
        reps[1].drain_pushes()
        for dm in (dm0, dm1):
            a, b = dm.device_types.get("cc-a"), dm.device_types.get("cc-b")
            assert (a.name, b.name) == ("A", "B")
            assert a.meta.id != b.meta.id
            assert len(dm.device_types.list(page_size=50).results) == \
                len(dm.device_types)
        # the two ranks agree on every id (shipped meta is authoritative)
        assert to_state(dm0.device_types.get("cc-b")) == \
            to_state(dm1.device_types.get("cc-b"))
    finally:
        _close_all(clusters, reps, host)


def test_compaction_bounds_index_and_late_joiner_state_transfer(tmp_path):
    """A long-running plane compacts: the op index/journal stay
    O(live + tail), and a rank behind the compaction floor converges by
    LWW state transfer (tombstones included) instead of op backfill —
    the cluster never grows without bound and never strands a late
    joiner (the reference's shared DB has both properties trivially)."""
    clusters, insts, reps, host = _mk_cluster_staggered(tmp_path)
    c0, c1 = clusters
    rep0 = EntityReplicator(c0, insts[0],
                            log_dir=str(tmp_path / "elog-r0"),
                            compact_threshold=30, compact_keep=5)
    rep0.attach()
    rep0.register_rpc(host.servers[0])
    reps.append(rep0)
    try:
        dm0 = insts[0].device_management
        for i in range(40):
            dm0.create_device_type(f"ct-{i}", f"Type {i}")
        dm0.device_types.delete("ct-3")          # tombstones must ship
        dm0.device_types.delete("ct-7")
        rep0.drain_pushes()
        assert rep0.counters["compactions"] >= 1
        assert rep0._total_ops <= 30             # bounded index
        # journal too: replaying it must NOT need the pruned ops
        # (checked structurally: the floor sits above seq 1)
        ops0 = rep0._ops_by_origin[0]
        assert ops0[0]["seq"] > 1

        # ---- late joiner: behind the floor -> full state transfer -----
        rep1 = EntityReplicator(c1, insts[1],
                                log_dir=str(tmp_path / "elog-r1"))
        rep1.attach()
        rep1.register_rpc(host.servers[1])
        reps.append(rep1)
        dm1 = insts[1].device_management
        assert "ct-0" not in dm1.device_types
        # the compacted rank answers an empty vector with the reset
        # marker — op backfill below the floor must be refused, loudly
        assert rep0.ops_since({}) == {"reset": True}
        pulled = rep1.sync_from_peers(best_effort=False)
        assert rep1.counters["state_transfers"] == 1
        assert pulled >= 38
        assert "ct-0" in dm1.device_types and "ct-39" in dm1.device_types
        assert "ct-3" not in dm1.device_types    # tombstone applied
        assert "ct-7" not in dm1.device_types
        assert to_state(dm1.device_types.get("ct-5")) == \
            to_state(dm0.device_types.get("ct-5"))
        # vector adopted: the NEXT push applies as a normal op
        dm0.create_device_type("ct-after", "After")
        rep0.drain_pushes()
        assert "ct-after" in dm1.device_types
        assert rep1.counters["state_transfers"] == 1   # no second reset
    finally:
        _close_all(clusters, reps, host)


def _mk_cluster_staggered(tmp_path):
    """Cluster + instances WITHOUT replicators (tests attach their own,
    at different times, with different compaction budgets)."""
    clusters, host, ports = _mk_cluster(tmp_path)
    insts = [SiteWhereTpuInstance(InstanceConfig(engine=EngineConfig()),
                                  engine=c) for c in clusters]
    return clusters, insts, [], host


def test_state_transfer_pages_over_shrunken_frame_cap(tmp_path,
                                                      monkeypatch):
    """ADVICE r5 medium: an LWW state dump larger than MAX_FRAME used to
    permanently strand a late joiner (the one-frame Cluster.entityState
    raised 413 on every anti-entropy pass). The paged transfer must
    converge it through a frame cap the FULL dump cannot fit — forced
    here by shrinking MAX_FRAME under the dump size and the page size
    under the cap."""
    import sitewhere_tpu.parallel.entity_sync as es
    import sitewhere_tpu.rpc.protocol as proto

    clusters, insts, reps, host = _mk_cluster_staggered(tmp_path)
    c0, c1 = clusters
    rep0 = EntityReplicator(c0, insts[0],
                            log_dir=str(tmp_path / "elog-r0"),
                            compact_threshold=30, compact_keep=5)
    rep0.attach()
    rep0.register_rpc(host.servers[0])
    reps.append(rep0)
    try:
        dm0 = insts[0].device_management
        pad = "x" * 300          # make each entity's state body meaty
        for i in range(60):
            dm0.create_device_type(f"pg-{i}", f"Type {i} {pad}")
        rep0.drain_pushes()
        assert rep0.counters["compactions"] >= 1   # floor above seq 1
        full = json.dumps(rep0.state_dump()).encode()
        cap = 16384
        assert len(full) > cap, "test premise: dump must exceed the cap"
        # shrink the wire cap below the dump AND the page size below the
        # cap — every entityState page must now fit where the old
        # one-frame dump could not
        monkeypatch.setattr(proto, "MAX_FRAME", cap)
        rep0.state_page_bytes = 4096
        rep0.state_page_entries = 16

        rep1 = EntityReplicator(c1, insts[1],
                                log_dir=str(tmp_path / "elog-r1"))
        rep1.attach()
        rep1.register_rpc(host.servers[1])
        reps.append(rep1)
        assert rep0.ops_since({}) == {"reset": True}   # behind the floor
        pulled = rep1.sync_from_peers(best_effort=False)
        assert pulled >= 60
        assert rep0.counters["state_pages_served"] >= 2, (
            "the transfer must actually have paged")
        dm1 = insts[1].device_management
        assert "pg-0" in dm1.device_types and "pg-59" in dm1.device_types
        assert to_state(dm1.device_types.get("pg-30")) == \
            to_state(dm0.device_types.get("pg-30"))
        # vector adopted from the final page: later ops apply normally
        dm0.create_device_type("pg-after", "After")
        rep0.drain_pushes()
        assert "pg-after" in dm1.device_types
        # an expired cursor (snapshot evicted) restarts, not wedges
        page = rep0.state_page(cursor={"tid": "gone", "pos": 3})
        assert page == {"expired": True}
    finally:
        _close_all(clusters, reps, host)


def test_compacted_journal_restart_replays_dump_plus_tail(tmp_path):
    """After compaction the journal is one state dump + the kept tail;
    a crash-restart replays both: full state back, vector preserved,
    op index rebuilt to exactly the tail."""
    clusters, insts, reps, host = _mk_cluster_staggered(tmp_path)
    c0 = clusters[0]
    rep0 = EntityReplicator(c0, insts[0],
                            log_dir=str(tmp_path / "elog-r0"),
                            compact_threshold=20, compact_keep=4)
    rep0.attach()
    rep0.register_rpc(host.servers[0])
    reps.append(rep0)
    try:
        dm0 = insts[0].device_management
        for i in range(30):
            dm0.create_device_type(f"rt-{i}", f"T{i}")
        dm0.device_types.delete("rt-1")
        rep0.drain_pushes()
        assert rep0.counters["compactions"] >= 1
        vec_before = dict(rep0.vector)
        tail_before = [o["seq"] for o in rep0._ops_by_origin[0]]

        rep0.close()
        inst0b = SiteWhereTpuInstance(
            InstanceConfig(engine=EngineConfig()), engine=c0)
        rep0b = EntityReplicator(c0, inst0b,
                                 log_dir=str(tmp_path / "elog-r0"),
                                 compact_threshold=20, compact_keep=4)
        rep0b.attach()
        reps[0] = rep0b
        dmb = inst0b.device_management
        assert "rt-0" in dmb.device_types and "rt-29" in dmb.device_types
        assert "rt-1" not in dmb.device_types     # tombstone survives
        assert rep0b.vector == vec_before
        assert [o["seq"] for o in rep0b._ops_by_origin[0]] == tail_before
        assert to_state(dmb.device_types.get("rt-29")) == \
            to_state(dm0.device_types.get("rt-29"))
    finally:
        _close_all(clusters, reps, host)


def test_concurrent_mutations_with_compaction_storm_converge(tmp_path):
    """§5.3 concurrency: two ranks mutating concurrently while a tiny
    compaction budget forces journal rewrites mid-stream — no deadlock
    (replicator lock -> store lock is the only order), no lost entity,
    and both ranks converge after drain + anti-entropy."""
    import threading

    clusters, insts, reps, host = _mk_cluster_staggered(tmp_path)
    for i, c in enumerate(clusters):
        rep = EntityReplicator(c, insts[i],
                               log_dir=str(tmp_path / f"elog-r{i}"),
                               compact_threshold=12, compact_keep=3)
        rep.attach()
        rep.register_rpc(host.servers[i])
        reps.append(rep)
    try:
        N = 25
        errs = []

        def spam(rank):
            try:
                dm = insts[rank].device_management
                for i in range(N):
                    dm.create_device_type(f"st-{rank}-{i}", f"T{rank}-{i}")
            except Exception as e:   # pragma: no cover - fail loudly
                errs.append(e)

        threads = [threading.Thread(target=spam, args=(r,))
                   for r in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "mutator deadlocked"
        assert not errs, errs
        for rep in reps:
            rep.drain_pushes()
        # pushes racing compaction floors may have been refused — the
        # pull path must close any residue
        for rep in reps:
            rep.sync_from_peers(best_effort=False)
        for rank in range(2):
            for i in range(N):
                tok = f"st-{rank}-{i}"
                a = insts[0].device_management.device_types.get(tok)
                b = insts[1].device_management.device_types.get(tok)
                assert to_state(a) == to_state(b), tok
        assert max(rep.counters["compactions"] for rep in reps) >= 1
        # bounded: neither index grew past threshold + one burst
        for rep in reps:
            assert rep._total_ops <= 12 + 2 * 3 + 1
    finally:
        _close_all(clusters, reps, host)


def test_tombstone_gc_safe_horizon_never_resurrects(tmp_path):
    """ISSUE 6 satellite: deleted entities leave LWW tombstones that
    previously lived forever (memory + state-transfer payloads). GC
    drops a tombstone only once EVERY rank's receipt vector covers the
    delete op — past that horizon no peer can still ship a pre-delete
    state, so GC can never resurrect (pinned below)."""
    clusters, insts, reps, host = _mk_replicated(tmp_path)
    r0, r1 = reps
    try:
        insts[0].device_management.create_device_type("gone-type", "Gone")
        store0 = insts[0].device_management.device_types
        store1 = insts[1].device_management.device_types
        r0.drain_pushes()
        assert store1.try_get("gone-type") is not None
        create_seq = r0.vector[0]
        store0.delete("gone-type")
        r0.drain_pushes()
        assert store1.try_get("gone-type") is None
        assert ("device-type", "gone-type") in r0._tombstones
        assert ("device-type", "gone-type") in r1._tombstones

        # horizon evidence: each rank must have SEEN the other's vector
        r0.sync_from_peers()
        r1.sync_from_peers()
        # too fresh: the age floor refuses (no race with in-flight
        # transfers)
        assert r1.gc_tombstones() == 0
        assert r0.gc_tombstones(min_age_ms=0) == 1
        assert r1.gc_tombstones(min_age_ms=0) == 1
        assert ("device-type", "gone-type") not in r0._last
        assert r0.metrics()["entity_tombstones"] == 0

        # --- never resurrects -----------------------------------------
        # (1) a full LWW state transfer after GC ships no trace of it
        assert r1._pull_state(0) == 0
        assert store1.try_get("gone-type") is None
        # (2) a replayed PRE-DELETE op (origin 0, the create's seq) is
        # blocked by the receipt vector, not re-applied
        res = r1.apply_op({"origin": 0, "seq": create_seq,
                           "ts": time.time() * 1000 + 10_000,
                           "action": "upsert", "kind": "device-type",
                           "token": "gone-type",
                           "state": {"meta": {"token": "gone-type",
                                              "id": 999},
                                     "name": "Zombie"}})
        assert res.get("duplicate")
        assert store1.try_get("gone-type") is None
    finally:
        _close_all(clusters, reps, host)


def test_tombstone_gc_waits_for_lagging_peer(tmp_path):
    """The safe half of the horizon: while ANY rank's vector does not
    cover the delete, the tombstone stays (a state transfer from the
    laggard could still carry pre-delete state)."""
    clusters, insts, reps, host = _mk_replicated(tmp_path)
    r0, r1 = reps
    try:
        insts[0].device_management.create_device_type("lag-type", "Lag")
        store0 = insts[0].device_management.device_types
        store0.delete("lag-type")
        r0.drain_pushes()
        # rank 0 has NEVER pulled rank 1's vector: no evidence -> no GC
        assert r0.gc_tombstones(min_age_ms=0) == 0
        # stale evidence: pretend rank 1 is far behind the delete
        with r0._lock:
            r0._peer_vectors[1] = {0: 0}
        assert r0.gc_tombstones(min_age_ms=0) == 0
        assert ("device-type", "lag-type") in r0._tombstones
        # real evidence unblocks it
        r0.sync_from_peers()
        assert r0.gc_tombstones(min_age_ms=0) == 1
    finally:
        _close_all(clusters, reps, host)
