"""Tests for telemetry windows + anomaly models (the tpu-analytics service)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sitewhere_tpu.models.anomaly import (
    AnomalyConfig,
    AnomalyModel,
    make_train_step,
    param_shardings,
)
from sitewhere_tpu.models.windows import (
    TelemetryWindows,
    append_measurements,
    snapshot_windows,
)

CFG = AnomalyConfig(sensors=8, window=16, hidden=128, lstm_hidden=128, latent=16)


def test_window_ring_append_and_snapshot(rng):
    m, w, c = 4, 8, 3
    wins = TelemetryWindows.zeros(m, w, c)
    # two batches: device 1 gets 5 then 6 rows -> ring wraps, order preserved
    vals1 = rng.random((5, c)).astype(np.float32)
    vals2 = rng.random((6, c)).astype(np.float32)

    def push(wins, vals, ts0):
        b = vals.shape[0]
        return append_measurements(
            wins,
            dev=jnp.full((b,), 1, jnp.int32),
            found=jnp.ones((b,), bool),
            etype=jnp.zeros((b,), jnp.int32),
            ts_ms=jnp.arange(ts0, ts0 + b, dtype=jnp.int32),
            seq=jnp.arange(b, dtype=jnp.int32),
            values=jnp.asarray(vals),
        )

    wins = push(wins, vals1, 0)
    wins = push(wins, vals2, 100)
    assert int(wins.filled[1]) == 11
    snap = np.asarray(snapshot_windows(wins))[1]  # [W, C] oldest..newest
    # last 8 of the 11 appended rows, in order
    expect = np.concatenate([vals1, vals2])[-w:]
    np.testing.assert_allclose(snap, expect, rtol=1e-6)


def test_window_interleaved_devices(rng):
    m, w, c = 3, 4, 2
    wins = TelemetryWindows.zeros(m, w, c)
    devs = np.array([0, 1, 0, 2, 1, 0], np.int32)
    vals = rng.random((6, c)).astype(np.float32)
    wins = append_measurements(
        wins,
        dev=jnp.asarray(devs),
        found=jnp.ones(6, bool),
        etype=jnp.zeros(6, jnp.int32),
        ts_ms=jnp.arange(6, dtype=jnp.int32),
        seq=jnp.arange(6, dtype=jnp.int32),
        values=jnp.asarray(vals),
    )
    for d in range(3):
        mine = vals[devs == d]
        assert int(wins.filled[d]) == len(mine)
        got = np.asarray(wins.data[d, : len(mine)])
        np.testing.assert_allclose(got, mine, rtol=1e-6)


def test_anomaly_model_forward_and_train(rng):
    model = AnomalyModel(CFG)
    x = jnp.asarray(rng.random((4, CFG.window, CFG.sensors)), jnp.float32)
    params = model.init(jax.random.key(0), x)
    scores = model.apply(params, x)
    assert scores.shape == (4,)
    assert np.all(np.isfinite(np.asarray(scores)))

    tx = optax.adamw(1e-3)
    step = jax.jit(make_train_step(model, tx))
    opt_state = tx.init(params)
    l0 = None
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, x)
        l0 = l0 if l0 is not None else float(loss)
    assert float(loss) < l0  # training reduces reconstruction+forecast error


def test_anomaly_model_dp_tp_sharded(rng):
    """Train step under a real (dp, tp) mesh: batch on dp, hidden on tp."""
    devs = np.asarray(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devs, ("dp", "tp"))
    model = AnomalyModel(CFG)
    x = jnp.asarray(rng.random((8, CFG.window, CFG.sensors)), jnp.float32)
    params = model.init(jax.random.key(0), x)
    params = jax.device_put(params, param_shardings(params, mesh, "tp"))
    x = jax.device_put(x, NamedSharding(mesh, P("dp")))
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)
    step = jax.jit(make_train_step(model, tx))
    params, opt_state, loss = step(params, opt_state, x)
    assert np.isfinite(float(loss))
    # params keep their tp sharding after the update
    flat = jax.tree_util.tree_leaves(params)
    assert any(
        "tp" in str(getattr(leaf, "sharding", "")) for leaf in flat
    )


def test_window_features_pallas_matches_reference(rng):
    from sitewhere_tpu.ops.window_features import (
        normalize_windows,
        window_features,
        window_features_reference,
    )

    x = jnp.asarray(rng.standard_normal((100, 16, 8)), jnp.float32)
    ref = window_features_reference(x)
    pal = window_features(x, tile_m=32, force_pallas=True)
    np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    normed = normalize_windows(x, ref)
    np.testing.assert_allclose(np.asarray(normed.mean(axis=1)), 0.0, atol=1e-4)
    np.testing.assert_allclose(np.asarray(normed.std(axis=1)), 1.0, atol=1e-2)


def test_analytics_service_end_to_end(rng):
    """Windows fill from live events through the pipeline; the analytics
    service trains, scores, and injects anomaly alerts back as events."""
    from sitewhere_tpu.engine import Engine, EngineConfig
    from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType
    from sitewhere_tpu.models.anomaly import AnomalyConfig
    from sitewhere_tpu.models.service import AnalyticsService

    W = 8
    engine = Engine(EngineConfig(
        device_capacity=32, token_capacity=64, assignment_capacity=64,
        store_capacity=4096, batch_capacity=32, channels=4,
        analytics_devices=16, analytics_window=W,
    ))
    svc = AnalyticsService(
        engine,
        AnomalyConfig(sensors=4, window=W, hidden=64, lstm_hidden=64, latent=8),
        threshold=2.5, min_fill=W,
    )
    # 8 devices emit W sinusoid-ish samples; device an-7 is wildly different
    for t in range(W):
        for d in range(8):
            val = float(np.sin(t / 3) + 0.01 * d) if d != 7 else float(1e3 * (t + 1))
            engine.process(DecodedRequest(
                type=RequestType.DEVICE_MEASUREMENT, device_token=f"an-{d}",
                measurements={"x": val},
            ))
    engine.flush()
    wins = engine.state.windows
    assert int(wins.filled[0]) == W  # windows actually filled by the pipeline
    loss = svc.train_on_live(batch_size=8, steps=3)
    assert np.isfinite(loss)
    result = svc.score_all()
    assert result["valid"][:8].all()
    assert not result["valid"][8:].any()
    n = svc.emit_anomaly_alerts(result)
    if n:  # alerts landed in device state as system alerts
        st = engine.get_device_state(result["anomalous_tokens"][0])
        assert st["recent_alerts"][0]["type"] == "analytics.anomaly"


def test_analytics_checkpoint_roundtrip(tmp_path):
    """Trained model params + score stats survive save/restore (orbax)."""
    import numpy as np

    from sitewhere_tpu.engine import Engine, EngineConfig
    from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType
    from sitewhere_tpu.models.service import AnalyticsService

    eng = Engine(EngineConfig(
        device_capacity=32, token_capacity=64, assignment_capacity=64,
        store_capacity=1024, batch_capacity=16, channels=4,
        analytics_devices=8, analytics_window=8))
    rng = np.random.default_rng(0)
    for step in range(10):
        for d in range(4):
            eng.process(DecodedRequest(
                type=RequestType.DEVICE_MEASUREMENT, device_token=f"an-{d}",
                measurements={"v": float(rng.standard_normal())},
                event_ts_ms=None))
        eng.flush()
    from sitewhere_tpu.models.anomaly import AnomalyConfig

    # tiny model: the roundtrip property is size-independent and the
    # default 256-hidden LSTM costs ~25s of CPU-mesh compile alone
    tiny = AnomalyConfig(sensors=4, window=8, hidden=32, lstm_hidden=32,
                         latent=8)
    svc = AnalyticsService(eng, cfg=tiny, min_fill=8, learning_rate=1e-3)
    loss = svc.train_on_live(batch_size=4, steps=2)
    assert loss == loss  # trained (not NaN)
    before = svc.score_all()

    svc.save_model(tmp_path / "ckpt")
    svc2 = AnalyticsService(eng, cfg=tiny, min_fill=8)
    svc2.restore_model(tmp_path / "ckpt")
    after = svc2.score_all()
    np.testing.assert_allclose(np.asarray(after["scores"]),
                               np.asarray(before["scores"]), rtol=1e-5)
    assert svc2.threshold == svc.threshold


def test_analytics_rest_surface():
    """Scores/train/detect endpoints over a live instance."""
    import asyncio
    import base64

    import numpy as np

    from sitewhere_tpu.engine import EngineConfig
    from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType
    from sitewhere_tpu.instance.instance import InstanceConfig, SiteWhereTpuInstance
    from sitewhere_tpu.web.rest import start_server

    async def go():
        import aiohttp

        inst = SiteWhereTpuInstance(InstanceConfig(engine=EngineConfig(
            device_capacity=32, token_capacity=64, assignment_capacity=64,
            store_capacity=1024, batch_capacity=16, channels=4,
            analytics_devices=8, analytics_window=16)))
        assert inst.analytics is not None
        rng = np.random.default_rng(0)
        for step in range(16):
            for d in range(3):
                inst.engine.process(DecodedRequest(
                    type=RequestType.DEVICE_MEASUREMENT,
                    device_token=f"ar-{d}",
                    measurements={"v": float(rng.standard_normal())}))
            inst.engine.flush()
        server = await start_server(inst)
        base = f"http://127.0.0.1:{server.port}"
        try:
            async with aiohttp.ClientSession() as s:
                basic = base64.b64encode(b"admin:password").decode()
                async with s.get(f"{base}/api/authapi/jwt",
                                 headers={"Authorization": f"Basic {basic}"}) as r:
                    jwt = (await r.json())["token"]
                h = {"Authorization": f"Bearer {jwt}"}
                async with s.post(f"{base}/api/analytics/train",
                                  json={"batchSize": 4, "steps": 1},
                                  headers=h) as r:
                    assert r.status == 200
                    assert (await r.json())["loss"] is not None
                async with s.get(f"{base}/api/analytics/scores", headers=h) as r:
                    body = await r.json()
                    assert body["numResults"] == 3
                async with s.post(f"{base}/api/analytics/detect", headers=h) as r:
                    assert r.status == 200
        finally:
            await server.cleanup()

    asyncio.new_event_loop().run_until_complete(go())
