"""Concurrency-safety stress tests (SURVEY.md §5.3).

The reference leans on Kafka partition ordering, single-writer executors,
and JPA transactions for safety; the engine's contract is one RLock
serializing mutations with async flush outputs drained before any host
read. These tests hammer that contract from many threads at once.
"""

import threading

import numpy as np

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType


def _engine():
    return Engine(EngineConfig(
        device_capacity=512, token_capacity=1024, assignment_capacity=1024,
        store_capacity=1 << 14, batch_capacity=64, channels=4,
    ))


def test_concurrent_ingest_and_queries():
    """8 writer threads + 4 reader threads; totals must balance exactly."""
    eng = _engine()
    N_WRITERS, PER_WRITER = 8, 200
    errors = []

    def writer(w: int):
        try:
            for i in range(PER_WRITER):
                eng.process(DecodedRequest(
                    type=RequestType.DEVICE_MEASUREMENT,
                    device_token=f"c-{w}-{i % 20}",
                    measurements={"v": float(i)},
                ))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def reader():
        try:
            for _ in range(30):
                eng.query_events(limit=5)
                eng.search_device_states(limit=5)
                eng.get_device("c-0-0")
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(w,))
               for w in range(N_WRITERS)]
    threads += [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors

    eng.flush()
    m = eng.metrics()
    total = N_WRITERS * PER_WRITER
    assert m["processed"] == total
    assert m["persisted"] == total            # every event expanded once
    assert m["registered"] == N_WRITERS * 20  # distinct tokens
    # host mirror agrees with device counters
    assert len(eng.devices) == N_WRITERS * 20
    # event store totals match
    res = eng.query_events(limit=1)
    assert res["total"] == min(total, eng.config.store_capacity)


def test_concurrent_admin_and_ingest():
    """Registrations/assignments racing with ingest keep ids consistent."""
    eng = _engine()
    errors = []

    def admin(w: int):
        try:
            for i in range(25):
                tok = f"adm-{w}-{i}"
                eng.register_device(tok)
                a = eng.create_assignment(tok)
                eng.release_assignment(a.token)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def ingest(w: int):
        try:
            for i in range(100):
                eng.process(DecodedRequest(
                    type=RequestType.DEVICE_MEASUREMENT,
                    device_token=f"adm-{w % 4}-{i % 25}",
                    measurements={"v": 1.0},
                ))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=admin, args=(w,)) for w in range(4)]
    threads += [threading.Thread(target=ingest, args=(w,)) for w in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    eng.flush()

    # assignment ids unique and mirrors consistent
    ids = [a.id for a in eng.assignments.values()]
    assert len(ids) == len(set(ids))
    # each admin device: default assignment ACTIVE + extra RELEASED
    for w in range(4):
        for i in range(25):
            asgs = eng.list_assignments(f"adm-{w}-{i}")
            statuses = sorted(a.status for a in asgs)
            assert statuses == ["ACTIVE", "RELEASED"], (w, i, statuses)
    # no device row double-allocated
    dids = list(eng.token_device.values())
    assert len(dids) == len(set(dids))
