"""Concurrency-safety stress tests (SURVEY.md §5.3).

The reference leans on Kafka partition ordering, single-writer executors,
and JPA transactions for safety; the engine's contract is one RLock
serializing mutations with async flush outputs drained before any host
read. These tests hammer that contract from many threads at once.
"""

import threading

import numpy as np

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType


def _engine():
    return Engine(EngineConfig(
        device_capacity=512, token_capacity=1024, assignment_capacity=1024,
        store_capacity=1 << 14, batch_capacity=64, channels=4,
    ))


def test_concurrent_ingest_and_queries():
    """8 writer threads + 4 reader threads; totals must balance exactly."""
    eng = _engine()
    N_WRITERS, PER_WRITER = 8, 200
    errors = []

    def writer(w: int):
        try:
            for i in range(PER_WRITER):
                eng.process(DecodedRequest(
                    type=RequestType.DEVICE_MEASUREMENT,
                    device_token=f"c-{w}-{i % 20}",
                    measurements={"v": float(i)},
                ))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def reader():
        try:
            for _ in range(30):
                eng.query_events(limit=5)
                eng.search_device_states(limit=5)
                eng.get_device("c-0-0")
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(w,))
               for w in range(N_WRITERS)]
    threads += [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors

    eng.flush()
    m = eng.metrics()
    total = N_WRITERS * PER_WRITER
    assert m["processed"] == total
    assert m["persisted"] == total            # every event expanded once
    assert m["registered"] == N_WRITERS * 20  # distinct tokens
    # host mirror agrees with device counters
    assert len(eng.devices) == N_WRITERS * 20
    # event store totals match
    res = eng.query_events(limit=1)
    assert res["total"] == min(total, eng.config.store_capacity)


def test_concurrent_admin_and_ingest():
    """Registrations/assignments racing with ingest keep ids consistent."""
    eng = _engine()
    errors = []

    def admin(w: int):
        try:
            for i in range(25):
                tok = f"adm-{w}-{i}"
                eng.register_device(tok)
                a = eng.create_assignment(tok)
                eng.release_assignment(a.token)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def ingest(w: int):
        try:
            for i in range(100):
                eng.process(DecodedRequest(
                    type=RequestType.DEVICE_MEASUREMENT,
                    device_token=f"adm-{w % 4}-{i % 25}",
                    measurements={"v": 1.0},
                ))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=admin, args=(w,)) for w in range(4)]
    threads += [threading.Thread(target=ingest, args=(w,)) for w in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    eng.flush()

    # assignment ids unique and mirrors consistent
    ids = [a.id for a in eng.assignments.values()]
    assert len(ids) == len(set(ids))
    # each admin device: default assignment ACTIVE + extra RELEASED
    for w in range(4):
        for i in range(25):
            asgs = eng.list_assignments(f"adm-{w}-{i}")
            statuses = sorted(a.status for a in asgs)
            assert statuses == ["ACTIVE", "RELEASED"], (w, i, statuses)
    # no device row double-allocated
    dids = list(eng.token_device.values())
    assert len(dids) == len(set(dids))


def test_fair_tenancy_batch_formation():
    """A flooding tenant must not starve others: with fair_tenancy the
    first formed batch round-robins across tenants, so the small tenant's
    events all land in the first flush."""
    eng = Engine(EngineConfig(
        device_capacity=512, token_capacity=1024, assignment_capacity=1024,
        store_capacity=1 << 14, batch_capacity=64, channels=4,
        fair_tenancy=True, flush_interval_s=1e9,
    ))
    # tenant A floods 120 events FIRST, then tenant B stages 10. Suspend
    # the capacity auto-flush while queueing so one batch formation is
    # observable (the staging buffer itself stays 64 slots).
    eng.config.batch_capacity = 1 << 20
    for i in range(120):
        eng.process(DecodedRequest(
            type=RequestType.DEVICE_MEASUREMENT, device_token=f"a-{i % 8}",
            tenant="A", measurements={"v": 1.0}))
    for i in range(10):
        eng.process(DecodedRequest(
            type=RequestType.DEVICE_MEASUREMENT, device_token=f"b-{i}",
            tenant="B", measurements={"v": 2.0}))
    eng.config.batch_capacity = 64
    # one batch dispatch only (queries would force a full sync, so observe
    # the partial state via metrics + the fair queues directly)
    eng.flush_async()
    eng.drain()
    assert eng.metrics()["persisted"] == 64
    # all 10 of B's events made the first 64-slot batch (fair quota),
    # despite 120 of A's queued ahead of them
    assert eng.fair_backlog("B") == 0
    assert eng.fair_backlog("A") == 120 - (64 - 10)
    # draining the rest delivers everything exactly once
    eng.flush()
    assert eng.metrics()["persisted"] == 130
    assert eng.query_events(tenant="B", limit=100)["total"] == 10
    assert eng.query_events(tenant="A", limit=1)["total"] == 120
    assert eng.staged_count == 0


def test_fair_tenancy_off_is_fifo():
    """Default mode preserves strict FIFO: B's late events wait."""
    eng = Engine(EngineConfig(
        device_capacity=512, token_capacity=1024, assignment_capacity=1024,
        store_capacity=1 << 14, batch_capacity=64, channels=4,
        flush_interval_s=1e9,
    ))
    for i in range(60):
        eng.process(DecodedRequest(
            type=RequestType.DEVICE_MEASUREMENT, device_token=f"a-{i % 8}",
            tenant="A", measurements={"v": 1.0}))
    for i in range(10):
        eng.process(DecodedRequest(
            type=RequestType.DEVICE_MEASUREMENT, device_token=f"b-{i}",
            tenant="B", measurements={"v": 2.0}))
    # the auto-flush at 64 staged ran with only 4 of B's events; the other
    # 6 still sit in the FIFO buffer (queries would sync, so inspect direct)
    eng.drain()
    assert eng.metrics()["persisted"] == 64
    b_tid = eng.tenants.lookup("B")
    assert len(eng._buf) == 6
    assert all(t == b_tid for t in eng._buf.tenant_id[:6])
    eng.flush()
    assert eng.metrics()["persisted"] == 70
    assert eng.query_events(tenant="B", limit=100)["total"] == 10


def test_fair_tenancy_fast_path_and_toggle_off():
    """ingest_json_batch honors fairness, and rows queued before the flag
    is toggled off still drain (no flush() hang)."""
    eng = Engine(EngineConfig(
        device_capacity=512, token_capacity=1024, assignment_capacity=1024,
        store_capacity=1 << 14, batch_capacity=64, channels=4,
        fair_tenancy=True, flush_interval_s=1e9,
    ))
    eng.config.batch_capacity = 1 << 20    # suspend auto-dispatch
    payloads_a = [
        (b'{"deviceToken": "fa-%d", "type": "DeviceMeasurement",'
         b' "request": {"name": "v", "value": 1.0}}' % (i % 8))
        for i in range(100)
    ]
    eng.ingest_json_batch(payloads_a, tenant="A")
    for i in range(10):
        eng.process(DecodedRequest(
            type=RequestType.DEVICE_MEASUREMENT, device_token=f"fb-{i}",
            tenant="B", measurements={"v": 2.0}))
    eng.config.batch_capacity = 64
    assert eng._fair_queued == 110
    eng.flush_async()
    eng.drain()
    # first 64-slot batch round-robins: all 10 of B's rows made it
    assert eng.metrics()["persisted"] == 64
    assert eng.fair_backlog("B") == 0
    # toggling fairness off must not strand the queued remainder
    eng.config.fair_tenancy = False
    eng.flush()
    assert eng.metrics()["persisted"] == 110
    assert eng._fair_queued == 0


def test_concurrent_ingest_spool_query_and_replay(tmp_path):
    """Archive tier under contention: writers wrap the ring (forcing
    spooling) while readers run merged queries and a lagging consumer
    replays from disk. No exceptions, no losses, totals balance.
    (Retention expiry stays off here — expired rows would legitimately
    show up as consumer lag and the exact-totals assertions below would
    no longer be meaningful.)"""
    import json

    eng = Engine(EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=64, channels=4, batch_capacity=16,
        archive_dir=str(tmp_path / "arch"), archive_segment_rows=16))
    base = int(eng.epoch.base_unix_s * 1000)
    N_WRITERS, PER_WRITER = 4, 128
    errors = []
    done = threading.Event()

    def pay(tok, v, ts):
        return json.dumps({
            "deviceToken": tok, "type": "DeviceMeasurements",
            "request": {"measurements": {"t": v},
                        "eventDate": base + ts}}).encode()

    def writer(w):
        try:
            for i in range(PER_WRITER):
                eng.ingest_json_batch(
                    [pay(f"cw-{w}", float(i), w * 100000 + i)])
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def reader():
        try:
            while not done.is_set():
                eng.query_events(limit=20)
                eng.query_events(since_ms=0, until_ms=10_000, limit=20)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    feed = eng.make_feed_consumer("stress", max_batch=64)
    replayed = []

    def consumer():
        try:
            while not done.is_set():
                evs = feed.poll()
                if evs:
                    replayed.extend(evs)
                    feed.commit(evs)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = ([threading.Thread(target=writer, args=(w,))
                for w in range(N_WRITERS)]
               + [threading.Thread(target=reader) for _ in range(2)]
               + [threading.Thread(target=consumer)])
    for t in threads:
        t.start()
    for t in threads[:N_WRITERS]:
        t.join()
    eng.flush()
    done.set()
    for t in threads[N_WRITERS:]:
        t.join()
    assert not errors, errors
    total = N_WRITERS * PER_WRITER
    assert eng.metrics()["persisted"] == total
    assert eng.archive.lost_rows == 0
    # drain the consumer to the head: every event delivered at least once
    while True:
        evs = feed.poll()
        if not evs:
            break
        replayed.extend(evs)
        feed.commit(evs)
    assert len({e.event_id for e in replayed}) == total
    assert feed.lag_lost == 0
    # merged full-history total agrees
    assert eng.query_events(limit=1)["total"] == total
