"""Columnar archive pushdown (ISSUE 8): zone maps, bloom filters, and
batched tiered queries.

The contract under test: ``EventArchive.query`` (planner-driven — prunes
segments by zone maps + blooms, stops decoding once the page is provably
complete, materializes only the columns a query touches) must return
results BYTE-IDENTICAL to ``EventArchive.query_unpruned``, the retained
pre-pushdown full scan — across ts-tie ordering, bloom false positives,
gap-registered partitions, eviction caps, and mixed ring+archive pages —
while provably decoding fewer segments than exist when predicates are
selective."""

import json
import threading

import numpy as np
import pytest

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.utils.archive import (EventArchive, _bloom_positions,
                                         _COLUMNS)


def meas(eng: Engine, token: str, value: float, ts_rel: int) -> bytes:
    base = int(eng.epoch.base_unix_s * 1000)
    return json.dumps({
        "deviceToken": token,
        "type": "DeviceMeasurements",
        "request": {"measurements": {"temp": value},
                    "eventDate": base + ts_rel},
    }).encode()


SMALL_CFG = dict(
    device_capacity=64, token_capacity=128, assignment_capacity=128,
    store_capacity=64, channels=4, batch_capacity=16,
    archive_segment_rows=16,
)


def small_engine(tmp_path, **kw) -> Engine:
    cfg = dict(SMALL_CFG, archive_dir=str(tmp_path / "arch"))
    cfg.update(kw)
    return Engine(EngineConfig(**cfg))


def fill_history(eng, n=4 * 64, tenants=3, devices=8, tie_every=3):
    """Ingest ``n`` events with ts TIES across segment boundaries
    (ts advances once per ``tie_every`` events) over several devices and
    tenants — the ordering-sensitive workload for the parity pin. Each
    device keeps ONE tenant (a token is bound to the tenant that
    registered it; a mismatched tenant would reject the event)."""
    for i in range(n):
        dev = i % devices
        eng.ingest_json_batch(
            [meas(eng, f"pd-{dev}", float(i), 1000 + i // tie_every)],
            tenant=f"ten{dev % tenants}")
    eng.flush()


def rows_equal(a: list[dict], b: list[dict]) -> bool:
    """Byte-level row-list comparison: same length, same key sets, every
    column value (numpy scalar or array) exactly equal, same order."""
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if ra.keys() != rb.keys():
            return False
        for k in ra:
            va, vb = ra[k], rb[k]
            if isinstance(va, np.ndarray) or isinstance(vb, np.ndarray):
                if not np.array_equal(np.asarray(va), np.asarray(vb)):
                    return False
            elif va != vb:
                return False
    return True


def assert_parity(arch: EventArchive, **filters):
    ta, ra = arch.query(**filters)
    tb, rb = arch.query_unpruned(**filters)
    assert ta == tb, (filters, ta, tb)
    assert rows_equal(ra, rb), filters


# ------------------------------------------------------------------ parity
def test_pushdown_parity_matrix(tmp_path):
    """The planner-driven scan is byte-identical to the unpruned oracle
    across the whole filter surface, ts ties included."""
    eng = small_engine(tmp_path)
    fill_history(eng)
    arch = eng.archive
    assert len(arch.segments) >= 4
    dev3 = eng.token_device[eng.tokens.lookup("pd-3")]
    ten1 = eng.tenants.lookup("ten1")
    for f in (
        {},
        {"limit": 0},     # count-only page (the distributed path
                          # forwards caller limits verbatim)
        {"limit": 1},
        {"limit": 5},
        {"limit": 500},
        {"device": dev3},
        {"device": dev3, "limit": 3},
        {"tenant": ten1, "limit": 10},
        {"etype": 0, "limit": 300},
        {"since_ms": 1000, "until_ms": 1010},
        {"since_ms": 1030},
        {"until_ms": 1005, "limit": 4},
        {"device": dev3, "since_ms": 1002, "until_ms": 1050, "limit": 7},
        {"device": 999999},
        {"tenant": 999999},
        {"max_pos": {0: 100}, "limit": 20},
        {"max_pos": {0: 37}, "device": dev3},
        {"max_pos": {0: 17}, "since_ms": 1001, "limit": 2},
        {"max_pos": {0: 0}},
        {"aux1": 0, "limit": 4},
    ):
        assert_parity(arch, **f)


def _cols(n=8, ts0=0, device=0, tenant=0):
    import types

    d = {c: np.zeros((n, 4) if c in ("values", "vmask") else (n, 2)
                     if c == "aux" else n,
                     np.float32 if c == "values" else
                     bool if c in ("vmask", "valid") else np.int32)
         for c in _COLUMNS}
    d["ts_ms"][:] = np.arange(ts0, ts0 + n, dtype=np.int32)
    d["valid"][:] = True
    d["device"][:] = device
    d["tenant"][:] = tenant
    return types.SimpleNamespace(**d)


def test_bloom_false_positive_still_exact(tmp_path):
    """A bloom false positive costs one decode, never a wrong row: the
    planner admits the segment, the row-level mask finds nothing, and the
    result stays byte-identical to the oracle."""
    lo, hi = 1, 10_000_000
    # find a value whose k=2 bloom bits are covered by {lo, hi}'s bits —
    # a guaranteed false positive (4734 with the shipped hash; re-derived
    # here so a hash change re-finds one instead of silently passing)
    allowed: dict[int, np.uint64] = {}
    for v in (lo, hi):
        for w, m in _bloom_positions(v):
            allowed[w] = allowed.get(w, np.uint64(0)) | m
    fp = next(v for v in range(2, 3_000_000)
              if all((allowed.get(w, np.uint64(0)) & m) != 0
                     for w, m in _bloom_positions(v)))
    arch = EventArchive(tmp_path / "fp", segment_rows=8, topology="single/1")
    sl = _cols(8, ts0=100)
    sl.device[::2] = lo          # zone map spans [lo, hi] so the interval
    sl.device[1::2] = hi         # cannot prune fp; only the bloom could
    arch.append_segment(0, 0, sl)
    before = arch.plan_decoded
    total, rows = arch.query(device=fp)
    assert total == 0 and rows == []
    assert arch.plan_decoded == before + 1      # survived planning, decoded
    assert_parity(arch, device=fp)
    # a value the bloom genuinely never saw IS pruned without a decode
    miss = next(v for v in range(2, 3_000_000)
                if not all((allowed.get(w, np.uint64(0)) & m) != 0
                           for w, m in _bloom_positions(v)))
    before_dec, before_pruned = arch.plan_decoded, arch.plan_pruned
    total, rows = arch.query(device=miss)
    assert total == 0 and rows == []
    assert arch.plan_decoded == before_dec       # never opened the file
    assert arch.plan_pruned == before_pruned + 1


def test_planner_prunes_and_early_stops(tmp_path):
    """Selective predicates decode strictly fewer segments than exist, and
    a small unfiltered page early-stops: older provably-full segments are
    counted from stats without being decoded."""
    eng = small_engine(tmp_path)
    # distinct devices per segment region so the device bloom can prune
    for i in range(4 * 64):
        eng.ingest_json_batch(
            [meas(eng, f"es-{i // 32}", float(i), 1000 + i)])
    eng.flush()
    arch = eng.archive
    n_segs = len(arch.segments)
    assert n_segs >= 4
    dev0 = eng.token_device[eng.tokens.lookup("es-0")]

    before = arch.plan_decoded
    assert_parity(arch, device=dev0)
    decoded = arch.plan_decoded - before
    assert 0 < decoded < n_segs          # pruning fired (parity ran 2 scans
                                         # but only query() counts)

    # tight old date range: every newer segment pruned by its ts zone
    before = arch.plan_decoded
    total, _ = arch.query(since_ms=1000, until_ms=1015)
    assert total == 16
    assert arch.plan_decoded - before < n_segs

    # unfiltered small page: newest-first early stop + count shortcuts —
    # the total still covers EVERY archived row
    before_dec, before_sc = arch.plan_decoded, arch.count_shortcuts
    total, rows = arch.query(limit=5)
    assert len(rows) == 5
    assert total == arch.query_unpruned(limit=5)[0]
    assert arch.plan_decoded - before_dec < n_segs
    assert arch.count_shortcuts > before_sc


def test_gap_registered_partition_parity(tmp_path):
    """Pushdown over an archive with a registered never-written gap and a
    physically missing middle segment stays exact."""
    eng = small_engine(tmp_path)
    for i in range(256):
        eng.ingest_json_batch([meas(eng, "gap-1", float(i), 1000 + i)])
    eng.flush()
    arch = eng.archive
    for seg in list(arch.segments):
        if 32 <= seg.start < 64:
            (tmp_path / "arch" / seg.path).unlink()
            arch.segments.remove(seg)
    arch._reindex()
    arch.register_gap(0, 32, 64)
    for f in ({}, {"limit": 10}, {"since_ms": 1020, "until_ms": 1070},
              {"max_pos": {0: 100}}):
        assert_parity(arch, **f)


def test_mixed_ring_archive_page_parity(tmp_path):
    """Engine-level: query_events pages that straddle the ring/archive
    boundary are byte-identical whether the archive side runs the
    pushdown planner or the unpruned oracle."""
    eng = small_engine(tmp_path)
    fill_history(eng)
    dev_filters = [
        {},
        {"limit": 300},
        {"device_token": "pd-2", "limit": 40},
        {"tenant": "ten0", "limit": 30},
        {"since_ms": 1000, "until_ms": 1040, "limit": 200},
        {"since_ms": 1060, "limit": 50},   # straddles the boundary
    ]
    pushed = [eng.query_events(**f) for f in dev_filters]
    arch = eng.archive
    orig = arch.query
    arch.query = arch.query_unpruned
    try:
        legacy = [eng.query_events(**f) for f in dev_filters]
    finally:
        arch.query = orig
    for f, a, b in zip(dev_filters, pushed, legacy):
        assert a == b, f


def test_concurrent_queries_share_archive_round(monkeypatch, tmp_path):
    """Coalesced queries ride ONE archive pass: the round leader scans the
    tier for every entry, so Q concurrent historical queries decode each
    surviving segment at most once (shared LRU) — and each caller still
    gets its own exact merge."""
    import sitewhere_tpu.engine as engine_mod

    eng = small_engine(tmp_path)
    fill_history(eng, n=256, devices=8)
    eng.query_events(limit=5)    # warm compile so the race below is tame
    orig_fetch = engine_mod._fetch_query_result
    gate = threading.Event()

    def slow_fetch(tree):
        gate.wait(5.0)
        return orig_fetch(tree)

    monkeypatch.setattr(engine_mod, "_fetch_query_result", slow_fetch)
    results: dict[int, dict] = {}
    errors: list[Exception] = []

    def query(i):
        try:
            results[i] = eng.query_events(device_token=f"pd-{i}", limit=64)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=query, args=(i,)) for i in range(8)]
    threads[0].start()
    while eng._query_batcher.programs == 0 and threads[0].is_alive():
        threading.Event().wait(0.005)
    for t in threads[1:]:
        t.start()
    deadline = 300
    while len(eng._query_batcher._queue) < 7 and deadline:
        threading.Event().wait(0.01)
        deadline -= 1
    gate.set()
    for t in threads:
        t.join()
    assert not errors, errors
    assert eng._query_batcher.max_coalesced >= 2
    # every caller's merged page is exactly its device's history
    for i in range(8):
        assert results[i]["total"] == 32
        assert all(e["deviceToken"] == f"pd-{i}"
                   for e in results[i]["events"])


# ----------------------------------------------------------- cache sharing
def test_get_row_and_read_rows_share_decode_cache(tmp_path, monkeypatch):
    """Satellite: by-id lookups and chunked replay must not re-np.load the
    segment file per call — they ride the same LRU decode cache as the
    query path."""
    import sitewhere_tpu.utils.archive as archive_mod

    eng = small_engine(tmp_path)
    for i in range(128):
        eng.ingest_json_batch([meas(eng, "cz-1", float(i), 1000 + i)])
    eng.flush()
    arch = eng.archive
    seg = arch.segments[0]
    loads = [0]
    real_load = archive_mod.np.load

    def counting_load(*a, **k):
        loads[0] += 1
        return real_load(*a, **k)

    monkeypatch.setattr(archive_mod.np, "load", counting_load)
    arch.cache.retain(set())             # start cold
    for pos in range(seg.start, seg.start + seg.count):
        assert arch.get_row(seg.part, pos) is not None
    assert loads[0] == 1                 # one decode for the whole walk
    for off in range(0, seg.count, 4):
        cols, n = arch.read_rows(seg.part, seg.start + off, 4)
        assert n == 4
    assert loads[0] == 1                 # replay reused the same entry
    assert arch.cache.hits > 0


def test_cache_is_lru_bounded(tmp_path):
    arch = EventArchive(tmp_path / "lru", segment_rows=4,
                        topology="single/1", cache_segments=2)
    for k in range(5):
        arch.append_segment(0, k * 8, _cols(8, ts0=k * 100))
    for k in range(5):
        assert arch.get_row(0, k * 8) is not None
    assert len(arch.cache._entries) <= 2


# ------------------------------------------------------------- quarantine
def test_corrupt_segment_quarantined_not_fatal(tmp_path, caplog):
    """Satellite: a truncated/corrupt segment file must not abort the
    index rebuild — it is renamed aside, counted, and loudly logged while
    the rest of the archive keeps serving."""
    eng = small_engine(tmp_path)
    for i in range(256):
        eng.ingest_json_batch([meas(eng, "cor-1", float(i), 1000 + i)])
    eng.flush()
    segs = sorted(eng.archive.segments, key=lambda s: s.start)
    victim = segs[1]
    good_rows = eng.archive.total_rows() - victim.count
    (tmp_path / "arch" / victim.path).write_bytes(b"\x50\x4b\x03\x04 trunc")
    (tmp_path / "arch" / "index.json").unlink()
    with caplog.at_level("WARNING"):
        arch = EventArchive(tmp_path / "arch", segment_rows=16,
                            topology="single/1")
    assert arch.corrupt_segments == 1
    assert arch.total_rows() == good_rows
    assert any("QUARANTINED" in r.message for r in caplog.records)
    quarantined = list((tmp_path / "arch").glob("*.corrupt"))
    assert [q.name for q in quarantined] == [victim.path + ".corrupt"]
    # the surviving history still queries exactly
    assert_parity(arch, since_ms=1100, until_ms=1150)
    total, _ = arch.query(limit=5)
    assert total == good_rows


def test_corrupt_known_segment_quarantined_at_decode(tmp_path, caplog):
    """A segment the manifest vouches for is adopted WITHOUT being opened
    (the stats fast path), so rot behind an intact index.json only
    surfaces at first decode — it must quarantine there too, not fail
    every query round that plans over it."""
    eng = small_engine(tmp_path)
    for i in range(256):
        eng.ingest_json_batch([meas(eng, "rot-1", float(i), 1000 + i)])
    eng.flush()
    segs = sorted(eng.archive.segments, key=lambda s: s.start)
    victim = segs[1]
    good_rows = eng.archive.total_rows() - victim.count
    (tmp_path / "arch" / victim.path).write_bytes(b"\x50\x4b\x03\x04 rot")
    # index.json stays INTACT: the reopen adopts the bad file untouched
    arch = EventArchive(tmp_path / "arch", segment_rows=16,
                        topology="single/1")
    assert arch.corrupt_segments == 0
    assert any(s.path == victim.path for s in arch.segments)
    # an unfiltered wide page decodes every segment -> hits the rot;
    # the query still answers with everything else
    with caplog.at_level("WARNING"):
        total, rows = arch.query(limit=500)
    assert arch.corrupt_segments == 1
    assert total == good_rows and len(rows) == good_rows
    assert any("QUARANTINED" in r.message for r in caplog.records)
    assert [q.name for q in (tmp_path / "arch").glob("*.corrupt")] \
        == [victim.path + ".corrupt"]
    # the index dropped it everywhere: manifest, by-id, replay, parity
    assert all(s.path != victim.path for s in arch.segments)
    man = json.loads((tmp_path / "arch" / "index.json").read_text())
    assert all(e["path"] != victim.path for e in man["segments"])
    assert arch.get_row(victim.part, victim.start) is None
    cols, n = arch.read_rows(victim.part, victim.start, 4)
    assert cols is None and n == 0
    assert_parity(arch, since_ms=1100, until_ms=1150)
    assert arch.query(limit=5)[0] == good_rows


# --------------------------------------------------------------- backfill
def test_stats_backfill_from_pre_pushdown_manifest(tmp_path):
    """A manifest written before the pushdown tier carries no stats: the
    planner back-fills them lazily on first plan (predicate columns only)
    and persists them, and results stay exact throughout."""
    eng = small_engine(tmp_path)
    fill_history(eng, n=128)
    man = tmp_path / "arch" / "index.json"
    m = json.loads(man.read_text())
    for e in m["segments"]:
        e.pop("stats", None)
    man.write_text(json.dumps(m))
    arch = EventArchive(tmp_path / "arch", segment_rows=16,
                        topology="single/1")
    assert all(s.stats is None for s in arch.segments)
    assert_parity(arch, since_ms=1005, until_ms=1020)
    assert all(s.stats is not None for s in arch.segments)
    # ...and the back-fill persisted: a reopen sees them immediately
    again = EventArchive(tmp_path / "arch", segment_rows=16,
                         topology="single/1")
    assert all(s.stats is not None for s in again.segments)


def test_rebuild_from_pre_pushdown_segment_files(tmp_path):
    """Manifest-less rebuild over segment files that predate the stats
    members (no seg_nrows/stats_json inside the npz) falls back to the
    full-column read and computes stats on the spot."""
    arch = EventArchive(tmp_path / "old", segment_rows=8,
                        topology="single/1")
    arch.append_segment(0, 0, _cols(8, ts0=500, device=7))
    seg = arch.segments[0]
    # rewrite the file the way the pre-pushdown writer did
    with np.load(tmp_path / "old" / seg.path) as z:
        cols = {c: np.asarray(z[c]) for c in _COLUMNS}
    with open(tmp_path / "old" / seg.path, "wb") as f:
        np.savez(f, part=np.int64(0), start=np.int64(0),
                 topology=np.str_("single/1"), **cols)
    (tmp_path / "old" / "index.json").unlink()
    again = EventArchive(tmp_path / "old", segment_rows=8,
                         topology="single/1")
    assert again.total_rows() == 8
    s = again.segments[0]
    assert s.stats is not None and s.stats["rows"] == 8
    assert s.ts_min == 500 and s.ts_max == 507
    assert_parity(again, device=7)


# ----------------------------------------------------------------- metrics
def test_archive_gauges_exported_at_scrape(tmp_path):
    """swtpu_archive_* gauges export at scrape time (Prometheus REGISTRY,
    NOT engine.metrics() — the dispatch-shape equality pin stays
    untouched)."""
    from sitewhere_tpu.utils.metrics import (REGISTRY, archive_metrics,
                                             export_engine_metrics)

    eng = small_engine(tmp_path)
    fill_history(eng, n=128)
    eng.query_events(device_token="pd-1", since_ms=1000, until_ms=1010,
                     limit=20)
    export_engine_metrics(eng)
    inst = archive_metrics(REGISTRY)
    arch = eng.archive
    assert inst["segments"].value() == len(arch.segments)
    assert inst["rows"].value() == arch.total_rows()
    assert inst["bytes"].value() > 0
    assert inst["queries"].value() == arch.queries > 0
    assert (inst["considered"].value()
            == arch.plan_considered
            == arch.plan_pruned + arch.plan_decoded + arch.count_shortcuts)
    assert "archived_rows" in eng.metrics()      # pre-existing key only
    assert not any(k.startswith("swtpu_archive") for k in eng.metrics())
    # planner passes export too (ISSUE 10 satellite: the batched round
    # contributes exactly one — pinned below)
    assert inst["planner_calls"].value() == arch.planner_calls > 0


# ------------------------------------------- batched planning (ISSUE 10)
def test_query_batch_is_one_planner_call_with_per_query_parity(tmp_path):
    """N archive requests through query_batch share exactly ONE planner
    pass, and every per-request result is identical to a standalone
    query() with the same arguments."""
    eng = small_engine(tmp_path)
    fill_history(eng)
    arch = eng.archive
    dev3 = eng.token_device[eng.tokens.lookup("pd-3")]
    ten1 = eng.tenants.lookup("ten1")
    reqs = [
        {"limit": 5, "filters": {}},
        {"limit": 3, "filters": {"device": dev3}},
        {"limit": 10, "filters": {"tenant": ten1}},
        {"limit": 0, "filters": {"since_ms": 1000, "until_ms": 1015}},
        {"limit": 4, "filters": {"device": 999999}},
    ]
    mp = {0: 64}
    before = arch.planner_calls
    batched = arch.query_batch(reqs, max_pos=mp)
    assert arch.planner_calls == before + 1          # ONE pass for all N
    assert len(batched) == len(reqs)
    for req, got in zip(reqs, batched):
        want = arch.query(max_pos=mp, limit=req["limit"],
                          **req["filters"])
        assert got[0] == want[0]
        assert [(r["part"], r["pos"]) for r in got[1]] == \
            [(r["part"], r["pos"]) for r in want[1]]


def test_batcher_round_plans_archive_requests_once(monkeypatch, tmp_path):
    """Engine-level pin: ALL archive requests of one QueryBatcher round
    ride a single SegmentPlanner call (the PR-8 follow-up — previously
    shared tables but per-query plan evaluation)."""
    import sitewhere_tpu.engine as engine_mod

    eng = small_engine(tmp_path)
    fill_history(eng, n=256, devices=8)
    eng.query_events(limit=5)                 # warm compile
    orig_fetch = engine_mod._fetch_query_result
    gate = threading.Event()

    def slow_fetch(tree):
        gate.wait(5.0)
        return orig_fetch(tree)

    monkeypatch.setattr(engine_mod, "_fetch_query_result", slow_fetch)
    results: dict[int, dict] = {}
    errors: list[Exception] = []

    def query(i):
        try:
            results[i] = eng.query_events(device_token=f"pd-{i}", limit=64)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    calls0 = eng.archive.planner_calls
    threads = [threading.Thread(target=query, args=(i,)) for i in range(8)]
    threads[0].start()
    while eng._query_batcher.programs == 0 and threads[0].is_alive():
        threading.Event().wait(0.005)
    for t in threads[1:]:
        t.start()
    deadline = 300
    while len(eng._query_batcher._queue) < 7 and deadline:
        threading.Event().wait(0.01)
        deadline -= 1
    gate.set()
    for t in threads:
        t.join()
    assert not errors, errors
    assert eng._query_batcher.max_coalesced >= 2
    # two rounds ran (the leader's own, then the 7 coalesced followers):
    # one planner pass EACH — not one per query
    assert eng.archive.planner_calls - calls0 == 2, \
        (eng.archive.planner_calls, calls0)
    for i in range(8):
        assert results[i]["total"] == 32
        assert all(e["deviceToken"] == f"pd-{i}"
                   for e in results[i]["events"])


# ------------------------------------------------------------------ stress
@pytest.mark.slow
def test_pushdown_stress_10x_ring(tmp_path):
    """Heavy variant: 10x-ring archive, parity across a broad filter
    sweep, and pruning ratios that actually bite at scale."""
    eng = small_engine(tmp_path, store_capacity=128, batch_capacity=32)
    n = 10 * 128
    # devices CLUSTER in time (one device per 80-event stretch) so the
    # per-segment device blooms/zones have something to prune
    for lo in range(0, n, 32):
        eng.ingest_json_batch(
            [meas(eng, f"st-{(lo + j) // 80}", float(lo + j),
                  1000 + (lo + j) // 2)
             for j in range(32)])
    eng.flush()
    arch = eng.archive
    assert arch.total_rows() >= n - 128 - arch.segment_rows
    devs = [eng.token_device[eng.tokens.lookup(f"st-{d}")] for d in range(16)]
    for f in ({}, {"limit": 3}, {"limit": 1000},
              {"since_ms": 1000, "until_ms": 1099},
              {"since_ms": 1400}, {"until_ms": 1200, "limit": 64},
              *({"device": d} for d in devs[:6]),
              {"device": devs[0], "since_ms": 1050, "until_ms": 1450},
              {"tenant": eng.tenants.lookup("default"), "limit": 200}):
        assert_parity(arch, **f)
    before = arch.plan_decoded
    arch.query(device=devs[3])
    assert arch.plan_decoded - before < len(arch.segments) // 2
