"""Span-level tracing & profiling plane (ISSUE 10).

Pins the acceptance surface: the SpanTracer ring mirrors the
flight-recorder contracts (fixed capacity + wrap-around eviction,
disabled => hot paths are no-ops), head-based sampling is seeded and
deterministic per TRACE (all spans of one trace agree, across tracers
with the same seed), the tail-keep pass rescues slow outliers from a
head-drop, one ingested-then-queried event on a 2-rank replicated
cluster resolves BY ITS SINGLE TRACE ID to one stitched multi-rank
Chrome-trace timeline (owner lifecycle + forward hop + standby apply),
and none of it leaks into ``engine.metrics()`` (the dispatch-shape
equality pin runs with tracing enabled — span_trace defaults on).

scripts/trace2perfetto.py is smoke-invoked here so the offline
converter can't rot.
"""

import json
import subprocess
import sys
import threading
import time

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.loadgen import generate_measurements_message
from sitewhere_tpu.utils.tracing import (NULL_SPAN, SpanTracer,
                                         debug_bundle, new_trace_id,
                                         profile_threads)

SMALL = dict(device_capacity=64, token_capacity=128,
             assignment_capacity=128, store_capacity=4096,
             batch_capacity=16, channels=4)


def _engine(**kw) -> Engine:
    cfg = dict(SMALL)
    cfg.update(kw)
    return Engine(EngineConfig(**cfg))


def _batch(prefix="sp", n=16, base=0):
    return [generate_measurements_message(f"{prefix}-{i % 8}", base + i)
            for i in range(n)]


# ===================================================================
# SpanTracer unit pins (mirror the flight-recorder contracts)
# ===================================================================

def test_span_ring_wraps_and_reindexes():
    """A full ring evicts oldest-first and unindexes the evicted span —
    the same bounded-memory pin as the flight recorder's."""
    tr = SpanTracer(capacity=4)
    tids = [new_trace_id() for _ in range(10)]
    for i, tid in enumerate(tids):
        tr.record(f"op{i}", 0, 1000, trace_id=tid)
    assert len(tr) == 4
    assert tr.recorded == 10 and tr.dropped == 6
    for tid in tids[:6]:                      # evicted: index cleaned
        assert tr.spans_of(tid) == []
    for tid in tids[6:]:                      # survivors resolve
        assert len(tr.spans_of(tid)) == 1
    names = {d["name"] for d in tr.recent(10)}
    assert names == {"op6", "op7", "op8", "op9"}


def test_disabled_tracer_is_noop():
    """enabled=False => begin() hands out the shared null span, record()
    drops, nothing allocates in the ring — the disabled-recorder pin."""
    tr = SpanTracer(capacity=8, enabled=False)
    sp = tr.begin("ingest.decode", payloads=5)
    assert sp is NULL_SPAN
    sp.annotate(extra=1)
    sp.end()                                   # idempotent no-op
    with tr.begin("query.round") as sp2:
        assert sp2 is NULL_SPAN
    assert tr.record("repl.apply", 0, 100, trace_id="ab" * 16) is None
    assert len(tr) == 0 and tr.recorded == 0 and tr.sampled_out == 0
    assert tr.recent(10) == []


def test_head_sampling_seeded_deterministic_and_trace_consistent():
    """The head verdict is a pure hash of (trace id, seed): two tracers
    with the same seed agree on every trace; every span of one trace
    shares its verdict (a sampled trace is complete, not shredded)."""
    a = SpanTracer(capacity=1024, sample=0.5, seed=7)
    b = SpanTracer(capacity=1024, sample=0.5, seed=7)
    c = SpanTracer(capacity=1024, sample=0.5, seed=8)
    tids = [new_trace_id() for _ in range(200)]
    va = [a.head_sampled(t) for t in tids]
    assert va == [b.head_sampled(t) for t in tids]
    assert va != [c.head_sampled(t) for t in tids]   # seed matters
    assert 20 < sum(va) < 180                        # ~half kept
    # all spans of one kept trace land; all spans of one dropped trace
    # are sampled out together (uniform durations defeat tail-keep only
    # once its window has history — use a fresh name per trace)
    kept = next(t for t, v in zip(tids, va) if v)
    dropped = next(t for t, v in zip(tids, va) if not v)
    for i in range(3):
        a.record(f"k{i}", 0, 1000, trace_id=kept)
    assert len(a.spans_of(kept)) == 3
    tr2 = SpanTracer(capacity=1024, sample=0.0, seed=7)
    for i in range(40):                     # saturate one name's window
        tr2.record("drop.me", 0, 1000, trace_id=dropped)
    assert tr2.sampled_out > 0


def test_tail_keep_rescues_slow_outliers():
    """sample=0: head drops everything, but a slowest-decile span still
    lands in the ring — the records an operator hunts survive any
    sampling rate."""
    tr = SpanTracer(capacity=256, sample=0.0)
    tid = new_trace_id()
    for i in range(64):                     # constant-duration baseline
        tr.record("repl.send", 0, 1_000_000, trace_id=tid)
    assert tr.sampled_out > 0               # uniform stream IS sampled out
    slow = tr.record("repl.send", 0, 50_000_000, trace_id=tid)
    assert slow is not None                 # 50ms outlier tail-kept
    assert any(d["durUs"] == 50_000.0 for d in tr.spans_of(tid))


def test_nested_spans_inherit_trace_and_parent():
    tr = SpanTracer(capacity=64)
    tid = new_trace_id()
    with tr.begin("query.round", trace_id=tid, q=3) as root:
        with tr.begin("query.round.archive") as child:
            assert child.trace_id == tid
            assert child.parent_id == root.span_id
    spans = tr.spans_of(tid)
    assert len(spans) == 2
    by_name = {d["name"]: d for d in spans}
    assert by_name["query.round.archive"]["parentId"] == \
        by_name["query.round"]["spanId"]
    assert by_name["query.round"]["tags"] == {"q": 3}


# ===================================================================
# Engine-level: lifecycle timelines, metrics() isolation
# ===================================================================

def test_ingest_timeline_has_lifecycle_spans(tmp_path):
    """One ingested batch's trace id yields a Chrome-trace document with
    the decode/WAL/dispatch/device stage intervals (derived from the
    flight record — the hot path pays nothing new) ready for Perfetto."""
    eng = _engine(wal_dir=str(tmp_path / "wal"))
    s = eng.ingest_json_batch(_batch())
    eng.flush()
    doc = eng.get_trace_timeline(s["trace_id"])
    assert doc["traceId"] == s["trace_id"]
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in xs}
    assert {"ingest", "ingest.decode", "ingest.wal_append",
            "ingest.dispatch_wait", "ingest.device"} <= names
    # flight-derived stage intervals nest inside the lifecycle root on
    # the wall axis (live spans — e.g. ingest.shard_decode — ride a
    # DIFFERENT wall anchor, the import-time perf_counter offset, so
    # they may drift a few ms relative to the record's time.time() base)
    root = next(e for e in xs if e["name"] == "ingest")
    for e in xs:
        if e["name"].startswith("ingest.") and e.get("cat") == "flight":
            # tolerance: ts values are wall-clock MICROseconds (~1.8e15),
            # where one float64 ULP is ~0.25us — summing (base+t0)+dur
            # can land up to ~0.5us past the exactly-representable root
            # end, so a sub-ULP tolerance flakes on wall-clock parity
            assert e["ts"] >= root["ts"] - 1.0
            assert e["ts"] + e["dur"] <= root["ts"] + root["dur"] + 1.0
    # Perfetto requirements: numeric pids/tids + naming metadata
    assert all(isinstance(e["pid"], int) and isinstance(e["tid"], int)
               for e in doc["traceEvents"])
    assert any(e["name"] == "process_name" for e in doc["traceEvents"])


def test_query_round_records_spans_on_the_query_trace():
    eng = _engine()
    eng.ingest_json_batch(_batch(prefix="qs"))
    eng.flush()
    res = eng.query_events(device_token="qs-1")
    assert res["total"] >= 1
    names = {d["name"] for d in eng.tracer.recent(50)}
    assert {"query.round.snapshot", "query.round.fetch"} <= names


def test_tracer_stays_out_of_engine_metrics():
    """The dispatch-shape equality pin (test_ingest.py) runs with
    span_trace on by default; this is the explicit half — toggling the
    tracer cannot change the metrics() dict at all."""
    on = _engine(span_trace=True)
    off = _engine(span_trace=False)
    b = _batch(prefix="mx")
    on.ingest_json_batch(b)
    on.flush()
    on.query_events(device_token="mx-1")
    off.ingest_json_batch(b)
    off.flush()
    off.query_events(device_token="mx-1")
    m_on, m_off = on.metrics(), off.metrics()
    assert set(m_on) == set(m_off)
    assert not any("span" in k for k in m_on)
    assert m_on == m_off


# ===================================================================
# Wall-clock sampling profiler
# ===================================================================

def test_profile_threads_folds_named_stacks():
    stop = threading.Event()

    def busy():
        while not stop.is_set():
            sum(range(500))

    t = threading.Thread(target=busy, name="prof-victim", daemon=True)
    t.start()
    try:
        prof = profile_threads(0.3, interval_s=0.01,
                               thread_filter=lambda n: n == "prof-victim")
        assert prof["samples"] >= 5
        assert prof["threads"] == ["prof-victim"]
        assert prof["folded"]
        for line in prof["folded"].splitlines():
            stack, n = line.rsplit(" ", 1)
            assert stack.startswith("prof-victim;") and int(n) >= 1
        assert any(".busy" in s for s in prof["stacks"])
    finally:
        stop.set()
        t.join(2)


# ===================================================================
# Debug bundle + offline Perfetto converter (satellite)
# ===================================================================

def test_debug_bundle_and_trace2perfetto_roundtrip(tmp_path):
    """The bundle is one self-contained JSON document (config, strict
    0.0.4 exposition with NO exemplar syntax, flights, slowest traces
    with events, spans, WAL posture), and scripts/trace2perfetto.py
    converts it into a standalone Perfetto file — smoke-invoked as a
    subprocess so the converter can't rot."""
    from tests.test_metrics_exposition import lint_prometheus

    eng = _engine(wal_dir=str(tmp_path / "wal"))
    for k in range(3):
        eng.ingest_json_batch(_batch(prefix="db", base=k * 100))
        eng.flush()
    bundle = debug_bundle(eng)
    assert bundle["config"]["span_trace"] is True
    assert bundle["flights"] and bundle["slowestTraces"]
    assert bundle["wal"]["groupCommit"] is not None
    assert bundle["spanStats"]["capacity"] == eng.tracer.capacity
    # the embedded exposition stays on the 0.0.4 surface: lint-clean,
    # no exemplar syntax (satellite: exposition lint over new endpoints)
    lint_prometheus(bundle["prometheus"])
    assert "# {" not in bundle["prometheus"]
    slowest = bundle["slowestTraces"][0]
    assert slowest["traceId"] and slowest["events"]

    path = tmp_path / "bundle.json"
    path.write_text(json.dumps(bundle))
    out = tmp_path / "trace.perfetto.json"
    r = subprocess.run(
        [sys.executable, "scripts/trace2perfetto.py", str(path),
         "--trace", slowest["traceId"], "-o", str(out)],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    doc = json.loads(out.read_text())
    assert doc["traceId"] == slowest["traceId"]
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert xs and any(e["name"] == "ingest" for e in xs)
    assert any(e["name"] == "process_name" for e in doc["traceEvents"])


# ===================================================================
# Acceptance: stitched multi-rank timeline on a replicated cluster
# ===================================================================

def test_stitched_multirank_timeline(tmp_path):
    """One ingested-then-queried event on a 2-rank RF=2 cluster
    resolves, by its single trace id, to ONE stitched Chrome-trace
    timeline: decode/WAL/dispatch/device spans on the OWNER rank, the
    forward-hop span on the ingress rank, and the standby-apply span on
    the follower — every span event tagged with that trace id."""
    from tests.test_cluster import _close, meas, tokens_owned_by
    from tests.test_cluster_observability import _mk_replicated_cluster

    clusters, feeds, host = _mk_replicated_cluster(tmp_path)
    c0, _c1 = clusters
    try:
        # rank-1-owned tokens via rank 0: ingress forwards, rank 1 owns
        # the lifecycle, rank 0 hosts leader-1's standby
        toks = tokens_owned_by(1, 3, prefix="stl")
        s = c0.ingest_json_batch([meas(t, "t", 1.0, 80 + i)
                                  for i, t in enumerate(toks)])
        c0.flush()
        tid = s["trace_id"]
        assert tid and len(tid) == 32
        assert c0.query_events(device_token=toks[0])["total"] == 1
        deadline = time.monotonic() + 20        # standby apply is async
        while (not all(f.drained() for f in feeds)
               and time.monotonic() < deadline):
            time.sleep(0.02)

        doc = c0.get_trace_timeline(tid)
        assert doc["traceId"] == tid
        # pid metadata names each rank's lane group
        rank_of_pid = {e["pid"]: e["args"]["name"]
                       for e in doc["traceEvents"]
                       if e.get("name") == "process_name"}
        assert set(rank_of_pid.values()) == {"rank 0", "rank 1"}
        xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        by_rank = {}
        for e in xs:
            by_rank.setdefault(rank_of_pid[e["pid"]], set()).add(e["name"])
        # owner lifecycle: decode -> WAL -> dispatch -> device on rank 1
        assert {"ingest.decode", "ingest.wal_append",
                "ingest.dispatch_wait", "ingest.device"} \
            <= by_rank["rank 1"], by_rank
        # ingress: the forward hop (live span) on rank 0
        assert "forward.hop" in by_rank["rank 0"], by_rank
        # replication: leader-1's send + the follower's standby apply
        assert "repl.send" in by_rank["rank 1"], by_rank
        assert "repl.apply" in by_rank["rank 0"], by_rank
        # every span event carries THE trace id (one trace, one document)
        for e in xs:
            if e.get("cat") == "span":
                assert e["args"]["traceId"] == tid
    finally:
        for f in feeds:
            f.stop()
        _close(clusters, host)
