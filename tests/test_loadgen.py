"""Load-generator tests (SURVEY.md §4d: the CI-runnable analog of the
reference's manual EventSourceTests senders)."""

import json

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.loadgen import (
    LoadStats,
    generate_measurements_message,
    run_engine_load,
)


def _engine():
    return Engine(EngineConfig(
        device_capacity=256, token_capacity=512, assignment_capacity=512,
        store_capacity=8192, batch_capacity=128, channels=8,
    ))


def test_canonical_message_decodes():
    msg = json.loads(generate_measurements_message("lg-1", 7))
    assert msg["deviceToken"] == "lg-1"
    assert msg["type"] == "DeviceMeasurement"
    assert msg["request"]["name"] == "engine.temperature"
    assert msg["request"]["metadata"]["seq"] == "7"


def test_engine_load_reaches_device_state():
    eng = _engine()
    stats = run_engine_load(eng, n_batches=4, batch_size=64, n_devices=16,
                            warmup_batches=1)
    assert isinstance(stats, LoadStats)
    assert stats.events_sent == 4 * 64
    assert stats.events_decoded == stats.events_sent
    assert stats.events_failed == 0
    assert stats.events_per_s > 0
    assert stats.latency_p50_ms <= stats.latency_p99_ms <= stats.latency_max_ms
    # every generated device registered and aggregated state
    st = eng.get_device_state("lg-0")
    assert st is not None and "engine.temperature" in st["measurements"]
    assert eng.metrics()["persisted"] >= stats.events_sent


def test_rest_load_five_by_hundred():
    """The reference's 5 threads x 100 messages pattern over live HTTP."""
    import asyncio
    import base64

    from sitewhere_tpu.instance.instance import InstanceConfig, SiteWhereTpuInstance
    from sitewhere_tpu.loadgen import run_rest_load
    from sitewhere_tpu.web.rest import start_server

    async def go():
        import aiohttp

        inst = SiteWhereTpuInstance(InstanceConfig(engine=EngineConfig(
            device_capacity=64, token_capacity=128, assignment_capacity=128,
            store_capacity=4096, batch_capacity=16, channels=4)))
        server = await start_server(inst)
        base = f"http://127.0.0.1:{server.port}"
        try:
            async with aiohttp.ClientSession() as s:
                basic = base64.b64encode(b"admin:password").decode()
                async with s.get(
                    f"{base}/api/authapi/jwt",
                    headers={"Authorization": f"Basic {basic}"},
                ) as r:
                    jwt = (await r.json())["token"]
            stats = await run_rest_load(base, jwt, n_workers=5,
                                        msgs_per_worker=20)
            inst.engine.flush()
            state = inst.engine.get_device_state("rest-lg-0")
        finally:
            await server.cleanup()
        return stats, state

    stats, state = asyncio.new_event_loop().run_until_complete(go())
    assert stats.events_sent == 100
    assert stats.events_failed == 0
    assert state is not None


def test_engine_load_pipelined_matches_sync_results():
    """Async steady-state ingest persists the same events; host mirrors
    catch up on drain."""
    eng = _engine()
    stats = run_engine_load(eng, n_batches=4, batch_size=64, n_devices=16,
                            warmup_batches=1, pipelined=True)
    assert stats.events_decoded == stats.events_sent
    assert eng.metrics()["persisted"] >= stats.events_sent
    # mirrors synced: every device visible through the host API
    for i in range(16):
        assert eng.get_device(f"lg-{i}") is not None


def test_flush_async_drain_semantics():
    """flush_async defers host sync; queries force _sync_mirrors."""
    from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType

    eng = _engine()
    for i in range(10):
        eng.process(DecodedRequest(type=RequestType.DEVICE_MEASUREMENT,
                                   device_token=f"as-{i}",
                                   measurements={"x": float(i)}))
    eng.flush_async()
    # device-side registered; host mirror may lag until a query syncs it
    st = eng.get_device_state("as-3")        # get_device_state syncs mirrors
    assert st is not None and st["measurements"]["x"]["value"] == 3.0
    assert eng.get_device("as-9") is not None
    summaries = eng.drain()                   # nothing pending -> zero summary
    assert summaries[-1]["registered"] == 0


# ---------------------------------------------------------------------
# Open-loop mixed-workload harness (ISSUE 7)
# ---------------------------------------------------------------------
def _open_loop_imports():
    from sitewhere_tpu.loadgen import (OpenLoopSpec, TenantLoad,
                                       build_open_loop_schedule,
                                       run_open_loop,
                                       schedule_fingerprint)
    return (OpenLoopSpec, TenantLoad, build_open_loop_schedule,
            run_open_loop, schedule_fingerprint)


def test_open_loop_schedule_is_byte_for_byte_deterministic():
    """Same seed => identical payload STREAM (byte-equal) and identical
    arrival schedule; a different seed diverges."""
    import dataclasses

    (OpenLoopSpec, TenantLoad, build, _run, fingerprint) = \
        _open_loop_imports()
    spec = OpenLoopSpec(
        tenants=(TenantLoad("a", 2000.0, n_devices=8, query_every=3,
                            mutate_every=5),
                 TenantLoad("b", 1000.0, n_devices=8)),
        duration_s=0.4, frame_size=32, seed=7)
    s1, s2 = build(spec), build(spec)
    assert fingerprint(s1) == fingerprint(s2)
    assert len(s1) == len(s2) and len(s1) > 0
    for a, b in zip(s1, s2):
        assert (a.kind, a.tenant, a.t_s) == (b.kind, b.tenant, b.t_s)
        assert a.payloads == b.payloads          # byte-for-byte
        assert a.arrivals == b.arrivals
        assert a.query == b.query and a.mutate == b.mutate
    s3 = build(dataclasses.replace(spec, seed=8))
    assert fingerprint(s3) != fingerprint(s1)


def test_open_loop_schedule_shape():
    """Arrival offsets are per event and monotone within a frame; query
    and mutation ops ride the configured cadence."""
    (OpenLoopSpec, TenantLoad, build, _run, _fp) = _open_loop_imports()
    spec = OpenLoopSpec(
        tenants=(TenantLoad("a", 3000.0, n_devices=4, query_every=2,
                            mutate_every=3),),
        duration_s=0.3, frame_size=16, seed=1)
    sched = build(spec)
    kinds = [op.kind for op in sched]
    assert "query" in kinds and "mutate" in kinds
    times = [op.t_s for op in sched]
    assert times == sorted(times)
    for op in sched:
        if op.kind != "ingest":
            continue
        assert len(op.payloads) == len(op.arrivals) <= 16
        assert list(op.arrivals) == sorted(op.arrivals)
        assert op.t_s == op.arrivals[-1]   # frame departs with its last event
    # the first mutation registers before any update of the same token
    muts = [op.mutate for op in sched if op.kind == "mutate"]
    first_seen = {}
    for kind, token, _md in muts:
        if token not in first_seen:
            first_seen[token] = kind
    assert all(k == "register" for k in first_seen.values())


def test_open_loop_mixed_ops_end_to_end():
    (OpenLoopSpec, TenantLoad, build, run, _fp) = _open_loop_imports()
    eng = _engine()
    # warm: the first flush pays the jit compile, which must not land in
    # the measured run
    run_engine_load(eng, n_batches=1, batch_size=32, n_devices=8,
                    warmup_batches=1)
    spec = OpenLoopSpec(
        tenants=(TenantLoad("alpha", 2500.0, n_devices=8, query_every=3,
                            mutate_every=4),
                 TenantLoad("bravo", 1000.0, n_devices=8)),
        duration_s=0.4, frame_size=32, seed=5)
    sched = build(spec)
    expected = sum(len(op.payloads) for op in sched if op.kind == "ingest")
    res = run(eng, sched, checkpoint_frames=2)
    assert res.events == expected
    assert res.queries > 0 and res.query_p99_ms is not None
    assert res.mutations > 0
    for t in ("alpha", "bravo"):
        d = res.per_tenant[t]
        assert d["events"] > 0
        assert d["e2e_p50_ms"] <= d["e2e_p99_ms"] <= d["e2e_p999_ms"]
        # on-pace run: e2e (arrival-based) ~ service (submit-based)
        assert d["e2e_p50_ms"] >= d["service_p50_ms"] - 1e-6
    eng.flush()
    assert eng.metrics()["persisted"] >= expected


def test_open_loop_historical_queries_hit_the_archive_tier(tmp_path):
    """ISSUE 8 satellite: ``history_every`` emits deterministic historical
    query markers (a date range ending ``history_age_ms`` before now —
    resolved against the engine epoch at fire time), and on an
    archive-primed engine those queries actually traverse the tiered
    (ring + disk) read path."""
    import time

    (OpenLoopSpec, TenantLoad, build, run, fingerprint) = \
        _open_loop_imports()
    eng = Engine(EngineConfig(
        device_capacity=64, token_capacity=128, assignment_capacity=128,
        store_capacity=64, channels=4, batch_capacity=16,
        archive_segment_rows=16, archive_dir=str(tmp_path / "ha")))
    # prime >= 4x ring so the history range falls beyond the ring
    base = int(eng.epoch.base_unix_s * 1000)
    old = base + int(eng.epoch.now_ms()) - 30_000
    for i in range(4 * 64):
        eng.ingest_json_batch([json.dumps({
            "deviceToken": f"hist-{i % 4}", "type": "DeviceMeasurements",
            "request": {"measurements": {"t": float(i)},
                        "eventDate": old + i}}).encode()])
    eng.flush()
    assert eng.archive.total_rows() > 0
    spec = OpenLoopSpec(
        tenants=(TenantLoad("default", 1500.0, n_devices=4,
                            device_prefix="hist", history_every=2,
                            history_age_ms=5_000),),
        duration_s=0.3, frame_size=32, seed=11)
    s1, s2 = build(spec), build(spec)
    assert fingerprint(s1) == fingerprint(s2)   # markers stay deterministic
    hist_ops = [op for op in s1 if op.kind == "query"
                and "history_age_ms" in op.query]
    assert hist_ops and all(op.query["limit"] == 20 for op in hist_ops)
    assert any("device_token" in op.query for op in hist_ops)
    before = eng.archive.queries
    t0 = time.perf_counter()
    res = run(eng, s1, checkpoint_frames=2)
    assert res.history_queries == len(hist_ops)
    assert res.history_p99_ms is not None and res.history_p99_ms > 0
    # the tiered path was exercised: every history query planned a scan
    assert eng.archive.queries >= before + len(hist_ops)
    assert time.perf_counter() - t0 < 60


def test_open_loop_drives_sharded_engine():
    """ISSUE 16 satellite: the open-loop driver accepts the mesh-sharded
    SPMD engine as a target — ingest frames fan out over the shard lanes,
    queries traverse the fused cross-shard round, and mutations land on
    their owner shards, all through the same duck-typed surface."""
    from sitewhere_tpu.parallel.sharded import SpmdEngine

    (OpenLoopSpec, TenantLoad, build, run, _fp) = _open_loop_imports()
    eng = SpmdEngine(EngineConfig(
        device_capacity=256, token_capacity=512, assignment_capacity=512,
        store_capacity=8192, batch_capacity=128, channels=8,
        use_native=False), n_shards=2)
    run_engine_load(eng, n_batches=1, batch_size=32, n_devices=8,
                    warmup_batches=1)                      # warm compile
    spec = OpenLoopSpec(
        tenants=(TenantLoad("alpha", 2500.0, n_devices=8, query_every=3,
                            mutate_every=4),),
        duration_s=0.3, frame_size=32, seed=5)
    sched = build(spec)
    expected = sum(len(op.payloads) for op in sched if op.kind == "ingest")
    res = run(eng, sched, checkpoint_frames=2)
    assert res.events == expected
    assert res.queries > 0 and res.query_p99_ms is not None
    assert res.mutations > 0
    eng.flush()
    assert eng.metrics()["persisted"] >= expected
    # the stream actually spanned the mesh: both shard lanes own devices
    assert all(eng._next_local_device[s] > 0 for s in range(2))


def test_open_loop_backlog_latency_includes_queueing_delay():
    """THE open-loop property: when the engine is artificially slowed
    below the offered rate, recorded wire->state latency GROWS with the
    backlog (scheduled arrival -> visible), far beyond the per-frame
    service time a closed-loop driver would report."""
    import time as _time

    (OpenLoopSpec, TenantLoad, build, run, _fp) = _open_loop_imports()

    class SlowEngine:
        """Every ingest stalls: service time >> scheduled inter-frame
        gap, so arrivals pile up behind the driver."""

        def __init__(self, inner, stall_s):
            self._inner = inner
            self._stall = stall_s

        def ingest_json_batch(self, payloads, tenant="default"):
            _time.sleep(self._stall)
            return self._inner.ingest_json_batch(payloads, tenant)

        def __getattr__(self, name):
            return getattr(self._inner, name)

    eng = _engine()
    run_engine_load(eng, n_batches=1, batch_size=32, n_devices=8,
                    warmup_batches=1)                      # warm compile
    stall = 0.03
    # offered: one 16-event frame every ~4ms; served: >= 30ms per frame
    spec = OpenLoopSpec(
        tenants=(TenantLoad("bl", 4000.0, n_devices=8),),
        duration_s=0.25, frame_size=16, seed=11)
    sched = build(spec)
    res = run(SlowEngine(eng, stall), sched, checkpoint_frames=1)
    d = res.per_tenant["bl"]
    n_frames = sum(1 for op in sched if op.kind == "ingest")
    assert n_frames >= 10
    # the LAST frames waited behind the whole backlog: max e2e latency
    # must exceed several service times, and the p99 must sit well above
    # the single-frame stall
    assert res.max_lateness_s > 3 * stall
    assert d["e2e_max_ms"] > 5 * stall * 1e3
    assert d["e2e_p99_ms"] > 2 * stall * 1e3


def test_open_loop_slo_histogram_matches_loadgen_p99():
    """Acceptance pin (ISSUE 7): per-tenant swtpu_ingest_e2e_seconds p99
    computed via Histogram.quantile from the scrape-time flight-record
    harvest matches the loadgen-measured p99 within one bucket width.
    The comparable loadgen family is service_* (submit -> visible): the
    flight record's clock starts at ingest entry."""
    import bisect

    from sitewhere_tpu.utils.metrics import (E2E_LATENCY_BUCKETS,
                                             MetricsRegistry,
                                             export_engine_metrics)

    (OpenLoopSpec, TenantLoad, build, run, _fp) = _open_loop_imports()
    eng = _engine()
    run_engine_load(eng, n_batches=1, batch_size=64, n_devices=16,
                    warmup_batches=1)                      # warm compile
    spec = OpenLoopSpec(
        tenants=(TenantLoad("slo", 4000.0, n_devices=16),),
        duration_s=0.5, frame_size=64, seed=3)
    sched = build(spec)
    res = run(eng, sched, checkpoint_frames=1)
    reg = MetricsRegistry()
    export_engine_metrics(eng, reg)                        # harvests SLO
    hist = reg.histogram("swtpu_ingest_e2e_seconds")
    assert hist.count_where(tenant="slo") == res.per_tenant["slo"]["events"]
    slo_p99 = hist.quantile_where(0.99, tenant="slo")
    load_p99 = res.per_tenant["slo"]["service_p99_ms"] / 1e3
    i = bisect.bisect_left(E2E_LATENCY_BUCKETS, load_p99)
    i = min(i, len(E2E_LATENCY_BUCKETS) - 1)
    width = E2E_LATENCY_BUCKETS[i] - (E2E_LATENCY_BUCKETS[i - 1] if i
                                      else 0.0)
    assert abs(slo_p99 - load_p99) <= width + 1e-9, \
        (slo_p99, load_p99, width)
