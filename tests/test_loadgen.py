"""Load-generator tests (SURVEY.md §4d: the CI-runnable analog of the
reference's manual EventSourceTests senders)."""

import json

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.loadgen import (
    LoadStats,
    generate_measurements_message,
    run_engine_load,
)


def _engine():
    return Engine(EngineConfig(
        device_capacity=256, token_capacity=512, assignment_capacity=512,
        store_capacity=8192, batch_capacity=128, channels=8,
    ))


def test_canonical_message_decodes():
    msg = json.loads(generate_measurements_message("lg-1", 7))
    assert msg["deviceToken"] == "lg-1"
    assert msg["type"] == "DeviceMeasurement"
    assert msg["request"]["name"] == "engine.temperature"
    assert msg["request"]["metadata"]["seq"] == "7"


def test_engine_load_reaches_device_state():
    eng = _engine()
    stats = run_engine_load(eng, n_batches=4, batch_size=64, n_devices=16,
                            warmup_batches=1)
    assert isinstance(stats, LoadStats)
    assert stats.events_sent == 4 * 64
    assert stats.events_decoded == stats.events_sent
    assert stats.events_failed == 0
    assert stats.events_per_s > 0
    assert stats.latency_p50_ms <= stats.latency_p99_ms <= stats.latency_max_ms
    # every generated device registered and aggregated state
    st = eng.get_device_state("lg-0")
    assert st is not None and "engine.temperature" in st["measurements"]
    assert eng.metrics()["persisted"] >= stats.events_sent


def test_rest_load_five_by_hundred():
    """The reference's 5 threads x 100 messages pattern over live HTTP."""
    import asyncio
    import base64

    from sitewhere_tpu.instance.instance import InstanceConfig, SiteWhereTpuInstance
    from sitewhere_tpu.loadgen import run_rest_load
    from sitewhere_tpu.web.rest import start_server

    async def go():
        import aiohttp

        inst = SiteWhereTpuInstance(InstanceConfig(engine=EngineConfig(
            device_capacity=64, token_capacity=128, assignment_capacity=128,
            store_capacity=4096, batch_capacity=16, channels=4)))
        server = await start_server(inst)
        base = f"http://127.0.0.1:{server.port}"
        try:
            async with aiohttp.ClientSession() as s:
                basic = base64.b64encode(b"admin:password").decode()
                async with s.get(
                    f"{base}/api/authapi/jwt",
                    headers={"Authorization": f"Basic {basic}"},
                ) as r:
                    jwt = (await r.json())["token"]
            stats = await run_rest_load(base, jwt, n_workers=5,
                                        msgs_per_worker=20)
            inst.engine.flush()
            state = inst.engine.get_device_state("rest-lg-0")
        finally:
            await server.cleanup()
        return stats, state

    stats, state = asyncio.new_event_loop().run_until_complete(go())
    assert stats.events_sent == 100
    assert stats.events_failed == 0
    assert state is not None


def test_engine_load_pipelined_matches_sync_results():
    """Async steady-state ingest persists the same events; host mirrors
    catch up on drain."""
    eng = _engine()
    stats = run_engine_load(eng, n_batches=4, batch_size=64, n_devices=16,
                            warmup_batches=1, pipelined=True)
    assert stats.events_decoded == stats.events_sent
    assert eng.metrics()["persisted"] >= stats.events_sent
    # mirrors synced: every device visible through the host API
    for i in range(16):
        assert eng.get_device(f"lg-{i}") is not None


def test_flush_async_drain_semantics():
    """flush_async defers host sync; queries force _sync_mirrors."""
    from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType

    eng = _engine()
    for i in range(10):
        eng.process(DecodedRequest(type=RequestType.DEVICE_MEASUREMENT,
                                   device_token=f"as-{i}",
                                   measurements={"x": float(i)}))
    eng.flush_async()
    # device-side registered; host mirror may lag until a query syncs it
    st = eng.get_device_state("as-3")        # get_device_state syncs mirrors
    assert st is not None and st["measurements"]["x"]["value"] == 3.0
    assert eng.get_device("as-9") is not None
    summaries = eng.drain()                   # nothing pending -> zero summary
    assert summaries[-1]["registered"] == 0
