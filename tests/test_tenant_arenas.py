"""Per-tenant HBM arenas: hard retention isolation in the event store.

VERDICT item: a burst tenant must not evict other tenants' events
(reference: engine-per-tenant isolation,
InboundProcessingMicroservice.java:84-86)."""

import json

import numpy as np
import pytest

from sitewhere_tpu.engine import Engine, EngineConfig


def eng_with_arenas(arenas=4, store_capacity=256, batch=16):
    return Engine(EngineConfig(
        device_capacity=128, token_capacity=256, assignment_capacity=256,
        store_capacity=store_capacity, batch_capacity=batch, channels=4,
        tenant_arenas=arenas))


def meas(token, value, ts):
    return json.dumps({"deviceToken": token, "type": "DeviceMeasurements",
                       "request": {"measurements": {"m": value},
                                   "eventDate": ts}}).encode()


def test_flood_tenant_cannot_evict_others():
    """Tenant 'bulk' writes 10x the whole store capacity; tenant 'tiny's
    events remain fully retained and queryable."""
    eng = eng_with_arenas(arenas=4, store_capacity=256, batch=16)
    base = int(eng.epoch.base_unix_s * 1000)
    # tiny writes 8 events first
    eng.ingest_json_batch([meas(f"t-{i}", float(i), base + i)
                           for i in range(8)], tenant="tiny")
    eng.flush()
    # bulk floods: 2560 events >> 256-row store
    for r in range(20):
        eng.ingest_json_batch(
            [meas(f"b-{i}", 1.0, base + 10_000 + r * 128 + i)
             for i in range(128)], tenant="bulk")
    eng.flush()
    res = eng.query_events(tenant="tiny", limit=50)
    assert res["total"] == 8           # nothing evicted
    vals = sorted(e["measurements"]["m"] for e in res["events"])
    assert vals == [float(i) for i in range(8)]
    # bulk capped at its arena's capacity (256/4 = 64 retained)
    res_b = eng.query_events(tenant="bulk", limit=100)
    assert res_b["total"] == 64


def test_shared_ring_still_evicts_across_tenants():
    """With arenas=1 (default) the classic shared-ring behavior holds —
    the flood DOES evict (regression guard that arenas change behavior
    only when enabled)."""
    eng = eng_with_arenas(arenas=1, store_capacity=256, batch=16)
    base = int(eng.epoch.base_unix_s * 1000)
    eng.ingest_json_batch([meas(f"t-{i}", float(i), base + i)
                           for i in range(8)], tenant="tiny")
    eng.flush()
    for r in range(4):
        eng.ingest_json_batch(
            [meas(f"b-{i}", 1.0, base + 10_000 + r * 128 + i)
             for i in range(128)], tenant="bulk")
    eng.flush()
    assert eng.query_events(tenant="tiny", limit=50)["total"] == 0


def test_arena_wrap_and_order():
    """One arena wraps independently; newest-first query order holds."""
    eng = eng_with_arenas(arenas=4, store_capacity=256, batch=16)
    base = int(eng.epoch.base_unix_s * 1000)
    for r in range(6):
        eng.ingest_json_batch([meas("w-1", float(r * 16 + i),
                                    base + r * 100 + i)
                               for i in range(16)], tenant="wrap")
    eng.flush()
    res = eng.query_events(tenant="wrap", limit=64)
    assert res["total"] == 64          # arena capacity, not 96
    newest = res["events"][0]["measurements"]["m"]
    assert newest == 95.0              # latest survives the wrap


def test_feed_consumes_across_arenas():
    """Outbound feed drains every arena with per-arena offsets; event ids
    stay unique and committable."""
    from sitewhere_tpu.outbound.feed import FeedConsumer

    eng = eng_with_arenas(arenas=4, store_capacity=256, batch=16)
    base = int(eng.epoch.base_unix_s * 1000)
    for t in ("alpha", "beta", "gamma"):
        eng.ingest_json_batch([meas(f"{t}-{i}", float(i), base + i)
                               for i in range(5)], tenant=t)
    eng.flush()
    feed = FeedConsumer(eng, "grp")
    evs = feed.poll()
    assert len(evs) == 15
    assert len({e.event_id for e in evs}) == 15
    feed.commit(evs)
    assert feed.poll() == []
    # new traffic resumes from committed offsets
    eng.ingest_json_batch([meas("alpha-0", 99.0, base + 500)],
                          tenant="alpha")
    eng.flush()
    evs2 = feed.poll()
    assert len(evs2) == 1 and evs2[0].measurements["m"] == 99.0


def test_get_event_by_arena_encoded_id():
    eng = eng_with_arenas(arenas=4, store_capacity=256, batch=16)
    base = int(eng.epoch.base_unix_s * 1000)
    eng.ingest_json_batch([meas("ge-1", 42.0, base + 1)], tenant="acme")
    eng.flush()
    from sitewhere_tpu.outbound.feed import FeedConsumer

    evs = FeedConsumer(eng, "g").poll()
    assert len(evs) == 1
    ev = eng.get_event(evs[0].event_id)
    assert ev is not None and ev["measurements"]["m"] == 42.0
    assert eng.get_event(evs[0].event_id + 4) is None   # next pos: unwritten
