"""Stage-time autotuner (ISSUE 4 tentpole, pillar 3).

The decision policy is pure (utils/autotune.decide) so it pins cheaply;
the engine-level tests check the control loop actually reads the flight
recorder, applies ONE knob per evaluation through set_ingest_tuning, and
exports its beliefs as gauges. scan_chunk changes rebuild the arena pool
— the rebuilt pipeline must keep producing identical results.
"""

import dataclasses

import numpy as np
import pytest

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.loadgen import generate_measurements_message
from sitewhere_tpu.utils.autotune import StageTimeAutotuner, decide

SMALL = dict(device_capacity=1 << 10, token_capacity=1 << 11,
             assignment_capacity=1 << 11, store_capacity=1 << 12,
             batch_capacity=128)

BOUNDS = {"max_workers": 4, "max_depth": 4, "max_chunk": 8}
CUR = {"ingest_workers": 1, "dispatch_depth": 1, "scan_chunk": 1}


# -------------------------------------------------------------- the policy
def test_decide_decode_bound_widens_fanout():
    out = decide({"decode_ms": 5.0, "wal_ms": 0.5, "dispatch_wait_ms": 0.2,
                  "device_ms": 1.0}, CUR, BOUNDS)
    assert out[0][0] == "ingest_workers" and out[0][1] == 2


def test_decide_device_bound_deepens_dispatch():
    out = decide({"decode_ms": 0.3, "wal_ms": 0.1, "dispatch_wait_ms": 0.2,
                  "device_ms": 5.0}, CUR, BOUNDS)
    assert ("dispatch_depth", 2) in [(k, v) for k, v, _ in out]


def test_decide_dispatch_overhead_raises_chunk():
    out = decide({"decode_ms": 0.5, "wal_ms": 0.1, "dispatch_wait_ms": 9.0,
                  "device_ms": 1.0}, CUR, BOUNDS)
    assert ("scan_chunk", 2) in [(k, v) for k, v, _ in out]


def test_decide_sheds_overprovisioned_knobs():
    out = decide({"decode_ms": 0.2, "wal_ms": 0.1, "dispatch_wait_ms": 0.1,
                  "device_ms": 5.0},
                 {"ingest_workers": 3, "dispatch_depth": 1, "scan_chunk": 4},
                 BOUNDS)
    knobs = {(k, v) for k, v, _ in out}
    assert ("ingest_workers", 2) in knobs
    assert ("scan_chunk", 2) in knobs


def test_decide_respects_bounds():
    out = decide({"decode_ms": 9.0, "wal_ms": 0.1, "dispatch_wait_ms": 9.0,
                  "device_ms": 0.1},
                 {"ingest_workers": 4, "dispatch_depth": 4, "scan_chunk": 8},
                 BOUNDS)
    for knob, value, _ in out:
        assert value <= BOUNDS[{"ingest_workers": "max_workers",
                                "dispatch_depth": "max_depth",
                                "scan_chunk": "max_chunk"}[knob]]


def test_decide_hysteresis_dead_zone():
    """Between the raise and shed thresholds nothing moves — a noisy
    window must not ping-pong a knob."""
    out = decide({"decode_ms": 1.0, "wal_ms": 0.2, "dispatch_wait_ms": 1.0,
                  "device_ms": 1.5},
                 {"ingest_workers": 2, "dispatch_depth": 2, "scan_chunk": 2},
                 BOUNDS)
    assert out == []


# ---------------------------------------------------------- engine control
def test_autotuner_adapts_from_flight_records():
    eng = Engine(EngineConfig(**SMALL, autotune=True, autotune_interval=4))
    assert eng._autotuner is not None
    for b in range(16):
        eng.ingest_json_batch([
            generate_measurements_message(f"at-{i % 20}", b * 128 + i)
            for i in range(128)])
    eng.flush()
    t = eng._autotuner
    assert t.evaluations >= 2
    # on the CPU backend the device step dominates by orders of
    # magnitude: the tuner must have deepened dispatch_depth
    assert eng.config.dispatch_depth > 1
    assert t.decisions, "no decision recorded"
    d = t.decisions[0]
    assert {"knob", "from", "to", "reason", "stats"} <= set(d)


def test_autotuner_gauges_exported():
    from sitewhere_tpu.utils.metrics import REGISTRY

    eng = Engine(EngineConfig(**SMALL, autotune=True, autotune_interval=2))
    for b in range(8):
        eng.ingest_json_batch([
            generate_measurements_message(f"ag-{i % 10}", b * 128 + i)
            for i in range(128)])
    eng.flush()
    text = REGISTRY.expose_text()
    assert "swtpu_autotune_dispatch_depth" in text
    assert "swtpu_autotune_ingest_workers" in text


def test_autotuner_needs_min_samples():
    eng = Engine(EngineConfig(**SMALL, autotune=True))
    t = eng._autotuner
    assert t.window_stats() is None       # empty recorder
    assert t.evaluate() is None           # and evaluate() tolerates it


def test_scan_chunk_retune_rebuilds_and_stays_correct():
    """set_ingest_tuning(scan_chunk=...) mid-run: the pool + scan step
    rebuild, in-flight work drains, and subsequent ingest persists
    exactly — results identical to a never-retuned engine."""
    def run(retune):
        eng = Engine(EngineConfig(**SMALL))
        if eng._arena_pool is None:
            pytest.skip("native arena path unavailable")
        eng.epoch.base_unix_s = 1700000000.0 - 1000.0
        eng.epoch.now_ms = lambda: 999
        pay = [generate_measurements_message(f"rc-{i % 30}", i)
               for i in range(600)]
        eng.ingest_json_batch(pay[:300])
        if retune:
            applied = eng.set_ingest_tuning(scan_chunk=2)
            assert applied["scan_chunk"] == 2
            assert eng._arena_step is not None
        eng.ingest_json_batch(pay[300:])
        eng.flush()
        if retune:   # and back down: rebuild to single-step shape
            eng.set_ingest_tuning(scan_chunk=1)
            assert eng._arena_step is None
        return eng

    import jax

    a, b = run(False), run(True)
    assert a.metrics()["persisted"] == b.metrics()["persisted"] == 600
    sa, sb = jax.device_get(a.state.store), jax.device_get(b.state.store)
    for f in dataclasses.fields(sa):
        assert np.array_equal(np.asarray(getattr(sa, f.name)),
                              np.asarray(getattr(sb, f.name))), \
            f"store.{f.name} diverges"


def test_autotuner_scan_chunk_gated_by_opt_in():
    eng = Engine(EngineConfig(**SMALL, autotune=True))
    t = eng._autotuner
    assert not t.adapt_scan_chunk
    eng2 = Engine(EngineConfig(**SMALL, autotune=True,
                               autotune_scan_chunk=True))
    assert eng2._autotuner.adapt_scan_chunk
