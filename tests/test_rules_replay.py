"""Replay/standby discipline of the streaming-rules tier (ISSUE 13) and
the admin-path standby-visibility satellite (the PR-6 documented limit).

Contract: alert events are dedup-keyed by rule+group+window, so
  * kill/recover re-evaluates rules over WAL replay and emits EXACTLY
    the fires the dead owner never shipped — zero lost, zero duplicate;
  * a standby running the same rule set over the same stream carries
    identical rule state; promotion emits only the un-shipped tail;
  * admin-path ``register_device`` (non-wire REST) is WAL-carried as its
    wire-form envelope, so it replays AND replica-feed publishes.
"""

import json

import pytest

from sitewhere_tpu.engine import WAL_BINARY, Engine, EngineConfig
from sitewhere_tpu.rules import RuleSet, RulesManager
from sitewhere_tpu.rules import oracle
from sitewhere_tpu.utils.checkpoint import (replay_wal_into,
                                            restore_engine, save_engine)

CFG = dict(device_capacity=256, token_capacity=512,
           assignment_capacity=512, store_capacity=4096,
           batch_capacity=32, channels=4, rule_groups=64,
           rollup_buckets=8)

RULESET = {
    "name": "rp",
    "rules": [
        {"name": "hot", "kind": "threshold", "channel": "temp",
         "op": ">", "value": 90.0, "cooldownMs": 1000},
        {"name": "burst", "kind": "window", "agg": "sum",
         "channel": "temp", "op": ">=", "value": 200.0,
         "windowMs": 2000},
        {"name": "silent", "kind": "absence", "channel": "temp",
         "deadlineMs": 3000},
    ],
    "rollups": [{"name": "temp-1s", "channel": "temp",
                 "windowMs": 1000, "scope": "device"}],
}


def _engine(tmp_path=None, name="wal", **kw):
    cfg = dict(CFG, **kw)
    if tmp_path is not None:
        cfg["wal_dir"] = str(tmp_path / name)
    return Engine(EngineConfig(**cfg))


def _meas(eng, tok, v, ts_rel):
    return json.dumps({
        "deviceToken": tok, "type": "DeviceMeasurement",
        "request": {"name": "temp", "value": v,
                    "eventDate": int(eng.epoch.base_unix_s * 1000)
                    + ts_rel}}).encode()


def _stream(n=72, devs=4, quiet_after=36):
    out = []
    for i in range(n):
        d = i % devs
        if d == 0 and i >= quiet_after:
            d = 1
        v = 96.5 if i % 9 == 0 else 30.0 + (i % 20) * 0.5
        out.append((d, v, i * 100))
    return out


def _oracle_keys(events, final_wm):
    ev = [{"ts": ts, "group": d, "value": v} for d, v, ts in events]
    exp = set()
    for g, w in oracle.threshold_fire_keys(ev, op=0, value=90.0,
                                           cooldown_ms=1000):
        exp.add(f"swr:hot:q-{g}:{w}")
    for g, w in oracle.window_fire_keys(ev, agg="sum", op=1, value=200.0,
                                        window_ms=2000):
        exp.add(f"swr:burst:q-{g}:{w}")
    for g, w in oracle.absence_fire_keys(ev, op=1, value=float("-inf"),
                                         deadline_ms=3000,
                                         final_watermark=final_wm):
        exp.add(f"swr:silent:q-{g}:{w}")
    return exp


def _feed(eng, events, lo, hi, chunk=24):
    for b in range(lo, hi, chunk):
        eng.ingest_json_batch([_meas(eng, f"q-{d}", v, ts)
                               for d, v, ts in events[b:min(b + chunk,
                                                            hi)]])
    eng.flush()


def test_kill_recover_reevaluation_zero_loss_zero_dup(tmp_path):
    """The chaos slice: half the stream emitted, half fired-but-unpolled,
    SIGKILL, recover from snapshot + WAL replay with the rule set
    reinstalled — the union of pre/post alert keys is exactly the
    oracle's, the intersection empty, and the recovered store holds each
    alert exactly once."""
    events = _stream()
    eng = _engine(tmp_path)
    mgr = RulesManager(eng)
    mgr.load(RuleSet.parse(RULESET), precompile=False)
    save_engine(eng, tmp_path / "snap")
    _feed(eng, events, 0, 36)
    pre = mgr.poll()                   # emitted + WAL-carried
    _feed(eng, events, 36, len(events))
    eng.wal.sync()
    eng.wal.close()                    # "SIGKILL" — pending fires lost?
    del eng

    r2 = restore_engine(tmp_path / "snap")
    m2 = RulesManager(r2)
    m2.load(RuleSet.parse(RULESET), precompile=False)
    replay_wal_into(r2, 0, tmp_path / "wal")
    post = m2.poll()
    pre_keys = {a["alternateId"] for a in pre}
    post_keys = {a["alternateId"] for a in post}
    assert pre_keys and post_keys
    assert not (pre_keys & post_keys), "duplicate alert after recovery"
    assert pre_keys | post_keys == _oracle_keys(events, events[-1][2])
    # store-level: every alert exactly once, queryable by its dedup key
    r2.flush()
    from sitewhere_tpu.core.types import EventType

    q = r2.query_events(etype=EventType.ALERT, limit=200)
    assert q["total"] == len(pre_keys | post_keys)
    # rollups rebuilt by replay match the oracle exactly
    ev = [{"ts": ts, "group": d, "value": v} for d, v, ts in events]
    want = oracle.rollup_oracle(ev, window_ms=1000, buckets=8)
    for g in range(4):
        got = m2.read_rollup("temp-1s", group=f"q-{g}")
        got_map = {b["windowStartMs"]: (b["count"], b["sum"], b["min"],
                                        b["max"])
                   for b in got["buckets"]}
        want_map = {st[0] * 1000: (st[1], st[2], st[3], st[4])
                    for (gg, s), st in want.items() if gg == g}
        assert got_map == want_map


def test_standby_runs_rules_and_promotion_emits_only_the_tail():
    """A standby applies the owner's stream (alert events included, as
    the replica feed ships them) with the same rule set but emission
    OFF: its carried rule state tracks the owner's, and promotion emits
    exactly the fires the dead owner never polled out — dedup-keyed
    against the replayed alerts, nothing twice."""
    events = _stream()
    owner = Engine(EngineConfig(**CFG))
    standby = Engine(EngineConfig(**CFG))
    standby.epoch = owner.epoch
    omgr = RulesManager(owner)
    smgr = RulesManager(standby, active=False)
    omgr.load(RuleSet.parse(RULESET), precompile=False)
    smgr.load(RuleSet.parse(RULESET), precompile=False)

    # "replica feed": every owner ingest batch (rule alerts included —
    # the manager emits through this very path) applies on the standby
    orig = owner.ingest_json_batch

    def forwarding(payloads, tenant="default", **kw):
        res = orig(payloads, tenant, **kw)
        standby.ingest_json_batch(list(payloads), tenant)
        return res

    owner.ingest_json_batch = forwarding
    _feed(owner, events, 0, 36)
    pre = omgr.poll()                  # shipped to the standby too
    _feed(owner, events, 36, len(events))
    standby.flush()
    # standby rule state == owner rule state (same stream, same kernel)
    import numpy as np

    ow, st = owner.state.rules.rules, standby.state.rules.rules
    assert np.array_equal(np.asarray(ow.fired_key),
                          np.asarray(st.fired_key))
    assert int(ow.fires) == int(st.fires)
    # a passive poll emits nothing and harvests nothing
    assert smgr.poll() == []
    # owner dies; standby promotes: resync registers the replayed alert
    # keys, the next poll emits only the unshipped tail
    suppressed0 = smgr.alerts_suppressed
    smgr.promote()
    post = smgr.poll()
    pre_keys = {a["alternateId"] for a in pre}
    post_keys = {a["alternateId"] for a in post}
    assert pre_keys and post_keys
    assert not (pre_keys & post_keys)
    assert pre_keys | post_keys == _oracle_keys(events, events[-1][2])
    assert smgr.alerts_suppressed > suppressed0   # dedup did real work


def test_admin_register_device_is_wal_replayed(tmp_path):
    """Satellite (PR-6 documented limit): a non-wire REST-path
    registration must survive WAL-only recovery — the admin mutation is
    logged as its wire-form envelope."""
    eng = _engine(tmp_path)
    eng.register_device("adm-1", device_type="sensor", tenant="t1",
                        area="zone-9")
    eng.ingest_json_batch([_meas(eng, "adm-1", 20.0, 100)], tenant="t1")
    eng.flush()
    eng.wal.sync()
    eng.wal.close()
    del eng

    r2 = _engine(tmp_path)             # same WAL dir, empty state
    replay_wal_into(r2, -1, tmp_path / "wal")
    info = r2.get_device("adm-1")
    assert info is not None
    assert info.device_type == "sensor"
    assert info.tenant == "t1" and info.area == "zone-9"
    assert r2.metrics()["persisted"] == 1


def test_admin_register_publishes_one_feed_record_and_wire_path_none():
    """The admin path publishes exactly ONE replica-feed record per
    registration; the wire path (process) keeps its single envelope —
    no double-publish from the nested admin call."""
    import tempfile

    eng = Engine(EngineConfig(**CFG, wal_dir=tempfile.mkdtemp(
        prefix="swtpu-admfeed-")))
    published = []

    class FeedStub:
        def publish(self, tag, payloads, tenant, ticket, now_ms):
            published.append((tag, len(payloads), tenant))

    eng.replica_feed = FeedStub()
    eng.register_device("fd-1", tenant="t2")
    assert published == [(WAL_BINARY, 1, "t2")]
    # idempotent get-or-create: no second record
    eng.register_device("fd-1", tenant="t2")
    assert len(published) == 1
    # wire-path registration envelope: exactly one record, logged by
    # process() itself (the nested admin call is suppressed)
    from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType

    req = DecodedRequest(type=RequestType.REGISTER_DEVICE,
                         device_token="fd-2", tenant="t2",
                         extras={"deviceTypeToken": "sensor"})
    eng.process(req)
    assert len(published) == 2
    assert eng.get_device("fd-2").device_type == "sensor"


@pytest.mark.slow
def test_admin_register_standby_visible_through_real_replication(tmp_path):
    """End to end through the PR-6 machinery: an admin registration on
    the owner rank lands in the follower's standby engine registry."""
    from tests.test_replication import (_close, _mk_replicated_cluster,
                                        _wait)
    from tests.test_cluster import tokens_owned_by

    clusters, feeds, appliers, servers, host, ports = \
        _mk_replicated_cluster(tmp_path)
    c0 = clusters[0]
    try:
        tok = tokens_owned_by(0, 1, prefix="admrep")[0]
        did = c0.register_device(tok, tenant="default")
        assert did is not None
        _wait(feeds[0].drained, what="feed drain")
        st = appliers[1]._standby(0)
        assert st is not None
        st.engine.flush()
        tid = st.engine.tokens.lookup(tok)
        assert tid >= 0
        assert st.engine.token_device.get(tid) is not None
    finally:
        _close(clusters, feeds, host)
