"""scripts/bench_diff.py — bench-trajectory tooling (ISSUE 14 satellite).

Smoke-invokes the CLI on two synthetic bench JSONs (the BENCH_SCHEMA
gate/report split): report-field drift never fails the diff, a violated
hard gate (or a gate that silently dropped out of the new run) always
does, and the delta table prints for shared numeric fields."""

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "bench_diff.py"


def _base() -> dict:
    return {
        "value": 100_000, "latency_p99_ms": 12.5,
        "trace_overhead_pct": 1.2, "span_overhead_pct": 0.8,
        "conservation_overhead_pct": 0.5,
        "conservation_headline_violations": 0,
        "query_batch_parity": True, "archive_parity": True,
        "archive_ring_multiple": 11.0, "fairness_admitted_loss": 0,
    }


def _run(old: dict, new: dict, tmp_path):
    a = tmp_path / "old.json"
    b = tmp_path / "new.json"
    a.write_text(json.dumps(old))
    b.write_text(json.dumps(new))
    return subprocess.run([sys.executable, str(SCRIPT), str(a), str(b)],
                          capture_output=True, text=True, timeout=60)


def test_report_drift_passes_and_prints_deltas(tmp_path):
    new = _base() | {"value": 70_000, "latency_p99_ms": 30.0}
    res = _run(_base(), new, tmp_path)
    assert res.returncode == 0, res.stderr
    assert "latency_p99_ms" in res.stdout and "-30.0%" in res.stdout
    assert "no hard-gate regressions" in res.stdout


def test_gate_violation_fails(tmp_path):
    for bad in ({"trace_overhead_pct": 4.7},
                {"conservation_headline_violations": 2},
                {"archive_parity": False},
                {"archive_ring_multiple": 3.0}):
        res = _run(_base(), _base() | bad, tmp_path)
        field = next(iter(bad))
        assert res.returncode == 1, (bad, res.stdout, res.stderr)
        assert f"GATE {field}" in res.stderr


def test_relational_gate_batched_vs_sequential(tmp_path):
    """BENCH_SCHEMA's relational gate: batched QPS must beat
    sequential QPS within the SAME run."""
    ok = _base() | {"query_batched_qps": 900.0,
                    "query_sequential_qps": 500.0}
    assert _run(ok, ok, tmp_path).returncode == 0
    bad = _base() | {"query_batched_qps": 400.0,
                     "query_sequential_qps": 500.0}
    res = _run(ok, bad, tmp_path)
    assert res.returncode == 1
    assert "GATE query_batched_qps" in res.stderr


def test_dropped_gate_is_a_regression(tmp_path):
    new = _base()
    del new["query_batch_parity"]
    res = _run(_base(), new, tmp_path)
    assert res.returncode == 1
    assert "ABSENT" in res.stderr
    # ...but a gate absent from BOTH runs (leg never ran) is fine
    old = _base()
    del old["query_batch_parity"]
    assert _run(old, new, tmp_path).returncode == 0


def test_spmd_gates_enforced(tmp_path):
    """ISSUE 16: the multi-chip SPMD leg's parity/zero/min gates fail
    the diff when violated, and a silently dropped SPMD gate is a
    regression like any other leg's."""
    ok = _base() | {
        "spmd_shards": 2, "spmd_store_parity": True,
        "spmd_query_parity": True, "spmd_metrics_equal": True,
        "spmd_rules_parity": True, "spmd_steady_recompiles": 0,
        "spmd_excess_retraces": 0, "conservation_spmd_violations": 0,
        "spmd_ingest_events_per_s": 7500.0,
    }
    assert _run(ok, ok, tmp_path).returncode == 0
    # report-field drift (ingest rate) never gates
    res = _run(ok, ok | {"spmd_ingest_events_per_s": 3000.0}, tmp_path)
    assert res.returncode == 0, res.stderr
    for bad in ({"spmd_store_parity": False},
                {"spmd_query_parity": False},
                {"spmd_rules_parity": False},
                {"spmd_steady_recompiles": 3},
                {"conservation_spmd_violations": 1},
                {"spmd_shards": 1}):
        res = _run(ok, ok | bad, tmp_path)
        field = next(iter(bad))
        assert res.returncode == 1, (bad, res.stdout, res.stderr)
        assert f"GATE {field}" in res.stderr
    dropped = dict(ok)
    del dropped["spmd_store_parity"]
    res = _run(ok, dropped, tmp_path)
    assert res.returncode == 1
    assert "GATE spmd_store_parity" in res.stderr
    assert "ABSENT" in res.stderr


def test_analytics_gates_enforced(tmp_path):
    """ISSUE 19: the historical-analytics leg's parity/interference/
    recompile/ledger gates fail the diff when violated; throughput
    fields (devices/s, bytes/s) trend as reports and never gate."""
    ok = _base() | {
        "analytics_score_parity": True,
        "analytics_compressed_parity": True,
        "analytics_interference_pct": 0.9,
        "analytics_steady_recompiles": 0,
        "analytics_rollup_spill_parity": True,
        "conservation_analytics_violations": 0,
        "analytics_devices_per_s": 5000.0,
        "analytics_bytes_per_s": 8.0e6,
    }
    assert _run(ok, ok, tmp_path).returncode == 0
    res = _run(ok, ok | {"analytics_devices_per_s": 900.0,
                         "analytics_bytes_per_s": 1.0e6}, tmp_path)
    assert res.returncode == 0, res.stderr
    for bad in ({"analytics_score_parity": False},
                {"analytics_compressed_parity": False},
                {"analytics_interference_pct": 4.2},
                {"analytics_steady_recompiles": 2},
                {"analytics_rollup_spill_parity": False},
                {"conservation_analytics_violations": 1}):
        res = _run(ok, ok | bad, tmp_path)
        field = next(iter(bad))
        assert res.returncode == 1, (bad, res.stdout, res.stderr)
        assert f"GATE {field}" in res.stderr
    dropped = dict(ok)
    del dropped["analytics_score_parity"]
    res = _run(ok, dropped, tmp_path)
    assert res.returncode == 1
    assert "GATE analytics_score_parity" in res.stderr
    assert "ABSENT" in res.stderr


def test_unreadable_input_is_usage_error(tmp_path):
    res = subprocess.run(
        [sys.executable, str(SCRIPT), str(tmp_path / "missing.json"),
         str(tmp_path / "missing2.json")],
        capture_output=True, text=True, timeout=60)
    assert res.returncode == 2


def test_committed_bench_covers_every_smoke_gate():
    """CI guard (ISSUE 15 satellite): the COMMITTED BENCH.json must
    (a) pass a self-diff — every hard gate it carries still holds —
    and (b) cover the full SMOKE_GATES set, so a gate silently dropped
    from bench.py fails the tier-1 suite, not just the next bench run."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_diff", SCRIPT)
    bd = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bd)
    bench = json.loads((REPO / "BENCH.json").read_text())
    missing = sorted(bd.SMOKE_GATES - set(bench))
    assert not missing, (
        f"committed BENCH.json is missing smoke gate(s) {missing} — a "
        "bench leg was dropped (or BENCH.json was not regenerated "
        "after adding a gate)")
    failures = bd.check_gates(bench, bench)
    assert not failures, failures
    assert bd.SMOKE_GATES <= set(bd.GATES), \
        "SMOKE_GATES names a gate the GATES table no longer evaluates"
    # negative control: dropping a passing gate from the 'new' run is a
    # regression the tool itself reports
    trimmed = dict(bench)
    trimmed.pop("cluster_chaos_no_loss")
    assert any("ABSENT" in f for f in bd.check_gates(bench, trimmed))
