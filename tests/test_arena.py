"""Zero-copy ingest arena path: equivalence, backpressure, durability.

The arena path (ISSUE 2 tentpole) replaces the decode->copy->emit staging
chain with pooled SoA buffers the native scanner fills directly. These
tests pin its contract:

  * pipeline results are BYTE-IDENTICAL to the legacy copy-staging path
    on mixed JSON/binary traffic (including the scan_chunk>1 arena scan
    step);
  * an exhausted pool applies backpressure (blocks on the oldest
    in-flight dispatch) instead of allocating or corrupting;
  * WAL-before-dispatch ordering holds: every accepted row is in the WAL
    before the device program that persists it is dispatched.
"""

import dataclasses
import json

import numpy as np
import pytest

from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.ingest.arena import ArenaPool, StagingArena
from sitewhere_tpu.ingest.decoders import encode_binary_request
from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType
from sitewhere_tpu.loadgen import generate_measurements_message

SMALL = dict(device_capacity=1 << 10, token_capacity=1 << 11,
             assignment_capacity=1 << 11, store_capacity=1 << 12,
             batch_capacity=128)


def _mixed_payloads():
    jpay = [generate_measurements_message(f"ar-{i % 40}", i,
                                          value=float(i % 90))
            for i in range(300)]
    # a couple of alert + location envelopes exercise the non-default
    # transforms (level fold, fixed location lanes)
    jpay += [json.dumps({
        "deviceToken": f"ar-{i % 40}", "type": "DeviceAlert",
        "request": {"type": "engine.overheat", "level": "Critical",
                    "eventDate": None}}).encode() for i in range(10)]
    jpay += [json.dumps({
        "deviceToken": f"ar-{i % 40}", "type": "DeviceLocation",
        "request": {"latitude": 33.75 + i, "longitude": -84.39,
                    "elevation": 300.0}}).encode() for i in range(10)]
    bpay = [encode_binary_request(DecodedRequest(
        type=RequestType.DEVICE_MEASUREMENT, device_token=f"ar-{i % 50}",
        measurements={"fuel.level": float(i % 100)},
        event_ts_ms=1700000000000 + i)) for i in range(180)]
    return jpay, bpay


def _run_engine(**overrides):
    eng = Engine(EngineConfig(**SMALL, **overrides))
    # pin the time base so arena/legacy runs produce identical columns
    eng.epoch.base_unix_s = 1700000000.0 - 1000.0
    eng.epoch.now_ms = lambda: 12345
    jpay, bpay = _mixed_payloads()
    eng.ingest_json_batch(jpay)
    eng.ingest_binary_batch(bpay)
    eng.flush()
    return eng


def _store_arrays(eng):
    import jax

    st = jax.device_get(eng.state.store)
    return {f.name: np.asarray(getattr(st, f.name))
            for f in dataclasses.fields(st)}


def test_arena_path_matches_legacy_byte_identical():
    arena_eng = _run_engine()
    if arena_eng._arena_pool is None:
        pytest.skip("native arena path unavailable")
    legacy_eng = _run_engine(ingest_arenas=-1)
    assert legacy_eng._arena_pool is None
    a, b = _store_arrays(arena_eng), _store_arrays(legacy_eng)
    for name in a:
        assert np.array_equal(a[name], b[name]), f"store.{name} diverges"
    import jax

    dsa = jax.device_get(arena_eng.state.device_state)
    dsb = jax.device_get(legacy_eng.state.device_state)
    for f in dataclasses.fields(dsa):
        assert np.array_equal(np.asarray(getattr(dsa, f.name)),
                              np.asarray(getattr(dsb, f.name))), \
            f"device_state.{f.name} diverges"
    ma, mb = arena_eng.metrics(), legacy_eng.metrics()
    for k in ("processed", "found", "missed", "registered", "persisted"):
        assert ma[k] == mb[k]
    # the arena run staged every batch row copy-free
    assert arena_eng.host_counters.get("staged_copy_rows", 0) == 0
    assert arena_eng.host_counters["arena_rows"] == 500


def test_arena_scan_chunk_matches_single_step():
    base = _run_engine()
    if base._arena_pool is None:
        pytest.skip("native arena path unavailable")
    scan = _run_engine(scan_chunk=4)
    assert scan._arena_step is not None
    a, b = _store_arrays(base), _store_arrays(scan)
    for name in a:
        assert np.array_equal(a[name], b[name]), f"store.{name} diverges"


class _FakeTicket:
    """Stand-in for a dispatch output array: not ready until blocked on."""

    def __init__(self):
        self.blocked = False

    def is_ready(self):
        return self.blocked

    def block_until_ready(self):
        self.blocked = True
        return self


def test_arena_pool_exhaustion_blocks_on_oldest():
    pool = ArenaPool(2, 64, 8)
    a1 = pool.acquire()
    t1 = _FakeTicket()
    pool.retire(a1, t1)
    a2 = pool.acquire()
    t2 = _FakeTicket()
    pool.retire(a2, t2)
    # both arenas in flight, neither ready: the next acquire must wait
    # on the OLDEST dispatch and recycle its arena
    a3 = pool.acquire()
    assert pool.waits == 1
    assert t1.blocked and not t2.blocked
    assert a3 is a1
    assert a3.cursor == 0 and not a3.valid.any()


def test_arena_pool_recycles_ready_without_waiting():
    pool = ArenaPool(2, 64, 8)
    a1 = pool.acquire()
    t1 = _FakeTicket()
    t1.blocked = True   # dispatch already finished
    pool.retire(a1, t1)
    a2 = pool.acquire()   # reclaims a1 opportunistically, no wait
    a3 = pool.acquire()
    assert pool.waits == 0
    assert a2 is not a3
    assert a1 in (a2, a3)


def test_engine_single_arena_backpressure_correctness():
    """ingest_arenas=1 forces constant recycle-through-the-oldest: every
    event must still persist exactly once."""
    eng = Engine(EngineConfig(**SMALL, ingest_arenas=1, dispatch_depth=2))
    if eng._arena_pool is None:
        pytest.skip("native arena path unavailable")
    assert eng._arena_pool.n_arenas == 1
    for b in range(6):
        eng.ingest_json_batch([
            generate_measurements_message(f"bp-{i % 30}", b * 128 + i)
            for i in range(128)])
    eng.flush()
    assert eng.metrics()["persisted"] == 6 * 128
    assert "arena_pool_waits" in eng.metrics()


def test_wal_records_precede_arena_dispatch(tmp_path):
    """Durability ordering: by the time a device program is dispatched,
    every row it carries is already group-appended (and flushed) to the
    WAL — accepted => durable => dispatched, never the reverse."""
    from sitewhere_tpu.utils.ingestlog import IngestLog

    wal_dir = tmp_path / "wal"
    eng = Engine(EngineConfig(**SMALL, wal_dir=str(wal_dir)))
    if eng._arena_pool is None:
        pytest.skip("native arena path unavailable")
    real_step = eng._step
    dispatched = []

    def checking_step(state, batch):
        n_valid = int(np.sum(np.asarray(batch.valid)))
        wal_records = sum(
            1 for _ in IngestLog(wal_dir, readonly=True).replay())
        assert wal_records >= sum(dispatched) + n_valid, \
            "dispatch ran ahead of the WAL"
        dispatched.append(n_valid)
        return real_step(state, batch)

    eng._step = checking_step
    eng.ingest_json_batch([
        generate_measurements_message(f"wd-{i % 20}", i)
        for i in range(300)])   # 2 full arenas dispatch mid-ingest
    eng.flush()
    assert sum(dispatched) == 300
    assert len(dispatched) >= 2


def test_wal_group_append_replays_identically(tmp_path):
    """append_many frames records byte-identically to per-record append:
    replay returns the same payload sequence either way."""
    from sitewhere_tpu.utils.ingestlog import IngestLog

    payloads = [f"payload-{i}".encode() for i in range(50)]
    head = b"\x01tenant\x00"
    a = IngestLog(tmp_path / "a")
    for p in payloads:
        a.append(head + p)
    a.sync()
    b = IngestLog(tmp_path / "b")
    b.append_many(payloads, head)
    b.sync()
    assert list(IngestLog(tmp_path / "a", readonly=True).replay()) == \
        list(IngestLog(tmp_path / "b", readonly=True).replay())


def test_native_device_token_precedence():
    """An envelope carrying BOTH deviceToken and hardwareId must decode
    to the deviceToken in either key order (routing and registration
    agree; ADVICE r5)."""
    from sitewhere_tpu.ingest.fast_decode import (NativeBatchDecoder,
                                                  native_available)
    from sitewhere_tpu.native.binding import NativeInterner

    if not native_available():
        pytest.skip("native library unavailable")
    tokens = NativeInterner(1 << 10)
    dec = NativeBatchDecoder(tokens, 8)
    body = {"type": "DeviceMeasurement",
            "request": {"name": "t", "value": 1.0}}
    p1 = json.dumps({"hardwareId": "hw-1", "deviceToken": "dt-1",
                     **body}).encode()
    p2 = json.dumps({"deviceToken": "dt-1", "hardwareId": "hw-1",
                     **body}).encode()
    res = dec.decode([p1, p2])
    want = tokens.lookup("dt-1")
    assert want >= 0
    assert res.token_id[0] == want
    assert res.token_id[1] == want


def test_strict_channels_arena_staging_and_rollback():
    """Strict engines keep the all-or-nothing native decode + rollback,
    then stage the validated batch through the arenas: accepted batches
    match the legacy strict path byte-for-byte, and a rejected batch
    leaks neither lanes nor rows on either path."""
    import jax

    from sitewhere_tpu.engine import ChannelCapacityError

    def run(**kw):
        eng = Engine(EngineConfig(**SMALL, channels=3,
                                  strict_channels=True, **kw))
        eng.epoch.base_unix_s = 1700000000.0 - 1000.0
        eng.epoch.now_ms = lambda: 12345
        ok_pay = [json.dumps({
            "deviceToken": f"sc-{i % 8}", "type": "DeviceMeasurement",
            "request": {"measurements": {"a": float(i), "b": float(i + 1)},
                        "eventDate": None}}).encode() for i in range(40)]
        eng.ingest_json_batch(ok_pay)
        with pytest.raises(ChannelCapacityError):
            eng.ingest_json_batch([json.dumps({
                "deviceToken": "sc-x", "type": "DeviceMeasurement",
                "request": {"measurements": {"c": 3.0, "d": 4.0},
                            "eventDate": None}}).encode()])
        eng.flush()
        return eng

    arena_eng = run()
    if arena_eng._arena_pool is None:
        pytest.skip("native arena path unavailable")
    legacy_eng = run(ingest_arenas=-1)
    assert arena_eng.metrics()["persisted"] == 40
    assert legacy_eng.metrics()["persisted"] == 40
    # the rejected batch rolled its interned names back on both paths
    assert len(arena_eng.channel_map.names) == 2
    assert len(legacy_eng.channel_map.names) == 2
    sa = jax.device_get(arena_eng.state.store)
    sb = jax.device_get(legacy_eng.state.store)
    for f in dataclasses.fields(sa):
        assert np.array_equal(np.asarray(getattr(sa, f.name)),
                              np.asarray(getattr(sb, f.name))), \
            f"store.{f.name} diverges"


def test_register_envelope_mid_batch_does_not_hang():
    """A RegisterDevice envelope inside a batch re-enters the admin path
    (register_device -> _sync_mirrors) while the arena commit is still
    building its valid mask; that re-entry must neither deadlock nor
    dispatch the half-committed arena."""
    eng = Engine(EngineConfig(**SMALL))
    if eng._arena_pool is None:
        pytest.skip("native arena path unavailable")
    # first fill the arena partially so the commit re-entry happens with
    # cursor > 0 (the case where _sync_mirrors could otherwise spin on a
    # fill arena that flush_async refuses to dispatch mid-commit)
    eng.ingest_json_batch([generate_measurements_message(f"rg-{i % 10}", i)
                           for i in range(50)])
    assert eng._arena_fill is not None and eng._arena_fill.cursor == 50
    payloads = [generate_measurements_message(f"rg-{i % 10}", 50 + i)
                for i in range(60)]
    payloads.insert(30, json.dumps({
        "deviceToken": "rg-admin", "type": "RegisterDevice",
        "request": {"deviceTypeToken": "mega2560"}}).encode())
    s = eng.ingest_json_batch(payloads)
    assert s["decoded"] == 61 and s["failed"] == 0 and s["staged"] == 60
    eng.flush()
    assert eng.metrics()["persisted"] == 110
    assert eng.get_device("rg-admin").device_type == "mega2560"


@pytest.mark.slow
def test_arena_stress_many_cycles():
    """Pool-churn stress: hundreds of partial and full arena dispatches
    with interleaved flushes keep counts exact."""
    eng = Engine(EngineConfig(**SMALL, ingest_arenas=2))
    if eng._arena_pool is None:
        pytest.skip("native arena path unavailable")
    total = 0
    rng = np.random.default_rng(3)
    for b in range(200):
        n = int(rng.integers(1, 200))
        eng.ingest_json_batch([
            generate_measurements_message(f"st-{i % 64}", b * 256 + i)
            for i in range(n)])
        total += n
        if b % 7 == 0:
            eng.flush_async()
    eng.flush()
    assert eng.metrics()["persisted"] == total
